"""``python -m peritext_tpu.obs`` — render telemetry artifacts.

Reads Perfetto/Chrome trace-event JSON (a ``Tracer.chrome_trace()`` dump,
``/trace.json`` scrape, or obs-smoke artifact) or flight-recorder JSONL and
prints a per-stage / per-host summary table: span count, total wall, mean,
and p50/p95/p99 per (stage, host).  The ``fleet`` command instead reads
``/convergence.json`` scrapes (or ``/health.json`` bodies carrying a
``convergence`` key) from one or more hosts and renders the fleet's
replication-lag picture: per (host, peer) ops-behind/ahead watermarks,
staleness, failures, and any divergence incidents.

The ``serve`` command reads ``/serve.json`` scrapes (or ``/health.json``
bodies carrying a ``serve`` key) from one or more serving hosts and
renders the serving tier's load picture: sessions, bounded-queue depth
vs watermarks, typed verdict tallies (admitted / delayed / shed by
reason), degradations, and the autotuned round-open window — exiting 1
when any host is under sustained overload (backpressure engaged) or has
shed load, so the command doubles as a fleet serving-health check.

The ``plan`` command reads one devprof snapshot (a ``/devprof.json``
scrape, a ``/health.json`` body carrying a ``devprof`` key, or an
obs-smoke artifact) — plus, optionally, the perf ledger for the
admission-window term — and prints the closed-loop planner's
:class:`~peritext_tpu.plan.tuner.PlanProposal`: the proposed statics
(stream widths, slot capacity, page size, fused depth, admission
window) next to the observed configuration, with the modeled
padded-FLOPs / recompile / dispatch terms that justify them.  Exit 1
when the proposal beats the current configuration beyond the tolerance
band ("your statics are stale" — the cue to replay the proposal through
a bench row), 0 inside the band.

The ``perf`` command reads the append-only perf ledger
(:mod:`peritext_tpu.obs.ledger`: bench ladder rows + devprof snapshots,
one JSONL record per run) and renders the LAST record as a diff table
against its rolling same-device reference; ``--gate`` makes a regression
beyond the tolerance bands exit 1 — the CI perf-gate job.

The ``why`` command is the perf gate's attribution engine
(:func:`peritext_tpu.obs.latency.attribute`): it judges the ledger's last
record exactly like ``perf``, then explains WHAT moved — diffing the
failing row's per-stage latency decomposition (admit → window → stage →
dispatch → commit → visibility) against the per-stage median over the
rolling reference, attaching the devprof shape-bucket / occupancy
deltas, and deterministically naming the dominant moved stage (largest
positive delta; ties break to the earliest stage in the taxonomy).
``--row`` targets a specific row instead of the first failing one.

The ``incidents`` command reads ``/incidents.json`` scrapes (or
``/health.json`` bodies carrying an ``incidents`` key) and renders the
correlated incident table: typed kind, lifecycle status, scope
(hosts/docs), open/resolve rounds, and each incident's root-cause
candidate ordering — exiting 1 while any incident is open, so the
command doubles as a fleet incident check.

The ``status`` command is the one-look roll-up: given a live
MetricsServer base URL (``http://host:port``) or a snapshot directory
(``health.json`` / ``convergence.json`` / ``serve.json`` /
``fleet.json`` / ``latency.json`` / ``incidents.json`` /
``devprof.json`` / ``plan.json`` / ``timeseries.json`` /
``trace.json``), it renders one
table over every plane present and exits with the COMPOSITE of the
per-plane CLI contracts (the worst plane wins).  Every JSON endpoint
the MetricsServer can mount has a row here — the surface-mount audit
test pins that equivalence.

The ``history`` command reads the history plane (a ``/timeseries.json``
scrape, a snapshot directory holding ``timeseries.json`` or
``history.json``, a ``health.json`` body carrying a ``history`` key, or
a direct file path) and renders the retained trend: by default a
per-gauge-key table (points, first → last, delta, min/max envelope)
sorted so the biggest movers lead; ``--key`` renders one gauge's
``[round, value]`` points instead (``--rate`` adds the per-round
derivative, ``--window N`` limits to the trailing N frames).  Exit 1
while any anomaly finding is active — the command doubles as a fleet
drift check.

The ``top`` command is the single-refresh fleet dashboard: the
``status`` roll-up table composed with the history plane's biggest
recent movers and its active anomaly findings — one look at what is
unhealthy NOW next to what has been drifting.  Exits like ``status``
(the worst plane wins; an active anomaly surfaces through the
``timeseries`` plane row).

The ``flight`` command reads a directory of flight-recorder dumps
(``flight-<host>-<pid>-<n>-<reason>.jsonl``) and renders the merged
cross-host black-box timeline (:func:`peritext_tpu.obs.incidents.
merge_flight_dumps`): every record host-attributed from its dump's
filename, ordered by timestamp, with the per-trace causal groupings.

Usage::

    python -m peritext_tpu.obs summary trace.json [more.json ...]
    python -m peritext_tpu.obs summary flight-*.jsonl --json
    python -m peritext_tpu.obs merge -o merged.json hostA.json hostB.json
    python -m peritext_tpu.obs fleet hostA-convergence.json hostB.json
    python -m peritext_tpu.obs serve hostA-serve.json hostB-serve.json
    python -m peritext_tpu.obs perf perf/reference_ledger.jsonl --gate
    python -m peritext_tpu.obs plan devprof.json --ledger perf/ledger.jsonl
    python -m peritext_tpu.obs why perf/ledger.jsonl --row serve_sustained
    python -m peritext_tpu.obs incidents hostA-incidents.json hostB.json
    python -m peritext_tpu.obs status http://127.0.0.1:9100
    python -m peritext_tpu.obs status snapshot-dir/
    python -m peritext_tpu.obs history http://127.0.0.1:9100
    python -m peritext_tpu.obs history snapshot-dir/ --key serve.queue.depth
    python -m peritext_tpu.obs top http://127.0.0.1:9100
    python -m peritext_tpu.obs flight dump-dir/

``summary`` is the default command (``python -m peritext_tpu.obs t.json``
works).  Exit codes: 0 ok (fleet: converged; serve: healthy; perf: no
regression; why: clean; plan: statics within tolerance; incidents: none
open; status/top: every plane clean; history: no active anomaly), 1 no
spans
found / fleet has lag or divergence / serve has overload or shedding /
perf ``--gate`` regression / why regression (attributed or not) / plan
proposal beats the current statics beyond tolerance / open incidents /
any plane in the status or top roll-up unhealthy / an active history
anomaly, 2 unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence


def load_spans(path: str | Path) -> List[Dict]:
    """Normalized span rows ``{name, host, duration_s, trace_id}`` from a
    Chrome trace JSON or a flight-recorder JSONL file."""
    text = Path(path).read_text()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if doc is not None:  # chrome trace: object with traceEvents, or a list
        events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
        return [
            {
                "name": e.get("name", "?"),
                "host": e.get("args", {}).get("host", str(e.get("pid", "?"))),
                "duration_s": e.get("dur", 0) / 1e6,
                "trace_id": e.get("args", {}).get("trace_id"),
            }
            for e in events
            if e.get("ph") == "X"
        ]
    # flight-recorder JSONL: one record per line, spans have kind == "span"
    spans = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        if rec.get("kind") == "span":
            spans.append({
                "name": rec.get("name", "?"),
                "host": rec.get("host", "?"),
                "duration_s": rec.get("duration_s", 0.0),
                "trace_id": rec.get("trace_id"),
            })
    return spans


def _pct(durs: List[float], q: float) -> float:
    if not durs:
        return 0.0
    idx = min(len(durs) - 1, max(0, int(q * len(durs)) - (0 if q * len(durs) % 1 else 1)))
    return durs[idx]


def summarize(spans: Sequence[Dict]) -> List[Dict]:
    """Per-(stage, host) rows sorted by total wall descending."""
    groups: Dict[tuple, List[float]] = {}
    for sp in spans:
        groups.setdefault((sp["name"], sp["host"]), []).append(sp["duration_s"])
    rows = []
    for (name, host), durs in sorted(groups.items()):
        durs = sorted(durs)
        total = sum(durs)
        rows.append({
            "stage": name,
            "host": host,
            "count": len(durs),
            "total_ms": round(total * 1e3, 3),
            "mean_ms": round(total / len(durs) * 1e3, 3),
            "p50_ms": round(_pct(durs, 0.50) * 1e3, 3),
            "p95_ms": round(_pct(durs, 0.95) * 1e3, 3),
            "p99_ms": round(_pct(durs, 0.99) * 1e3, 3),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def render_table(rows: Sequence[Dict], cols: Optional[List[str]] = None,
                 left_cols: int = 2) -> str:
    cols = cols or ["stage", "host", "count", "total_ms", "mean_ms",
                    "p50_ms", "p95_ms", "p99_ms"]
    cells = [[str(r[c]) for c in cols] for r in rows]
    widths = [max(len(c), *(len(row[i]) for row in cells)) if cells else len(c)
              for i, c in enumerate(cols)]
    def fmt(row):
        return "  ".join(
            v.ljust(w) if i < left_cols else v.rjust(w)
            for i, (v, w) in enumerate(zip(row, widths))
        )
    lines = [fmt(cols), fmt(["-" * w for w in widths])]
    lines.extend(fmt(row) for row in cells)
    return "\n".join(lines)


# -- fleet view (convergence.json scrapes) ----------------------------------


def load_convergence(path: str | Path) -> Dict:
    """One host's convergence snapshot from a ``/convergence.json`` scrape
    or a ``/health.json`` body whose ``convergence`` key carries it."""
    doc = json.loads(Path(path).read_text())
    if isinstance(doc, dict) and "convergence" in doc:
        doc = doc["convergence"]
    if not isinstance(doc, dict) or "peers" not in doc:
        raise ValueError(f"{path}: not a convergence snapshot")
    return doc


def fleet_rows(snapshots: Sequence[Dict]) -> List[Dict]:
    """Flatten host snapshots into per-(host, peer) lag rows."""
    rows = []
    for snap in snapshots:
        host = snap.get("host", "?")
        for peer, rec in sorted(snap.get("peers", {}).items()):
            rows.append({
                "host": host,
                "peer": peer,
                "lag_ops": rec.get("ops_behind", 0),
                "ahead_ops": rec.get("ops_ahead", 0),
                "stale_rounds": rec.get("staleness_rounds", 0),
                "failures": rec.get("failures", 0),
                "outcome": rec.get("last_outcome", "?"),
                "divergent": "YES" if rec.get("divergent") else "",
                "last_error": rec.get("last_error"),
            })
    rows.sort(key=lambda r: (-r["lag_ops"], -r["stale_rounds"],
                             r["host"], r["peer"]))
    return rows


# -- serve view (/serve.json scrapes) ----------------------------------------


def load_serve(path: str | Path) -> Dict:
    """One host's serving snapshot from a ``/serve.json`` scrape or a
    ``/health.json`` body whose ``serve`` key carries it."""
    doc = json.loads(Path(path).read_text())
    if isinstance(doc, dict) and "serve" in doc and "queue" not in doc:
        doc = doc["serve"]
    if not isinstance(doc, dict) or "queue" not in doc or "window" not in doc:
        raise ValueError(f"{path}: not a serve snapshot")
    return doc


def serve_rows(snapshots: Sequence[Dict]) -> List[Dict]:
    """Flatten host serve snapshots into per-host load rows."""
    rows = []
    for snap in snapshots:
        q = snap.get("queue", {})
        verdicts = q.get("verdicts", {})
        shed_reasons = verdicts.get("shed_reasons", {})
        # health reads RECENCY: sheds since the tier last kept up (an old
        # scrape without the field falls back to the lifetime counter)
        recent = snap.get("recent_sheds", verdicts.get("shed", 0))
        rows.append({
            "host": snap.get("host", "?"),
            "sessions": snap.get("sessions", 0),
            "docs": snap.get("docs", 0),
            "depth": f"{q.get('depth', 0)}/{q.get('max_depth', 0)}",
            "peak": q.get("peak", 0),
            "admitted": verdicts.get("admitted", 0),
            "delayed": verdicts.get("delayed", 0),
            "shed": verdicts.get("shed", 0),
            "recent_sheds": recent,
            "degraded": snap.get("degraded_docs", 0),
            "window_ms": round(
                snap.get("window", {}).get("seconds", 0.0) * 1e3, 2
            ),
            "overloaded": "YES" if (
                snap.get("overloaded") or q.get("backpressure")
            ) else "",
            "shed_reasons": ",".join(
                f"{k}:{v}" for k, v in sorted(shed_reasons.items())
            ),
        })
    rows.sort(key=lambda r: (r["overloaded"] != "YES", -r["recent_sheds"],
                             r["host"]))
    return rows


# -- incident view (/incidents.json scrapes) ---------------------------------


def load_incidents(path: str | Path) -> Dict:
    """One monitor's incident snapshot from an ``/incidents.json`` scrape
    or a ``/health.json`` body whose ``incidents`` key carries it."""
    doc = json.loads(Path(path).read_text())
    if (isinstance(doc, dict) and isinstance(doc.get("incidents"), dict)):
        doc = doc["incidents"]  # health.json composition
    if (not isinstance(doc, dict) or "by_kind" not in doc
            or not isinstance(doc.get("incidents"), list)):
        raise ValueError(f"{path}: not an incidents snapshot")
    return doc


def incident_rows(snapshots: Sequence[Dict]) -> List[Dict]:
    """Flatten monitor snapshots into per-incident rows, open first."""
    rows = []
    for snap in snapshots:
        monitor = snap.get("host", "?")
        for inc in snap.get("incidents", []):
            cands = inc.get("candidates", [])
            root = cands[0] if cands else {}
            rows.append({
                "monitor": monitor,
                "id": inc.get("id", "?"),
                "kind": inc.get("kind", "?"),
                "status": inc.get("status", "?"),
                "hosts": ",".join(inc.get("hosts", [])),
                "docs": ",".join(inc.get("docs", [])),
                "opened": inc.get("opened_round"),
                "resolved": (inc.get("resolved_round")
                             if inc.get("resolved_round") is not None
                             else "-"),
                "signals": inc.get("signals", 0),
                "root_value": root.get("value", 0),
                "candidates": ",".join(
                    f"{c.get('kind')}@{c.get('host')}" for c in cands
                ),
            })
    rows.sort(key=lambda r: (r["status"] == "resolved", r["monitor"],
                             r["id"]))
    return rows


def _incidents_command(args) -> int:
    """Render the correlated incident table (see module doc)."""
    snapshots = []
    for p in args.paths:
        try:
            snapshots.append(load_incidents(p))
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"unreadable incidents snapshot {p}: {exc}",
                  file=sys.stderr)
            return 2
    rows = incident_rows(snapshots)
    open_count = sum(s.get("open", 0) for s in snapshots)
    resolved = sum(s.get("resolved", 0) for s in snapshots)
    digests = sorted({s.get("digest") for s in snapshots})
    if args.json:
        print(json.dumps({
            "monitors": len(snapshots), "open": open_count,
            "resolved": resolved, "digests": digests, "rows": rows,
        }, indent=2))
    else:
        agree = ("" if len(snapshots) < 2 else
                 " · views AGREE" if len(digests) == 1
                 else " · views DISAGREE")
        print(f"{len(snapshots)} monitor(s) · {open_count} open · "
              f"{resolved} resolved{agree}")
        if rows:
            print(render_table(
                rows,
                cols=["monitor", "id", "kind", "status", "hosts",
                      "opened", "resolved", "signals", "candidates"],
                left_cols=5,
            ))
        else:
            print("no incidents recorded")
    # an open incident is exit 1: the command doubles as a fleet
    # incident check (CI / cron), mirroring serve/fleet
    return 1 if open_count else 0


# -- status roll-up (live MetricsServer or snapshot dir) ---------------------

#: plane -> (route/filename stem, evaluator).  Evaluators return
#: (exit_code, summary_string) from the plane's already-parsed JSON body,
#: with the SAME health predicates the per-plane commands apply.
def _eval_health(doc: Dict) -> tuple:
    counters = doc.get("counters", {})
    rollbacks = int(counters.get("supervisor.rollbacks", 0))
    quarantines = sum(
        v for k, v in counters.items()
        if k.startswith("streaming.quarantines")
    )
    return 0, (f"{len(counters)} counters · rollbacks {rollbacks} · "
               f"quarantines {int(quarantines)}")


def _eval_convergence(doc: Dict) -> tuple:
    lag = int(doc.get("total_lag_ops", 0))
    div = int(doc.get("divergence_incidents", 0))
    code = 1 if (lag or div) else 0
    return code, (f"{len(doc.get('peers', {}))} peer(s) · lag {lag} ops · "
                  f"{div} divergence")


def _eval_serve(doc: Dict) -> tuple:
    q = doc.get("queue", {})
    recent = int(doc.get("recent_sheds",
                         q.get("verdicts", {}).get("shed", 0)))
    overloaded = bool(doc.get("overloaded") or q.get("backpressure"))
    code = 1 if (overloaded or recent) else 0
    return code, (f"{doc.get('sessions', 0)} session(s) · "
                  f"depth {q.get('depth', 0)}/{q.get('max_depth', 0)} · "
                  f"recent sheds {recent}"
                  + (" · OVERLOADED" if overloaded else ""))


def _eval_fleet(doc: Dict) -> tuple:
    leases = doc.get("leases", {}).get("leases", {})
    dead = sum(1 for r in leases.values() if r.get("verdict") == "dead")
    failed = len(doc.get("failed_docs", []))
    code = 1 if (dead or failed) else 0
    return code, (f"{len(doc.get('hosts', {}))} host(s) · {dead} dead · "
                  f"{len(doc.get('serving', {}))} docs · "
                  f"{failed} failed · "
                  f"{doc.get('failovers', 0)} failover(s)")


def _eval_latency(doc: Dict) -> tuple:
    slo = doc.get("slo", {})
    burn = float(slo.get("burn_rate", 0.0) or 0.0)
    code = 1 if burn > 1.0 else 0
    return code, (f"windows {doc.get('windows', 0)} · "
                  f"burn rate {burn} · "
                  f"violating {slo.get('violating_frac', 0)}")


def _eval_incidents(doc: Dict) -> tuple:
    open_count = int(doc.get("open", 0))
    code = 1 if open_count else 0
    kinds = ",".join(
        k for k, v in doc.get("by_kind", {}).items() if v
    )
    return code, (f"{open_count} open · {doc.get('resolved', 0)} resolved"
                  + (f" · {kinds}" if kinds else ""))


def _eval_devprof(doc: Dict) -> tuple:
    sites = doc.get("sites", {}) or {}
    dispatches = sum(int(r.get("dispatches", 0)) for r in sites.values())
    tot = doc.get("occupancy_totals", {}) or {}
    # informational: the profiler reports cost, it has no health verdict
    return 0, (f"{len(sites)} jit site(s) · dispatches {dispatches} · "
               f"padding_waste {tot.get('padding_waste', 0)}")


def _eval_plan(doc: Dict) -> tuple:
    modeled = doc.get("modeled", {}) or {}
    cur = modeled.get("current_score") or 0
    new = modeled.get("proposed_score")
    tol = modeled.get("tolerance", 0.1)
    # the `plan` command's own contract: stale statics are exit 1
    stale = bool(cur) and new is not None and (cur - new) / cur > tol
    hist = modeled.get("history") or {}
    return (1 if stale else 0), (
        f"score {cur} -> {new} · "
        f"savings {modeled.get('savings_frac', 0)}"
        + (f" · history rows {hist.get('rows')}" if hist else "")
        + (" · STALE STATICS" if stale else "")
    )


def _eval_timeseries(doc: Dict) -> tuple:
    anomaly = doc.get("anomaly", {}) or {}
    active = anomaly.get("active") or []
    kinds = ",".join(sorted({a.get("kind", "?") for a in active}))
    return (1 if active else 0), (
        f"rounds {doc.get('rounds', 0)} · "
        f"frames {doc.get('frames_retained', 0)} · "
        f"segments {doc.get('segments', 0)} · "
        f"{len(active)} active anomaly(ies)"
        + (f" · {kinds}" if kinds else "")
    )


def _eval_trace(doc) -> tuple:
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else (doc or [])
    spans = sum(1 for e in events
                if isinstance(e, dict) and e.get("ph") == "X")
    # informational: a trace dump is evidence, not a verdict
    return 0, f"{len(events)} event(s) · {spans} span(s)"


#: every JSON endpoint MetricsServer can mount has a row here — the
#: surface-mount audit test (tests/test_obs_surface.py) pins route stems
#: == status plane stems, so adding an endpoint without a status row (or
#: vice versa) fails loudly
_STATUS_PLANES = (
    ("health", _eval_health),
    ("convergence", _eval_convergence),
    ("serve", _eval_serve),
    ("fleet", _eval_fleet),
    ("latency", _eval_latency),
    ("incidents", _eval_incidents),
    ("devprof", _eval_devprof),
    ("plan", _eval_plan),
    ("timeseries", _eval_timeseries),
    ("trace", _eval_trace),
)


def _status_source(src: str, plane: str):
    """One plane's JSON body from a MetricsServer base URL or snapshot
    dir.  Returns the parsed body, None when the plane is absent (no
    route / no file), or raises for a present-but-unreadable source."""
    if src.startswith(("http://", "https://")):
        import urllib.error
        import urllib.request

        url = f"{src.rstrip('/')}/{plane}.json"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None  # plane not mounted on this server
            raise
    path = Path(src) / f"{plane}.json"
    if not path.exists():
        return None
    return json.loads(path.read_text())


def _status_rows(src: str) -> tuple:
    """Evaluate every mounted plane at ``src`` — shared by ``status``
    and ``top``.  Returns ``(rows, codes)``; absent planes are skipped,
    present-but-unreadable ones render as exit-2 rows."""
    rows = []
    codes = []
    for plane, evaluator in _STATUS_PLANES:
        try:
            doc = _status_source(src, plane)
        except Exception as exc:  # noqa: BLE001 - every failure renders as a row
            rows.append({"plane": plane, "status": "UNREADABLE",
                         "exit": 2, "summary": str(exc)})
            codes.append(2)
            continue
        if doc is None:
            continue
        code, summary = evaluator(doc)
        rows.append({
            "plane": plane,
            "status": "ok" if code == 0 else "ATTENTION",
            "exit": code,
            "summary": summary,
        })
        codes.append(code)
    return rows, codes


def _status_command(args) -> int:
    """The one-look fleet roll-up (see module doc)."""
    rows, codes = _status_rows(args.src)
    if not rows:
        print(f"status: no plane snapshots found at {args.src} "
              "(expected <plane>.json files or MetricsServer routes)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"src": args.src, "exit": max(codes),
                          "planes": rows}, indent=2))
    else:
        print(f"{args.src} · {len(rows)} plane(s) · "
              f"{sum(1 for c in codes if c)} need attention")
        print(render_table(rows, cols=["plane", "status", "exit", "summary"],
                           left_cols=2))
    # composite contract: the worst per-plane exit code wins
    return max(codes)


# -- history view (/timeseries.json scrapes) ---------------------------------


def _load_history(src: str) -> Dict:
    """The history plane's snapshot from a MetricsServer base URL, a
    snapshot directory (``timeseries.json`` or ``history.json``), a
    ``health.json`` body carrying a ``history`` key, or a direct file."""
    if src.startswith(("http://", "https://")):
        doc = _status_source(src, "timeseries")
        if doc is None:
            raise ValueError("no /timeseries.json route mounted")
    else:
        p = Path(src)
        if p.is_file():
            doc = json.loads(p.read_text())
        else:
            doc = None
            for stem in ("timeseries", "history", "health"):
                f = p / f"{stem}.json"
                if f.exists():
                    doc = json.loads(f.read_text())
                    break
            if doc is None:
                raise ValueError(
                    f"no timeseries.json/history.json under {src}")
    if (isinstance(doc, dict) and "tiers" not in doc
            and isinstance(doc.get("history"), dict)):
        doc = doc["history"]  # health.json composition
    if not isinstance(doc, dict) or "tiers" not in doc:
        raise ValueError(f"{src}: not a history-plane snapshot")
    return doc


def _history_command(args) -> int:
    """Render the history plane's trend view (see module doc)."""
    from .timeseries import (
        chronological_frames,
        key_summary,
        series_points,
        series_rate,
        snapshot_keys,
    )

    try:
        snap = _load_history(args.src)
    except Exception as exc:  # noqa: BLE001 - every failure is one typed exit
        print(f"unreadable history snapshot {args.src}: {exc}",
              file=sys.stderr)
        return 2
    anomaly = snap.get("anomaly", {}) or {}
    active = anomaly.get("active") or []
    frames = chronological_frames(snap)
    header = (
        f"{snap.get('host', '?')} · rounds {snap.get('rounds', 0)} · "
        f"{snap.get('frames_retained', len(frames))} frame(s) across "
        f"{len(snap.get('tiers') or [])} tier(s) · "
        f"{snap.get('segments', 0)} segment(s) · "
        f"{len(active)} active anomaly(ies)"
    )
    if args.key:
        points = series_points(snap, args.key, window=args.window)
        if not points:
            print(f"history: no points for key '{args.key}' "
                  f"({len(snapshot_keys(snap))} keys retained)",
                  file=sys.stderr)
            return 2
        summary = key_summary(snap, args.key, window=args.window)
        if args.json:
            body = {"key": args.key, "points": points, "summary": summary,
                    "anomalies": active}
            if args.rate:
                body["rate"] = series_rate(points)
            print(json.dumps(body, indent=2))
        else:
            print(header)
            rates = {r: v for r, v in series_rate(points)}
            rows = []
            for r, v in points:
                row = {"round": int(r), "value": v}
                if args.rate:
                    row["rate"] = rates.get(r, "-")
                rows.append(row)
            cols = ["round", "value"] + (["rate"] if args.rate else [])
            print(render_table(rows, cols=cols, left_cols=0))
            print(
                f"{args.key}: min {summary['min']} · max {summary['max']} · "
                f"p50 {summary['p50']} · p95 {summary['p95']} · "
                f"delta {summary['delta']}"
            )
    else:
        summaries = [
            key_summary(snap, key, window=args.window)
            for key in snapshot_keys(snap)
        ]
        summaries = [s for s in summaries if s.get("points")]
        # the moving gauges lead; ties break on the key itself
        summaries.sort(key=lambda s: (-abs(s.get("delta") or 0.0), s["key"]))
        if args.json:
            print(json.dumps({"src": args.src, "summaries": summaries,
                              "anomalies": active}, indent=2))
        else:
            print(header)
            rows = [
                {"key": s["key"], "points": s["points"], "first": s["first"],
                 "last": s["last"], "delta": s["delta"], "min": s["min"],
                 "max": s["max"]}
                for s in summaries
            ]
            if rows:
                print(render_table(
                    rows, cols=["key", "points", "first", "last", "delta",
                                "min", "max"], left_cols=1))
            else:
                print("no gauge frames retained yet")
    if active and not args.json:
        for a in active:
            print(
                f"anomaly: {a.get('key')} [{a.get('kind')}] z={a.get('z')} "
                f"value {a.get('value')} vs median {a.get('median')} "
                f"@ round {a.get('round')}", file=sys.stderr,
            )
    # an active anomaly is exit 1: the command doubles as a fleet drift
    # check (CI / cron), mirroring serve/fleet/incidents
    return 1 if active else 0


def _top_command(args) -> int:
    """The single-refresh fleet dashboard (see module doc)."""
    from .timeseries import key_summary, snapshot_keys

    rows, codes = _status_rows(args.src)
    if not rows:
        print(f"top: no plane snapshots found at {args.src} "
              "(expected <plane>.json files or MetricsServer routes)",
              file=sys.stderr)
        return 2
    try:
        snap = _load_history(args.src)
    except Exception:  # noqa: BLE001 - the dashboard degrades to status-only
        snap = None
    movers: List[Dict] = []
    active: List[Dict] = []
    if snap is not None:
        anomaly = snap.get("anomaly", {}) or {}
        active = anomaly.get("active") or []
        summaries = [key_summary(snap, k, window=args.window)
                     for k in snapshot_keys(snap)]
        movers = [s for s in summaries if s.get("points") and s.get("delta")]
        movers.sort(key=lambda s: (-abs(s.get("delta") or 0.0), s["key"]))
        movers = movers[:args.top]
    if args.json:
        print(json.dumps({
            "src": args.src, "exit": max(codes), "planes": rows,
            "movers": movers, "anomalies": active,
        }, indent=2))
        return max(codes)
    print(f"{args.src} · {len(rows)} plane(s) · "
          f"{sum(1 for c in codes if c)} need attention · "
          f"{len(active)} active anomaly(ies)")
    print(render_table(rows, cols=["plane", "status", "exit", "summary"],
                       left_cols=2))
    if movers:
        window = args.window if args.window else "all"
        print(f"top {len(movers)} mover(s) over the trailing "
              f"{window} frame(s):")
        print(render_table(
            [{"key": s["key"], "first": s["first"], "last": s["last"],
              "delta": s["delta"]} for s in movers],
            cols=["key", "first", "last", "delta"], left_cols=1))
    elif snap is not None:
        print("history: no gauge movement recorded")
    else:
        print("history: plane not mounted (arm GLOBAL_HISTORY to trend)")
    for a in active:
        print(f"anomaly: {a.get('key')} [{a.get('kind')}] z={a.get('z')} "
              f"@ round {a.get('round')}", file=sys.stderr)
    # status semantics: the worst plane wins (an active anomaly already
    # surfaces as the timeseries plane's exit-1 row)
    return max(codes)


def _flight_command(args) -> int:
    """Render the merged cross-host black-box timeline (see module doc)."""
    from .incidents import merge_flight_dumps

    root = Path(args.dir)
    if not root.is_dir():
        print(f"flight: {args.dir} is not a directory", file=sys.stderr)
        return 2
    dumps = sorted(root.glob("flight-*.jsonl"))
    if not dumps:
        print(f"flight: no flight-*.jsonl dumps under {args.dir}",
              file=sys.stderr)
        return 2
    merged = merge_flight_dumps(dumps)
    if args.json:
        print(json.dumps(merged, indent=2, default=str))
        return 0
    base = (float(merged["timeline"][0].get("ts", 0.0) or 0.0)
            if merged["timeline"] else 0.0)
    print(f"{len(merged['dumps'])} dump(s) · "
          f"{len(merged['hosts'])} host(s) · {merged['records']} record(s) · "
          f"{len(merged['traces'])} trace(s)"
          + (f" · {merged['skipped']} skipped" if merged["skipped"] else ""))
    rows = []
    for rec in merged["timeline"][-args.tail:]:
        label = (rec.get("name") or rec.get("reason")
                 or rec.get("provider") or "")
        rows.append({
            "t_ms": round((float(rec.get("ts", 0.0) or 0.0) - base) * 1e3, 3),
            "host": rec.get("host", "?"),
            "kind": rec.get("kind", "?"),
            "what": label,
            "trace": (str(rec.get("trace_id"))[-8:]
                      if rec.get("trace_id") else ""),
        })
    if rows:
        print(render_table(rows, cols=["t_ms", "host", "kind", "what",
                                       "trace"], left_cols=0))
    for trace, recs in sorted(merged["traces"].items()):
        hosts = sorted({r["host"] for r in recs})
        print(f"  trace …{trace[-8:]}: {len(recs)} record(s) across "
              f"{','.join(hosts)}")
    return 0


def _perf_command(args) -> int:
    """Render/gate the perf ledger (see module doc)."""
    from . import ledger as _ledger

    try:
        records = _ledger.load_ledger(args.ledger)
    except (OSError, ValueError) as exc:
        print(f"unreadable perf ledger {args.ledger}: {exc}", file=sys.stderr)
        return 2
    if not records:
        print(f"empty perf ledger {args.ledger}", file=sys.stderr)
        return 2
    report = _ledger.evaluate(
        records,
        tolerance=(args.tolerance / 100.0 if args.tolerance is not None
                   else None),
        window=args.window if args.window is not None else _ledger.DEFAULT_WINDOW,
        match=args.match,
    )
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        cand = report["candidate"]
        sha = (cand.get("sha") or "?")[:12]
        dev = (cand.get("device") or {})
        print(
            f"{len(records)} record(s) · candidate sha {sha} · "
            f"config {cand.get('config')} · device "
            f"{dev.get('platform')}/{dev.get('kind')} · "
            f"{report['reference_records']} matching reference record(s)"
        )
        rows = [
            {
                "row": v["row"],
                "unit": v["unit"],
                "ref": "-" if v["ref"] is None else v["ref"],
                "value": "-" if v["value"] is None else v["value"],
                "delta_pct": "-" if v["delta_pct"] is None else v["delta_pct"],
                "band_pct": v["band_pct"],
                "status": v["status"],
            }
            for v in report["rows"]
        ]
        if rows:
            print(render_table(
                rows,
                cols=["row", "unit", "ref", "value", "delta_pct",
                      "band_pct", "status"],
            ))
        else:
            print("candidate record carries no rows")
    if args.gate and report["regressed"]:
        print("perf gate: REGRESSION detected", file=sys.stderr)
        return 1
    return 0


def _why_command(args) -> int:
    """Render the latency-plane regression attribution (see module doc)."""
    from . import ledger as _ledger
    from .latency import STAGES, attribute

    try:
        records = _ledger.load_ledger(args.ledger)
    except (OSError, ValueError) as exc:
        print(f"unreadable perf ledger {args.ledger}: {exc}", file=sys.stderr)
        return 2
    if not records:
        print(f"empty perf ledger {args.ledger}", file=sys.stderr)
        return 2
    try:
        report = attribute(
            records,
            row=args.row,
            window=args.window,
            match=args.match,
            tolerance=(args.tolerance / 100.0 if args.tolerance is not None
                       else None),
        )
    except ValueError as exc:
        print(f"why: {exc}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        cand = report["candidate"]
        sha = (cand.get("sha") or "?")[:12]
        print(
            f"{len(records)} record(s) · candidate sha {sha} · "
            f"{report['reference_records']} matching reference record(s)"
        )
        if report["verdict"] == "clean":
            print("why: gate passes — nothing to attribute")
            return 0
        print(
            f"row {report['row']} [{report['status']}]: "
            f"{report['ref']} -> {report['value']} {report['unit']} "
            f"(delta {report['delta']}"
            + (f", {report['delta_pct']}%" if report.get("delta_pct")
               is not None else "")
            + ")"
        )
        cand_stages = report.get("candidate_stages_ms")
        ref_stages = report.get("reference_stages_ms")
        deltas = report.get("stage_deltas_ms")
        if cand_stages and ref_stages and deltas is not None:
            rows = [
                {
                    "stage": s,
                    "ref_ms": ref_stages.get(s, "-"),
                    "value_ms": cand_stages.get(s, "-"),
                    "delta_ms": deltas.get(s, "-"),
                }
                for s in sorted(
                    set(cand_stages) | set(ref_stages),
                    key=lambda n: (STAGES.index(n) if n in STAGES
                                   else len(STAGES), n),
                )
            ]
            print(render_table(
                rows, cols=["stage", "ref_ms", "value_ms", "delta_ms"],
                left_cols=1,
            ))
        dp = report.get("devprof")
        if dp:
            d = dp["delta"]
            print(
                "devprof: distinct_shapes "
                f"{d.get('distinct_shapes')} · dispatches "
                f"{d.get('dispatches')} · padding_waste "
                f"{d.get('padding_waste')}"
            )
        if report["verdict"] == "regression-attributed":
            print(f"why: dominant moved stage is "
                  f"'{report['dominant_stage']}'", file=sys.stderr)
        elif report["verdict"] == "no-decomposition":
            print(
                "why: no latency decomposition on candidate or reference "
                "rows — arm the plane and re-run the bench", file=sys.stderr,
            )
        else:
            print(
                "why: regression with no stage moving up — look outside "
                "the latency plane", file=sys.stderr,
            )
    # a regression — whether or not attribution could name a stage — is
    # exit 1, mirroring `perf --gate`; clean is 0
    return 0 if report["verdict"] == "clean" else 1


def _plan_command(args) -> int:
    """The closed-loop planner's operator surface (see module doc)."""
    from ..plan import PlanProposal, propose  # noqa: F401 - typed surface
    from ..plan.model import load_devprof

    try:
        snapshot = load_devprof(args.snapshot)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"unreadable devprof snapshot {args.snapshot}: {exc}",
              file=sys.stderr)
        return 2
    ledger_records = None
    if args.ledger:
        from . import ledger as _ledger

        try:
            ledger_records = _ledger.load_ledger(args.ledger)
        except (OSError, ValueError) as exc:
            print(f"unreadable perf ledger {args.ledger}: {exc}",
                  file=sys.stderr)
            return 2
    history = None
    if getattr(args, "history", None):
        # a timeseries.json snapshot, a health.json carrying `history`,
        # or a plain JSON list of occupancy rows/floats — anything
        # plan.history_values normalizes
        try:
            history = json.loads(Path(args.history).read_text())
        except (OSError, json.JSONDecodeError) as exc:
            print(f"unreadable occupancy history {args.history}: {exc}",
                  file=sys.stderr)
            return 2
        if (isinstance(history, dict) and "occupancy_rows" not in history
                and isinstance(history.get("history"), dict)):
            history = history["history"]
    tolerance = (args.tolerance / 100.0 if args.tolerance is not None
                 else None)
    kwargs = {} if tolerance is None else {"tolerance": tolerance}
    proposal = propose(snapshot, ledger_records, history=history, **kwargs)
    stale = proposal.beats_current(
        tolerance if tolerance is not None else
        proposal.modeled.get("tolerance", 0.1)
    )
    if args.json:
        print(json.dumps(
            {**proposal.to_json(), "beats_current": stale}, indent=2,
        ))
    else:
        modeled = proposal.modeled
        print(
            f"planner: modeled score {modeled['current_score']} -> "
            f"{modeled['proposed_score']} "
            f"(savings {modeled['savings_frac'] * 100:.1f}%, tolerance "
            f"{modeled['tolerance'] * 100:.0f}%, utilization "
            f"{modeled['utilization'] * 100:.1f}%)"
        )
        body = proposal.to_json()
        rows = [
            {"static": key,
             "current": body["current"].get(key, "-"),
             "proposed": body["proposal"][key]}
            for key in body["proposal"]
        ]
        print(render_table(rows, cols=["static", "current", "proposed"],
                           left_cols=1))
        print(
            f"modeled: padded_flops {modeled['padded_flops_current']} -> "
            f"{modeled['padded_flops_proposed']} · recompiles "
            f"{modeled['recompiles_current']} -> "
            f"{modeled['recompiles_proposed']} · dispatches "
            f"{modeled['dispatches_current']} -> "
            f"{modeled['dispatches_proposed']}"
        )
        hist = modeled.get("history")
        if hist:
            occ = hist.get("occupancy") or {}
            print(
                f"history: {hist['rows']} occupancy row(s) · "
                f"p90 {occ.get('p90')} · sparse_frac "
                f"{occ.get('sparse_frac')} · dispatch weight "
                f"x{hist['dispatch_weight_factor']} · "
                "history-weighted terms: "
                + ", ".join(hist["weighted_terms"])
            )
        if stale:
            print(
                "plan: proposal beats current statics beyond tolerance — "
                "replay it through a bench row before re-pinning",
                file=sys.stderr,
            )
        else:
            print("plan: current statics are within tolerance")
    # "stale statics" is exit 1: the command doubles as a CI/cron check
    # that the pinned configuration still matches the observed workload
    return 1 if stale else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # default command: `python -m peritext_tpu.obs trace.json` == summary
    if argv and argv[0] not in ("summary", "merge", "fleet", "serve", "perf",
                                "plan", "why", "incidents", "status",
                                "history", "top", "flight", "-h", "--help"):
        argv.insert(0, "summary")
    parser = argparse.ArgumentParser(
        prog="python -m peritext_tpu.obs", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd")
    p_sum = sub.add_parser("summary", help="per-stage/per-host summary table")
    p_sum.add_argument("paths", nargs="+")
    p_sum.add_argument("--json", action="store_true",
                       help="machine-readable rows instead of the table")
    p_merge = sub.add_parser("merge", help="merge chrome traces into one")
    p_merge.add_argument("paths", nargs="+")
    p_merge.add_argument("-o", "--out", required=True)
    p_fleet = sub.add_parser(
        "fleet", help="per-peer replication-lag table from convergence.json "
        "scrapes",
    )
    p_fleet.add_argument("paths", nargs="+")
    p_fleet.add_argument("--json", action="store_true",
                         help="machine-readable rows instead of the table")
    p_serve = sub.add_parser(
        "serve", help="per-host serving-tier load table from serve.json "
        "scrapes (exit 1 on overload/shedding)",
    )
    p_serve.add_argument("paths", nargs="+")
    p_serve.add_argument("--json", action="store_true",
                         help="machine-readable rows instead of the table")
    p_perf = sub.add_parser(
        "perf", help="perf-ledger diff table: last record vs its rolling "
        "same-device reference",
    )
    p_perf.add_argument("ledger", help="JSONL perf-ledger path")
    p_perf.add_argument("--gate", action="store_true",
                        help="exit 1 when any row regresses beyond its band")
    p_perf.add_argument("--json", action="store_true",
                        help="machine-readable verdicts instead of the table")
    p_perf.add_argument("--tolerance", type=float, default=None, metavar="PCT",
                        help="override every row's tolerance band (percent)")
    p_perf.add_argument("--window", type=int, default=None, metavar="N",
                        help="rolling-reference window (prior records; "
                        "default 5)")
    p_perf.add_argument("--match", choices=("device", "platform", "any"),
                        default="device",
                        help="how strictly reference records must match the "
                        "candidate's device fingerprint (default: device)")
    p_why = sub.add_parser(
        "why", help="latency-plane regression attribution: name the "
        "dominant moved stage behind a perf-gate failure (exit 1 on "
        "regression)",
    )
    p_why.add_argument("ledger", help="JSONL perf-ledger path")
    p_why.add_argument("--row", default=None, metavar="NAME",
                       help="attribute this row instead of the first "
                       "failing one")
    p_why.add_argument("--json", action="store_true",
                       help="machine-readable attribution instead of the "
                       "table")
    p_why.add_argument("--window", type=int, default=None, metavar="N",
                       help="rolling-reference window (prior records; "
                       "default 5)")
    p_why.add_argument("--match", choices=("device", "platform", "any"),
                       default="device",
                       help="how strictly reference records must match the "
                       "candidate's device fingerprint (default: device)")
    p_why.add_argument("--tolerance", type=float, default=None, metavar="PCT",
                       help="override every row's tolerance band (percent)")
    p_plan = sub.add_parser(
        "plan", help="closed-loop planner proposal from a devprof snapshot "
        "(exit 1 when the proposal beats the current statics)",
    )
    p_plan.add_argument("snapshot", help="devprof.json / health.json path")
    p_plan.add_argument("--ledger", default=None, metavar="PATH",
                        help="perf-ledger JSONL for the admission-window "
                        "term (optional)")
    p_plan.add_argument("--history", default=None, metavar="PATH",
                        help="history-plane snapshot (timeseries.json / "
                        "health.json) or occupancy-row JSON: weight the "
                        "cost model by the observed occupancy distribution")
    p_plan.add_argument("--json", action="store_true",
                        help="machine-readable proposal instead of the table")
    p_plan.add_argument("--tolerance", type=float, default=None, metavar="PCT",
                        help="savings band (percent) below which the current "
                        "statics stand (default 10)")
    p_inc = sub.add_parser(
        "incidents", help="correlated incident table from incidents.json "
        "scrapes (exit 1 on open incidents)",
    )
    p_inc.add_argument("paths", nargs="+")
    p_inc.add_argument("--json", action="store_true",
                       help="machine-readable rows instead of the table")
    p_status = sub.add_parser(
        "status", help="one-look roll-up across every plane from a live "
        "MetricsServer URL or a snapshot directory (exit = worst plane)",
    )
    p_status.add_argument("src", help="http(s)://host:port base URL or a "
                          "directory of <plane>.json snapshots")
    p_status.add_argument("--json", action="store_true",
                          help="machine-readable plane rows instead of the "
                          "table")
    p_hist = sub.add_parser(
        "history", help="history-plane trend table from a timeseries.json "
        "scrape / snapshot dir / URL (exit 1 on active anomaly)",
    )
    p_hist.add_argument("src", help="MetricsServer base URL, snapshot "
                        "directory, or timeseries.json file")
    p_hist.add_argument("--key", default=None, metavar="GAUGE",
                        help="render one gauge's [round, value] points "
                        "instead of the per-key trend table")
    p_hist.add_argument("--window", type=int, default=None, metavar="N",
                        help="trailing frames to summarize (default: all "
                        "retained)")
    p_hist.add_argument("--rate", action="store_true",
                        help="with --key: add the per-round derivative "
                        "column")
    p_hist.add_argument("--json", action="store_true",
                        help="machine-readable body instead of the table")
    p_top = sub.add_parser(
        "top", help="single-refresh fleet dashboard: plane status roll-up "
        "+ the history plane's biggest movers (exit = worst plane)",
    )
    p_top.add_argument("src", help="http(s)://host:port base URL or a "
                       "directory of <plane>.json snapshots")
    p_top.add_argument("--window", type=int, default=16, metavar="N",
                       help="trailing frames for the movers table "
                       "(default 16)")
    p_top.add_argument("--top", type=int, default=10, metavar="N",
                       help="movers to show (default 10)")
    p_top.add_argument("--json", action="store_true",
                       help="machine-readable dashboard instead of tables")
    p_flight = sub.add_parser(
        "flight", help="merged cross-host black-box timeline from a "
        "directory of flight-recorder dumps",
    )
    p_flight.add_argument("dir", help="directory holding flight-*.jsonl "
                          "dumps")
    p_flight.add_argument("--json", action="store_true",
                          help="machine-readable merged timeline instead of "
                          "the table")
    p_flight.add_argument("--tail", type=int, default=40, metavar="N",
                          help="show the last N timeline records "
                          "(default 40)")
    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.print_help()
        return 2

    if args.cmd == "perf":
        return _perf_command(args)

    if args.cmd == "why":
        return _why_command(args)

    if args.cmd == "plan":
        return _plan_command(args)

    if args.cmd == "incidents":
        return _incidents_command(args)

    if args.cmd == "status":
        return _status_command(args)

    if args.cmd == "history":
        return _history_command(args)

    if args.cmd == "top":
        return _top_command(args)

    if args.cmd == "flight":
        return _flight_command(args)

    if args.cmd == "serve":
        snapshots = []
        for p in args.paths:
            try:
                snapshots.append(load_serve(p))
            except (OSError, ValueError, json.JSONDecodeError) as exc:
                print(f"unreadable serve snapshot {p}: {exc}",
                      file=sys.stderr)
                return 2
        rows = serve_rows(snapshots)
        # SUSTAINED overload/shedding only: backpressure currently engaged,
        # or sheds since the tier last kept up — a host that shed during a
        # past blip and recovered must not latch unhealthy forever
        total_shed = sum(r["recent_sheds"] for r in rows)
        overloaded = sum(1 for r in rows if r["overloaded"] == "YES")
        if args.json:
            print(json.dumps({
                "hosts": len(snapshots), "overloaded_hosts": overloaded,
                "total_shed": total_shed, "rows": rows,
            }, indent=2))
        else:
            print(f"{len(snapshots)} host(s) · {overloaded} overloaded · "
                  f"{total_shed} frame(s) recently shed")
            print(render_table(
                rows,
                cols=["host", "sessions", "docs", "depth", "peak",
                      "admitted", "delayed", "shed", "recent_sheds",
                      "degraded", "window_ms", "overloaded"],
                left_cols=1,
            ))
            for r in rows:
                if r["shed_reasons"]:
                    print(f"  {r['host']}: shed {r['shed_reasons']}")
        # a tier under sustained overload or shedding load is exit 1: the
        # command doubles as a CI/cron serving-health check
        return 1 if (overloaded or total_shed) else 0

    if args.cmd == "fleet":
        snapshots = []
        for p in args.paths:
            try:
                snapshots.append(load_convergence(p))
            except (OSError, ValueError, json.JSONDecodeError) as exc:
                print(f"unreadable convergence snapshot {p}: {exc}",
                      file=sys.stderr)
                return 2
        rows = fleet_rows(snapshots)
        incidents = sum(s.get("divergence_incidents", 0) for s in snapshots)
        total_lag = sum(r["lag_ops"] for r in rows)
        if args.json:
            print(json.dumps({
                "hosts": len(snapshots), "total_lag_ops": total_lag,
                "divergence_incidents": incidents, "rows": rows,
            }, indent=2))
        else:
            print(f"{len(snapshots)} host(s) · {len(rows)} peer link(s) · "
                  f"lag {total_lag} ops · {incidents} divergence incident(s)")
            print(render_table(
                rows,
                cols=["host", "peer", "lag_ops", "ahead_ops", "stale_rounds",
                      "failures", "outcome", "divergent"],
            ))
        # a fleet with outstanding lag or any divergence is exit 1: the
        # command doubles as a CI/cron convergence check
        return 1 if (total_lag or incidents) else 0

    if args.cmd == "merge":
        from .spans import merge_traces

        traces = []
        for p in args.paths:
            try:
                traces.append(json.loads(Path(p).read_text()))
            except (OSError, json.JSONDecodeError) as exc:
                print(f"unreadable trace {p}: {exc}", file=sys.stderr)
                return 2
        Path(args.out).write_text(json.dumps(merge_traces(*traces)))
        print(f"merged {len(traces)} trace(s) -> {args.out}")
        return 0

    spans: List[Dict] = []
    for p in args.paths:
        try:
            spans.extend(load_spans(p))
        except (OSError, json.JSONDecodeError) as exc:
            print(f"unreadable trace {p}: {exc}", file=sys.stderr)
            return 2
    if not spans:
        print("no spans found", file=sys.stderr)
        return 1
    rows = summarize(spans)
    if args.json:
        print(json.dumps(rows, indent=2))
    else:
        hosts = sorted({sp["host"] for sp in spans})
        traces = sorted({sp["trace_id"] for sp in spans if sp["trace_id"]})
        print(f"{len(spans)} spans · {len(hosts)} host(s) · "
              f"{len(traces)} trace(s)")
        print(render_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
