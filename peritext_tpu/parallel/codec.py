"""Binary change-frame codec (the DCN wire format).

The reference serializes changes as JSON (``src/micromerge.ts:563-564``
"can be JSON-encoded to send to another node") — fine for two browser tabs,
wasteful for a pod streaming 100K docs of changes between hosts.  This codec
packs a batch of changes into one compact frame:

* a string table (actor ids, mark attrs, and a JSON spillover for op shapes
  outside the fast path), UTF-8 with varint lengths;
* the op payload as a single zigzag-varint int32 stream (native C++ varint
  core when available, pure Python otherwise — identical bytes either way).

Text-CRDT ops (insert / delete / addMark / removeMark on the text list) take
the fast integer path; anything else (map ops, exotic values) is embedded as
per-op JSON via the string table, so the codec is lossless over the full
``Change`` model: ``decode_frame(encode_frame(cs))`` round-trips exactly and
interoperates with the JSON wire format.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Tuple

import numpy as np

from .. import native
from ..core.opids import HEAD, ROOT
from ..core.types import AFTER, BEFORE, Boundary, Change, END_OF_TEXT, Operation, START_OF_TEXT
from ..schema import ALL_MARKS, MARK_INDEX

_MAGIC = b"PTXF"
_VERSION = 1
_HEADER = struct.Struct("<4sBIIQQ")  # magic, ver, n_changes, n_strings, n_ints, payload_len

_BK_TO_INT = {BEFORE: 0, AFTER: 1, START_OF_TEXT: 2, END_OF_TEXT: 3}
_INT_TO_BK = {v: k for k, v in _BK_TO_INT.items()}

_OP_INSERT, _OP_DEL, _OP_ADDMARK, _OP_REMOVEMARK, _OP_JSON = 0, 1, 2, 3, 4
# map-object ops (device map-register path; reference map LWW
# src/micromerge.ts:1151-1175)
_OP_MAKEMAP, _OP_MAPSET, _OP_MAPDEL = 5, 6, 7

# value-kind encoding inside _OP_MAPSET (packed.VK_*: 1 str, 2 int, 3 true,
# 4 false, 5 null — VK_STR payload is a string-table index)
_VK_STR, _VK_INT, _VK_TRUE, _VK_FALSE, _VK_NULL = 1, 2, 3, 4, 5


# -- pure-python varint fallback (same bytes as the native core) ------------


def _py_varint_encode(values) -> bytes:
    out = bytearray()
    for v in values:
        z = ((int(v) << 1) ^ (int(v) >> 31)) & 0xFFFFFFFF
        while True:
            byte = z & 0x7F
            z >>= 7
            if z:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
    return bytes(out)


def _py_varint_decode(data: bytes, expected: int) -> List[int]:
    out: List[int] = []
    z, shift = 0, 0
    for byte in data:
        z |= (byte & 0x7F) << shift
        if byte & 0x80:
            shift += 7
            if shift > 28:
                raise ValueError("malformed varint payload")
            continue
        out.append((z >> 1) ^ -(z & 1))
        z, shift = 0, 0
    if shift != 0 or len(out) != expected:
        raise ValueError("malformed varint payload")
    return out


class _StringTable:
    def __init__(self) -> None:
        self._index: Dict[str, int] = {}
        self.strings: List[str] = []

    def intern(self, s: str) -> int:
        idx = self._index.get(s)
        if idx is None:
            idx = len(self.strings)
            self._index[s] = idx
            self.strings.append(s)
        return idx


def _flatten_op(op: Operation, table: _StringTable, ints: List[int]) -> None:
    def opid_pair(opid) -> Tuple[int, int]:
        return int(opid[0]), table.intern(opid[1])

    def obj_triple(obj):
        if obj is ROOT:
            return (0, 0, 0)
        ctr, actor = opid_pair(obj)
        return (1, ctr, actor)

    fast_insert = (
        op.action == "set"
        and op.insert
        and isinstance(op.value, str)
        and len(op.value) == 1
        and op.obj is not ROOT
    )
    if fast_insert:
        ref = (0, 0, 0) if op.elem_id is HEAD else (1, *opid_pair(op.elem_id))
        ints += [_OP_INSERT, *obj_triple(op.obj), *opid_pair(op.opid), *ref, ord(op.value)]
    elif op.action == "del" and op.elem_id is not None and op.obj is not ROOT:
        ints += [_OP_DEL, *obj_triple(op.obj), *opid_pair(op.opid), *opid_pair(op.elem_id)]
    elif op.action in ("addMark", "removeMark") and op.mark_type in MARK_INDEX:
        # Fast path only for the exact attr shape the decoder reconstructs
        # ({"url": str} on link, {"id": str} on comment); everything else —
        # extra keys, {}, attrs on other mark types — spills to JSON so the
        # round-trip stays lossless.
        expected_key = {"link": "url", "comment": "id"}.get(op.mark_type)
        attr_idx = 0
        if op.attrs:
            if (
                expected_key is not None
                and set(op.attrs) == {expected_key}
                and isinstance(op.attrs[expected_key], str)
            ):
                attr_idx = table.intern(op.attrs[expected_key]) + 1
            else:  # exotic attrs: JSON spillover
                ints += [_OP_JSON, table.intern(json.dumps(op.to_json()))]
                return
        elif op.attrs is not None:  # attrs == {} must round-trip as {}
            ints += [_OP_JSON, table.intern(json.dumps(op.to_json()))]
            return

        def boundary(b: Boundary):
            kind = _BK_TO_INT[b.kind]
            if b.elem is not None:
                return (kind, *opid_pair(b.elem))
            return (kind, 0, 0)

        kind = _OP_ADDMARK if op.action == "addMark" else _OP_REMOVEMARK
        ints += [
            kind,
            *obj_triple(op.obj),
            *opid_pair(op.opid),
            MARK_INDEX[op.mark_type],
            *boundary(op.start),
            *boundary(op.end),
            attr_idx,
        ]
    elif op.action == "makeMap" and op.key is not None:
        ints += [_OP_MAKEMAP, *obj_triple(op.obj), *opid_pair(op.opid),
                 table.intern(op.key)]
    elif (
        op.action == "del" and op.key is not None and op.elem_id is None
    ):
        ints += [_OP_MAPDEL, *obj_triple(op.obj), *opid_pair(op.opid),
                 table.intern(op.key)]
    elif op.action == "set" and not op.insert and op.key is not None:
        v = op.value
        if isinstance(v, bool):
            enc = (_VK_TRUE if v else _VK_FALSE, 0)
        elif v is None:
            enc = (_VK_NULL, 0)
        elif isinstance(v, str):
            enc = (_VK_STR, table.intern(v))
        elif isinstance(v, int) and -(2**31) <= v < 2**31:
            enc = (_VK_INT, v)
        else:  # floats / containers: JSON spillover keeps the codec lossless
            ints += [_OP_JSON, table.intern(json.dumps(op.to_json()))]
            return
        ints += [_OP_MAPSET, *obj_triple(op.obj), *opid_pair(op.opid),
                 table.intern(op.key), *enc]
    else:
        ints += [_OP_JSON, table.intern(json.dumps(op.to_json()))]


def encode_frame(changes: List[Change]) -> bytes:
    """Pack a batch of changes into one binary frame."""
    table = _StringTable()
    ints: List[int] = []
    for change in changes:
        ints += [table.intern(change.actor), change.seq, change.start_op]
        deps = sorted((change.deps or {}).items())
        ints.append(len(deps))
        for actor, seq in deps:
            ints += [table.intern(actor), seq]
        ints.append(len(change.ops))
        for op in change.ops:
            _flatten_op(op, table, ints)

    payload = native.varint_encode(np.asarray(ints, np.int32)) if native.available() else None
    if payload is None:
        payload = _py_varint_encode(ints)

    parts = [
        _HEADER.pack(_MAGIC, _VERSION, len(changes), len(table.strings), len(ints), len(payload))
    ]
    for s in table.strings:
        raw = s.encode("utf-8")
        parts.append(_py_varint_encode([len(raw)]))
        parts.append(raw)
    parts.append(payload)
    return b"".join(parts)


class _IntReader:
    def __init__(self, values) -> None:
        self.values = values
        self.pos = 0

    def take(self, n: int = 1):
        vals = self.values[self.pos : self.pos + n]
        if len(vals) != n:
            raise ValueError("truncated frame payload")
        self.pos += n
        return [int(v) for v in vals]


def _string(strings: List[str], idx: int) -> str:
    # Explicit bounds check: a corrupt (e.g. zigzag-negative) index must be a
    # ValueError, never a silent strings[-1] hit or an IndexError.
    if not 0 <= idx < len(strings):
        raise ValueError("string-table index out of range")
    return strings[idx]


def _read_op(r: _IntReader, strings: List[str]) -> Operation:
    (kind,) = r.take()
    if kind == _OP_JSON:
        (idx,) = r.take()
        return Operation.from_json(json.loads(_string(strings, idx)))

    def obj_of(vals):
        flag, ctr, actor = vals
        return ROOT if flag == 0 else (ctr, _string(strings, actor))

    obj = obj_of(r.take(3))
    ctr, actor = r.take(2)
    opid = (ctr, _string(strings, actor))
    if kind == _OP_MAKEMAP:
        (key_idx,) = r.take()
        return Operation(
            action="makeMap", obj=obj, opid=opid, key=_string(strings, key_idx)
        )
    if kind == _OP_MAPDEL:
        (key_idx,) = r.take()
        return Operation(
            action="del", obj=obj, opid=opid, key=_string(strings, key_idx)
        )
    if kind == _OP_MAPSET:
        key_idx, vkind, payload = r.take(3)
        if vkind == _VK_STR:
            value = _string(strings, payload)
        elif vkind == _VK_INT:
            value = payload
        elif vkind == _VK_TRUE:
            value = True
        elif vkind == _VK_FALSE:
            value = False
        elif vkind == _VK_NULL:
            value = None
        else:
            raise ValueError(f"unknown map value kind {vkind}")
        return Operation(
            action="set", obj=obj, opid=opid, key=_string(strings, key_idx),
            value=value,
        )
    if kind == _OP_INSERT:
        flag, rctr, ractor, cp = r.take(4)
        elem = HEAD if flag == 0 else (rctr, _string(strings, ractor))
        return Operation(
            action="set", obj=obj, opid=opid, elem_id=elem, insert=True, value=chr(cp)
        )
    if kind == _OP_DEL:
        ectr, eactor = r.take(2)
        return Operation(
            action="del", obj=obj, opid=opid, elem_id=(ectr, _string(strings, eactor))
        )
    if kind not in (_OP_ADDMARK, _OP_REMOVEMARK):
        raise ValueError(f"unknown op kind {kind}")
    # marks
    (mark_idx,) = r.take()
    sk, sctr, sactor = r.take(3)
    ek, ectr, eactor = r.take(3)
    (attr_idx,) = r.take()
    if not 0 <= mark_idx < len(ALL_MARKS):
        raise ValueError("mark type index out of range")
    mark_type = ALL_MARKS[mark_idx]

    def boundary(kind_int, bctr, bactor) -> Boundary:
        if kind_int not in _INT_TO_BK:
            raise ValueError("bad boundary kind")
        bk = _INT_TO_BK[kind_int]
        if bk in (BEFORE, AFTER):
            return Boundary(bk, (bctr, _string(strings, bactor)))
        return Boundary(bk)

    attrs = None
    if attr_idx > 0:
        key = "url" if mark_type == "link" else "id"
        attrs = {key: _string(strings, attr_idx - 1)}
    return Operation(
        action="addMark" if kind == _OP_ADDMARK else "removeMark",
        obj=obj,
        opid=opid,
        start=boundary(sk, sctr, sactor),
        end=boundary(ek, ectr, eactor),
        mark_type=mark_type,
        attrs=attrs,
    )


def decode_frame(data: bytes) -> List[Change]:
    """Inverse of :func:`encode_frame`; raises ValueError on corrupt frames."""
    try:
        return _decode_frame(data)
    except ValueError:
        raise
    except (IndexError, KeyError, TypeError, OverflowError, UnicodeDecodeError,
            struct.error) as exc:
        # Normalize every corruption symptom to the documented contract.
        raise ValueError(f"corrupt frame: {exc!r}") from exc


def frame_parts(data: bytes):
    """Split a frame into ``(strings, payload_ints, n_changes)`` without
    materializing Change objects — the input to the native frame-ingest fast
    path (native.parse_changes).  Raises ValueError on corrupt frames."""
    try:
        return _frame_parts(data)
    except ValueError:
        raise
    except (IndexError, OverflowError, UnicodeDecodeError, struct.error) as exc:
        raise ValueError(f"corrupt frame: {exc!r}") from exc


def _frame_parts(data: bytes):
    if len(data) < _HEADER.size:
        raise ValueError("frame too short")
    magic, version, n_changes, n_strings, n_ints, payload_len = _HEADER.unpack_from(data)
    if magic != _MAGIC or version != _VERSION:
        raise ValueError("bad frame magic/version")
    body = len(data) - _HEADER.size
    # Every header count costs at least one body byte, so any count larger
    # than the body is corrupt — checked BEFORE sizing any allocation from it.
    if payload_len > body or n_ints > payload_len or n_strings > body:
        raise ValueError("frame header counts exceed frame size")
    if n_changes * 5 > n_ints:  # a change costs >= 5 ints
        raise ValueError("frame header counts exceed frame size")

    pos = _HEADER.size
    strings: List[str] = []
    for _ in range(n_strings):
        # string length is a single non-negative varint
        z, shift = 0, 0
        while True:
            if pos >= len(data) or shift > 28:
                raise ValueError("truncated string table")
            byte = data[pos]
            pos += 1
            z |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        length = (z >> 1) ^ -(z & 1)
        if length < 0 or pos + length > len(data):
            raise ValueError("truncated string table")
        strings.append(data[pos : pos + length].decode("utf-8"))
        pos += length

    payload = data[pos : pos + payload_len]
    if len(payload) != payload_len:
        raise ValueError("truncated payload")
    values = native.varint_decode(payload, n_ints) if native.available() else None
    if values is None:
        values = _py_varint_decode(payload, n_ints)
    return strings, values, n_changes


def _decode_frame(data: bytes) -> List[Change]:
    strings, values, n_changes = _frame_parts(data)
    r = _IntReader(values)
    changes: List[Change] = []
    for _ in range(n_changes):
        actor_idx, seq, start_op = r.take(3)
        (n_deps,) = r.take()
        if n_deps < 0:
            raise ValueError("negative dep count")
        deps = {}
        for _ in range(n_deps):
            a, s = r.take(2)
            deps[_string(strings, a)] = s
        (n_ops,) = r.take()
        if n_ops < 0:
            raise ValueError("negative op count")
        ops = [_read_op(r, strings) for _ in range(n_ops)]
        changes.append(
            Change(
                actor=_string(strings, actor_idx), seq=seq, deps=deps,
                start_op=start_op, ops=ops,
            )
        )
    if r.pos != len(r.values):
        raise ValueError("trailing garbage in frame payload")
    return changes
