"""Binary change-frame codec (the DCN wire format).

The reference serializes changes as JSON (``src/micromerge.ts:563-564``
"can be JSON-encoded to send to another node") — fine for two browser tabs,
wasteful for a pod streaming 100K docs of changes between hosts.  This codec
packs a batch of changes into one compact frame:

* a string table (actor ids, mark attrs, and a JSON spillover for op shapes
  outside the fast path), UTF-8 with varint lengths;
* the op payload as a single zigzag-varint int32 stream (native C++ varint
  core when available, pure Python otherwise — identical bytes either way).

Text-CRDT ops (insert / delete / addMark / removeMark on the text list) take
the fast integer path; anything else (map ops, exotic values) is embedded as
per-op JSON via the string table, so the codec is lossless over the full
``Change`` model: ``decode_frame(encode_frame(cs))`` round-trips exactly and
interoperates with the JSON wire format.
"""

from __future__ import annotations

import contextlib
import json
import struct
import zlib
from collections import ChainMap
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import native
from ..core.errors import DecodeError
from ..core.opids import HEAD, ROOT
from ..core.types import AFTER, BEFORE, Boundary, Change, END_OF_TEXT, Operation, START_OF_TEXT
from ..schema import ALL_MARKS, MARK_INDEX

_MAGIC = b"PTXF"
#: wire version this codec EMITS; both 1 and 2 are decoded.  v2 adds per-op
#: delta flags (below) that elide the redundant ids dominating v1's
#: ~12 bytes/op, roughly halving bytes/op and thereby doubling the op rate
#: any fixed-bandwidth DCN/tunnel link can carry (VERDICT r2 weak #4).
_VERSION = 2
#: v3/v4 are SESSION-scoped transport versions (VERDICT r3 task 3): the
#: string table persists across a peer link's frames (each frame advertises
#: only NEW strings after a varint base = the shared-table size, for sync
#: checking), and v4 additionally deflate-compresses the body.  They are
#: decodable only through a WireSession — the storage/ingest format stays
#: self-contained v1/v2 (``WireSession.decode_frame`` returns normalized v2
#: bytes for consumers that store or re-fan frames).
#: v5 is a TRACED v2: identical body, plus a fixed 16-byte trace-context
#: field (trace id + parent span id, observability spans) between header
#: and string table.  Like v3/v4 it is a TRANSPORT format — emission is
#: version-negotiated (the anti-entropy frontier advertises ``WIRE_CAPS``,
#: so an old peer is never sent one), and ingest/storage normalize to v2
#: via :func:`strip_trace_context`.  The context is telemetry only: it
#: never reaches merge state, and stripping it yields byte-identical v2.
#: v6 is a CHECKED v5: the same fixed trace-context field (all-zero when no
#: trace is live), the same v2 body, plus a 4-byte CRC32 TRAILER over every
#: preceding byte of the frame (header included).  The codec already rejects
#: structurally invalid frames, but a bit flip that leaves the structure
#: valid-looking used to be the transport's problem (ROADMAP "wire-frame
#: checksum"); the trailer closes that gap for untrusted links — a mismatch
#: raises :class:`DecodeError`, so quarantine attributes payload corruption
#: precisely.  Like v5 it is caps-negotiated (sent only to peers advertising
#: ``caps >= 6``) and normalizes to v5/v2 for ingest/storage.
_DECODABLE_VERSIONS = (1, 2, 3, 4, 5, 6)
_SESSION_VERSIONS = (3, 4)
_VERSION_TRACED = 5
_VERSION_CHECKED = 6
_TRACE_CTX = struct.Struct("<QQ")  # trace id, parent span id
_CRC = struct.Struct("<I")  # v6 CRC32 trailer
#: transport capability level advertised in anti-entropy frontiers: the
#: highest wire version this codec decodes (>= _VERSION_TRACED means the
#: peer may send trace-context frames; >= _VERSION_CHECKED additionally
#: CRC-trailered ones)
WIRE_CAPS = 6
#: bounded inflate for v4: a legit frame body deflates ~2-4x, so cap the
#: inflated size well above that but proportional to the wire bytes — a
#: crafted bomb must not expand unboundedly.
_INFLATE_CAP_FACTOR = 64
_INFLATE_CAP_FLOOR = 1 << 20
#: absolute cap on dep entries one frame may materialize on decode — the
#: budget is charged BEFORE allocation, so this bounds peak decode memory at
#: a few hundred MB against crafted many-strings × many-changes frames whose
#: scaled budget would otherwise grow quadratically with frame size.  Real
#: frames sit orders of magnitude below it: DEPS_SAME runs share one
#: materialized dict and charge O(1) per change, so the r3 advisor's
#: 120-actor × 6000-change anti-entropy repro charges only ~6K; even a
#: worst-case all-delta frame of that shape charges 720K.
_DEP_HARD_CEILING = 4_000_000
#: encoder-side split threshold (decode-charge units) for
#: :func:`encode_frame_chunks` — well under the ceiling so a legitimately
#: huge backlog never produces a frame the receiver must reject
_ENCODE_CHUNK_CHARGE = _DEP_HARD_CEILING // 8
_HEADER = struct.Struct("<4sBIIQQ")  # magic, ver, n_changes, n_strings, n_ints, payload_len

_BK_TO_INT = {BEFORE: 0, AFTER: 1, START_OF_TEXT: 2, END_OF_TEXT: 3}
_INT_TO_BK = {v: k for k, v in _BK_TO_INT.items()}

_OP_INSERT, _OP_DEL, _OP_ADDMARK, _OP_REMOVEMARK, _OP_JSON = 0, 1, 2, 3, 4
# map-object ops (device map-register path; reference map LWW
# src/micromerge.ts:1151-1175)
_OP_MAKEMAP, _OP_MAPSET, _OP_MAPDEL = 5, 6, 7

# v2 per-op flag bits, packed above the 3-bit kind in the op's first int.
# Flags refer to the PREVIOUS non-JSON op of the same frame (encoder and
# decoders keep identical frame-scoped context):
#   OPID_SEQ — op id == (change.start_op + op_index, change.actor): the id
#              pair is elided (micromerge assigns change ops sequential
#              counters, reference makeNewOp src/micromerge.ts:876-886, so
#              this holds for essentially every op)
#   OBJ_PREV — same container object as the previous op (text ops all hit
#              the doc's text list): the obj triple is elided
#   REF_PREV — insert only: elem ref == previous op's op id (multi-char
#              inserts chain per-char ops, reference :604-613): ref elided
#   REF_HEAD — insert only: elem ref is HEAD: ref elided.  An insert with
#              neither ref flag carries an explicit (dctr, strid) anchor.
_F_OPID_SEQ, _F_OBJ_PREV, _F_REF_PREV, _F_REF_HEAD = 1, 2, 4, 8
_KIND_BITS = 3
_KIND_MASK = (1 << _KIND_BITS) - 1

# v2 change-header flag bits, packed above the actor strid in the header's
# first int (combo = strid << 4 | flags).  Each elides a field whose value
# the decoder's frame context predicts:
#   DSEQ_ZERO   — seq == last seq of this actor in frame + 1
#   DSTART_ZERO — start_op == this actor's previous change's op-counter end
#   DEPS_SAME   — dep set identical to this actor's previous change's
#                 (own-actor dep advancing to seq-1 as always)
#   NOPS_ONE    — exactly one op
_H_DSEQ_ZERO, _H_DSTART_ZERO, _H_DEPS_SAME, _H_NOPS_ONE = 1, 2, 4, 8
_H_FLAG_BITS = 4

# v2 insert codepoints are stored biased (cp - _CHAR_BIAS): the uniform
# zigzag stream spends 2 bytes on any value > 63, and unbiased ASCII letters
# all land there; centering on lower-case text puts common chars in 1 byte.
_CHAR_BIAS = 110

# value-kind encoding inside _OP_MAPSET (packed.VK_*: 1 str, 2 int, 3 true,
# 4 false, 5 null — VK_STR payload is a string-table index)
_VK_STR, _VK_INT, _VK_TRUE, _VK_FALSE, _VK_NULL = 1, 2, 3, 4, 5


# -- pure-python varint fallback (same bytes as the native core) ------------


def _py_varint_encode(values) -> bytes:
    out = bytearray()
    for v in values:
        z = ((int(v) << 1) ^ (int(v) >> 31)) & 0xFFFFFFFF
        while True:
            byte = z & 0x7F
            z >>= 7
            if z:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
    return bytes(out)


def _py_varint_decode(data: bytes, expected: int) -> List[int]:
    out: List[int] = []
    z, shift = 0, 0
    for byte in data:
        z |= (byte & 0x7F) << shift
        if byte & 0x80:
            shift += 7
            if shift > 28:
                raise ValueError("malformed varint payload")
            continue
        out.append((z >> 1) ^ -(z & 1))
        z, shift = 0, 0
    if shift != 0 or len(out) != expected:
        raise ValueError("malformed varint payload")
    return out


class _StringTable:
    def __init__(self) -> None:
        self._index: Dict[str, int] = {}
        self.strings: List[str] = []

    def intern(self, s: str) -> int:
        idx = self._index.get(s)
        if idx is None:
            idx = len(self.strings)
            self._index[s] = idx
            self.strings.append(s)
        return idx


_NO_PREV = object()


class _FrameCtx:
    """Frame-scoped delta context shared by the encoder and every decoder.

    Op level: the previous non-JSON op's container object and op id.
    Change level (header compression): per-actor last seq and op-counter
    end seen in this frame, and per-actor last dep seq referenced — small
    fuzz-shaped changes (1-2 ops) are otherwise dominated by header bytes."""

    __slots__ = ("prev_obj", "prev_opid", "last_seq", "prev_end", "dep_base",
                 "dep_set", "dep_dict")

    def __init__(self) -> None:
        self.prev_obj = _NO_PREV
        self.prev_opid = None
        self.last_seq: Dict[int, int] = {}   # actor strid -> last change seq
        self.prev_end: Dict[int, int] = {}   # actor strid -> start_op + nops
        self.dep_base: Dict[int, int] = {}   # actor strid -> last dep seq
        #: actor strid -> (own_elided, ((dep strid, dep seq), ...)) of the
        #: actor's previous change in frame (DEPS_SAME reference)
        self.dep_set: Dict[int, tuple] = {}
        #: decode side only: actor strid -> the materialized string-keyed
        #: dict for dep_set's explicit part, shared across a DEPS_SAME run
        #: so N same-clock changes cost one dict, not N copies of it
        self.dep_dict: Dict[int, dict] = {}


def _flatten_op(
    op: Operation, table: _StringTable, ints: List[int],
    ctx: _FrameCtx, change: Change, op_index: int,
) -> None:
    def opid_pair(opid) -> Tuple[int, int]:
        return int(opid[0]), table.intern(opid[1])

    def obj_triple(obj):
        if obj is ROOT:
            return (0, 0, 0)
        ctr, actor = opid_pair(obj)
        return (1, ctr, actor)

    def emit(kind: int, body: Tuple[int, ...], ref=None, extra_flags: int = 0) -> None:
        """v2 op emission: flags elide obj/opid/ref when the frame context
        predicts them; `ref` (insert only) is the elem_id or HEAD.  Explicit
        element counters (insert ref, delete target, mark anchors) are
        stored as deltas against the op's own counter — same-doc ids cluster,
        so the zigzag varint usually fits one byte."""
        flags = extra_flags
        if op.opid == (change.start_op + op_index, change.actor):
            flags |= _F_OPID_SEQ
        if ctx.prev_obj is not _NO_PREV and op.obj == ctx.prev_obj:
            flags |= _F_OBJ_PREV
        ref_ints: Tuple[int, ...] = ()
        if kind == _OP_INSERT:
            if ctx.prev_opid is not None and ref == ctx.prev_opid:
                flags |= _F_REF_PREV
            elif ref is HEAD:
                flags |= _F_REF_HEAD
            else:
                ref_ints = (int(ref[0]) - int(op.opid[0]), table.intern(ref[1]))
        ints.append(kind | (flags << _KIND_BITS))
        if not flags & _F_OBJ_PREV:
            ints.extend(obj_triple(op.obj))
        if not flags & _F_OPID_SEQ:
            ints.extend(opid_pair(op.opid))
        ints.extend(ref_ints)
        ints.extend(body)
        ctx.prev_obj = op.obj
        ctx.prev_opid = op.opid

    def spill() -> None:
        # JSON rows carry their ids inside the JSON; they neither read nor
        # advance the delta context (decoders match)
        ints.extend([_OP_JSON, table.intern(json.dumps(op.to_json()))])

    fast_insert = (
        op.action == "set"
        and op.insert
        and isinstance(op.value, str)
        and len(op.value) == 1
        and op.obj is not ROOT
    )
    if fast_insert:
        emit(_OP_INSERT, (ord(op.value) - _CHAR_BIAS,), ref=op.elem_id)
    elif op.action == "del" and op.elem_id is not None and op.obj is not ROOT:
        emit(_OP_DEL, (
            int(op.elem_id[0]) - int(op.opid[0]), table.intern(op.elem_id[1]),
        ))
    elif op.action in ("addMark", "removeMark") and op.mark_type in MARK_INDEX:
        # Fast path only for the exact attr shape the decoder reconstructs
        # ({"url": str} on link, {"id": str} on comment); everything else —
        # extra keys, {}, attrs on other mark types — spills to JSON so the
        # round-trip stays lossless.
        expected_key = {"link": "url", "comment": "id"}.get(op.mark_type)
        attr_idx = 0
        if op.attrs:
            if (
                expected_key is not None
                and set(op.attrs) == {expected_key}
                and isinstance(op.attrs[expected_key], str)
            ):
                attr_idx = table.intern(op.attrs[expected_key]) + 1
            else:  # exotic attrs: JSON spillover
                spill()
                return
        elif op.attrs is not None:  # attrs == {} must round-trip as {}
            spill()
            return

        mtype = MARK_INDEX[op.mark_type]
        if mtype > 3:  # 2-bit packing below; larger schemas spill losslessly
            spill()
            return
        sk = _BK_TO_INT[op.start.kind]
        ek = _BK_TO_INT[op.end.kind]
        if (op.start.elem is None) != (sk >= 2) or (op.end.elem is None) != (ek >= 2):
            spill()  # malformed boundary shape: JSON keeps it lossless
            return
        # one packed kinds int (mtype|sk|ek, 2 bits each, <= 63: one byte)
        # + anchors only where the boundary kind has one; the end counter is
        # delta'd against the start anchor (spans are short) else the op id
        body: List[int] = [mtype | (sk << 2) | (ek << 4)]
        base_ctr = int(op.opid[0])
        if op.start.elem is not None:
            body += [int(op.start.elem[0]) - base_ctr,
                     table.intern(op.start.elem[1])]
            base_ctr = int(op.start.elem[0])
        if op.end.elem is not None:
            body += [int(op.end.elem[0]) - base_ctr,
                     table.intern(op.end.elem[1])]
        body.append(attr_idx)
        kind = _OP_ADDMARK if op.action == "addMark" else _OP_REMOVEMARK
        emit(kind, tuple(body))
    elif op.action == "makeList" and op.key is not None:
        # v2 fast path: makeList rides the makeMap kind with the (otherwise
        # insert-only) _F_REF_HEAD bit — v1 spilled it to a ~70-byte JSON
        # string per frame, the single largest string-table entry
        emit(_OP_MAKEMAP, (table.intern(op.key),), extra_flags=_F_REF_HEAD)
    elif op.action == "makeMap" and op.key is not None:
        emit(_OP_MAKEMAP, (table.intern(op.key),))
    elif (
        op.action == "del" and op.key is not None and op.elem_id is None
    ):
        emit(_OP_MAPDEL, (table.intern(op.key),))
    elif op.action == "set" and not op.insert and op.key is not None:
        v = op.value
        if isinstance(v, bool):
            enc = (_VK_TRUE if v else _VK_FALSE, 0)
        elif v is None:
            enc = (_VK_NULL, 0)
        elif isinstance(v, str):
            enc = (_VK_STR, table.intern(v))
        elif isinstance(v, int) and -(2**31) <= v < 2**31:
            enc = (_VK_INT, v)
        else:  # floats / containers: JSON spillover keeps the codec lossless
            spill()
            return
        emit(_OP_MAPSET, (table.intern(op.key), *enc))
    else:
        spill()


def encode_frame(changes: List[Change]) -> bytes:
    """Pack a batch of changes into one binary frame.

    v2 change headers are delta-encoded against the frame-scoped per-actor
    state (``_FrameCtx``): seq against the actor's last seq in frame + 1,
    start_op against the actor's previous change's op-counter end, dep seqs
    against the per-actor dep chain — and the actor's own ``(actor, seq-1)``
    dep (which ``change()`` always records, reference
    src/micromerge.ts:572-577) is elided behind a flag bit in the dep count.
    Small changes (1-2 ops, the anti-entropy norm) drop from ~11 to ~4
    header bytes."""
    return _encode_frame(changes, _StringTable())


def _encode_frame(
    changes: List[Change], table: "_StringTable",
    session: bool = False, comp=None,
) -> bytes:
    session_base = len(table.strings)
    ints: List[int] = []
    ctx = _FrameCtx()
    for change in changes:
        a = table.intern(change.actor)
        dseq = change.seq - ctx.last_seq.get(a, 0) - 1
        dstart = change.start_op - ctx.prev_end.get(a, 0)
        deps = sorted((change.deps or {}).items())
        own_elided = 0
        explicit = []
        for actor, seq in deps:
            if actor == change.actor and seq == change.seq - 1 and not own_elided:
                own_elided = 1
                continue
            explicit.append((table.intern(actor), seq))
        deps_same = ctx.dep_set.get(a) == (own_elided, tuple(explicit))
        hflags = (
            (_H_DSEQ_ZERO if dseq == 0 else 0)
            | (_H_DSTART_ZERO if dstart == 0 else 0)
            | (_H_DEPS_SAME if deps_same else 0)
            | (_H_NOPS_ONE if len(change.ops) == 1 else 0)
        )
        ints.append((a << _H_FLAG_BITS) | hflags)
        if dseq != 0:
            ints.append(dseq)
        if dstart != 0:
            ints.append(dstart)
        if not deps_same:
            # dep-count wire int: (count << 2) | (delta_mode << 1) | own_elided.
            # Delta mode sends only the ENTRIES THAT CHANGED vs this actor's
            # previous dep set (vector clocks advance one entry per received
            # change, so most of the clock repeats change-to-change).
            stored = ctx.dep_set.get(a)
            delta_ok = (
                stored is not None and stored[0] == own_elided
                and [da for da, _ in stored[1]] == [da for da, _ in explicit]
            )
            if delta_ok:
                changed = [
                    (da, ds, old)
                    for (da, ds), (_, old) in zip(explicit, stored[1])
                    if ds != old
                ]
                ints.append((len(changed) << 2) | 2 | own_elided)
                for da, ds, old in changed:
                    ints += [da, ds - old]
                    ctx.dep_base[da] = ds
            else:
                ints.append((len(explicit) << 2) | own_elided)
                for da, ds in explicit:
                    # base: the larger of the dep chain and the actor's last
                    # seq seen in frame — causally-ordered frames make deps
                    # implied (delta 0), per-actor-grouped frames chain well
                    base = max(ctx.dep_base.get(da, 0), ctx.last_seq.get(da, 0))
                    ints += [da, ds - base]
                    ctx.dep_base[da] = ds
            ctx.dep_set[a] = (own_elided, tuple(explicit))
        if len(change.ops) != 1:
            ints.append(len(change.ops))
        ctx.last_seq[a] = change.seq
        ctx.prev_end[a] = change.start_op + len(change.ops)
        for i, op in enumerate(change.ops):
            _flatten_op(op, table, ints, ctx, change, i)

    payload = native.varint_encode(np.asarray(ints, np.int32)) if native.available() else None
    if payload is None:
        payload = _py_varint_encode(ints)

    if not session:
        parts = [_HEADER.pack(_MAGIC, _VERSION, len(changes),
                              len(table.strings), len(ints), len(payload))]
        parts += _string_section(table.strings)
        parts.append(payload)
        return b"".join(parts)

    # session frame: advertise only strings NEW since `base`, preceded by a
    # varint of `base` itself (the decoder verifies it against its shared
    # table — a dropped frame surfaces as "wire session out of sync", never
    # as silently misresolved string ids)
    new = table.strings[session_base:]
    body = b"".join(
        [_py_varint_encode([session_base])] + _string_section(new) + [payload]
    )
    if comp is not None:  # v4: streaming deflate, one window per link
        blob = comp.compress(body) + comp.flush(zlib.Z_SYNC_FLUSH)
        return _HEADER.pack(_MAGIC, 4, len(changes), len(new),
                            len(ints), len(blob)) + blob
    return _HEADER.pack(_MAGIC, 3, len(changes), len(new),
                        len(ints), len(payload)) + body


class _IntReader:
    def __init__(self, values) -> None:
        self.values = values
        self.pos = 0

    def take(self, n: int = 1):
        vals = self.values[self.pos : self.pos + n]
        if len(vals) != n:
            raise ValueError("truncated frame payload")
        self.pos += n
        return [int(v) for v in vals]


def _string(strings: List[str], idx: int) -> str:
    # Explicit bounds check: a corrupt (e.g. zigzag-negative) index must be a
    # ValueError, never a silent strings[-1] hit or an IndexError.
    if not 0 <= idx < len(strings):
        raise ValueError("string-table index out of range")
    return strings[idx]


def _read_op(
    r: _IntReader, strings: List[str], version: int, ctx: _FrameCtx,
    ch_actor: str, start_op: int, op_index: int,
) -> Operation:
    (first,) = r.take()
    if version >= 2:
        kind, flags = first & _KIND_MASK, first >> _KIND_BITS
    else:
        kind, flags = first, 0
    if kind == _OP_JSON:
        if flags:
            raise ValueError("flags on a JSON-spillover op")
        (idx,) = r.take()
        return Operation.from_json(json.loads(_string(strings, idx)))
    if flags >> 4:
        raise ValueError("unknown op flag bits")
    if flags & _F_REF_PREV and kind != _OP_INSERT:
        raise ValueError("REF_PREV on a non-insert op")
    if flags & _F_REF_HEAD and kind not in (_OP_INSERT, _OP_MAKEMAP):
        raise ValueError("REF_HEAD on an op kind without one")
    if (flags & _F_REF_PREV) and (flags & _F_REF_HEAD):
        raise ValueError("conflicting insert ref flags")

    def obj_of(vals):
        flag, ctr, actor = vals
        return ROOT if flag == 0 else (ctr, _string(strings, actor))

    prev_opid = ctx.prev_opid  # the PREVIOUS op's id, for REF_PREV below
    if flags & _F_OBJ_PREV:
        if ctx.prev_obj is _NO_PREV:
            raise ValueError("OBJ_PREV with no previous op in frame")
        obj = ctx.prev_obj
    else:
        obj = obj_of(r.take(3))
    if flags & _F_OPID_SEQ:
        opid = (start_op + op_index, ch_actor)
    else:
        ctr, actor = r.take(2)
        opid = (ctr, _string(strings, actor))
    ctx.prev_obj = obj
    ctx.prev_opid = opid
    if kind == _OP_MAKEMAP:
        (key_idx,) = r.take()
        return Operation(
            action="makeList" if flags & _F_REF_HEAD else "makeMap",
            obj=obj, opid=opid, key=_string(strings, key_idx),
        )
    if kind == _OP_MAPDEL:
        (key_idx,) = r.take()
        return Operation(
            action="del", obj=obj, opid=opid, key=_string(strings, key_idx)
        )
    if kind == _OP_MAPSET:
        key_idx, vkind, payload = r.take(3)
        if vkind == _VK_STR:
            value = _string(strings, payload)
        elif vkind == _VK_INT:
            value = payload
        elif vkind == _VK_TRUE:
            value = True
        elif vkind == _VK_FALSE:
            value = False
        elif vkind == _VK_NULL:
            value = None
        else:
            raise ValueError(f"unknown map value kind {vkind}")
        return Operation(
            action="set", obj=obj, opid=opid, key=_string(strings, key_idx),
            value=value,
        )
    if kind == _OP_INSERT:
        if flags & _F_REF_PREV:
            if prev_opid is None:
                raise ValueError("REF_PREV with no previous op in frame")
            elem = prev_opid
        elif flags & _F_REF_HEAD:
            elem = HEAD
        elif version >= 2:
            rctr, ractor = r.take(2)
            elem = (rctr + opid[0], _string(strings, ractor))
        else:
            flag, rctr, ractor = r.take(3)
            elem = HEAD if flag == 0 else (rctr, _string(strings, ractor))
        (cp,) = r.take()
        if version >= 2:
            cp += _CHAR_BIAS
        return Operation(
            action="set", obj=obj, opid=opid, elem_id=elem, insert=True, value=chr(cp)
        )
    if kind == _OP_DEL:
        ectr, eactor = r.take(2)
        if version >= 2:
            ectr += opid[0]
        return Operation(
            action="del", obj=obj, opid=opid, elem_id=(ectr, _string(strings, eactor))
        )
    if kind not in (_OP_ADDMARK, _OP_REMOVEMARK):
        raise ValueError(f"unknown op kind {kind}")
    # marks
    if version >= 2:
        (packed,) = r.take()
        mark_idx, sk, ek = packed & 3, (packed >> 2) & 3, (packed >> 4) & 3
        if packed >> 6:
            raise ValueError("mark kind-packing overflow")
        base_ctr = opid[0]
        sctr = sactor = ectr = eactor = 0
        if sk <= 1:  # BEFORE/AFTER carry an anchor
            dctr, sactor = r.take(2)
            sctr = base_ctr + dctr
            base_ctr = sctr
        if ek <= 1:
            dctr, eactor = r.take(2)
            ectr = base_ctr + dctr
        (attr_idx,) = r.take()
    else:
        (mark_idx,) = r.take()
        sk, sctr, sactor = r.take(3)
        ek, ectr, eactor = r.take(3)
        (attr_idx,) = r.take()
    if not 0 <= mark_idx < len(ALL_MARKS):
        raise ValueError("mark type index out of range")
    mark_type = ALL_MARKS[mark_idx]

    def boundary(kind_int, bctr, bactor) -> Boundary:
        if kind_int not in _INT_TO_BK:
            raise ValueError("bad boundary kind")
        bk = _INT_TO_BK[kind_int]
        if bk in (BEFORE, AFTER):
            return Boundary(bk, (bctr, _string(strings, bactor)))
        return Boundary(bk)

    attrs = None
    if attr_idx > 0:
        key = "url" if mark_type == "link" else "id"
        attrs = {key: _string(strings, attr_idx - 1)}
    return Operation(
        action="addMark" if kind == _OP_ADDMARK else "removeMark",
        obj=obj,
        opid=opid,
        start=boundary(sk, sctr, sactor),
        end=boundary(ek, ectr, eactor),
        mark_type=mark_type,
        attrs=attrs,
    )


@contextlib.contextmanager
def _normalize_decode_errors(on_fail: "Optional[Callable[[], None]]" = None):
    """THE corruption contract, defined once: every symptom a corrupt frame
    can raise inside a decode path (wrong magic/length ValueError, index or
    key misses, varint overflow, bad UTF-8, short struct reads) normalizes
    to :class:`DecodeError`; ``on_fail`` runs before re-raising (e.g.
    :class:`WireSession` breaking its link state)."""
    try:
        yield
    except DecodeError:
        if on_fail is not None:
            on_fail()
        raise
    except ValueError as exc:
        if on_fail is not None:
            on_fail()
        raise DecodeError(str(exc)) from exc
    except (IndexError, KeyError, TypeError, OverflowError, UnicodeDecodeError,
            struct.error) as exc:
        if on_fail is not None:
            on_fail()
        raise DecodeError(f"corrupt frame: {exc!r}") from exc


def encode_frame_traced(changes: List[Change], trace_id: int,
                        span_id: int) -> bytes:
    """A v5 frame: :func:`encode_frame` output carrying a compact trace
    context (observability spans, ``obs/spans.py``).  Send ONLY to a peer
    whose frontier advertised ``caps >= WIRE_CAPS``."""
    raw = encode_frame(changes)
    magic, _, n_ch, n_str, n_ints, plen = _HEADER.unpack_from(raw)
    return (
        _HEADER.pack(magic, _VERSION_TRACED, n_ch, n_str, n_ints, plen)
        + _TRACE_CTX.pack(int(trace_id) & 0xFFFFFFFFFFFFFFFF,
                          int(span_id) & 0xFFFFFFFFFFFFFFFF)
        + raw[_HEADER.size:]
    )


def encode_frame_checked(changes: List[Change], trace_id: int = 0,
                         span_id: int = 0) -> bytes:
    """A v6 frame: :func:`encode_frame` output carrying the fixed trace
    context (zeros = none live) plus a CRC32 trailer over every preceding
    byte.  Send ONLY to a peer whose frontier advertised ``caps >= 6``."""
    raw = encode_frame(changes)
    magic, _, n_ch, n_str, n_ints, plen = _HEADER.unpack_from(raw)
    body = (
        _HEADER.pack(magic, _VERSION_CHECKED, n_ch, n_str, n_ints, plen)
        + _TRACE_CTX.pack(int(trace_id) & 0xFFFFFFFFFFFFFFFF,
                          int(span_id) & 0xFFFFFFFFFFFFFFFF)
        + raw[_HEADER.size:]
    )
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def strip_trace_context(data: bytes):
    """``((trace_id, span_id) | None, self-contained v1/v2-style bytes)``.

    Total function: anything that is not a well-formed v5/v6 frame passes
    through unchanged with a ``None`` context (downstream decode classifies
    corruption as usual), so ingest paths can call it unconditionally —
    the storage/ingest format stays v1/v2, the context is telemetry.  A v6
    frame whose CRC trailer mismatches ALSO passes through unchanged (still
    version 6): the corruption surfaces as the decoder's typed
    :class:`DecodeError`, never silently as a stripped-but-damaged v2."""
    if len(data) < _HEADER.size + _TRACE_CTX.size or data[:4] != _MAGIC:
        return None, data
    if data[4] == _VERSION_CHECKED:
        if (len(data) < _HEADER.size + _TRACE_CTX.size + _CRC.size
                or _CRC.unpack_from(data, len(data) - _CRC.size)[0]
                != zlib.crc32(data[:-_CRC.size]) & 0xFFFFFFFF):
            return None, data  # corrupt: let the decoder raise DecodeError
        ctx = _TRACE_CTX.unpack_from(data, _HEADER.size)
        magic, _, n_ch, n_str, n_ints, plen = _HEADER.unpack_from(data)
        plain = (_HEADER.pack(magic, 2, n_ch, n_str, n_ints, plen)
                 + data[_HEADER.size + _TRACE_CTX.size:-_CRC.size])
        return (ctx if ctx != (0, 0) else None), plain
    if data[4] != _VERSION_TRACED:
        return None, data
    ctx = _TRACE_CTX.unpack_from(data, _HEADER.size)
    magic, _, n_ch, n_str, n_ints, plen = _HEADER.unpack_from(data)
    plain = (_HEADER.pack(magic, 2, n_ch, n_str, n_ints, plen)
             + data[_HEADER.size + _TRACE_CTX.size:])
    return ctx, plain


def decode_frame_traced(data: bytes):
    """``(changes, (trace_id, span_id) | None)`` — :func:`decode_frame`
    plus the v5 trace context when the frame carries one."""
    ctx, _ = strip_trace_context(data)
    return decode_frame(data), ctx


def decode_frame(data: bytes) -> List[Change]:
    """Inverse of :func:`encode_frame` (v5 traced frames decode too; the
    context is ignored here — :func:`decode_frame_traced` surfaces it);
    raises :class:`DecodeError` (a ValueError subclass, so pre-existing
    handlers keep working) on corrupt frames.

    Returned ``Change.deps`` mappings must be treated as read-only: a run of
    changes with identical clocks (DEPS_SAME on the wire) shares one
    materialized mapping, so a run of N same-clock changes decodes in O(1)
    memory per change instead of N vector-clock copies.  Every consumer in
    the tree only reads deps (``causal.py``, ``doc.py:420``, ``to_json``
    copies)."""
    with _normalize_decode_errors():
        changes, end = _decode_frame(data)
        if end != len(data):
            raise DecodeError("trailing garbage after frame")
        return changes


def encode_frame_chunks(
    changes: List[Change], session: "Optional[WireSession]" = None,
) -> List[bytes]:
    """Encode a change batch as ONE OR MORE frames, splitting so that no
    single frame's decode-side dep charge (sum of vector-clock sizes) comes
    near ``_DEP_HARD_CEILING`` — an unbounded anti-entropy backlog from a
    many-actor session must never encode a frame its peer's own decoder
    would reject as a blowup (review finding r4).  With a ``session`` the
    chunks are v3/v4 session frames sharing one string dictionary (actor
    names and attrs are advertised once, not per chunk) — the session must
    be FRESH so the train is self-contained (chunk 1 advertises base=0 and
    starts the deflate stream; a used session would produce a train only
    its own paired decoder can read).  Inverse: :func:`decode_frame_multi`
    on the concatenation, or per-chunk ``decode_frame`` (plain chunks
    only)."""
    if session is not None and (
        session._enc_table.strings or session._comp is not None
    ):
        raise ValueError(
            "encode_frame_chunks requires a FRESH WireSession: the chunk "
            "train must be self-contained (decode_frame_multi is its inverse)"
        )
    enc = session.encode_frame if session is not None else encode_frame
    if not changes:
        return [enc(changes)]
    chunks, cur, charge = [], [], 0
    for ch in changes:
        c = 1 + len(ch.deps or {})
        if cur and charge + c > _ENCODE_CHUNK_CHARGE:
            chunks.append(enc(cur))
            cur, charge = [], 0
        cur.append(ch)
        charge += c
    chunks.append(enc(cur))
    return chunks


_PRESET_DICT_CACHE: Optional[bytes] = None


def _preset_dict() -> bytes:
    """The protocol preset deflate dictionary (see WireSession ``preset``).
    Loaded once from wire_preset.bin next to this module; a missing file is
    a packaging error surfaced at first use, not at import."""
    global _PRESET_DICT_CACHE
    if _PRESET_DICT_CACHE is None:
        import pathlib

        path = pathlib.Path(__file__).parent / "wire_preset.bin"
        try:
            _PRESET_DICT_CACHE = path.read_bytes()
        except OSError as exc:
            raise RuntimeError(
                f"wire preset dictionary missing ({path}): regenerate with "
                "scripts/gen_wire_dict.py or construct WireSession without "
                "preset=True"
            ) from exc
    return _PRESET_DICT_CACHE


class WireSession:
    """Session-scoped wire codec for one ORDERED peer link (VERDICT r3 task
    3): the string dictionary persists across frames, so repeated actor
    names, mark attrs, urls and comment ids are advertised once per link
    instead of once per frame.  ``compress=True`` additionally deflates each
    frame body (wire v4; bounded inflate on decode).

    Each END of a link holds its own instance — an encoder session must only
    ever encode, a decoder session only decode, and frames must be decoded
    in encode order (the base varint in every frame verifies this: loss or
    reordering raises "wire session out of sync" rather than misresolving
    ids).  The dictionary is BOUNDED: at ``reset_at`` strings the encoder
    starts a fresh epoch whose first frame advertises base=0, which tells
    the decoder to clear.  The reference's wire has no analog (JSON per
    change, src/micromerge.ts:563-564); this is the ChangeQueue batching
    rationale (src/changeQueue.ts:16-28) taken to its wire conclusion."""

    def __init__(self, compress: bool = False, reset_at: int = 65536,
                 preset: bool = False) -> None:
        self.compress = compress
        # Preset deflate dictionary (round-5, VERDICT r4 task 8): per-doc
        # links start with a COLD deflate window, measured 6.17-6.99 B/op
        # on bench frames vs 5.27 for a host-link mux; priming the window
        # with the protocol dictionary (wire_preset.bin, provenance in
        # scripts/gen_wire_dict.py) recovers most of the shared-window
        # advantage for fresh links (5.63 measured).  Negotiated
        # out-of-band like ``compress`` itself; a mismatch fails closed —
        # zlib raises (dict-stream decoded without the dict, or wrong
        # DICTID), surfaced as the usual corrupt-frame ValueError.
        self.preset = bool(preset and compress)
        self.reset_at = reset_at
        self._enc_table = _StringTable()
        self._dec_strings: List[str] = []
        # v4 deflate runs as ONE stream across the link's frames (each frame
        # body is a Z_SYNC_FLUSH-terminated segment): later frames reference
        # earlier frames' window, worth ~8% wire on bench shapes over
        # per-frame deflate.  Created lazily so compress=False sessions pay
        # nothing.
        self._comp = None
        self._decomp = None
        #: set when a decode error may have consumed deflate-stream state
        #: that cannot be rolled back; the session must then be discarded
        self._broken = False

    def encode_frame(self, changes: List[Change]) -> bytes:
        if len(self._enc_table.strings) >= self.reset_at:
            self._enc_table = _StringTable()  # epoch reset: next base is 0
        if not self.compress:
            return _encode_frame(changes, self._enc_table, session=True)
        if self._comp is None:
            self._comp = (
                zlib.compressobj(6, zlib.DEFLATED, zlib.MAX_WBITS, 8,
                                 zlib.Z_DEFAULT_STRATEGY, _preset_dict())
                if self.preset else zlib.compressobj(6)
            )
        return _encode_frame(
            changes, self._enc_table, session=True, comp=self._comp,
        )

    def _inflate(self, comp: bytes) -> bytes:
        """Segment inflate through the link's persistent stream, under a
        wire-proportional cap (crafted-bomb guard: a sub-KB segment must not
        expand unboundedly)."""
        if self._decomp is None:
            self._decomp = (zlib.decompressobj(zdict=_preset_dict())
                            if self.preset else zlib.decompressobj())
        cap = max(_INFLATE_CAP_FLOOR, _INFLATE_CAP_FACTOR * len(comp))
        try:
            out = self._decomp.decompress(comp, cap)
        except zlib.error as exc:
            raise ValueError(f"corrupt frame: {exc}") from exc
        if self._decomp.unconsumed_tail or self._decomp.unused_data:
            raise ValueError("frame inflate truncated, trailing, or over bound")
        return out

    def _decode_guard(self):
        """Snapshot for error recovery: a failed decode rolls the string
        table back to the pre-frame length, and — because bytes already fed
        to the persistent inflate stream cannot be un-fed — latches the
        session broken when a deflate stream exists, so a retry can never
        silently desync (review r4)."""
        if self._broken:
            raise DecodeError(
                "wire session broken by an earlier decode error — discard "
                "the session and resync the link"
            )
        return len(self._dec_strings)

    def _decode_failed(self, n0: int) -> None:
        del self._dec_strings[n0:]
        if self._decomp is not None:
            self._broken = True

    def decode_frame(self, data: bytes) -> List[Change]:
        n0 = self._decode_guard()
        with _normalize_decode_errors(on_fail=lambda: self._decode_failed(n0)):
            changes, end = _decode_frame(
                data, 0, session_strings=self._dec_strings, inflate=self._inflate
            )
            if end != len(data):
                raise DecodeError("trailing garbage after frame")
            return changes

    def decode_frame_normalized(self, data: bytes):
        """(changes, self-contained v2 bytes) — for consumers that store or
        re-fan frames (StreamingMerge ingest, multihost ``on_frame``): the
        session dictionary is a TRANSPORT artifact; the storage format stays
        v2.  The v2 bytes are a fresh ``encode_frame`` of the decoded
        changes, so each normalized frame carries only the strings IT
        references — never the cumulative session table (a K-chunk backlog
        would otherwise fan out O(K²) string bytes, review r4)."""
        changes = self.decode_frame(data)
        return changes, encode_frame(changes)


def decode_frame_multi(data: bytes) -> List[Change]:
    """Decode one or more concatenated frames (the ``encode_frame_chunks``
    wire shape) into a single change list.  Session (v3/v4) chunk trains are
    self-contained: the first chunk advertises base=0, so a fresh table
    decodes the whole concatenation.  Raises ValueError on corrupt frames,
    same contract as :func:`decode_frame`."""
    changes: List[Change] = []
    pos = 0
    sess = WireSession()  # fresh table + inflate stream for the train
    with _normalize_decode_errors():
        while pos < len(data):
            part, pos = _decode_frame(
                data, pos, session_strings=sess._dec_strings,
                inflate=sess._inflate,
            )
            changes.extend(part)
    return changes


def iter_frames(data: bytes):
    """Yield each individual frame's bytes from a concatenation, WITHOUT
    decoding payloads (header + string-table walk only) — used to fan a
    multi-frame anti-entropy payload out to per-frame consumers
    (``multihost.on_frame``)."""
    pos = 0
    while pos < len(data):
        if len(data) - pos < _HEADER.size:
            raise DecodeError("frame too short")
        magic, version, _, n_strings, _, payload_len = _HEADER.unpack_from(data, pos)
        if magic != _MAGIC or version not in _DECODABLE_VERSIONS:
            raise DecodeError("bad frame magic/version")
        p = pos + _HEADER.size
        if version == 4:  # body is one deflate blob of payload_len bytes
            end = p + payload_len
        else:
            if version == 3:  # session base varint precedes the table
                _, p = _read_varint(data, p)
            elif version in (_VERSION_TRACED, _VERSION_CHECKED):
                p += _TRACE_CTX.size  # fixed trace-context field
            end = _walk_string_table(data, p, n_strings) + payload_len
            if version == _VERSION_CHECKED:
                end += _CRC.size  # the CRC32 trailer rides inside the frame
        if end > len(data):
            raise DecodeError("truncated payload")
        yield data[pos:end]
        pos = end


def frame_parts(data: bytes):
    """Split a frame into ``(strings, payload_ints, n_changes, version)``
    without materializing Change objects — the input to the native
    frame-ingest fast path (native.parse_changes).  Raises ValueError on
    corrupt frames."""
    with _normalize_decode_errors():
        return _frame_parts(data)[:4]


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    """One zigzag varint at ``pos``; returns (value, next pos)."""
    z, shift = 0, 0
    while True:
        if pos >= len(data) or shift > 28:
            raise ValueError("truncated varint")
        byte = data[pos]
        pos += 1
        z |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


def _walk_string_table(data: bytes, pos: int, n_strings: int, out=None) -> int:
    """Walk ``n_strings`` varint-length-prefixed strings starting at ``pos``,
    returning the position after the table; decoded strings are appended to
    ``out`` when given (``iter_frames`` walks for bounds only).  ONE
    implementation on purpose: frame boundaries must be computed identically
    by every reader (review r4)."""
    for _ in range(n_strings):
        length, pos = _read_varint(data, pos)
        if length < 0 or pos + length > len(data):
            raise ValueError("truncated string table")
        if out is not None:
            out.append(data[pos : pos + length].decode("utf-8"))
        pos += length
    return pos


def _string_section(strings) -> List[bytes]:
    out = []
    for s in strings:
        raw = s.encode("utf-8")
        out.append(_py_varint_encode([len(raw)]))
        out.append(raw)
    return out


def _sync_session_table(table: List[str], base: int) -> None:
    """Verify a session frame's advertised base against the shared table:
    base 0 is an encoder epoch reset (bounded dictionaries), anything else
    must equal the table size exactly — a dropped or reordered frame
    surfaces HERE, never as silently misresolved string ids."""
    if base == 0:
        table.clear()
    elif base != len(table):
        raise ValueError(
            f"wire session out of sync: frame base {base}, table {len(table)}"
        )


def _frame_parts(data: bytes, start: int = 0, session_strings=None,
                 inflate=None):
    if len(data) - start < _HEADER.size:
        raise ValueError("frame too short")
    magic, version, n_changes, n_strings, n_ints, payload_len = _HEADER.unpack_from(
        data, start
    )
    if magic != _MAGIC or version not in _DECODABLE_VERSIONS:
        raise ValueError("bad frame magic/version")
    if version in _SESSION_VERSIONS and session_strings is None:
        raise ValueError(
            "session wire frame (v3/v4) outside a WireSession — the "
            "storage/ingest format is self-contained v1/v2"
        )
    body = len(data) - start - _HEADER.size
    # Every header count costs at least one body byte, so any count larger
    # than the body is corrupt — checked BEFORE sizing any allocation from
    # it.  (v4's payload_len is the COMPRESSED body size; n_ints is checked
    # against the bounded inflate output below instead.)
    if payload_len > body or n_strings > body:
        raise ValueError("frame header counts exceed frame size")
    if version != 4 and n_ints > payload_len:
        raise ValueError("frame header counts exceed frame size")
    # minimum ints per change: v1 writes a 5-int header; v2+'s delta-elided
    # header can shrink to 2 ints (combo + op count)
    if n_changes * (5 if version == 1 else 2) > n_ints:
        raise ValueError("frame header counts exceed frame size")

    pos = start + _HEADER.size
    checked = version == _VERSION_CHECKED
    if version in (_VERSION_TRACED, _VERSION_CHECKED):
        # traced (v5) / checked (v6) v2: skip the fixed telemetry field,
        # decode the v2 body; v6 additionally verifies its CRC trailer
        # (after the body's end is located, below)
        if len(data) - pos < _TRACE_CTX.size:
            raise ValueError("truncated trace context")
        pos += _TRACE_CTX.size
        version = 2
    if version == 4:
        comp = data[pos : pos + payload_len]
        if len(comp) != payload_len:
            raise ValueError("truncated payload")
        end = pos + payload_len
        if inflate is None:
            raise ValueError(
                "session wire frame (v4) outside a WireSession"
            )
        inner = inflate(comp)
        base, p = _read_varint(inner, 0)
        if base < 0:
            raise ValueError("negative session base")
        _sync_session_table(session_strings, base)
        p = _walk_string_table(inner, p, n_strings, session_strings)
        payload = inner[p:]
        if n_ints > len(payload):
            raise ValueError("frame header counts exceed frame size")
        strings = session_strings
    elif version == 3:
        base, pos = _read_varint(data, pos)
        if base < 0:
            raise ValueError("negative session base")
        _sync_session_table(session_strings, base)
        pos = _walk_string_table(data, pos, n_strings, session_strings)
        strings = session_strings
        payload = data[pos : pos + payload_len]
        if len(payload) != payload_len:
            raise ValueError("truncated payload")
        end = pos + payload_len
    else:
        strings = []
        pos = _walk_string_table(data, pos, n_strings, strings)
        payload = data[pos : pos + payload_len]
        if len(payload) != payload_len:
            raise ValueError("truncated payload")
        end = pos + payload_len
    if checked:
        # v6: the CRC32 trailer covers header + trace context + body; a
        # mismatch is payload corruption, typed DecodeError via the
        # normalization contract — undetectable bit flips no longer exist
        # on checked links
        if len(data) - end < _CRC.size:
            raise ValueError("truncated checksum trailer")
        if (_CRC.unpack_from(data, end)[0]
                != zlib.crc32(data[start:end]) & 0xFFFFFFFF):
            raise ValueError("frame checksum mismatch")
        end += _CRC.size
    values = native.varint_decode(payload, n_ints) if native.available() else None
    if values is None:
        values = _py_varint_decode(payload, n_ints)
    return strings, values, n_changes, version, end


def _decode_frame(data: bytes, start: int = 0, session_strings=None,
                  inflate=None):
    strings, values, n_changes, version, end = _frame_parts(
        data, start, session_strings, inflate
    )
    return _changes_of(strings, values, n_changes, version), end


def _changes_of(strings, values, n_changes: int, version: int) -> List[Change]:
    r = _IntReader(values)
    changes: List[Change] = []
    ctx = _FrameCtx()
    # Decode-size budget on MATERIALIZED dep entries.  DEPS_SAME runs share
    # one dict (charged O(1) per change), so the budget only meters paths
    # that genuinely allocate: full/delta dep lists, whose legitimate size
    # scales with the session's actor set — i.e. the frame's own string
    # table (ADVICE r3 high: a 120-actor session's vector clocks are valid
    # data, not an attack).  The hard ceiling keeps a crafted
    # many-strings × many-changes frame from quadratic blowup.
    dep_budget = min(
        max(10_000, (64 + 2 * len(strings)) * n_changes + 4 * len(values)),
        _DEP_HARD_CEILING,
    )
    deps_decoded = 0
    for _ in range(n_changes):
        if version >= 2:
            (combo,) = r.take()
            actor_idx, hflags = combo >> _H_FLAG_BITS, combo & ((1 << _H_FLAG_BITS) - 1)
            if not 0 <= actor_idx < len(strings):
                raise ValueError("actor index out of range")
            dseq = 0 if hflags & _H_DSEQ_ZERO else r.take()[0]
            dstart = 0 if hflags & _H_DSTART_ZERO else r.take()[0]
            seq = ctx.last_seq.get(actor_idx, 0) + 1 + dseq
            start_op = ctx.prev_end.get(actor_idx, 0) + dstart
            actor = _string(strings, actor_idx)
            if hflags & _H_DEPS_SAME:
                stored = ctx.dep_set.get(actor_idx)
                if stored is None:
                    raise ValueError("DEPS_SAME with no previous change of actor")
                own_elided, explicit = stored
                shared = ctx.dep_dict[actor_idx]
                # Reuse the run's materialized dict: O(1) per change.  The
                # per-change own dep (seq advances) layers on via ChainMap,
                # with `shared` first so an explicit entry for the actor's
                # own key wins — same precedence as the dict-build path.
                if own_elided:
                    deps = ChainMap(shared, {actor: seq - 1})
                else:
                    deps = shared
                deps_decoded += 1 + own_elided
                if deps_decoded > dep_budget:
                    raise ValueError("frame dep expansion exceeds decode budget")
            else:
                (ndeps_wire,) = r.take()
                if ndeps_wire < 0:
                    raise ValueError("negative dep count")
                own_elided = ndeps_wire & 1
                delta_mode = (ndeps_wire >> 1) & 1
                count = ndeps_wire >> 2
                stored = ctx.dep_set.get(actor_idx)
                # charge the budget BEFORE materializing, so a frame can
                # never allocate more than dep_budget entries total
                deps_decoded += own_elided + (
                    len(stored[1]) if delta_mode and stored is not None else count
                )
                if deps_decoded > dep_budget:
                    raise ValueError("frame dep expansion exceeds decode budget")
                if delta_mode:
                    if stored is None:
                        raise ValueError("dep delta with no previous change of actor")
                    entries = list(stored[1])
                    index_of = {da: i for i, (da, _) in enumerate(entries)}
                    for _ in range(count):
                        da, dds = r.take(2)
                        i = index_of.get(da)
                        if i is None:
                            raise ValueError("dep delta names an unknown actor")
                        ds = entries[i][1] + dds
                        entries[i] = (da, ds)
                        ctx.dep_base[da] = ds
                    explicit = tuple(entries)
                else:
                    explicit = []
                    seen = set()
                    for _ in range(count):
                        da, dds = r.take(2)
                        if da in seen:  # deps are a per-actor map: dups are crafted
                            raise ValueError("duplicate dep actor in change header")
                        seen.add(da)
                        base = max(ctx.dep_base.get(da, 0), ctx.last_seq.get(da, 0))
                        ds = base + dds
                        explicit.append((da, ds))
                        ctx.dep_base[da] = ds
                    explicit = tuple(explicit)
                ctx.dep_set[actor_idx] = (own_elided, explicit)
                shared = {_string(strings, da): ds for da, ds in explicit}
                ctx.dep_dict[actor_idx] = shared
                if own_elided:
                    deps = {actor: seq - 1}
                    deps.update(shared)  # explicit entry for own key wins
                else:
                    deps = shared
            n_ops = 1 if hflags & _H_NOPS_ONE else r.take()[0]
            if n_ops < 0:
                raise ValueError("negative op count")
            ctx.last_seq[actor_idx] = seq
            ctx.prev_end[actor_idx] = start_op + n_ops
        else:
            actor_idx, seq, start_op = r.take(3)
            (n_deps,) = r.take()
            if n_deps < 0:
                raise ValueError("negative dep count")
            deps = {}
            for _ in range(n_deps):
                a, s = r.take(2)
                deps[_string(strings, a)] = s
            (n_ops,) = r.take()
            if n_ops < 0:
                raise ValueError("negative op count")
            actor = _string(strings, actor_idx)
        ops = [
            _read_op(r, strings, version, ctx, actor, start_op, i)
            for i in range(n_ops)
        ]
        changes.append(
            Change(actor=actor, seq=seq, deps=deps, start_op=start_op, ops=ops)
        )
    if r.pos != len(r.values):
        raise ValueError("trailing garbage in frame payload")
    return changes
