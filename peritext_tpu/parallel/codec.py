"""Binary change-frame codec (the DCN wire format).

The reference serializes changes as JSON (``src/micromerge.ts:563-564``
"can be JSON-encoded to send to another node") — fine for two browser tabs,
wasteful for a pod streaming 100K docs of changes between hosts.  This codec
packs a batch of changes into one compact frame:

* a string table (actor ids, mark attrs, and a JSON spillover for op shapes
  outside the fast path), UTF-8 with varint lengths;
* the op payload as a single zigzag-varint int32 stream (native C++ varint
  core when available, pure Python otherwise — identical bytes either way).

Text-CRDT ops (insert / delete / addMark / removeMark on the text list) take
the fast integer path; anything else (map ops, exotic values) is embedded as
per-op JSON via the string table, so the codec is lossless over the full
``Change`` model: ``decode_frame(encode_frame(cs))`` round-trips exactly and
interoperates with the JSON wire format.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Tuple

import numpy as np

from .. import native
from ..core.opids import HEAD, ROOT
from ..core.types import AFTER, BEFORE, Boundary, Change, END_OF_TEXT, Operation, START_OF_TEXT
from ..schema import ALL_MARKS, MARK_INDEX

_MAGIC = b"PTXF"
#: wire version this codec EMITS; both 1 and 2 are decoded.  v2 adds per-op
#: delta flags (below) that elide the redundant ids dominating v1's
#: ~12 bytes/op, roughly halving bytes/op and thereby doubling the op rate
#: any fixed-bandwidth DCN/tunnel link can carry (VERDICT r2 weak #4).
_VERSION = 2
_DECODABLE_VERSIONS = (1, 2)
_HEADER = struct.Struct("<4sBIIQQ")  # magic, ver, n_changes, n_strings, n_ints, payload_len

_BK_TO_INT = {BEFORE: 0, AFTER: 1, START_OF_TEXT: 2, END_OF_TEXT: 3}
_INT_TO_BK = {v: k for k, v in _BK_TO_INT.items()}

_OP_INSERT, _OP_DEL, _OP_ADDMARK, _OP_REMOVEMARK, _OP_JSON = 0, 1, 2, 3, 4
# map-object ops (device map-register path; reference map LWW
# src/micromerge.ts:1151-1175)
_OP_MAKEMAP, _OP_MAPSET, _OP_MAPDEL = 5, 6, 7

# v2 per-op flag bits, packed above the 3-bit kind in the op's first int.
# Flags refer to the PREVIOUS non-JSON op of the same frame (encoder and
# decoders keep identical frame-scoped context):
#   OPID_SEQ — op id == (change.start_op + op_index, change.actor): the id
#              pair is elided (micromerge assigns change ops sequential
#              counters, reference makeNewOp src/micromerge.ts:876-886, so
#              this holds for essentially every op)
#   OBJ_PREV — same container object as the previous op (text ops all hit
#              the doc's text list): the obj triple is elided
#   REF_PREV — insert only: elem ref == previous op's op id (multi-char
#              inserts chain per-char ops, reference :604-613): ref elided
#   REF_HEAD — insert only: elem ref is HEAD: ref elided.  An insert with
#              neither ref flag carries an explicit (dctr, strid) anchor.
_F_OPID_SEQ, _F_OBJ_PREV, _F_REF_PREV, _F_REF_HEAD = 1, 2, 4, 8
_KIND_BITS = 3
_KIND_MASK = (1 << _KIND_BITS) - 1

# v2 change-header flag bits, packed above the actor strid in the header's
# first int (combo = strid << 4 | flags).  Each elides a field whose value
# the decoder's frame context predicts:
#   DSEQ_ZERO   — seq == last seq of this actor in frame + 1
#   DSTART_ZERO — start_op == this actor's previous change's op-counter end
#   DEPS_SAME   — dep set identical to this actor's previous change's
#                 (own-actor dep advancing to seq-1 as always)
#   NOPS_ONE    — exactly one op
_H_DSEQ_ZERO, _H_DSTART_ZERO, _H_DEPS_SAME, _H_NOPS_ONE = 1, 2, 4, 8
_H_FLAG_BITS = 4

# v2 insert codepoints are stored biased (cp - _CHAR_BIAS): the uniform
# zigzag stream spends 2 bytes on any value > 63, and unbiased ASCII letters
# all land there; centering on lower-case text puts common chars in 1 byte.
_CHAR_BIAS = 110

# value-kind encoding inside _OP_MAPSET (packed.VK_*: 1 str, 2 int, 3 true,
# 4 false, 5 null — VK_STR payload is a string-table index)
_VK_STR, _VK_INT, _VK_TRUE, _VK_FALSE, _VK_NULL = 1, 2, 3, 4, 5


# -- pure-python varint fallback (same bytes as the native core) ------------


def _py_varint_encode(values) -> bytes:
    out = bytearray()
    for v in values:
        z = ((int(v) << 1) ^ (int(v) >> 31)) & 0xFFFFFFFF
        while True:
            byte = z & 0x7F
            z >>= 7
            if z:
                out.append(byte | 0x80)
            else:
                out.append(byte)
                break
    return bytes(out)


def _py_varint_decode(data: bytes, expected: int) -> List[int]:
    out: List[int] = []
    z, shift = 0, 0
    for byte in data:
        z |= (byte & 0x7F) << shift
        if byte & 0x80:
            shift += 7
            if shift > 28:
                raise ValueError("malformed varint payload")
            continue
        out.append((z >> 1) ^ -(z & 1))
        z, shift = 0, 0
    if shift != 0 or len(out) != expected:
        raise ValueError("malformed varint payload")
    return out


class _StringTable:
    def __init__(self) -> None:
        self._index: Dict[str, int] = {}
        self.strings: List[str] = []

    def intern(self, s: str) -> int:
        idx = self._index.get(s)
        if idx is None:
            idx = len(self.strings)
            self._index[s] = idx
            self.strings.append(s)
        return idx


_NO_PREV = object()


class _FrameCtx:
    """Frame-scoped delta context shared by the encoder and every decoder.

    Op level: the previous non-JSON op's container object and op id.
    Change level (header compression): per-actor last seq and op-counter
    end seen in this frame, and per-actor last dep seq referenced — small
    fuzz-shaped changes (1-2 ops) are otherwise dominated by header bytes."""

    __slots__ = ("prev_obj", "prev_opid", "last_seq", "prev_end", "dep_base",
                 "dep_set")

    def __init__(self) -> None:
        self.prev_obj = _NO_PREV
        self.prev_opid = None
        self.last_seq: Dict[int, int] = {}   # actor strid -> last change seq
        self.prev_end: Dict[int, int] = {}   # actor strid -> start_op + nops
        self.dep_base: Dict[int, int] = {}   # actor strid -> last dep seq
        #: actor strid -> (own_elided, ((dep strid, dep seq), ...)) of the
        #: actor's previous change in frame (DEPS_SAME reference)
        self.dep_set: Dict[int, tuple] = {}


def _flatten_op(
    op: Operation, table: _StringTable, ints: List[int],
    ctx: _FrameCtx, change: Change, op_index: int,
) -> None:
    def opid_pair(opid) -> Tuple[int, int]:
        return int(opid[0]), table.intern(opid[1])

    def obj_triple(obj):
        if obj is ROOT:
            return (0, 0, 0)
        ctr, actor = opid_pair(obj)
        return (1, ctr, actor)

    def emit(kind: int, body: Tuple[int, ...], ref=None, extra_flags: int = 0) -> None:
        """v2 op emission: flags elide obj/opid/ref when the frame context
        predicts them; `ref` (insert only) is the elem_id or HEAD.  Explicit
        element counters (insert ref, delete target, mark anchors) are
        stored as deltas against the op's own counter — same-doc ids cluster,
        so the zigzag varint usually fits one byte."""
        flags = extra_flags
        if op.opid == (change.start_op + op_index, change.actor):
            flags |= _F_OPID_SEQ
        if ctx.prev_obj is not _NO_PREV and op.obj == ctx.prev_obj:
            flags |= _F_OBJ_PREV
        ref_ints: Tuple[int, ...] = ()
        if kind == _OP_INSERT:
            if ctx.prev_opid is not None and ref == ctx.prev_opid:
                flags |= _F_REF_PREV
            elif ref is HEAD:
                flags |= _F_REF_HEAD
            else:
                ref_ints = (int(ref[0]) - int(op.opid[0]), table.intern(ref[1]))
        ints.append(kind | (flags << _KIND_BITS))
        if not flags & _F_OBJ_PREV:
            ints.extend(obj_triple(op.obj))
        if not flags & _F_OPID_SEQ:
            ints.extend(opid_pair(op.opid))
        ints.extend(ref_ints)
        ints.extend(body)
        ctx.prev_obj = op.obj
        ctx.prev_opid = op.opid

    def spill() -> None:
        # JSON rows carry their ids inside the JSON; they neither read nor
        # advance the delta context (decoders match)
        ints.extend([_OP_JSON, table.intern(json.dumps(op.to_json()))])

    fast_insert = (
        op.action == "set"
        and op.insert
        and isinstance(op.value, str)
        and len(op.value) == 1
        and op.obj is not ROOT
    )
    if fast_insert:
        emit(_OP_INSERT, (ord(op.value) - _CHAR_BIAS,), ref=op.elem_id)
    elif op.action == "del" and op.elem_id is not None and op.obj is not ROOT:
        emit(_OP_DEL, (
            int(op.elem_id[0]) - int(op.opid[0]), table.intern(op.elem_id[1]),
        ))
    elif op.action in ("addMark", "removeMark") and op.mark_type in MARK_INDEX:
        # Fast path only for the exact attr shape the decoder reconstructs
        # ({"url": str} on link, {"id": str} on comment); everything else —
        # extra keys, {}, attrs on other mark types — spills to JSON so the
        # round-trip stays lossless.
        expected_key = {"link": "url", "comment": "id"}.get(op.mark_type)
        attr_idx = 0
        if op.attrs:
            if (
                expected_key is not None
                and set(op.attrs) == {expected_key}
                and isinstance(op.attrs[expected_key], str)
            ):
                attr_idx = table.intern(op.attrs[expected_key]) + 1
            else:  # exotic attrs: JSON spillover
                spill()
                return
        elif op.attrs is not None:  # attrs == {} must round-trip as {}
            spill()
            return

        mtype = MARK_INDEX[op.mark_type]
        if mtype > 3:  # 2-bit packing below; larger schemas spill losslessly
            spill()
            return
        sk = _BK_TO_INT[op.start.kind]
        ek = _BK_TO_INT[op.end.kind]
        if (op.start.elem is None) != (sk >= 2) or (op.end.elem is None) != (ek >= 2):
            spill()  # malformed boundary shape: JSON keeps it lossless
            return
        # one packed kinds int (mtype|sk|ek, 2 bits each, <= 63: one byte)
        # + anchors only where the boundary kind has one; the end counter is
        # delta'd against the start anchor (spans are short) else the op id
        body: List[int] = [mtype | (sk << 2) | (ek << 4)]
        base_ctr = int(op.opid[0])
        if op.start.elem is not None:
            body += [int(op.start.elem[0]) - base_ctr,
                     table.intern(op.start.elem[1])]
            base_ctr = int(op.start.elem[0])
        if op.end.elem is not None:
            body += [int(op.end.elem[0]) - base_ctr,
                     table.intern(op.end.elem[1])]
        body.append(attr_idx)
        kind = _OP_ADDMARK if op.action == "addMark" else _OP_REMOVEMARK
        emit(kind, tuple(body))
    elif op.action == "makeList" and op.key is not None:
        # v2 fast path: makeList rides the makeMap kind with the (otherwise
        # insert-only) _F_REF_HEAD bit — v1 spilled it to a ~70-byte JSON
        # string per frame, the single largest string-table entry
        emit(_OP_MAKEMAP, (table.intern(op.key),), extra_flags=_F_REF_HEAD)
    elif op.action == "makeMap" and op.key is not None:
        emit(_OP_MAKEMAP, (table.intern(op.key),))
    elif (
        op.action == "del" and op.key is not None and op.elem_id is None
    ):
        emit(_OP_MAPDEL, (table.intern(op.key),))
    elif op.action == "set" and not op.insert and op.key is not None:
        v = op.value
        if isinstance(v, bool):
            enc = (_VK_TRUE if v else _VK_FALSE, 0)
        elif v is None:
            enc = (_VK_NULL, 0)
        elif isinstance(v, str):
            enc = (_VK_STR, table.intern(v))
        elif isinstance(v, int) and -(2**31) <= v < 2**31:
            enc = (_VK_INT, v)
        else:  # floats / containers: JSON spillover keeps the codec lossless
            spill()
            return
        emit(_OP_MAPSET, (table.intern(op.key), *enc))
    else:
        spill()


def encode_frame(changes: List[Change]) -> bytes:
    """Pack a batch of changes into one binary frame.

    v2 change headers are delta-encoded against the frame-scoped per-actor
    state (``_FrameCtx``): seq against the actor's last seq in frame + 1,
    start_op against the actor's previous change's op-counter end, dep seqs
    against the per-actor dep chain — and the actor's own ``(actor, seq-1)``
    dep (which ``change()`` always records, reference
    src/micromerge.ts:572-577) is elided behind a flag bit in the dep count.
    Small changes (1-2 ops, the anti-entropy norm) drop from ~11 to ~4
    header bytes."""
    table = _StringTable()
    ints: List[int] = []
    ctx = _FrameCtx()
    for change in changes:
        a = table.intern(change.actor)
        dseq = change.seq - ctx.last_seq.get(a, 0) - 1
        dstart = change.start_op - ctx.prev_end.get(a, 0)
        deps = sorted((change.deps or {}).items())
        own_elided = 0
        explicit = []
        for actor, seq in deps:
            if actor == change.actor and seq == change.seq - 1 and not own_elided:
                own_elided = 1
                continue
            explicit.append((table.intern(actor), seq))
        deps_same = ctx.dep_set.get(a) == (own_elided, tuple(explicit))
        hflags = (
            (_H_DSEQ_ZERO if dseq == 0 else 0)
            | (_H_DSTART_ZERO if dstart == 0 else 0)
            | (_H_DEPS_SAME if deps_same else 0)
            | (_H_NOPS_ONE if len(change.ops) == 1 else 0)
        )
        ints.append((a << _H_FLAG_BITS) | hflags)
        if dseq != 0:
            ints.append(dseq)
        if dstart != 0:
            ints.append(dstart)
        if not deps_same:
            # dep-count wire int: (count << 2) | (delta_mode << 1) | own_elided.
            # Delta mode sends only the ENTRIES THAT CHANGED vs this actor's
            # previous dep set (vector clocks advance one entry per received
            # change, so most of the clock repeats change-to-change).
            stored = ctx.dep_set.get(a)
            delta_ok = (
                stored is not None and stored[0] == own_elided
                and [da for da, _ in stored[1]] == [da for da, _ in explicit]
            )
            if delta_ok:
                changed = [
                    (da, ds, old)
                    for (da, ds), (_, old) in zip(explicit, stored[1])
                    if ds != old
                ]
                ints.append((len(changed) << 2) | 2 | own_elided)
                for da, ds, old in changed:
                    ints += [da, ds - old]
                    ctx.dep_base[da] = ds
            else:
                ints.append((len(explicit) << 2) | own_elided)
                for da, ds in explicit:
                    # base: the larger of the dep chain and the actor's last
                    # seq seen in frame — causally-ordered frames make deps
                    # implied (delta 0), per-actor-grouped frames chain well
                    base = max(ctx.dep_base.get(da, 0), ctx.last_seq.get(da, 0))
                    ints += [da, ds - base]
                    ctx.dep_base[da] = ds
            ctx.dep_set[a] = (own_elided, tuple(explicit))
        if len(change.ops) != 1:
            ints.append(len(change.ops))
        ctx.last_seq[a] = change.seq
        ctx.prev_end[a] = change.start_op + len(change.ops)
        for i, op in enumerate(change.ops):
            _flatten_op(op, table, ints, ctx, change, i)

    payload = native.varint_encode(np.asarray(ints, np.int32)) if native.available() else None
    if payload is None:
        payload = _py_varint_encode(ints)

    parts = [
        _HEADER.pack(_MAGIC, _VERSION, len(changes), len(table.strings), len(ints), len(payload))
    ]
    for s in table.strings:
        raw = s.encode("utf-8")
        parts.append(_py_varint_encode([len(raw)]))
        parts.append(raw)
    parts.append(payload)
    return b"".join(parts)


class _IntReader:
    def __init__(self, values) -> None:
        self.values = values
        self.pos = 0

    def take(self, n: int = 1):
        vals = self.values[self.pos : self.pos + n]
        if len(vals) != n:
            raise ValueError("truncated frame payload")
        self.pos += n
        return [int(v) for v in vals]


def _string(strings: List[str], idx: int) -> str:
    # Explicit bounds check: a corrupt (e.g. zigzag-negative) index must be a
    # ValueError, never a silent strings[-1] hit or an IndexError.
    if not 0 <= idx < len(strings):
        raise ValueError("string-table index out of range")
    return strings[idx]


def _read_op(
    r: _IntReader, strings: List[str], version: int, ctx: _FrameCtx,
    ch_actor: str, start_op: int, op_index: int,
) -> Operation:
    (first,) = r.take()
    if version >= 2:
        kind, flags = first & _KIND_MASK, first >> _KIND_BITS
    else:
        kind, flags = first, 0
    if kind == _OP_JSON:
        if flags:
            raise ValueError("flags on a JSON-spillover op")
        (idx,) = r.take()
        return Operation.from_json(json.loads(_string(strings, idx)))
    if flags >> 4:
        raise ValueError("unknown op flag bits")
    if flags & _F_REF_PREV and kind != _OP_INSERT:
        raise ValueError("REF_PREV on a non-insert op")
    if flags & _F_REF_HEAD and kind not in (_OP_INSERT, _OP_MAKEMAP):
        raise ValueError("REF_HEAD on an op kind without one")
    if (flags & _F_REF_PREV) and (flags & _F_REF_HEAD):
        raise ValueError("conflicting insert ref flags")

    def obj_of(vals):
        flag, ctr, actor = vals
        return ROOT if flag == 0 else (ctr, _string(strings, actor))

    prev_opid = ctx.prev_opid  # the PREVIOUS op's id, for REF_PREV below
    if flags & _F_OBJ_PREV:
        if ctx.prev_obj is _NO_PREV:
            raise ValueError("OBJ_PREV with no previous op in frame")
        obj = ctx.prev_obj
    else:
        obj = obj_of(r.take(3))
    if flags & _F_OPID_SEQ:
        opid = (start_op + op_index, ch_actor)
    else:
        ctr, actor = r.take(2)
        opid = (ctr, _string(strings, actor))
    ctx.prev_obj = obj
    ctx.prev_opid = opid
    if kind == _OP_MAKEMAP:
        (key_idx,) = r.take()
        return Operation(
            action="makeList" if flags & _F_REF_HEAD else "makeMap",
            obj=obj, opid=opid, key=_string(strings, key_idx),
        )
    if kind == _OP_MAPDEL:
        (key_idx,) = r.take()
        return Operation(
            action="del", obj=obj, opid=opid, key=_string(strings, key_idx)
        )
    if kind == _OP_MAPSET:
        key_idx, vkind, payload = r.take(3)
        if vkind == _VK_STR:
            value = _string(strings, payload)
        elif vkind == _VK_INT:
            value = payload
        elif vkind == _VK_TRUE:
            value = True
        elif vkind == _VK_FALSE:
            value = False
        elif vkind == _VK_NULL:
            value = None
        else:
            raise ValueError(f"unknown map value kind {vkind}")
        return Operation(
            action="set", obj=obj, opid=opid, key=_string(strings, key_idx),
            value=value,
        )
    if kind == _OP_INSERT:
        if flags & _F_REF_PREV:
            if prev_opid is None:
                raise ValueError("REF_PREV with no previous op in frame")
            elem = prev_opid
        elif flags & _F_REF_HEAD:
            elem = HEAD
        elif version >= 2:
            rctr, ractor = r.take(2)
            elem = (rctr + opid[0], _string(strings, ractor))
        else:
            flag, rctr, ractor = r.take(3)
            elem = HEAD if flag == 0 else (rctr, _string(strings, ractor))
        (cp,) = r.take()
        if version >= 2:
            cp += _CHAR_BIAS
        return Operation(
            action="set", obj=obj, opid=opid, elem_id=elem, insert=True, value=chr(cp)
        )
    if kind == _OP_DEL:
        ectr, eactor = r.take(2)
        if version >= 2:
            ectr += opid[0]
        return Operation(
            action="del", obj=obj, opid=opid, elem_id=(ectr, _string(strings, eactor))
        )
    if kind not in (_OP_ADDMARK, _OP_REMOVEMARK):
        raise ValueError(f"unknown op kind {kind}")
    # marks
    if version >= 2:
        (packed,) = r.take()
        mark_idx, sk, ek = packed & 3, (packed >> 2) & 3, (packed >> 4) & 3
        if packed >> 6:
            raise ValueError("mark kind-packing overflow")
        base_ctr = opid[0]
        sctr = sactor = ectr = eactor = 0
        if sk <= 1:  # BEFORE/AFTER carry an anchor
            dctr, sactor = r.take(2)
            sctr = base_ctr + dctr
            base_ctr = sctr
        if ek <= 1:
            dctr, eactor = r.take(2)
            ectr = base_ctr + dctr
        (attr_idx,) = r.take()
    else:
        (mark_idx,) = r.take()
        sk, sctr, sactor = r.take(3)
        ek, ectr, eactor = r.take(3)
        (attr_idx,) = r.take()
    if not 0 <= mark_idx < len(ALL_MARKS):
        raise ValueError("mark type index out of range")
    mark_type = ALL_MARKS[mark_idx]

    def boundary(kind_int, bctr, bactor) -> Boundary:
        if kind_int not in _INT_TO_BK:
            raise ValueError("bad boundary kind")
        bk = _INT_TO_BK[kind_int]
        if bk in (BEFORE, AFTER):
            return Boundary(bk, (bctr, _string(strings, bactor)))
        return Boundary(bk)

    attrs = None
    if attr_idx > 0:
        key = "url" if mark_type == "link" else "id"
        attrs = {key: _string(strings, attr_idx - 1)}
    return Operation(
        action="addMark" if kind == _OP_ADDMARK else "removeMark",
        obj=obj,
        opid=opid,
        start=boundary(sk, sctr, sactor),
        end=boundary(ek, ectr, eactor),
        mark_type=mark_type,
        attrs=attrs,
    )


def decode_frame(data: bytes) -> List[Change]:
    """Inverse of :func:`encode_frame`; raises ValueError on corrupt frames."""
    try:
        return _decode_frame(data)
    except ValueError:
        raise
    except (IndexError, KeyError, TypeError, OverflowError, UnicodeDecodeError,
            struct.error) as exc:
        # Normalize every corruption symptom to the documented contract.
        raise ValueError(f"corrupt frame: {exc!r}") from exc


def frame_parts(data: bytes):
    """Split a frame into ``(strings, payload_ints, n_changes, version)``
    without materializing Change objects — the input to the native
    frame-ingest fast path (native.parse_changes).  Raises ValueError on
    corrupt frames."""
    try:
        return _frame_parts(data)
    except ValueError:
        raise
    except (IndexError, OverflowError, UnicodeDecodeError, struct.error) as exc:
        raise ValueError(f"corrupt frame: {exc!r}") from exc


def _frame_parts(data: bytes):
    if len(data) < _HEADER.size:
        raise ValueError("frame too short")
    magic, version, n_changes, n_strings, n_ints, payload_len = _HEADER.unpack_from(data)
    if magic != _MAGIC or version not in _DECODABLE_VERSIONS:
        raise ValueError("bad frame magic/version")
    body = len(data) - _HEADER.size
    # Every header count costs at least one body byte, so any count larger
    # than the body is corrupt — checked BEFORE sizing any allocation from it.
    if payload_len > body or n_ints > payload_len or n_strings > body:
        raise ValueError("frame header counts exceed frame size")
    # minimum ints per change: v1 writes a 5-int header; v2's delta-elided
    # header can shrink to 2 ints (combo + op count)
    if n_changes * (5 if version == 1 else 2) > n_ints:
        raise ValueError("frame header counts exceed frame size")

    pos = _HEADER.size
    strings: List[str] = []
    for _ in range(n_strings):
        # string length is a single non-negative varint
        z, shift = 0, 0
        while True:
            if pos >= len(data) or shift > 28:
                raise ValueError("truncated string table")
            byte = data[pos]
            pos += 1
            z |= (byte & 0x7F) << shift
            if not byte & 0x80:
                break
            shift += 7
        length = (z >> 1) ^ -(z & 1)
        if length < 0 or pos + length > len(data):
            raise ValueError("truncated string table")
        strings.append(data[pos : pos + length].decode("utf-8"))
        pos += length

    payload = data[pos : pos + payload_len]
    if len(payload) != payload_len:
        raise ValueError("truncated payload")
    values = native.varint_decode(payload, n_ints) if native.available() else None
    if values is None:
        values = _py_varint_decode(payload, n_ints)
    return strings, values, n_changes, version


def _decode_frame(data: bytes) -> List[Change]:
    strings, values, n_changes, version = _frame_parts(data)
    r = _IntReader(values)
    changes: List[Change] = []
    ctx = _FrameCtx()
    # Decode-size budget: DEPS_SAME/elided headers materialize dep entries
    # from ZERO wire ints, so a sub-MB crafted frame could otherwise expand
    # to multi-GB dep dicts.  Real sessions sit far below the budget (their
    # dep sets are the collaboration's actor set).
    dep_budget = max(10_000, 64 * n_changes + 4 * len(values))
    deps_decoded = 0
    for _ in range(n_changes):
        if version >= 2:
            (combo,) = r.take()
            actor_idx, hflags = combo >> _H_FLAG_BITS, combo & ((1 << _H_FLAG_BITS) - 1)
            if not 0 <= actor_idx < len(strings):
                raise ValueError("actor index out of range")
            dseq = 0 if hflags & _H_DSEQ_ZERO else r.take()[0]
            dstart = 0 if hflags & _H_DSTART_ZERO else r.take()[0]
            seq = ctx.last_seq.get(actor_idx, 0) + 1 + dseq
            start_op = ctx.prev_end.get(actor_idx, 0) + dstart
            actor = _string(strings, actor_idx)
            deps = {}
            if hflags & _H_DEPS_SAME:
                stored = ctx.dep_set.get(actor_idx)
                if stored is None:
                    raise ValueError("DEPS_SAME with no previous change of actor")
                own_elided, explicit = stored
            else:
                (ndeps_wire,) = r.take()
                if ndeps_wire < 0:
                    raise ValueError("negative dep count")
                own_elided = ndeps_wire & 1
                delta_mode = (ndeps_wire >> 1) & 1
                count = ndeps_wire >> 2
                if delta_mode:
                    stored = ctx.dep_set.get(actor_idx)
                    if stored is None:
                        raise ValueError("dep delta with no previous change of actor")
                    entries = list(stored[1])
                    index_of = {da: i for i, (da, _) in enumerate(entries)}
                    for _ in range(count):
                        da, dds = r.take(2)
                        i = index_of.get(da)
                        if i is None:
                            raise ValueError("dep delta names an unknown actor")
                        ds = entries[i][1] + dds
                        entries[i] = (da, ds)
                        ctx.dep_base[da] = ds
                    explicit = tuple(entries)
                else:
                    explicit = []
                    seen = set()
                    for _ in range(count):
                        da, dds = r.take(2)
                        if da in seen:  # deps are a per-actor map: dups are crafted
                            raise ValueError("duplicate dep actor in change header")
                        seen.add(da)
                        base = max(ctx.dep_base.get(da, 0), ctx.last_seq.get(da, 0))
                        ds = base + dds
                        explicit.append((da, ds))
                        ctx.dep_base[da] = ds
                    explicit = tuple(explicit)
                ctx.dep_set[actor_idx] = (own_elided, explicit)
            if own_elided:
                deps[actor] = seq - 1
            deps_decoded += own_elided + len(explicit)
            if deps_decoded > dep_budget:
                raise ValueError("frame dep expansion exceeds decode budget")
            for da, ds in explicit:
                deps[_string(strings, da)] = ds
            n_ops = 1 if hflags & _H_NOPS_ONE else r.take()[0]
            if n_ops < 0:
                raise ValueError("negative op count")
            ctx.last_seq[actor_idx] = seq
            ctx.prev_end[actor_idx] = start_op + n_ops
        else:
            actor_idx, seq, start_op = r.take(3)
            (n_deps,) = r.take()
            if n_deps < 0:
                raise ValueError("negative dep count")
            deps = {}
            for _ in range(n_deps):
                a, s = r.take(2)
                deps[_string(strings, a)] = s
            (n_ops,) = r.take()
            if n_ops < 0:
                raise ValueError("negative op count")
            actor = _string(strings, actor_idx)
        ops = [
            _read_op(r, strings, version, ctx, actor, start_op, i)
            for i in range(n_ops)
        ]
        changes.append(
            Change(actor=actor, seq=seq, deps=deps, start_op=start_op, ops=ops)
        )
    if r.pos != len(r.values):
        raise ValueError("trailing garbage in frame payload")
    return changes
