"""Device-mesh sharding of the document axis.

The merge workload is data-parallel over documents: every ``(D, ...)`` tensor
(op streams, packed state, resolved output) is sharded on its leading axis
across a 1-D ``jax.sharding.Mesh``; the kernels themselves are unchanged
(vmap over docs), XLA partitions them and inserts collectives only where the
program asks for cross-doc values (e.g. the convergence digest's global sum,
which becomes an all-reduce over ICI).

Per SURVEY.md §5.8 the cross-shard needs of this workload are intentionally
small: docs are independent; collectives exist for (a) global convergence
digests, (b) clock-frontier exchange, (c) rebalancing.  This module covers
(a) directly and provides the sharding plumbing the rest use.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOC_AXIS = "docs"


def make_mesh(num_devices: Optional[int] = None, axis_name: str = DOC_AXIS) -> Mesh:
    """1-D mesh over the first ``num_devices`` (default: all) devices."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def doc_sharding(mesh: Mesh, axis_name: str = DOC_AXIS) -> NamedSharding:
    """Shard the leading (doc) axis; replicate everything else."""
    return NamedSharding(mesh, P(axis_name))


def pad_doc_axis(array: np.ndarray, multiple: int) -> np.ndarray:
    """Pad the leading axis up to a multiple (sharding needs equal shards).
    Padded rows are all-zero => kind=PAD ops / empty docs, which the kernels
    treat as no-ops."""
    d = array.shape[0]
    target = -(-d // multiple) * multiple
    if target == d:
        return array
    pad_width = [(0, target - d)] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, pad_width)


def shard_docs(tree, mesh: Mesh, axis_name: str = DOC_AXIS):
    """device_put every leaf with its leading axis sharded over the mesh."""
    sharding = doc_sharding(mesh, axis_name)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def doc_digest_host(codepoints, slot_positions, slot_capacity: int) -> int:
    """uint32 digest of ONE document, bit-identical to its contribution in
    :func:`convergence_digest` — computed host-side.

    Lets scalar-replay (fallback/overflow) docs participate in cross-session
    digest comparison: the device formula depends only on visible codepoints,
    their slot positions in the convergent element order (tombstones
    included), and the pad-slot count — all of which a scalar replica can
    reproduce whenever the doc fits the device capacities.  ``codepoints``
    and ``slot_positions`` are the visible characters and their indices in
    full element order."""
    import numpy as np

    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        k1, k2, k3 = np.uint32(2654435761), np.uint32(40503), np.uint32(2246822519)
        pad = np.uint32(0x9E3779B9) * k3
        pad = pad ^ (pad >> np.uint32(15))
        cps = np.asarray(codepoints, np.uint32)
        pos = np.asarray(slot_positions, np.uint32)
        x = (cps * k1) ^ (pos * k2)
        x = x * k3
        x = x ^ (x >> np.uint32(15))
        n_pad = np.uint32(max(slot_capacity - len(cps), 0))
        total = np.uint32(x.sum(dtype=np.uint32)) + n_pad * pad
    return int(total & np.uint32(0xFFFFFFFF))


def convergence_digest(
    chars: jnp.ndarray, visible: jnp.ndarray, doc_mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Order-sensitive scalar digest of all documents' visible text.

    Computed inside the sharded program, so the final sum lowers to an XLA
    all-reduce across the mesh — the "global convergence check" collective.
    Two replicas of a batch converged iff their digests match (probabilistic,
    64-ish bits folded into int32 pairs).

    ``doc_mask`` (bool (D,)) zeroes excluded docs' contributions ENTIRELY —
    an excluded doc must not add even the pad-slot constant, so its host-side
    stand-in (:func:`doc_digest_host`) can be summed in instead.
    """
    d, s = chars.shape
    # Per-slot mix of (char, visible, position) with distinct odd multipliers.
    pos = jnp.arange(s, dtype=jnp.uint32)[None, :]
    x = chars.astype(jnp.uint32) * jnp.uint32(2654435761)
    x = x ^ (pos * jnp.uint32(40503))
    x = jnp.where(visible, x, jnp.uint32(0x9E3779B9))
    x = x * jnp.uint32(2246822519)
    x = x ^ (x >> 15)
    per_doc = jnp.sum(x, axis=1, dtype=jnp.uint32)
    if doc_mask is not None:
        per_doc = jnp.where(doc_mask, per_doc, jnp.uint32(0))
    return jnp.sum(per_doc, dtype=jnp.uint32)  # cross-shard all-reduce
