"""Device-mesh sharding of the document axis.

The merge workload is data-parallel over documents: every ``(D, ...)`` tensor
(op streams, packed state, resolved output) is sharded on its leading axis
across a 1-D ``jax.sharding.Mesh``; the kernels themselves are unchanged
(vmap over docs), XLA partitions them and inserts collectives only where the
program asks for cross-doc values (e.g. the convergence digest's global sum,
which becomes an all-reduce over ICI).

Per SURVEY.md §5.8 the cross-shard needs of this workload are intentionally
small: docs are independent; collectives exist for (a) global convergence
digests, (b) clock-frontier exchange, (c) rebalancing.  This module covers
(a) directly and provides the sharding plumbing the rest use.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.interning import content_hash32

DOC_AXIS = "docs"


def make_mesh(num_devices: Optional[int] = None, axis_name: str = DOC_AXIS) -> Mesh:
    """1-D mesh over the first ``num_devices`` (default: all) devices."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def doc_sharding(mesh: Mesh, axis_name: str = DOC_AXIS) -> NamedSharding:
    """Shard the leading (doc) axis; replicate everything else."""
    return NamedSharding(mesh, P(axis_name))


def pad_doc_axis(array: np.ndarray, multiple: int) -> np.ndarray:
    """Pad the leading axis up to a multiple (sharding needs equal shards).
    Padded rows are all-zero => kind=PAD ops / empty docs, which the kernels
    treat as no-ops."""
    d = array.shape[0]
    target = -(-d // multiple) * multiple
    if target == d:
        return array
    pad_width = [(0, target - d)] + [(0, 0)] * (array.ndim - 1)
    return np.pad(array, pad_width)


def shard_docs(tree, mesh: Mesh, axis_name: str = DOC_AXIS):
    """device_put every leaf with its leading axis sharded over the mesh."""
    sharding = doc_sharding(mesh, axis_name)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


# -- digest mixing constants (device and host mirrors share these) ---------
# Distinct odd 32-bit multipliers; the final avalanche (*_KF; x ^= x >> 15)
# matches across every part so host stand-ins are bit-identical.
_KC1 = 2654435761  # char / register-object
_KP = 40503  # slot position
_KF = 2246822519  # final multiply before the xor-shift avalanche
_KT = 374761393  # LWW mark-type salt
_KL = 3266489917  # link url content hash salt
_KCM = 461845907  # comment id content hash salt
_KK = 668265263  # register key salt
_KV = 2869860233  # register value salt
_KKIND = 951274213  # register value-kind salt
_PAD_SEED = 0x9E3779B9


def _av_host(x: int) -> int:
    """Host mirror of the device avalanche (uint32 wraparound)."""
    x = (x * _KF) & 0xFFFFFFFF
    return x ^ (x >> 15)


def format_digest_host(
    slot_positions, marks_per_char, mark_names, comment_type: int
) -> int:
    """Host mirror of :func:`per_doc_format_digest` for one scalar-replay
    doc: per visible character (at element-order slot ``s``), the active LWW
    mark types, the link url content hash, and the active comment-id content
    hashes — bit-identical to the device sums, so fallback docs participate
    in full-state digest comparison."""
    acc = 0
    for s, marks in zip(slot_positions, marks_per_char):
        for t, name in enumerate(mark_names):
            if t == comment_type:
                continue
            m = marks.get(name)
            if m and m.get("active"):
                acc = (acc + _av_host((((t + 1) * _KT) & 0xFFFFFFFF) ^ ((s * _KP) & 0xFFFFFFFF))) & 0xFFFFFFFF
        link = marks.get("link")
        # None-check, not truthiness: an EMPTY url string is interned on the
        # device side (id >= 1, so link_attr > 0 includes it) and must hash
        # here too or fallback/device peers diverge
        if link and link.get("active") and link.get("url") is not None:
            lh = content_hash32(link["url"])
            acc = (acc + _av_host(((lh * _KL) & 0xFFFFFFFF) ^ ((s * _KP) & 0xFFFFFFFF))) & 0xFFFFFFFF
        for c in marks.get("comment", []):
            ch = content_hash32(c["id"])
            acc = (acc + _av_host(((ch * _KCM) & 0xFFFFFFFF) ^ ((s * _KP) & 0xFFFFFFFF))) & 0xFFFFFFFF
    return acc


def register_digest_host(rows) -> int:
    """Host mirror of :func:`per_doc_register_digest`.  ``rows`` iterates
    ``(obj_u32, key_hash, kind, val_u32)`` for every LIVE register (deleted
    keys are absent, as in the materialized doc)."""
    acc = 0
    for obj_u32, key_h, kind, val_u32 in rows:
        x = (
            ((obj_u32 * _KC1) & 0xFFFFFFFF)
            ^ ((key_h * _KK) & 0xFFFFFFFF)
            ^ ((kind * _KKIND) & 0xFFFFFFFF)
            ^ ((val_u32 * _KV) & 0xFFFFFFFF)
        )
        acc = (acc + _av_host(x)) & 0xFFFFFFFF
    return acc


def doc_digest_host(codepoints, slot_positions, slot_capacity: int) -> int:
    """uint32 digest of ONE document, bit-identical to its contribution in
    :func:`convergence_digest` — computed host-side.

    Lets scalar-replay (fallback/overflow) docs participate in cross-session
    digest comparison: the device formula depends only on visible codepoints,
    their slot positions in the convergent element order (tombstones
    included), and the pad-slot count — all of which a scalar replica can
    reproduce whenever the doc fits the device capacities.  ``codepoints``
    and ``slot_positions`` are the visible characters and their indices in
    full element order."""
    import numpy as np

    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        k1, k2, k3 = np.uint32(2654435761), np.uint32(40503), np.uint32(2246822519)
        pad = np.uint32(0x9E3779B9) * k3
        pad = pad ^ (pad >> np.uint32(15))
        cps = np.asarray(codepoints, np.uint32)
        pos = np.asarray(slot_positions, np.uint32)
        x = (cps * k1) ^ (pos * k2)
        x = x * k3
        x = x ^ (x >> np.uint32(15))
        n_pad = np.uint32(max(slot_capacity - len(cps), 0))
        total = np.uint32(x.sum(dtype=np.uint32)) + n_pad * pad
    return int(total & np.uint32(0xFFFFFFFF))


def _avalanche(x: jnp.ndarray) -> jnp.ndarray:
    x = x * jnp.uint32(_KF)
    return x ^ (x >> 15)


def per_doc_text_digest(chars: jnp.ndarray, visible: jnp.ndarray) -> jnp.ndarray:
    """(D,) uint32 per-doc digest of visible text (char, position, pad)."""
    d, s = chars.shape
    pos = jnp.arange(s, dtype=jnp.uint32)[None, :]
    x = chars.astype(jnp.uint32) * jnp.uint32(_KC1)
    x = x ^ (pos * jnp.uint32(_KP))
    x = jnp.where(visible, x, jnp.uint32(_PAD_SEED))
    return jnp.sum(_avalanche(x), axis=1, dtype=jnp.uint32)


def convergence_digest(
    chars: jnp.ndarray, visible: jnp.ndarray, doc_mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Order-sensitive scalar digest of all documents' visible text.

    Computed inside the sharded program, so the final sum lowers to an XLA
    all-reduce across the mesh — the "global convergence check" collective.
    Two replicas of a batch converged iff their digests match (probabilistic,
    64-ish bits folded into int32 pairs).

    ``doc_mask`` (bool (D,)) zeroes excluded docs' contributions ENTIRELY —
    an excluded doc must not add even the pad-slot constant, so its host-side
    stand-in (:func:`doc_digest_host`) can be summed in instead.
    """
    per_doc = per_doc_text_digest(chars, visible)
    if doc_mask is not None:
        per_doc = jnp.where(doc_mask, per_doc, jnp.uint32(0))
    return jnp.sum(per_doc, dtype=jnp.uint32)  # cross-shard all-reduce


def per_doc_format_digest(
    visible: jnp.ndarray,
    lww_active: jnp.ndarray,
    link_attr: jnp.ndarray,
    comment_bits: jnp.ndarray,
    attr_hash: jnp.ndarray,
    comment_hash: jnp.ndarray,
    comment_type: int,
    link_type: int,
) -> jnp.ndarray:
    """(D,) uint32 digest of per-character FORMATTING state, gated by
    visibility (the reference's convergence oracle compares formatted text,
    test/fuzz.ts:245-278 — two docs with equal text but divergent marks must
    digest apart).

    Contributions are position-mixed sums, so they are independent of mark
    TABLE row order (concurrent deliveries append in arrival order) and —
    because interned ids enter only through the gathered content-hash tables
    ``attr_hash`` (D, A) / ``comment_hash`` (D, C) — independent of each
    session's intern order.  Comment sets fold as unordered sums over active
    ids, matching ops_to_marks' id-set semantics."""
    d, s = visible.shape
    pos = jnp.arange(s, dtype=jnp.uint32)[None, :]
    n_types = lww_active.shape[1]
    acc = jnp.zeros((d,), jnp.uint32)

    # LWW active bits per type (strong/em/link; comments handled as sets)
    for t in range(n_types):
        if t == comment_type:
            continue
        x = _avalanche(jnp.uint32((t + 1) * _KT) ^ (pos * jnp.uint32(_KP)))
        active = visible & lww_active[:, t, :]
        acc = acc + jnp.sum(jnp.where(active, x, 0), axis=1, dtype=jnp.uint32)

    # link winner url (content hash gathered through the session table)
    a_cap = attr_hash.shape[1]
    lh = jnp.take_along_axis(
        attr_hash, jnp.clip(link_attr, 0, a_cap - 1), axis=1
    )
    x = _avalanche((lh * jnp.uint32(_KL)) ^ (pos * jnp.uint32(_KP)))
    link_on = visible & lww_active[:, link_type, :] & (link_attr > 0)
    acc = acc + jnp.sum(jnp.where(link_on, x, 0), axis=1, dtype=jnp.uint32)

    # comment id sets: unordered sum over active dense ids of the id's
    # content hash mixed with position.  Static loop over capacity (W*32,
    # typically 32) — each term is a (D, S) masked sum, nothing (D, C, S)
    # sized is ever materialized.
    w = comment_bits.shape[1]
    for word in range(w):
        bits = comment_bits[:, word, :]  # (D, S) uint32
        for k in range(32):
            c = word * 32 + k
            if c >= comment_hash.shape[1]:
                break
            ch = comment_hash[:, c][:, None]  # (D, 1)
            x = _avalanche((ch * jnp.uint32(_KCM)) ^ (pos * jnp.uint32(_KP)))
            on = visible & (((bits >> k) & 1) == 1)
            acc = acc + jnp.sum(jnp.where(on, x, 0), axis=1, dtype=jnp.uint32)
    return acc


def per_doc_register_digest(
    r_obj: jnp.ndarray,
    r_key: jnp.ndarray,
    r_op: jnp.ndarray,
    r_kind: jnp.ndarray,
    r_val: jnp.ndarray,
    key_hash: jnp.ndarray,
    vk_deleted: int,
    vk_str: int,
) -> jnp.ndarray:
    """(D,) uint32 digest of the map-register table (LWW winner per
    (object, key) across root and nested maps — reference map state,
    src/micromerge.ts:1151-1175).

    A row contributes iff it holds a live winner (r_op != 0 and not a
    deletion — a deleted key equals a never-set key, as in the materialized
    doc).  The sum is row-order independent (arrival order differs across
    peers) and intern-order independent: keys and string values enter
    through the gathered content-hash table ``key_hash`` (D, K); object ids
    and child-object values are packed (ctr, actor) ids, already canonical
    across sessions that declare the same actor set."""
    k_cap = key_hash.shape[1]
    kh = jnp.take_along_axis(key_hash, jnp.clip(r_key, 0, k_cap - 1), axis=1)
    vh_str = jnp.take_along_axis(key_hash, jnp.clip(r_val, 0, k_cap - 1), axis=1)
    vh = jnp.where(r_kind == vk_str, vh_str, r_val.astype(jnp.uint32))
    x = (
        (r_obj.astype(jnp.uint32) * jnp.uint32(_KC1))
        ^ (kh * jnp.uint32(_KK))
        ^ (r_kind.astype(jnp.uint32) * jnp.uint32(_KKIND))
        ^ (vh * jnp.uint32(_KV))
    )
    x = _avalanche(x)
    live = (r_op != 0) & (r_kind != vk_deleted)
    return jnp.sum(jnp.where(live, x, 0), axis=1, dtype=jnp.uint32)
