"""In-process broadcast fan-out (reference ``src/pubsub.ts``).

The smallest transport in the replication stack: subscribers register a
callback; ``publish`` fans an update out to everyone except the sender.  Used
by the editor bridge and demos; the batch/TPU path uses
:mod:`peritext_tpu.parallel.anti_entropy` instead.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, TypeVar

from ..obs import GLOBAL_COUNTERS, GLOBAL_TRACER

T = TypeVar("T")


class Publisher(Generic[T]):
    """``monitor`` (optional, a
    :class:`~..obs.convergence.ConvergenceMonitor`) gives the in-process
    transport the same per-peer observability surface as the multihost
    one: every delivery records a clean exchange per subscriber, so a
    fleet view renders editor-bridge subscribers next to TCP peers (the
    faulty test double additionally records drops as failures)."""

    def __init__(self, monitor=None) -> None:
        self._subscribers: Dict[str, Callable[[T], None]] = {}
        self.monitor = monitor

    def subscribe(self, key: str, callback: Callable[[T], None]) -> None:
        if key in self._subscribers:
            raise ValueError(f"duplicate subscription key {key!r}")
        self._subscribers[key] = callback

    def unsubscribe(self, key: str) -> None:
        if key not in self._subscribers:
            raise ValueError(f"no subscription under key {key!r}")
        del self._subscribers[key]

    def publish(self, sender: str, update: T) -> None:
        with GLOBAL_TRACER.span("pubsub.publish", sender=sender):
            # deterministic fan-out order: subscription (arrival) order is
            # replica-local history and must not drive delivery (PTL001)
            for key, callback in sorted(self._subscribers.items()):
                if key != sender:
                    callback(update)
                    if self.monitor is not None:
                        self.monitor.observe_success(key)
        GLOBAL_COUNTERS.add("transport.pubsub_published")
