"""Host-side causal scheduling.

The device kernel applies a *linear*, padded op stream per document; it must
never see a change whose dependencies haven't been applied.  This module
linearizes an arbitrary set of changes into a deterministic admissible order
(and, for streaming, into causal waves).  Determinism matters only for
reproducibility — any admissible order converges, because op application is
commutative across causally-concurrent changes (that's the CRDT's job).

This replaces the reference's catch-and-requeue delivery loop
(test/merge.ts:4-23) with an explicit topological schedule: O(n log n) instead
of retry-until-fixpoint, and it yields the padded batches the TPU wants.
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional, Tuple

from ..core.errors import PeritextError
from ..core.types import Change, Clock


def _admissible(change: Change, clock: Clock) -> bool:
    if change.seq != clock.get(change.actor, 0) + 1:
        return False
    return all(clock.get(actor, 0) >= dep for actor, dep in (change.deps or {}).items())


def causal_schedule(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> Tuple[List[Change], List[Change]]:
    """Schedule as many changes as causally possible.

    Returns ``(ordered, stuck)``: ``ordered`` is a deterministic admissible
    order (smallest (actor, seq) among ready first); ``stuck`` are changes
    whose dependencies are absent from the set (e.g. lost in transit) —
    callers under faulty delivery leave them for the next anti-entropy round.
    """
    clock: Clock = dict(base_clock or {})
    pending: Dict[Tuple[str, int], Change] = {}
    for ch in changes:
        key = (ch.actor, ch.seq)
        if key in pending:
            continue  # duplicate delivery
        if ch.seq <= clock.get(ch.actor, 0):
            continue  # already incorporated
        pending[key] = ch

    # Reverse index: blocker (actor, seq) -> keys waiting on it.  A change
    # waits on its per-actor predecessor and on each unsatisfied dep; since
    # seqs apply in order, clock[a] reaches d exactly when (a, d) is applied.
    waiters: Dict[Tuple[str, int], List[Tuple[str, int]]] = {}
    for key, ch in pending.items():
        if ch.seq > 1 and clock.get(ch.actor, 0) < ch.seq - 1:
            waiters.setdefault((ch.actor, ch.seq - 1), []).append(key)
        for actor, dep in (ch.deps or {}).items():
            if clock.get(actor, 0) < dep and actor != ch.actor:
                waiters.setdefault((actor, dep), []).append(key)

    ready: List[Tuple[str, int]] = [k for k, c in pending.items() if _admissible(c, clock)]
    heapq.heapify(ready)
    out: List[Change] = []

    while ready:
        key = heapq.heappop(ready)
        ch = pending.pop(key, None)
        if ch is None:
            continue  # woken more than once
        out.append(ch)
        clock[ch.actor] = ch.seq
        for waiter in waiters.pop(key, ()):
            cand = pending.get(waiter)
            if cand is not None and _admissible(cand, clock):
                heapq.heappush(ready, waiter)

    stuck = [pending[k] for k in sorted(pending.keys())]
    return out, stuck


def causal_sort(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> List[Change]:
    """Order changes so every change's deps precede it; raises if the set has
    a causal gap relative to ``base_clock`` (strict variant of
    :func:`causal_schedule`)."""
    ordered, stuck = causal_schedule(changes, base_clock)
    if stuck:
        missing = sorted((c.actor, c.seq) for c in stuck)[:5]
        raise PeritextError(f"Causal gap: cannot schedule changes {missing}")
    return ordered


def causal_waves(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> List[List[Change]]:
    """Group changes into waves: wave k contains changes admissible once waves
    < k are applied.  Within a wave all changes are causally concurrent (up to
    per-actor seq chains), which is the unit a streaming pipeline can overlap."""
    clock: Clock = dict(base_clock or {})
    seen: set = set()
    remaining: List[Change] = []
    for ch in changes:
        key = (ch.actor, ch.seq)
        if key in seen or ch.seq <= clock.get(ch.actor, 0):
            continue  # duplicate or already incorporated
        seen.add(key)
        remaining.append(ch)
    waves: List[List[Change]] = []
    while remaining:
        wave = [ch for ch in remaining if _admissible(ch, clock)]
        if not wave:
            raise PeritextError("Causal gap: no admissible changes remain")
        wave.sort(key=lambda c: (c.actor, c.seq))
        for ch in wave:
            clock[ch.actor] = ch.seq
        applied = {(c.actor, c.seq) for c in wave}
        remaining = [c for c in remaining if (c.actor, c.seq) not in applied]
        waves.append(wave)
    return waves
