"""Host-side causal scheduling.

The device kernel applies a *linear*, padded op stream per document; it must
never see a change whose dependencies haven't been applied.  This module
linearizes an arbitrary set of changes into a deterministic admissible order
(and, for streaming, into causal waves).  Determinism matters only for
reproducibility — any admissible order converges, because op application is
commutative across causally-concurrent changes (that's the CRDT's job).

This replaces the reference's catch-and-requeue delivery loop
(test/merge.ts:4-23) with an explicit topological schedule: O(n log n) instead
of retry-until-fixpoint, and it yields the padded batches the TPU wants.
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import native
from ..core.errors import PeritextError
from ..core.types import Change, Clock

#: Below this many changes the Python scheduler wins (array setup overhead).
_NATIVE_THRESHOLD = 64


def _admissible(change: Change, clock: Clock) -> bool:
    if change.seq != clock.get(change.actor, 0) + 1:
        return False
    return all(clock.get(actor, 0) >= dep for actor, dep in (change.deps or {}).items())


def causal_schedule(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> Tuple[List[Change], List[Change]]:
    """Schedule as many changes as causally possible.

    Returns ``(ordered, stuck)``: ``ordered`` is a deterministic admissible
    order (smallest (actor, seq) among ready first); ``stuck`` are changes
    whose dependencies are absent from the set (e.g. lost in transit) —
    callers under faulty delivery leave them for the next anti-entropy round.

    Large sets route through the native C++ scheduler (peritext_tpu/native)
    when it is available; both implementations produce identical output.
    """
    changes = list(changes)
    if len(changes) >= _NATIVE_THRESHOLD:
        result = _native_schedule(changes, base_clock)
        if result is not None:
            return result
    clock: Clock = dict(base_clock or {})
    pending: Dict[Tuple[str, int], Change] = {}
    for ch in changes:
        key = (ch.actor, ch.seq)
        if key in pending:
            continue  # duplicate delivery
        if ch.seq <= clock.get(ch.actor, 0):
            continue  # already incorporated
        pending[key] = ch

    # Reverse index: blocker (actor, seq) -> keys waiting on it.  A change
    # waits on its per-actor predecessor and on each unsatisfied dep; since
    # seqs apply in order, clock[a] reaches d exactly when (a, d) is applied.
    waiters: Dict[Tuple[str, int], List[Tuple[str, int]]] = {}
    for key, ch in pending.items():
        if ch.seq > 1 and clock.get(ch.actor, 0) < ch.seq - 1:
            waiters.setdefault((ch.actor, ch.seq - 1), []).append(key)
        for actor, dep in (ch.deps or {}).items():
            if clock.get(actor, 0) < dep and actor != ch.actor:
                waiters.setdefault((actor, dep), []).append(key)

    ready: List[Tuple[str, int]] = [k for k, c in pending.items() if _admissible(c, clock)]
    heapq.heapify(ready)
    out: List[Change] = []

    while ready:
        key = heapq.heappop(ready)
        ch = pending.pop(key, None)
        if ch is None:
            continue  # woken more than once
        out.append(ch)
        clock[ch.actor] = ch.seq
        for waiter in waiters.pop(key, ()):
            cand = pending.get(waiter)
            if cand is not None and _admissible(cand, clock):
                heapq.heappush(ready, waiter)

    stuck = [pending[k] for k in sorted(pending.keys())]
    return out, stuck


def _native_schedule(
    changes: List[Change], base_clock: Optional[Clock]
) -> Optional[Tuple[List[Change], List[Change]]]:
    """Array form of the schedule for the C++ core (peritext_tpu/native).
    Actor indices are assigned in sorted-string order so the native heap's
    integer ordering reproduces the Python tie-break exactly."""
    if not native.available():
        return None
    actors = sorted(
        {ch.actor for ch in changes} | set(base_clock or {})
    )
    index = {a: i for i, a in enumerate(actors)}
    n = len(changes)
    actor_arr = np.fromiter((index[ch.actor] for ch in changes), np.int32, n)
    seq_arr = np.fromiter((ch.seq for ch in changes), np.int32, n)
    dep_off = np.zeros(n + 1, np.int32)
    dep_actor: List[int] = []
    dep_seq: List[int] = []
    for i, ch in enumerate(changes):
        for a, s in (ch.deps or {}).items():
            if a in index:
                dep_actor.append(index[a])
                dep_seq.append(s)
            elif s > 0:
                # dep on an actor absent from clock and set: never satisfiable
                # in this call; encode as an impossible self-dep
                dep_actor.append(index[ch.actor])
                dep_seq.append(np.iinfo(np.int32).max)
        dep_off[i + 1] = len(dep_actor)
    clock_arr = np.zeros(len(actors), np.int32)
    for a, s in (base_clock or {}).items():
        clock_arr[index[a]] = s

    order = native.causal_schedule_indices(
        actor_arr,
        seq_arr,
        dep_off,
        np.asarray(dep_actor, np.int32),
        np.asarray(dep_seq, np.int32),
        len(actors),
        clock_arr,
    )
    if order is None:
        return None
    ordered = [changes[i] for i in order]
    if len(ordered) == len(changes):
        return ordered, []  # nothing dropped: skip the stuck reconstruction
    scheduled = set(int(i) for i in order)
    clock0: Clock = dict(base_clock or {})
    pending: Dict[Tuple[str, int], int] = {}
    for i, ch in enumerate(changes):
        key = (ch.actor, ch.seq)
        if key in pending or ch.seq <= clock0.get(ch.actor, 0):
            continue
        pending[key] = i
    stuck = [
        changes[i] for k, i in sorted(pending.items()) if i not in scheduled
    ]
    return ordered, stuck


def causal_sort(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> List[Change]:
    """Order changes so every change's deps precede it; raises if the set has
    a causal gap relative to ``base_clock`` (strict variant of
    :func:`causal_schedule`)."""
    ordered, stuck = causal_schedule(changes, base_clock)
    if stuck:
        missing = sorted((c.actor, c.seq) for c in stuck)[:5]
        raise PeritextError(f"Causal gap: cannot schedule changes {missing}")
    return ordered


def causal_waves(
    changes: Iterable[Change], base_clock: Optional[Clock] = None
) -> List[List[Change]]:
    """Group changes into waves: wave k contains changes admissible once waves
    < k are applied.  Within a wave all changes are causally concurrent (up to
    per-actor seq chains), which is the unit a streaming pipeline can overlap."""
    clock: Clock = dict(base_clock or {})
    seen: set = set()
    remaining: List[Change] = []
    for ch in changes:
        key = (ch.actor, ch.seq)
        if key in seen or ch.seq <= clock.get(ch.actor, 0):
            continue  # duplicate or already incorporated
        seen.add(key)
        remaining.append(ch)
    waves: List[List[Change]] = []
    while remaining:
        wave = [ch for ch in remaining if _admissible(ch, clock)]
        if not wave:
            raise PeritextError("Causal gap: no admissible changes remain")
        wave.sort(key=lambda c: (c.actor, c.seq))
        for ch in wave:
            clock[ch.actor] = ch.seq
        applied = {(c.actor, c.seq) for c in wave}
        remaining = [c for c in remaining if (c.actor, c.seq) not in applied]
        waves.append(wave)
    return waves
