"""Replication and parallelism: transport, anti-entropy, causal scheduling,
and (device) mesh sharding of the document axis."""

from .anti_entropy import ChangeStore, apply_changes, get_missing_changes, sync
from .causal import causal_sort, causal_waves
from .change_queue import ChangeQueue
from .multihost import ReplicaServer, merge_changes, sync_with
from .pubsub import Publisher

__all__ = [
    "ChangeStore",
    "apply_changes",
    "get_missing_changes",
    "sync",
    "causal_sort",
    "causal_waves",
    "ChangeQueue",
    "Publisher",
    "ReplicaServer",
    "merge_changes",
    "sync_with",
]
