"""Replication and parallelism: transport, anti-entropy, causal scheduling,
and (device) mesh sharding of the document axis."""

from .anti_entropy import ChangeStore, apply_changes, get_missing_changes, sync
from .causal import causal_sort, causal_waves
from .change_queue import ChangeQueue
from .multihost import (
    ReplicaServer,
    RetryPolicy,
    SyncOutcome,
    merge_changes,
    sync_with,
    try_sync_with,
)
from .pubsub import Publisher


def __getattr__(name):
    # lazy: supervisor pulls in streaming (and through it the whole device
    # stack), whose import chain re-enters this package — eager import here
    # would be circular, and most transport users never need it
    if name == "GuardedSession":
        from .supervisor import GuardedSession

        return GuardedSession
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "GuardedSession",
    "ChangeStore",
    "apply_changes",
    "get_missing_changes",
    "sync",
    "causal_sort",
    "causal_waves",
    "ChangeQueue",
    "Publisher",
    "ReplicaServer",
    "RetryPolicy",
    "SyncOutcome",
    "merge_changes",
    "sync_with",
    "try_sync_with",
]
