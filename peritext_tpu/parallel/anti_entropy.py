"""Vector-clock anti-entropy and causal delivery.

The reference's replication protocol (reference ``test/merge.ts`` +
``src/micromerge.ts:892-902``): each actor keeps an append-only log of its own
changes; to sync, a replica diffs vector clocks to find what the peer is
missing, ships those changes, and the receiver applies them with a
catch-and-requeue loop that tolerates arbitrary delivery reordering.

This module is the host-side half of the TPU merge path too: the same clock
diff decides *what* to ship to the device, and :mod:`.causal` linearizes it
into an admissible order so the device kernel never sees an unmet dependency.
"""

from __future__ import annotations

import json
import zlib
from typing import Dict, List, Optional

from ..core.doc import Doc
from ..core.errors import PeritextError
from ..core.types import Change, Clock, Patch
from ..obs import GLOBAL_COUNTERS, GLOBAL_TRACER
from .causal import causal_sort


def change_digest(change: Change) -> int:
    """Stable uint32 content hash of ONE change — identical across hosts
    for identical change content (canonical sorted-key JSON through CRC32,
    avalanched so near-identical changes don't cancel in the sum)."""
    raw = json.dumps(
        change.to_json(), sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    x = zlib.crc32(raw) & 0xFFFFFFFF
    # the mesh digests' avalanche (mesh._av_host): sums of raw CRCs of
    # related payloads correlate; a multiply + xor-shift decorrelates them
    x = (x * 2246822519) & 0xFFFFFFFF
    return x ^ (x >> 15)


class ChangeStore:
    """Per-actor append-only change logs (the durable source of truth; any
    replica state is reconstructible by replay — event sourcing).

    The store also maintains per-actor PREFIX DIGESTS: ``_digests[actor][i]``
    is the commutative uint32 sum of the first ``i`` changes' content
    hashes, so :meth:`digest` — the store digest at an arbitrary frontier —
    is O(actors), cheap enough to attach to every anti-entropy frontier.
    Two stores with EQUAL frontiers hold the same change set iff their
    digests match (probabilistic, 32 bits), which is what turns "same
    frontier, different digest" into a detectable divergence incident
    (:mod:`~..obs.convergence`) instead of silent split-brain."""

    def __init__(self) -> None:
        self._logs: Dict[str, List[Change]] = {}
        self._digests: Dict[str, List[int]] = {}

    def append(self, change: Change) -> None:
        log = self._logs.setdefault(change.actor, [])
        if change.seq != len(log) + 1:
            raise PeritextError(
                f"Log gap for {change.actor}: have {len(log)}, appending seq {change.seq}"
            )
        log.append(change)
        prefix = self._digests.setdefault(change.actor, [0])
        prefix.append((prefix[-1] + change_digest(change)) & 0xFFFFFFFF)

    def digest(self, clock: Optional[Clock] = None) -> int:
        """Commutative uint32 digest of the change set at ``clock`` (default
        this store's own frontier): the sum over actors of the per-actor
        prefix digest at ``min(clock[actor], len(log))``.  Order-independent
        across actors by construction, so two replicas that merged the same
        changes in any order digest equal."""
        if clock is None:
            clock = self.clock()
        acc = 0
        for actor, seq in clock.items():
            prefix = self._digests.get(actor)
            if prefix is None or seq <= 0:
                continue
            acc = (acc + prefix[min(int(seq), len(prefix) - 1)]) & 0xFFFFFFFF
        return acc

    def log(self, actor: str) -> List[Change]:
        return self._logs.get(actor, [])

    def actors(self) -> List[str]:
        return list(self._logs.keys())

    def clock(self) -> Clock:
        # sorted so the clock's key order (which reaches wire frames) is a
        # function of the actor set, not of arrival order (PTL001)
        return {actor: len(log) for actor, log in sorted(self._logs.items())}

    def missing_changes(self, source_clock: Clock, target_clock: Clock) -> List[Change]:
        """Changes known to ``source`` but not ``target`` (reference
        getMissingChanges, test/merge.ts:25-38)."""
        changes: List[Change] = []
        for actor, seq in source_clock.items():
            have = target_clock.get(actor, 0)
            if have < seq:
                changes.extend(self._logs.get(actor, [])[have:seq])
        return changes


def get_missing_changes(source: Doc, target: Doc, store: ChangeStore) -> List[Change]:
    return store.missing_changes(source.clock, target.clock)


def apply_changes(doc: Doc, changes: List[Change]) -> List[Patch]:
    """Apply changes delivered in arbitrary order (with duplicates and
    already-applied changes tolerated), in one causal-sorted pass.

    Replaces the reference's catch-and-requeue retry loop (test/merge.ts:4-23)
    — O(n log n) instead of retry-until-fixpoint, and a causal gap in the
    input raises immediately with the stuck changes named instead of spinning
    to an iteration cap."""
    patches: List[Patch] = []
    for change in causal_sort(changes, doc.clock):
        patches.extend(doc.apply_change(change))
    return patches


def sync(left: Doc, right: Doc, store: ChangeStore,
         monitor=None) -> Dict[str, List[Patch]]:
    """Bidirectional anti-entropy between two replicas; returns patches each
    side produced.  With a :class:`~..obs.convergence.ConvergenceMonitor`,
    the pre-sync frontiers are ingested as lag watermarks (peer names
    ``left``/``right``) — the in-process analog of the multihost frontier
    hook, so a local two-replica session shows up in the same fleet view."""
    with GLOBAL_TRACER.span("anti-entropy.local-sync"):
        if monitor is not None:
            left_digest = store.digest(left.clock)
            right_digest = store.digest(right.clock)
            monitor.observe_frontier(
                "right", left.clock, right.clock,
                local_digest=left_digest, peer_digest=right_digest,
            )
            monitor.observe_frontier(
                "left", right.clock, left.clock,
                local_digest=right_digest, peer_digest=left_digest,
            )
        to_right = store.missing_changes(left.clock, right.clock)
        to_left = store.missing_changes(right.clock, left.clock)
        out = {
            "right": apply_changes(right, to_right),
            "left": apply_changes(left, to_left),
        }
        if monitor is not None:
            monitor.observe_success("right", pulled=len(to_left),
                                    pushed=len(to_right))
            monitor.observe_success("left", pulled=len(to_right),
                                    pushed=len(to_left))
    GLOBAL_COUNTERS.add("transport.local_syncs")
    GLOBAL_COUNTERS.add("transport.local_sync_changes", len(to_right) + len(to_left))
    return out
