"""Vector-clock anti-entropy and causal delivery.

The reference's replication protocol (reference ``test/merge.ts`` +
``src/micromerge.ts:892-902``): each actor keeps an append-only log of its own
changes; to sync, a replica diffs vector clocks to find what the peer is
missing, ships those changes, and the receiver applies them with a
catch-and-requeue loop that tolerates arbitrary delivery reordering.

This module is the host-side half of the TPU merge path too: the same clock
diff decides *what* to ship to the device, and :mod:`.causal` linearizes it
into an admissible order so the device kernel never sees an unmet dependency.
"""

from __future__ import annotations

from typing import Dict, List

from ..core.doc import Doc
from ..core.errors import PeritextError
from ..core.types import Change, Clock, Patch
from ..obs import GLOBAL_COUNTERS, GLOBAL_TRACER
from .causal import causal_sort


class ChangeStore:
    """Per-actor append-only change logs (the durable source of truth; any
    replica state is reconstructible by replay — event sourcing)."""

    def __init__(self) -> None:
        self._logs: Dict[str, List[Change]] = {}

    def append(self, change: Change) -> None:
        log = self._logs.setdefault(change.actor, [])
        if change.seq != len(log) + 1:
            raise PeritextError(
                f"Log gap for {change.actor}: have {len(log)}, appending seq {change.seq}"
            )
        log.append(change)

    def log(self, actor: str) -> List[Change]:
        return self._logs.get(actor, [])

    def actors(self) -> List[str]:
        return list(self._logs.keys())

    def clock(self) -> Clock:
        # sorted so the clock's key order (which reaches wire frames) is a
        # function of the actor set, not of arrival order (PTL001)
        return {actor: len(log) for actor, log in sorted(self._logs.items())}

    def missing_changes(self, source_clock: Clock, target_clock: Clock) -> List[Change]:
        """Changes known to ``source`` but not ``target`` (reference
        getMissingChanges, test/merge.ts:25-38)."""
        changes: List[Change] = []
        for actor, seq in source_clock.items():
            have = target_clock.get(actor, 0)
            if have < seq:
                changes.extend(self._logs.get(actor, [])[have:seq])
        return changes


def get_missing_changes(source: Doc, target: Doc, store: ChangeStore) -> List[Change]:
    return store.missing_changes(source.clock, target.clock)


def apply_changes(doc: Doc, changes: List[Change]) -> List[Patch]:
    """Apply changes delivered in arbitrary order (with duplicates and
    already-applied changes tolerated), in one causal-sorted pass.

    Replaces the reference's catch-and-requeue retry loop (test/merge.ts:4-23)
    — O(n log n) instead of retry-until-fixpoint, and a causal gap in the
    input raises immediately with the stuck changes named instead of spinning
    to an iteration cap."""
    patches: List[Patch] = []
    for change in causal_sort(changes, doc.clock):
        patches.extend(doc.apply_change(change))
    return patches


def sync(left: Doc, right: Doc, store: ChangeStore) -> Dict[str, List[Patch]]:
    """Bidirectional anti-entropy between two replicas; returns patches each
    side produced."""
    with GLOBAL_TRACER.span("anti-entropy.local-sync"):
        to_right = store.missing_changes(left.clock, right.clock)
        to_left = store.missing_changes(right.clock, left.clock)
        out = {
            "right": apply_changes(right, to_right),
            "left": apply_changes(left, to_left),
        }
    GLOBAL_COUNTERS.add("transport.local_syncs")
    GLOBAL_COUNTERS.add("transport.local_sync_changes", len(to_right) + len(to_left))
    return out
