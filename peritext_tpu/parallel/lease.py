"""Round-counted heartbeat leases: deterministic host-death detection.

A serving fleet has to decide "that host is dead" without a coordinator,
and the decision feeds :class:`~.router.FleetRouter` placement — so it must
be a DETERMINISTIC function of the observed heartbeat sequence, exactly the
way placement is a deterministic function of the observed load state: two
frontends that saw the same beats must reach the same death verdict on the
same tick, or they re-place the same doc onto different hosts (split-brain
placement, the failure the router's determinism exists to prevent).

Hence no wall clock and no RNG here (``parallel/`` is graftlint merge
scope; PTL006 machine-checks it, and the corpus carries a lease-shaped
true positive proving the rule fires on a ``time.monotonic()`` lease
stamp).  The lease unit is the OBSERVATION ROUND, not seconds: every
frontend bookkeeping round feeds one beat-or-miss observation per host,
and a host whose lease has ``lease_rounds`` consecutive misses is declared
dead.  Wall-clock pacing of the rounds themselves lives with the caller
(``serve/`` — outside merge scope), where it belongs.

Verdicts are a ladder, not a boolean:

* ``live``    — the latest observation was a beat;
* ``suspect`` — 1..lease_rounds-1 consecutive misses: the lease is
  draining, no action yet (a single dropped poll must not trigger a fleet
  re-placement);
* ``dead``    — ``lease_rounds`` consecutive misses.  LATCHED: later beats
  do not revive the host (its docs have been re-placed; a zombie host
  coming back must re-register through :meth:`reset`, the re-admission
  path, never silently resume serving stale placements).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Mapping

#: verdict vocabulary (the fleet exporters and the chaos oracle share it)
LIVE = "live"
SUSPECT = "suspect"
DEAD = "dead"


@dataclass
class Lease:
    """One host's lease state."""

    host: str
    #: consecutive missed observation rounds
    missed: int = 0
    #: total observation rounds this lease has been fed
    rounds: int = 0
    #: the round index (1-based) at which the dead verdict latched; 0 = alive
    dead_at_round: int = 0

    def verdict(self, lease_rounds: int) -> str:
        if self.dead_at_round:
            return DEAD
        if self.missed == 0:
            return LIVE
        return SUSPECT if self.missed < lease_rounds else DEAD

    def to_json(self) -> Dict:
        return {
            "missed": self.missed,
            "rounds": self.rounds,
            "dead_at_round": self.dead_at_round,
        }


class HeartbeatLedger:
    """Deterministic round-counted lease table (see module doc).

    ``lease_rounds`` is how many CONSECUTIVE missed observations kill a
    lease.  All iteration is sorted by host name; the same observation
    sequence produces the same verdict sequence on every replica that runs
    the ledger — pinned by test with two independently-fed ledgers.
    """

    def __init__(self, lease_rounds: int = 3) -> None:
        if lease_rounds < 1:
            raise ValueError(f"lease_rounds must be >= 1, got {lease_rounds}")
        self.lease_rounds = int(lease_rounds)
        self._leases: Dict[str, Lease] = {}
        self.ticks = 0
        self._newly_dead: List[str] = []

    # -- membership ----------------------------------------------------------

    def track(self, host: str) -> None:
        if host not in self._leases:
            self._leases[host] = Lease(host=host)

    def forget(self, host: str) -> None:
        self._leases.pop(host, None)

    def reset(self, host: str) -> None:
        """Re-admission: a host that was declared dead and has re-registered
        starts a fresh lease (the ONLY way out of the dead latch)."""
        self._leases[host] = Lease(host=host)

    def hosts(self) -> List[str]:
        return sorted(self._leases)

    # -- the observation round -----------------------------------------------

    def tick(self, beats: Mapping[str, bool]) -> Dict[str, str]:
        """Feed one observation round: ``beats[host]`` is True when the
        host answered this round's heartbeat.  A tracked host absent from
        ``beats`` counts as a miss (the poller could not even ask).
        Returns the post-tick verdict per host, and ``newly_dead`` below
        reports leases that latched dead ON this tick — the failover
        trigger must fire exactly once per death."""
        self.ticks += 1
        self._newly_dead = []
        verdicts: Dict[str, str] = {}
        for host in sorted(self._leases):
            lease = self._leases[host]
            lease.rounds += 1
            if lease.dead_at_round:
                verdicts[host] = DEAD
                continue
            if beats.get(host, False):
                lease.missed = 0
            else:
                lease.missed += 1
                if lease.missed >= self.lease_rounds:
                    lease.dead_at_round = lease.rounds
                    self._newly_dead.append(host)
            verdicts[host] = lease.verdict(self.lease_rounds)
        return verdicts

    def newly_dead(self) -> List[str]:
        """Hosts whose lease latched dead on the LAST :meth:`tick` (sorted;
        empty between deaths)."""
        return list(self._newly_dead)

    # -- readout --------------------------------------------------------------

    def verdict(self, host: str) -> str:
        return self._leases[host].verdict(self.lease_rounds)

    def lease(self, host: str) -> Lease:
        return self._leases[host]

    def dead_hosts(self) -> List[str]:
        return [
            h for h in sorted(self._leases)
            if self._leases[h].verdict(self.lease_rounds) == DEAD
        ]

    def snapshot(self) -> Dict:
        """JSON-serializable lease table (``/fleet.json`` section)."""
        return {
            "lease_rounds": self.lease_rounds,
            "ticks": self.ticks,
            "leases": {
                host: {
                    **self._leases[host].to_json(),
                    "verdict": self._leases[host].verdict(self.lease_rounds),
                }
                for host in sorted(self._leases)
            },
        }
