"""Double-buffered async host→device staging lane (ops/frames → device).

The fused round pipeline (parallel/streaming.py ``drain``) splits a round
batch's host half into SCHEDULE (causal admission into staging buffers —
mutates session clocks, so it must stay on the session's thread) and STAGE
(flatten the staged buffers into the fused program's concatenated tensors
and ``jax.device_put`` them — pure reads of buffers the batch exclusively
owns).  This module runs the STAGE half on a worker thread so batch k's
flatten + upload overlaps batch k+1's schedule on the main thread and batch
k-1's device math behind the async dispatch queue: the host parse/transfer
cost the streaming-vs-engine gap attributed (ISSUE 9 / FusionStitching's
host-boundary stitching) hides behind device compute instead of serializing
with it.

``depth`` bounds the in-flight staged batches (default 2 — the double
buffer): ``submit`` blocks when the lane is full, so a deep drain can never
pile unbounded staged tensors onto the host or device.

Determinism posture (this module lives in graftlint merge scope ON
PURPOSE): staging jobs are pure functions of their already-scheduled batch
— the worker introduces NO ordering freedom (handles resolve FIFO, commits
wait each handle in submission order), reads no clocks and draws no
randomness; timing telemetry is the caller's via obs spans.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Tuple

#: worker idle lifetime: a lane whose owner stopped draining (an abandoned
#: watchdog session, a dropped StreamingMerge) self-reaps instead of leaking
#: a thread per session; the next submit respawns transparently
IDLE_TIMEOUT_SECONDS = 10.0


class StagedHandle:
    """One staged batch's future: ``wait()`` returns the staging function's
    result (the device-resident input tensors) or re-raises its failure on
    the waiting thread — a staging fault surfaces inside the guarded commit
    that consumes it, never on a daemon thread."""

    __slots__ = ("_done", "_value", "_error")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None

    def _resolve(self, value) -> None:
        self._value = value
        self._done.set()

    def _reject(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    def ready(self) -> bool:
        return self._done.is_set()

    def wait(self):
        self._done.wait()
        if self._error is not None:
            raise self._error
        return self._value


class FrameStager:
    """The staging lane: a single worker thread consuming a bounded FIFO of
    ``(fn, args)`` jobs, each resolved into a :class:`StagedHandle`.

    One lane per session (lazily built by the fused drain); the worker is a
    daemon with an idle timeout, so abandoned sessions cost a bounded wait,
    not a leaked thread.  ``stats()`` exports job/error counters for the
    bench row's overlap accounting.
    """

    def __init__(self, depth: int = 2) -> None:
        if depth < 1:
            raise ValueError(f"stager depth must be >= 1, got {depth}")
        self.depth = depth
        self._queue: "queue.Queue[Optional[Tuple]]" = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.staged = 0
        self.errors = 0
        #: optional obs-span hook: when set (a zero-arg callable returning
        #: a context manager, e.g. ``lambda: tracer.span("staging.stage")``)
        #: each job executes inside one — the caller-owned timing telemetry
        #: the module contract promises, still clock-free here (spans
        #: measure durations; this module never reads a wall clock)
        self.span_factory: Optional[Callable] = None

    # -- submission ----------------------------------------------------------

    def submit(self, fn: Callable, *args) -> StagedHandle:
        """Enqueue one staging job; blocks while ``depth`` jobs are already
        in flight (the double-buffer bound).  Returns the job's handle."""
        if self._closed:
            raise RuntimeError("FrameStager is closed")
        handle = StagedHandle()
        # enqueue BEFORE ensuring the worker: the idle-timeout retire path
        # re-checks queue emptiness under the lock, so a job published first
        # either keeps the racing worker alive or is picked up by the fresh
        # worker spawned below — a job can never land on a worker-less lane
        self._queue.put((fn, args, handle))
        self._ensure_worker()
        return handle

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="peritext-stager", daemon=True
                )
                self._thread.start()

    # -- the worker ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            try:
                job = self._queue.get(timeout=IDLE_TIMEOUT_SECONDS)
            except queue.Empty:
                with self._lock:
                    # re-check under the lock: a submit may have raced the
                    # timeout; if so keep serving, else retire this worker
                    if self._queue.empty():
                        if self._thread is threading.current_thread():
                            self._thread = None
                        return
                continue
            if job is None:  # close() sentinel
                return
            fn, args, handle = job
            try:
                factory = self.span_factory
                if factory is not None:
                    with factory():
                        value = fn(*args)
                else:
                    value = fn(*args)
            except BaseException as exc:  # graftlint: boundary(staging worker forwards every failure to the committing waiter verbatim)
                self.errors += 1
                handle._reject(exc)
            else:
                # count BEFORE resolving: a consumer reading stats() right
                # after handle.wait() returns must never see an undercount
                self.staged += 1
                handle._resolve(value)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop accepting jobs and let the worker drain then exit.  Already-
        submitted handles still resolve; idempotent."""
        if self._closed:
            return
        self._closed = True
        with self._lock:
            alive = self._thread is not None and self._thread.is_alive()
        if alive:
            self._queue.put(None)

    def stats(self) -> dict:
        return {"staged": self.staged, "errors": self.errors,
                "depth": self.depth}
