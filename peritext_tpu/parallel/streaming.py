"""Streaming pod-scale merge (BASELINE config 5).

The batch path (`api.batch.DocBatch`) converges a *closed* set of change logs
in one shot.  This session engine converges an *open* stream: changes for up
to ``num_docs`` documents arrive over time (``ingest``), and each ``step``
applies everything admissible as one incremental device round on top of the
carried-over packed state — the device never replays history.

TPU-shaped design decisions:

* **Static shapes** — one compiled program for the whole session: per-round
  op streams are padded to fixed ``round_*_capacity`` widths; a doc whose
  round overflows a width simply defers the excess to the next round (the
  host-side pending queue is the elastic buffer, the device sees a constant
  shape).
* **Doc-axis sharding** — with a ``Mesh``, every (D, ...) tensor is sharded
  over the doc axis; documents are independent so steps need no cross-shard
  communication.  Cross-shard collectives appear exactly where SURVEY §5.8
  predicts: the global convergence digest / frontier reductions
  (:meth:`digest`), which XLA lowers to an all-reduce over the mesh.
* **Async overlap** — ``step`` only *dispatches* device work (JAX async
  dispatch): the next round's host-side causal scheduling and encoding
  overlaps the current round's device apply.  Reads (:meth:`read`,
  :meth:`digest`) are the synchronization points.
* **Event-sourced durability** — the session retains per-doc change logs, so
  any doc can fall back to scalar replay (undeclared actor, non-text ops,
  capacity overflow) and a session can checkpoint/restore through
  ``peritext_tpu.checkpoint``.

The reference has no analog (its replication is per-replica in-memory
callbacks); this is the TPU-native replacement for "a server holding many
collaborative documents", per BASELINE.json config 5.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import native
from ..core.doc import Doc
from ..core.types import Change, Clock, FormatSpan
from ..observability import GLOBAL_COUNTERS
from ..ops.decode import decode_doc_spans
from ..ops.encode import DocEncoder, _DocStreams, pad_doc_streams
from ..ops.frames import (
    FrameIngestError,
    ParsedChanges,
    parse_frame,
    schedule_split,
)
from ..ops.kernel import apply_batch_jit, encoded_arrays_of
from ..ops.packed import PackedDocs, empty_docs
from ..ops.resolve import resolve_jit
from ..utils.interning import Interner, OrderedActorTable
from .causal import causal_schedule
from .codec import decode_frame, encode_frame
from .mesh import convergence_digest, shard_docs

_digest_jit = jax.jit(convergence_digest)


@dataclass
class _DocSession:
    encoder: Optional[DocEncoder] = None
    clock: Clock = field(default_factory=dict)
    pending: List[Change] = field(default_factory=list)
    log: List[Change] = field(default_factory=list)
    fallback: bool = False
    # frame-native mode (ops/frames.py): raw wire frames are the event source
    # and pending ops live as flat parsed arrays, never Python objects
    frame_mode: bool = False
    frames: List[bytes] = field(default_factory=list)
    parsed: Optional[ParsedChanges] = None
    clock_arr: Optional[np.ndarray] = None
    text_obj: int = 0
    attrs: Optional[Interner] = None


class StreamingMerge:
    """Incremental multi-round merge of up to ``num_docs`` documents.

    ``actors`` declares the replica set whose changes may arrive (needed up
    front: packed op-ID order requires a complete ordered actor table; an
    undeclared actor demotes that doc to scalar-replay fallback).
    """

    def __init__(
        self,
        num_docs: int,
        actors: Sequence[str],
        slot_capacity: int = 256,
        mark_capacity: int = 128,
        tomb_capacity: int = 128,
        round_insert_capacity: int = 64,
        round_delete_capacity: int = 32,
        round_mark_capacity: int = 32,
        comment_capacity: int = 32,
        read_chunk: int = 8192,
        mesh=None,
    ) -> None:
        self.num_docs = num_docs
        self.actors = list(actors)
        self.mesh = mesh
        self.round_caps = (round_insert_capacity, round_delete_capacity, round_mark_capacity)
        self.comment_capacity = comment_capacity
        # Sharding needs equal shards: pad the DEVICE doc axis up to a mesh
        # multiple; padded rows are permanently empty docs (all-zero streams
        # are no-ops) and are invisible in the public API (num_docs, reads).
        self._padded_docs = (
            -(-num_docs // mesh.size) * mesh.size if mesh is not None else num_docs
        )
        # reads resolve the doc axis in blocks of this size (see the
        # block-cached resolution section); meshed state is never sliced
        self._read_chunk_requested = read_chunk
        self._read_chunk = (
            self._padded_docs if mesh is not None else max(1, min(read_chunk, max(num_docs, 1)))
        )
        self.docs = [_DocSession() for _ in range(num_docs)]
        self.rounds = 0
        self._patch_base: Dict[int, list] = {}
        # per-round cache of numpy-resolved doc blocks: (rounds, {bi: resolved})
        self._resolved_cache = (-1, {})
        self._actor_table = OrderedActorTable(self.actors)
        state = empty_docs(self._padded_docs, slot_capacity, mark_capacity, tomb_capacity)
        self.state: PackedDocs = shard_docs(state, mesh) if mesh is not None else state

    # -- ingestion ---------------------------------------------------------

    def ingest(self, doc_index: int, changes: Iterable[Change]) -> None:
        """Queue newly-arrived changes for one document (any order, dups ok)."""
        sess = self.docs[doc_index]
        changes = list(changes)
        if sess.frame_mode:
            # the doc's pending state lives as parsed arrays; route object
            # arrivals through the same (cheap) frame parse
            self.ingest_frame(doc_index, encode_frame(changes))
            return
        sess.pending.extend(changes)

    def ingest_frame(self, doc_index: int, data: bytes) -> None:
        """Queue one binary change frame (the wire format a peer host ships,
        parallel/codec.py) for one document — the native fast path: the C++
        core parses the payload straight into flat arrays and no Python
        ``Change`` objects are built unless the doc leaves the fast path.
        Raises ValueError on corrupt frames (nothing is queued)."""
        sess = self.docs[doc_index]
        object_bound = sess.fallback or sess.encoder is not None or bool(
            sess.pending or sess.log
        )
        if (not sess.frame_mode and object_bound) or not native.available():
            self.ingest(doc_index, decode_frame(data))
            return
        if not sess.frame_mode:
            sess.frame_mode = True
            sess.attrs = Interner()
            sess.parsed = ParsedChanges.empty()
            sess.clock_arr = np.zeros(len(self._actor_table), np.int32)
        try:
            parsed, sess.text_obj = parse_frame(
                data, self._actor_table, sess.attrs, sess.text_obj
            )
        except FrameIngestError:
            self._demote_frame_doc(sess, extra=decode_frame(data))
            return
        sess.frames.append(data)
        sess.parsed = sess.parsed.concat(parsed)

    def _demote_frame_doc(self, sess: _DocSession, extra: List[Change] = ()) -> None:
        """Leave the fast path: the doc becomes a scalar-replay fallback fed
        by its decoded frame history (its device rows may already hold applied
        ops, so only the oracle path is still correct for it)."""
        changes = [ch for f in sess.frames for ch in decode_frame(f)]
        changes.extend(extra)
        sess.log.extend(changes)
        if sess.clock_arr is not None:
            # fold the applied frontier into the object-path clock so
            # frontier() stays truthful across the demotion
            for idx in np.nonzero(sess.clock_arr)[0]:
                actor = self._actor_table.lookup(int(idx))
                sess.clock[actor] = max(sess.clock.get(actor, 0), int(sess.clock_arr[idx]))
        sess.frame_mode = False
        sess.frames = []
        sess.parsed = None
        sess.clock_arr = None
        sess.text_obj = 0
        sess.attrs = None
        sess.fallback = True
        GLOBAL_COUNTERS.add("streaming.fallback_docs")

    # -- the incremental device round --------------------------------------

    def step(self) -> int:
        """Apply every admissible pending change in one device round.

        Returns the number of changes scheduled this round.  Device work is
        dispatched asynchronously; the caller may immediately ingest and
        schedule the next round while the TPU runs this one.
        """
        ki, kd, km = self.round_caps
        per_doc: List[_DocStreams] = []
        fallback_rows: List[int] = []
        scheduled = 0

        for i, sess in enumerate(self.docs):
            streams = _DocStreams()
            if sess.frame_mode:
                per_doc.append(streams)
                continue  # scheduled in the frame-native pass below
            if sess.pending and not sess.fallback:
                if sess.encoder is None:
                    sess.encoder = DocEncoder(self.actors)
                ordered, stuck = causal_schedule(sess.pending, sess.clock)
                # budget the round to the static stream widths: admit a
                # prefix whose stream usage fits; the rest waits (shapes stay
                # constant, docs just take extra rounds)
                admitted, deferred = self._budget(ordered, ki, kd, km)
                if not admitted and ordered and self._never_fits(ordered[0], ki, kd, km):
                    # a single change larger than a round width can never be
                    # admitted: demote instead of wedging the doc (and every
                    # change behind it) forever — the frame path's batched
                    # scheduler does the same via its demote status
                    sess.fallback = True
                    GLOBAL_COUNTERS.add("streaming.fallback_docs")
                streams, ok = sess.encoder.encode_increment(admitted)
                if not ok:
                    sess.fallback = True
                    streams = _DocStreams()
                    GLOBAL_COUNTERS.add("streaming.fallback_docs")
                else:
                    for ch in admitted:
                        sess.clock[ch.actor] = ch.seq
                    scheduled += len(admitted)
                sess.log.extend(admitted)
                sess.pending = deferred + stuck
                if sess.fallback:
                    # keep full history for scalar replay; nothing on device
                    sess.log.extend(deferred + stuck)
                    sess.pending = []
            elif sess.pending and sess.fallback:
                sess.log.extend(sess.pending)
                sess.pending = []
            if sess.fallback:
                fallback_rows.append(i)
            per_doc.append(streams)

        frame_docs = [
            i for i, s in enumerate(self.docs)
            if s.frame_mode and s.parsed is not None and s.parsed.num_changes
        ]
        if scheduled == 0 and not frame_docs:
            return 0

        pad_rows = self._padded_docs - self.num_docs
        encoded = pad_doc_streams(
            per_doc + [_DocStreams()] * pad_rows,
            list(fallback_rows),
            [s.encoder.actors if s.encoder else None for s in self.docs]
            + [None] * pad_rows,
            [s.encoder.attrs if s.encoder else None for s in self.docs]
            + [None] * pad_rows,
            insert_capacity=ki,
            delete_capacity=kd,
            mark_capacity=km,
        )

        # Frame-native pass: schedule + split every frame-mode doc's parsed
        # arrays directly into the padded rows.  With the native core this is
        # ONE C++ call for all docs per round (pt_schedule_split_batch); the
        # per-doc Python version is the no-native fallback.
        if frame_docs:
            scheduled += self._step_frame_docs(frame_docs, encoded, (ki, kd, km))

        if scheduled == 0:
            return 0
        arrays = encoded_arrays_of(encoded)
        if self.mesh is not None:
            arrays = shard_docs(arrays, self.mesh)
        self.state = apply_batch_jit(self.state, arrays)
        self.rounds += 1
        GLOBAL_COUNTERS.add("streaming.rounds")
        GLOBAL_COUNTERS.add("streaming.scheduled_changes", scheduled)
        return scheduled

    def _step_frame_docs(self, frame_docs, encoded, caps) -> int:
        """Round-schedule all frame-mode docs into their padded rows."""
        if not native.available():
            return self._step_frame_docs_python(frame_docs, encoded, caps)

        merged = ParsedChanges.concat_many([self.docs[i].parsed for i in frame_docs])
        ch_off = np.concatenate(
            [[0], np.cumsum([self.docs[i].parsed.num_changes for i in frame_docs])]
        ).astype(np.int32)
        # (F, n_actors) clock matrix: mutated in place by the native call
        clock = np.ascontiguousarray(
            np.stack([self.docs[i].clock_arr for i in frame_docs]), np.int32
        )
        batch = native.schedule_split_batch(
            len(self._actor_table),
            ch_off,
            np.asarray(frame_docs, np.int32),
            np.asarray([self.docs[i].text_obj for i in frame_docs], np.int32),
            (merged.ch_actor, merged.ch_seq, merged.dep_off,
             merged.dep_actor, merged.dep_seq, merged.ops_off, merged.ops),
            clock,
            caps,
            (encoded.ins_ref, encoded.ins_op, encoded.ins_char),
            encoded.del_target,
            encoded.marks,
        )
        if batch is None:  # pragma: no cover - available() checked above
            return self._step_frame_docs_python(frame_docs, encoded, caps)

        _, n_ins, n_del, n_mark, n_admitted, admitted, status = batch
        scheduled = 0
        for j, i in enumerate(frame_docs):
            sess = self.docs[i]
            flags = admitted[ch_off[j] : ch_off[j + 1]]
            if status[j]:
                self._demote_frame_doc(sess)  # rows already zeroed natively
                continue
            sess.clock_arr = clock[j].copy()
            if flags.all():  # common case: everything admitted or consumed
                sess.parsed = ParsedChanges.empty()
            else:
                sess.parsed = sess.parsed.select(np.nonzero(flags == 0)[0])
            encoded.mark_count[i] = int(n_mark[j])
            encoded.num_ops[i] = int(n_ins[j] + n_del[j] + n_mark[j])
            scheduled += int(n_admitted[j])
        return scheduled

    def _step_frame_docs_python(self, frame_docs, encoded, caps) -> int:
        """Per-doc Python fallback (no native library)."""
        ki, kd, km = caps
        scheduled = 0
        for i in frame_docs:
            sess = self.docs[i]
            try:
                nch, (ni, nd, nm), deferred = schedule_split(
                    sess.parsed,
                    sess.clock_arr,
                    sess.text_obj,
                    (ki, kd, km),
                    (encoded.ins_ref[i], encoded.ins_op[i], encoded.ins_char[i]),
                    encoded.del_target[i],
                    {col: encoded.marks[col][i] for col in encoded.marks},
                    len(self._actor_table),
                )
            except FrameIngestError:
                for col in encoded.marks:  # discard any partial row writes
                    encoded.marks[col][i] = 0
                encoded.ins_ref[i] = 0
                encoded.ins_op[i] = 0
                encoded.ins_char[i] = 0
                encoded.del_target[i] = 0
                self._demote_frame_doc(sess)
                continue
            sess.parsed = deferred
            encoded.mark_count[i] = nm
            encoded.num_ops[i] = ni + nd + nm
            scheduled += nch
        return scheduled

    def drain(self, max_rounds: int = 1_000) -> int:
        """Step until no pending change is admissible; returns rounds run."""
        rounds = 0
        while rounds < max_rounds and self.step() > 0:
            rounds += 1
        return rounds

    @staticmethod
    def _op_counts(change: Change) -> tuple:
        """(inserts, deletes, marks) — the round-width cost model shared by
        admission budgeting and the never-fits demotion check."""
        ci = sum(1 for op in change.ops if op.action == "set" and op.insert)
        cd = sum(1 for op in change.ops if op.action == "del")
        cm = sum(1 for op in change.ops if op.action in ("addMark", "removeMark"))
        return ci, cd, cm

    @classmethod
    def _never_fits(cls, change: Change, ki: int, kd: int, km: int) -> bool:
        ci, cd, cm = cls._op_counts(change)
        return ci > ki or cd > kd or cm > km

    @classmethod
    def _budget(cls, ordered: List[Change], ki: int, kd: int, km: int):
        """Admit the longest causal prefix whose op streams fit the static
        round widths."""
        ins = dels = marks = 0
        admitted: List[Change] = []
        for idx, ch in enumerate(ordered):
            ci, cd, cm = cls._op_counts(ch)
            if ins + ci > ki or dels + cd > kd or marks + cm > km:
                return admitted, ordered[idx:]
            ins, dels, marks = ins + ci, dels + cd, marks + cm
            admitted.append(ch)
        return admitted, []

    # -- reads (synchronization points) ------------------------------------

    @staticmethod
    def _replay_changes(sess: _DocSession) -> List[Change]:
        """A doc's full change history for scalar replay: decoded wire frames
        in frame mode, the object log otherwise."""
        if sess.frame_mode:
            return [ch for f in sess.frames for ch in decode_frame(f)]
        return sess.log + sess.pending

    @staticmethod
    def _attr_table(sess: _DocSession):
        if sess.frame_mode:
            return sess.attrs
        return sess.encoder.attrs if sess.encoder else None

    # -- block-cached resolution ------------------------------------------
    #
    # Reads resolve the doc axis in fixed-size BLOCKS: at 100K docs a full-
    # batch span resolution materializes multi-GB comment planes and OOMs
    # HBM, while any single read only needs its own block.  Blocks are
    # cached per round (the hot pattern: many per-doc reads between steps)
    # with at most two blocks resident.  Mesh sessions use one whole-batch
    # block: state is sharded across devices there, and slicing would
    # gather across shards.

    def _block_bounds(self, block_index: int):
        lo = block_index * self._read_chunk
        return lo, min(lo + self._read_chunk, self._padded_docs)

    def _state_block(self, block_index: int) -> PackedDocs:
        lo, hi = self._block_bounds(block_index)
        if lo == 0 and hi == self._padded_docs:
            return self.state
        return PackedDocs(*(x[lo:hi] for x in self.state))

    def _resolved_block(self, block_index: int):
        """Numpy-converted span resolution of one doc block, cached per
        round so per-doc reads between steps share device work."""
        stamp, cache = self._resolved_cache
        if stamp != self.rounds:
            cache = {}
            self._resolved_cache = (self.rounds, cache)
        if block_index in cache:
            resolved = cache.pop(block_index)  # re-insert: LRU, not FIFO
            cache[block_index] = resolved
            return resolved
        resolved = resolve_jit(self._state_block(block_index), self.comment_capacity)
        resolved = type(resolved)(*(np.asarray(x) for x in resolved))
        if len(cache) >= 2:  # bound host memory at large scale
            cache.pop(next(iter(cache)))  # least-recently-used
        cache[block_index] = resolved
        return resolved

    def _resolved_doc(self, doc_index: int):
        """(resolved block, index of the doc within it)."""
        bi = doc_index // self._read_chunk
        return self._resolved_block(bi), doc_index - bi * self._read_chunk

    def read(self, doc_index: int) -> List[FormatSpan]:
        sess = self.docs[doc_index]
        if sess.fallback:
            return _replay_spans(self._replay_changes(sess))
        resolved, local = self._resolved_doc(doc_index)
        if bool(resolved.overflow[local]):
            return _replay_spans(self._replay_changes(sess))
        return decode_doc_spans(resolved, local, self._attr_table(sess))

    def read_patches(self, doc_index: int) -> List:
        """Incremental reference-shaped patches since this doc's previous
        ``read_patches`` call (the first call builds the doc from empty) —
        config 5's "async patch scatter": device state is diffed host-side
        between reads (ops/patches.py), keyed on stable element identities,
        so editors receive the same patch vocabulary the scalar path emits
        (insert/delete/addMark/removeMark, testing/accumulate.py model)."""
        from ..ops.patches import diff_patches

        chars = self._doc_chars(doc_index)
        base = self._patch_base.get(doc_index, [])
        patches = diff_patches(base, chars)
        self._patch_base[doc_index] = chars
        return patches

    def _doc_chars(self, doc_index: int):
        from ..ops.patches import doc_chars_device, doc_chars_scalar

        sess = self.docs[doc_index]
        if sess.fallback:
            return doc_chars_scalar(_replay_doc(self._replay_changes(sess)))
        resolved, local = self._resolved_doc(doc_index)
        if bool(resolved.overflow[local]):
            return doc_chars_scalar(_replay_doc(self._replay_changes(sess)))
        return doc_chars_device(
            resolved,
            local,
            self._attr_table(sess),
            np.asarray(self.state.elem_id[doc_index]),
            self._actor_table,
        )

    def resolve_cursors(self, doc_index: int, cursors) -> List[int]:
        """Resolve stable cursors (reference ``Cursor`` dicts, src/
        micromerge.ts:859-870) for one doc; see resolve_cursors_batch."""
        return self.resolve_cursors_batch({doc_index: list(cursors)})[doc_index]

    def resolve_cursors_batch(self, cursor_map) -> Dict[int, List[int]]:
        """Resolve cursors for many docs in ONE batched device call
        (ops/resolve.resolve_cursors; width bucketed so varying counts reuse
        one compiled program).  ``cursor_map``: {doc_index: [Cursor, ...]}.
        Fallback and overflowed docs resolve via scalar replay.  Returns
        visible indices per doc, -1 for absent elements."""
        from ..ops.resolve import (
            oracle_cursor_positions,
            pack_cursor_rows,
            resolve_cursors_jit,
        )

        overflow = np.asarray(self.state.overflow)
        device_map, replay_docs = {}, []
        for d, cursors in cursor_map.items():
            if self.docs[d].fallback or bool(overflow[d]):
                replay_docs.append(d)
            else:
                device_map[d] = cursors

        out: Dict[int, List[int]] = {}
        by_block: Dict[int, Dict[int, list]] = {}
        for d, cursors in device_map.items():
            by_block.setdefault(d // self._read_chunk, {})[d] = cursors
        for bi, block_map in by_block.items():
            lo, hi = self._block_bounds(bi)
            local_map = {d - lo: c for d, c in block_map.items()}
            cursor_elem = pack_cursor_rows(
                local_map, hi - lo, lambda d: self._actor_table
            )
            resolved = self._resolved_block(bi)
            positions = np.asarray(
                resolve_cursors_jit(
                    self._state_block(bi), jnp.asarray(resolved.visible), cursor_elem
                )
            )
            for d, cursors in block_map.items():
                out[d] = [int(p) for p in positions[d - lo, : len(cursors)]]
        for d in replay_docs:
            doc = _replay_doc(self._replay_changes(self.docs[d]))
            out[d] = oracle_cursor_positions(doc, cursor_map[d])
        return out

    def read_all(self) -> List[List[FormatSpan]]:
        out: List[List[FormatSpan]] = []
        for i, sess in enumerate(self.docs):
            resolved, local = self._resolved_doc(i)
            if sess.fallback or bool(resolved.overflow[local]):
                out.append(_replay_spans(self._replay_changes(sess)))
            else:
                out.append(decode_doc_spans(resolved, local, self._attr_table(sess)))
        return out

    # -- cross-shard reductions (the ICI/DCN collectives) ------------------

    def digest(self) -> int:
        """Global convergence digest over every DEVICE-RESIDENT doc's visible
        text: with a mesh, XLA lowers the cross-doc reduction to an all-reduce
        over ICI.  Two sessions that converged hold equal digests.

        Fallback and overflowed docs are masked out — exactly the docs the
        read paths route to scalar replay: their truth lives host-side and
        their device rows may hold residue whose exact content depends on
        round partitioning (compare those docs via read()).

        The digest is a doc-sum of per-doc hashes, so it is computed per
        read-block and summed mod 2^32 — identical to the whole-batch value
        while bounding device memory at 100K-doc scale."""
        on_device_all = np.asarray(
            [not s.fallback for s in self.docs]
            + [False] * (self._padded_docs - self.num_docs),
            bool,
        )
        total = 0
        n_blocks = -(-self._padded_docs // self._read_chunk)
        for bi in range(n_blocks):
            lo, hi = self._block_bounds(bi)
            resolved = resolve_jit(self._state_block(bi), self.comment_capacity)
            mask = jnp.logical_and(
                jnp.asarray(on_device_all[lo:hi, None]),
                jnp.logical_not(resolved.overflow)[:, None],
            )
            visible = jnp.logical_and(resolved.visible, mask)
            total = (total + int(_digest_jit(resolved.char, visible))) & 0xFFFFFFFF
        return total

    # -- checkpoint support (peritext_tpu.checkpoint.save_session) ----------

    def doc_history_frames(self, doc_index: int) -> List[bytes]:
        """The doc's full ingested history as wire frames — the durable,
        event-sourced form (re-ingesting them reconstructs the doc exactly;
        duplicate-tolerant, so crash-replay overlap is safe).  Frame-mode
        docs return their raw frames; object/fallback docs re-encode their
        log (lossless: the codec JSON-spills anything exotic)."""
        sess = self.docs[doc_index]
        if sess.frame_mode:
            return list(sess.frames)
        changes = self._replay_changes(sess)
        return [encode_frame(changes)] if changes else []

    @property
    def config(self) -> Dict[str, int]:
        """Constructor-shape configuration (for checkpoint restore)."""
        return {
            "num_docs": self.num_docs,
            "slot_capacity": self.state.slot_capacity,
            "mark_capacity": self.state.mark_capacity,
            "tomb_capacity": self.state.tomb_capacity,
            "round_insert_capacity": self.round_caps[0],
            "round_delete_capacity": self.round_caps[1],
            "round_mark_capacity": self.round_caps[2],
            "comment_capacity": self.comment_capacity,
            # the REQUESTED value: a mesh session's effective block is its
            # whole padded batch, but a meshless restore must block reads
            "read_chunk": self._read_chunk_requested,
        }

    def frontier(self) -> Clock:
        """Merged vector-clock frontier across all docs (host-side metadata)."""
        merged: Clock = {}
        for sess in self.docs:
            if sess.frame_mode:
                for idx in np.nonzero(sess.clock_arr)[0]:
                    actor = self._actor_table.lookup(int(idx))
                    merged[actor] = max(merged.get(actor, 0), int(sess.clock_arr[idx]))
            else:
                for actor, seq in sess.clock.items():
                    merged[actor] = max(merged.get(actor, 0), seq)
        return merged

    def overflow_count(self) -> int:
        """Docs the device read path cannot serve: apply-time capacity
        overflow OR resolve-time errors (mark anchor not found, comment attr
        beyond capacity) — exactly the docs read() routes to scalar replay
        and digest() masks.  A nonzero count on a converged session means
        capacities should be raised for the workload (correctness is
        preserved via replay either way)."""
        n_blocks = -(-self._padded_docs // self._read_chunk)
        return sum(
            int(np.asarray(self._resolved_block(bi).overflow).sum())
            for bi in range(n_blocks)
        )

    def pending_count(self) -> int:
        return sum(
            (s.parsed.num_changes if s.frame_mode and s.parsed is not None else len(s.pending))
            for s in self.docs
        )


def _replay_doc(changes: List[Change]) -> Doc:
    doc = Doc("streaming-fallback")
    ordered, stuck = causal_schedule(changes)
    for ch in ordered:
        doc.apply_change(ch)
    return doc


def _replay_spans(changes: List[Change]) -> List[FormatSpan]:
    return _replay_doc(changes).get_text_with_formatting(["text"])


def rebalance(workload_sizes: Sequence[int], num_shards: int) -> List[List[int]]:
    """Greedy load-balance: assign doc indices to shards equalizing total op
    counts (host-side placement; docs are independent so no device
    all-to-all is needed — placement happens before transfer)."""
    order = sorted(range(len(workload_sizes)), key=lambda i: -workload_sizes[i])
    shards: List[List[int]] = [[] for _ in range(num_shards)]
    loads = [0] * num_shards
    for i in order:
        target = loads.index(min(loads))
        shards[target].append(i)
        loads[target] += workload_sizes[i]
    return shards
