"""Streaming pod-scale merge (BASELINE config 5).

The batch path (`api.batch.DocBatch`) converges a *closed* set of change logs
in one shot.  This session engine converges an *open* stream: changes for up
to ``num_docs`` documents arrive over time (``ingest``), and each ``step``
applies everything admissible as one incremental device round on top of the
carried-over packed state — the device never replays history.

TPU-shaped design decisions:

* **Static shapes** — one compiled program for the whole session: per-round
  op streams are padded to fixed ``round_*_capacity`` widths; a doc whose
  round overflows a width simply defers the excess to the next round (the
  host-side pending queue is the elastic buffer, the device sees a constant
  shape).
* **Doc-axis sharding** — with a ``Mesh``, every (D, ...) tensor is sharded
  over the doc axis; documents are independent so steps need no cross-shard
  communication.  Cross-shard collectives appear exactly where SURVEY §5.8
  predicts: the global convergence digest / frontier reductions
  (:meth:`digest`), which XLA lowers to an all-reduce over the mesh.
* **Async overlap** — ``step`` only *dispatches* device work (JAX async
  dispatch): the next round's host-side causal scheduling and encoding
  overlaps the current round's device apply.  Reads (:meth:`read`,
  :meth:`digest`) are the synchronization points.
* **Event-sourced durability** — the session retains per-doc change logs, so
  any doc can fall back to scalar replay (undeclared actor, non-text ops,
  capacity overflow) and a session can checkpoint/restore through
  ``peritext_tpu.checkpoint``.

The reference has no analog (its replication is per-replica in-memory
callbacks); this is the TPU-native replacement for "a server holding many
collaborative documents", per BASELINE.json config 5.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import native
from ..core.doc import Doc
from ..core.types import Change, Clock, FormatSpan
from ..observability import GLOBAL_COUNTERS
from ..ops.decode import decode_doc_spans
from ..ops.encode import DocEncoder, _DocStreams, pad_doc_streams
from ..ops.frames import (
    FrameIngestError,
    ParsedChanges,
    parse_frame,
    schedule_split,
)
from ..ops.kernel import apply_batch_jit, encoded_arrays_of
from ..ops.packed import PackedDocs, empty_docs
from ..ops.resolve import resolve_jit
from ..utils.interning import Interner, OrderedActorTable
from .causal import causal_schedule
from .codec import decode_frame, encode_frame
from .mesh import convergence_digest, shard_docs


@dataclass
class _DocSession:
    encoder: Optional[DocEncoder] = None
    clock: Clock = field(default_factory=dict)
    pending: List[Change] = field(default_factory=list)
    log: List[Change] = field(default_factory=list)
    fallback: bool = False
    # frame-native mode (ops/frames.py): raw wire frames are the event source
    # and pending ops live as flat parsed arrays, never Python objects
    frame_mode: bool = False
    frames: List[bytes] = field(default_factory=list)
    parsed: Optional[ParsedChanges] = None
    clock_arr: Optional[np.ndarray] = None
    text_obj: int = 0
    attrs: Optional[Interner] = None


class StreamingMerge:
    """Incremental multi-round merge of up to ``num_docs`` documents.

    ``actors`` declares the replica set whose changes may arrive (needed up
    front: packed op-ID order requires a complete ordered actor table; an
    undeclared actor demotes that doc to scalar-replay fallback).
    """

    def __init__(
        self,
        num_docs: int,
        actors: Sequence[str],
        slot_capacity: int = 256,
        mark_capacity: int = 128,
        tomb_capacity: int = 128,
        round_insert_capacity: int = 64,
        round_delete_capacity: int = 32,
        round_mark_capacity: int = 32,
        comment_capacity: int = 32,
        mesh=None,
    ) -> None:
        self.num_docs = num_docs
        self.actors = list(actors)
        self.mesh = mesh
        self.round_caps = (round_insert_capacity, round_delete_capacity, round_mark_capacity)
        self.comment_capacity = comment_capacity
        # Sharding needs equal shards: pad the DEVICE doc axis up to a mesh
        # multiple; padded rows are permanently empty docs (all-zero streams
        # are no-ops) and are invisible in the public API (num_docs, reads).
        self._padded_docs = (
            -(-num_docs // mesh.size) * mesh.size if mesh is not None else num_docs
        )
        self.docs = [_DocSession() for _ in range(num_docs)]
        self.rounds = 0
        self._patch_base: Dict[int, list] = {}
        self._resolved_cache = None  # (rounds, numpy ResolvedDocs)
        self._actor_table = OrderedActorTable(self.actors)
        state = empty_docs(self._padded_docs, slot_capacity, mark_capacity, tomb_capacity)
        self.state: PackedDocs = shard_docs(state, mesh) if mesh is not None else state

    # -- ingestion ---------------------------------------------------------

    def ingest(self, doc_index: int, changes: Iterable[Change]) -> None:
        """Queue newly-arrived changes for one document (any order, dups ok)."""
        sess = self.docs[doc_index]
        changes = list(changes)
        if sess.frame_mode:
            # the doc's pending state lives as parsed arrays; route object
            # arrivals through the same (cheap) frame parse
            self.ingest_frame(doc_index, encode_frame(changes))
            return
        sess.pending.extend(changes)

    def ingest_frame(self, doc_index: int, data: bytes) -> None:
        """Queue one binary change frame (the wire format a peer host ships,
        parallel/codec.py) for one document — the native fast path: the C++
        core parses the payload straight into flat arrays and no Python
        ``Change`` objects are built unless the doc leaves the fast path.
        Raises ValueError on corrupt frames (nothing is queued)."""
        sess = self.docs[doc_index]
        object_bound = sess.fallback or sess.encoder is not None or bool(
            sess.pending or sess.log
        )
        if (not sess.frame_mode and object_bound) or not native.available():
            self.ingest(doc_index, decode_frame(data))
            return
        if not sess.frame_mode:
            sess.frame_mode = True
            sess.attrs = Interner()
            sess.parsed = ParsedChanges.empty()
            sess.clock_arr = np.zeros(len(self._actor_table), np.int32)
        try:
            parsed, sess.text_obj = parse_frame(
                data, self._actor_table, sess.attrs, sess.text_obj
            )
        except FrameIngestError:
            self._demote_frame_doc(sess, extra=decode_frame(data))
            return
        sess.frames.append(data)
        sess.parsed = sess.parsed.concat(parsed)

    def _demote_frame_doc(self, sess: _DocSession, extra: List[Change] = ()) -> None:
        """Leave the fast path: the doc becomes a scalar-replay fallback fed
        by its decoded frame history (its device rows may already hold applied
        ops, so only the oracle path is still correct for it)."""
        changes = [ch for f in sess.frames for ch in decode_frame(f)]
        changes.extend(extra)
        sess.log.extend(changes)
        if sess.clock_arr is not None:
            # fold the applied frontier into the object-path clock so
            # frontier() stays truthful across the demotion
            for idx in np.nonzero(sess.clock_arr)[0]:
                actor = self._actor_table.lookup(int(idx))
                sess.clock[actor] = max(sess.clock.get(actor, 0), int(sess.clock_arr[idx]))
        sess.frame_mode = False
        sess.frames = []
        sess.parsed = None
        sess.clock_arr = None
        sess.text_obj = 0
        sess.attrs = None
        sess.fallback = True
        GLOBAL_COUNTERS.add("streaming.fallback_docs")

    # -- the incremental device round --------------------------------------

    def step(self) -> int:
        """Apply every admissible pending change in one device round.

        Returns the number of changes scheduled this round.  Device work is
        dispatched asynchronously; the caller may immediately ingest and
        schedule the next round while the TPU runs this one.
        """
        ki, kd, km = self.round_caps
        per_doc: List[_DocStreams] = []
        fallback_rows: List[int] = []
        scheduled = 0

        for i, sess in enumerate(self.docs):
            streams = _DocStreams()
            if sess.frame_mode:
                per_doc.append(streams)
                continue  # scheduled in the frame-native pass below
            if sess.pending and not sess.fallback:
                if sess.encoder is None:
                    sess.encoder = DocEncoder(self.actors)
                ordered, stuck = causal_schedule(sess.pending, sess.clock)
                # budget the round to the static stream widths: admit a
                # prefix whose stream usage fits; the rest waits (shapes stay
                # constant, docs just take extra rounds)
                admitted, deferred = self._budget(ordered, ki, kd, km)
                if not admitted and ordered and self._never_fits(ordered[0], ki, kd, km):
                    # a single change larger than a round width can never be
                    # admitted: demote instead of wedging the doc (and every
                    # change behind it) forever — the frame path's batched
                    # scheduler does the same via its demote status
                    sess.fallback = True
                    GLOBAL_COUNTERS.add("streaming.fallback_docs")
                streams, ok = sess.encoder.encode_increment(admitted)
                if not ok:
                    sess.fallback = True
                    streams = _DocStreams()
                    GLOBAL_COUNTERS.add("streaming.fallback_docs")
                else:
                    for ch in admitted:
                        sess.clock[ch.actor] = ch.seq
                    scheduled += len(admitted)
                sess.log.extend(admitted)
                sess.pending = deferred + stuck
                if sess.fallback:
                    # keep full history for scalar replay; nothing on device
                    sess.log.extend(deferred + stuck)
                    sess.pending = []
            elif sess.pending and sess.fallback:
                sess.log.extend(sess.pending)
                sess.pending = []
            if sess.fallback:
                fallback_rows.append(i)
            per_doc.append(streams)

        frame_docs = [
            i for i, s in enumerate(self.docs)
            if s.frame_mode and s.parsed is not None and s.parsed.num_changes
        ]
        if scheduled == 0 and not frame_docs:
            return 0

        pad_rows = self._padded_docs - self.num_docs
        encoded = pad_doc_streams(
            per_doc + [_DocStreams()] * pad_rows,
            list(fallback_rows),
            [s.encoder.actors if s.encoder else None for s in self.docs]
            + [None] * pad_rows,
            [s.encoder.attrs if s.encoder else None for s in self.docs]
            + [None] * pad_rows,
            insert_capacity=ki,
            delete_capacity=kd,
            mark_capacity=km,
        )

        # Frame-native pass: schedule + split every frame-mode doc's parsed
        # arrays directly into the padded rows.  With the native core this is
        # ONE C++ call for all docs per round (pt_schedule_split_batch); the
        # per-doc Python version is the no-native fallback.
        if frame_docs:
            scheduled += self._step_frame_docs(frame_docs, encoded, (ki, kd, km))

        if scheduled == 0:
            return 0
        arrays = encoded_arrays_of(encoded)
        if self.mesh is not None:
            arrays = shard_docs(arrays, self.mesh)
        self.state = apply_batch_jit(self.state, arrays)
        self.rounds += 1
        GLOBAL_COUNTERS.add("streaming.rounds")
        GLOBAL_COUNTERS.add("streaming.scheduled_changes", scheduled)
        return scheduled

    def _step_frame_docs(self, frame_docs, encoded, caps) -> int:
        """Round-schedule all frame-mode docs into their padded rows."""
        if not native.available():
            return self._step_frame_docs_python(frame_docs, encoded, caps)

        merged = ParsedChanges.concat_many([self.docs[i].parsed for i in frame_docs])
        ch_off = np.concatenate(
            [[0], np.cumsum([self.docs[i].parsed.num_changes for i in frame_docs])]
        ).astype(np.int32)
        # (F, n_actors) clock matrix: mutated in place by the native call
        clock = np.ascontiguousarray(
            np.stack([self.docs[i].clock_arr for i in frame_docs]), np.int32
        )
        batch = native.schedule_split_batch(
            len(self._actor_table),
            ch_off,
            np.asarray(frame_docs, np.int32),
            np.asarray([self.docs[i].text_obj for i in frame_docs], np.int32),
            (merged.ch_actor, merged.ch_seq, merged.dep_off,
             merged.dep_actor, merged.dep_seq, merged.ops_off, merged.ops),
            clock,
            caps,
            (encoded.ins_ref, encoded.ins_op, encoded.ins_char),
            encoded.del_target,
            encoded.marks,
        )
        if batch is None:  # pragma: no cover - available() checked above
            return self._step_frame_docs_python(frame_docs, encoded, caps)

        _, n_ins, n_del, n_mark, n_admitted, admitted, status = batch
        scheduled = 0
        for j, i in enumerate(frame_docs):
            sess = self.docs[i]
            flags = admitted[ch_off[j] : ch_off[j + 1]]
            if status[j]:
                self._demote_frame_doc(sess)  # rows already zeroed natively
                continue
            sess.clock_arr = clock[j].copy()
            if flags.all():  # common case: everything admitted or consumed
                sess.parsed = ParsedChanges.empty()
            else:
                sess.parsed = sess.parsed.select(np.nonzero(flags == 0)[0])
            encoded.mark_count[i] = int(n_mark[j])
            encoded.num_ops[i] = int(n_ins[j] + n_del[j] + n_mark[j])
            scheduled += int(n_admitted[j])
        return scheduled

    def _step_frame_docs_python(self, frame_docs, encoded, caps) -> int:
        """Per-doc Python fallback (no native library)."""
        ki, kd, km = caps
        scheduled = 0
        for i in frame_docs:
            sess = self.docs[i]
            try:
                nch, (ni, nd, nm), deferred = schedule_split(
                    sess.parsed,
                    sess.clock_arr,
                    sess.text_obj,
                    (ki, kd, km),
                    (encoded.ins_ref[i], encoded.ins_op[i], encoded.ins_char[i]),
                    encoded.del_target[i],
                    {col: encoded.marks[col][i] for col in encoded.marks},
                    len(self._actor_table),
                )
            except FrameIngestError:
                for col in encoded.marks:  # discard any partial row writes
                    encoded.marks[col][i] = 0
                encoded.ins_ref[i] = 0
                encoded.ins_op[i] = 0
                encoded.ins_char[i] = 0
                encoded.del_target[i] = 0
                self._demote_frame_doc(sess)
                continue
            sess.parsed = deferred
            encoded.mark_count[i] = nm
            encoded.num_ops[i] = ni + nd + nm
            scheduled += nch
        return scheduled

    def drain(self, max_rounds: int = 1_000) -> int:
        """Step until no pending change is admissible; returns rounds run."""
        rounds = 0
        while rounds < max_rounds and self.step() > 0:
            rounds += 1
        return rounds

    @staticmethod
    def _op_counts(change: Change) -> tuple:
        """(inserts, deletes, marks) — the round-width cost model shared by
        admission budgeting and the never-fits demotion check."""
        ci = sum(1 for op in change.ops if op.action == "set" and op.insert)
        cd = sum(1 for op in change.ops if op.action == "del")
        cm = sum(1 for op in change.ops if op.action in ("addMark", "removeMark"))
        return ci, cd, cm

    @classmethod
    def _never_fits(cls, change: Change, ki: int, kd: int, km: int) -> bool:
        ci, cd, cm = cls._op_counts(change)
        return ci > ki or cd > kd or cm > km

    @classmethod
    def _budget(cls, ordered: List[Change], ki: int, kd: int, km: int):
        """Admit the longest causal prefix whose op streams fit the static
        round widths."""
        ins = dels = marks = 0
        admitted: List[Change] = []
        for idx, ch in enumerate(ordered):
            ci, cd, cm = cls._op_counts(ch)
            if ins + ci > ki or dels + cd > kd or marks + cm > km:
                return admitted, ordered[idx:]
            ins, dels, marks = ins + ci, dels + cd, marks + cm
            admitted.append(ch)
        return admitted, []

    # -- reads (synchronization points) ------------------------------------

    @staticmethod
    def _replay_changes(sess: _DocSession) -> List[Change]:
        """A doc's full change history for scalar replay: decoded wire frames
        in frame mode, the object log otherwise."""
        if sess.frame_mode:
            return [ch for f in sess.frames for ch in decode_frame(f)]
        return sess.log + sess.pending

    @staticmethod
    def _attr_table(sess: _DocSession):
        if sess.frame_mode:
            return sess.attrs
        return sess.encoder.attrs if sess.encoder else None

    def _resolved_numpy(self):
        """Numpy-converted span resolution of the current device state,
        cached per round: read/read_all/read_patches called per doc between
        steps share ONE device resolve + host transfer instead of D."""
        if self._resolved_cache is not None and self._resolved_cache[0] == self.rounds:
            return self._resolved_cache[1]
        resolved = resolve_jit(self.state, self.comment_capacity)
        resolved = type(resolved)(*(np.asarray(x) for x in resolved))
        self._resolved_cache = (self.rounds, resolved)
        return resolved

    def read(self, doc_index: int) -> List[FormatSpan]:
        sess = self.docs[doc_index]
        overflow = bool(np.asarray(self.state.overflow)[doc_index])
        if sess.fallback or overflow:
            return _replay_spans(self._replay_changes(sess))
        resolved = self._resolved_numpy()
        return decode_doc_spans(resolved, doc_index, self._attr_table(sess))

    def read_patches(self, doc_index: int) -> List:
        """Incremental reference-shaped patches since this doc's previous
        ``read_patches`` call (the first call builds the doc from empty) —
        config 5's "async patch scatter": device state is diffed host-side
        between reads (ops/patches.py), keyed on stable element identities,
        so editors receive the same patch vocabulary the scalar path emits
        (insert/delete/addMark/removeMark, testing/accumulate.py model)."""
        from ..ops.patches import diff_patches

        chars = self._doc_chars(doc_index)
        base = self._patch_base.get(doc_index, [])
        patches = diff_patches(base, chars)
        self._patch_base[doc_index] = chars
        return patches

    def _doc_chars(self, doc_index: int):
        from ..ops.patches import doc_chars_device, doc_chars_scalar

        sess = self.docs[doc_index]
        overflow = bool(np.asarray(self.state.overflow)[doc_index])
        if sess.fallback or overflow:
            return doc_chars_scalar(_replay_doc(self._replay_changes(sess)))
        resolved = self._resolved_numpy()
        return doc_chars_device(
            resolved,
            doc_index,
            self._attr_table(sess),
            np.asarray(self.state.elem_id)[doc_index],
            self._actor_table,
        )

    def resolve_cursors(self, doc_index: int, cursors) -> List[int]:
        """Resolve stable cursors (reference ``Cursor`` dicts, src/
        micromerge.ts:859-870) for one doc; see resolve_cursors_batch."""
        return self.resolve_cursors_batch({doc_index: list(cursors)})[doc_index]

    def resolve_cursors_batch(self, cursor_map) -> Dict[int, List[int]]:
        """Resolve cursors for many docs in ONE batched device call
        (ops/resolve.resolve_cursors; width bucketed so varying counts reuse
        one compiled program).  ``cursor_map``: {doc_index: [Cursor, ...]}.
        Fallback and overflowed docs resolve via scalar replay.  Returns
        visible indices per doc, -1 for absent elements."""
        from ..ops.resolve import (
            oracle_cursor_positions,
            pack_cursor_rows,
            resolve_cursors_jit,
        )

        overflow = np.asarray(self.state.overflow)
        device_map, replay_docs = {}, []
        for d, cursors in cursor_map.items():
            if self.docs[d].fallback or bool(overflow[d]):
                replay_docs.append(d)
            else:
                device_map[d] = cursors

        out: Dict[int, List[int]] = {}
        if device_map:
            cursor_elem = pack_cursor_rows(
                device_map, self._padded_docs, lambda d: self._actor_table
            )
            resolved = self._resolved_numpy()
            positions = np.asarray(
                resolve_cursors_jit(
                    self.state, jnp.asarray(resolved.visible), cursor_elem
                )
            )
            for d, cursors in device_map.items():
                out[d] = [int(p) for p in positions[d, : len(cursors)]]
        for d in replay_docs:
            doc = _replay_doc(self._replay_changes(self.docs[d]))
            out[d] = oracle_cursor_positions(doc, cursor_map[d])
        return out

    def read_all(self) -> List[List[FormatSpan]]:
        resolved = self._resolved_numpy()
        overflow = np.asarray(resolved.overflow)
        out: List[List[FormatSpan]] = []
        for i, sess in enumerate(self.docs):
            if sess.fallback or bool(overflow[i]):
                out.append(_replay_spans(self._replay_changes(sess)))
            else:
                out.append(decode_doc_spans(resolved, i, self._attr_table(sess)))
        return out

    # -- cross-shard reductions (the ICI/DCN collectives) ------------------

    def digest(self) -> int:
        """Global convergence digest over every DEVICE-RESIDENT doc's visible
        text: with a mesh, XLA lowers the cross-doc reduction to an all-reduce
        over ICI.  Two sessions that converged hold equal digests.

        Fallback and overflowed docs are masked out — exactly the docs the
        read paths route to scalar replay: their truth lives host-side and
        their device rows may hold residue whose exact content depends on
        round partitioning (compare those docs via read())."""
        resolved = resolve_jit(self.state, self.comment_capacity)
        on_device = np.asarray(
            [not s.fallback for s in self.docs]
            + [False] * (self._padded_docs - self.num_docs),
            bool,
        )[:, None]  # (padded D, 1)
        mask = jnp.logical_and(
            jnp.asarray(on_device), jnp.logical_not(resolved.overflow)[:, None]
        )
        visible = jnp.logical_and(resolved.visible, mask)
        return int(jax.jit(convergence_digest)(resolved.char, visible))

    # -- checkpoint support (peritext_tpu.checkpoint.save_session) ----------

    def doc_history_frames(self, doc_index: int) -> List[bytes]:
        """The doc's full ingested history as wire frames — the durable,
        event-sourced form (re-ingesting them reconstructs the doc exactly;
        duplicate-tolerant, so crash-replay overlap is safe).  Frame-mode
        docs return their raw frames; object/fallback docs re-encode their
        log (lossless: the codec JSON-spills anything exotic)."""
        sess = self.docs[doc_index]
        if sess.frame_mode:
            return list(sess.frames)
        changes = self._replay_changes(sess)
        return [encode_frame(changes)] if changes else []

    @property
    def config(self) -> Dict[str, int]:
        """Constructor-shape configuration (for checkpoint restore)."""
        return {
            "num_docs": self.num_docs,
            "slot_capacity": self.state.slot_capacity,
            "mark_capacity": self.state.mark_capacity,
            "tomb_capacity": self.state.tomb_capacity,
            "round_insert_capacity": self.round_caps[0],
            "round_delete_capacity": self.round_caps[1],
            "round_mark_capacity": self.round_caps[2],
            "comment_capacity": self.comment_capacity,
        }

    def frontier(self) -> Clock:
        """Merged vector-clock frontier across all docs (host-side metadata)."""
        merged: Clock = {}
        for sess in self.docs:
            if sess.frame_mode:
                for idx in np.nonzero(sess.clock_arr)[0]:
                    actor = self._actor_table.lookup(int(idx))
                    merged[actor] = max(merged.get(actor, 0), int(sess.clock_arr[idx]))
            else:
                for actor, seq in sess.clock.items():
                    merged[actor] = max(merged.get(actor, 0), seq)
        return merged

    def pending_count(self) -> int:
        return sum(
            (s.parsed.num_changes if s.frame_mode and s.parsed is not None else len(s.pending))
            for s in self.docs
        )


def _replay_doc(changes: List[Change]) -> Doc:
    doc = Doc("streaming-fallback")
    ordered, stuck = causal_schedule(changes)
    for ch in ordered:
        doc.apply_change(ch)
    return doc


def _replay_spans(changes: List[Change]) -> List[FormatSpan]:
    return _replay_doc(changes).get_text_with_formatting(["text"])


def rebalance(workload_sizes: Sequence[int], num_shards: int) -> List[List[int]]:
    """Greedy load-balance: assign doc indices to shards equalizing total op
    counts (host-side placement; docs are independent so no device
    all-to-all is needed — placement happens before transfer)."""
    order = sorted(range(len(workload_sizes)), key=lambda i: -workload_sizes[i])
    shards: List[List[int]] = [[] for _ in range(num_shards)]
    loads = [0] * num_shards
    for i in order:
        target = loads.index(min(loads))
        shards[target].append(i)
        loads[target] += workload_sizes[i]
    return shards
