"""Streaming pod-scale merge (BASELINE config 5).

The batch path (`api.batch.DocBatch`) converges a *closed* set of change logs
in one shot.  This session engine converges an *open* stream: changes for up
to ``num_docs`` documents arrive over time (``ingest``), and each ``step``
applies everything admissible as one incremental device round on top of the
carried-over packed state — the device never replays history.

TPU-shaped design decisions:

* **Static shapes** — one compiled program for the whole session: per-round
  op streams are padded to fixed ``round_*_capacity`` widths; a doc whose
  round overflows a width simply defers the excess to the next round (the
  host-side pending queue is the elastic buffer, the device sees a constant
  shape).
* **Doc-axis sharding** — with a ``Mesh``, every (D, ...) tensor is sharded
  over the doc axis; documents are independent so steps need no cross-shard
  communication.  Cross-shard collectives appear exactly where SURVEY §5.8
  predicts: the global convergence digest / frontier reductions
  (:meth:`digest`), which XLA lowers to an all-reduce over the mesh.
* **Async overlap** — ``step`` only *dispatches* device work (JAX async
  dispatch): the next round's host-side causal scheduling and encoding
  overlaps the current round's device apply.  Reads (:meth:`read`,
  :meth:`digest`) are the synchronization points.
* **Event-sourced durability** — the session retains per-doc change logs, so
  any doc can fall back to scalar replay (undeclared actor, non-text ops,
  capacity overflow) and a session can checkpoint/restore through
  ``peritext_tpu.checkpoint``.

The reference has no analog (its replication is per-replica in-memory
callbacks); this is the TPU-native replacement for "a server holding many
collaborative documents", per BASELINE.json config 5.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import native
from ..core.doc import Doc
from ..core.errors import DecodeError
from ..core.types import Change, Clock, FormatSpan
from ..obs import (
    GLOBAL_COUNTERS,
    GLOBAL_DEVPROF,
    GLOBAL_HISTOGRAMS,
    GLOBAL_TRACER,
    MergeStats,
    SIZE_BUCKETS,
    TraceContext,
    note_jit_dispatch,
    occupancy_key,
)
from ..ops.decode import decode_doc_spans
from ..ops.encode import DocEncoder, _DocStreams
from ..ops.encode import MAP_STREAM_COLS, MARK_COLS
from ..ops.frames import (
    FRAME_CORRUPT,
    FRAME_DEMOTE,
    FRAME_OK,
    KIND_MARK,
    FrameIngestError,
    ParsedChanges,
    parse_frames_bulk,
    schedule_split,
)
from ..schema import MARK_INDEX
from ..ops.kernel import (
    apply_batch_jit,
    apply_batch_staged_rounds,
    apply_batch_staged_rounds_jit,
    apply_batch_stacked_rounds,
    apply_batch_stacked_rounds_jit,
    apply_batch_stacked_rounds_multi_jit,
    encoded_arrays_of,
    resolve_insert_impl,
    resolve_state_donation,
)
from ..ops.packed import PackedDocs, empty_docs
from ..ops.resolve import resolve, resolve_jit
from ..utils.interning import Interner, OrderedActorTable
from ..utils.shapes import next_pow2
from .causal import causal_schedule
from .codec import decode_frame, encode_frame, strip_trace_context
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DOC_AXIS, convergence_digest, shard_docs

@partial(jax.jit, static_argnums=1)
def _resolve_digest_jit(state: PackedDocs, comment_capacity: int, row_mask):
    """Fused span resolution + TEXT-ONLY convergence digest in ONE program:
    resolution runs with the comment planes compiled away (this digest never
    reads them — resolve.py ``with_comments``), and only the scalar digest
    plus the overflow vector ever reach the host."""
    resolved = resolve(state, comment_capacity, with_comments=False)
    mask = row_mask & ~resolved.overflow
    # masked docs contribute ZERO (not the pad constant): their host-side
    # replay hash is summed in instead (digest())
    return (
        convergence_digest(resolved.char, resolved.visible, doc_mask=mask),
        resolved.overflow,
    )


def _per_doc_full_digest(state, resolved, row_mask,
                         sess_attr, sess_key, comment_hash, row_map,
                         obj_attr, obj_key):
    """(D,) uint32 per-doc FULL-STATE hashes — visible text, resolved
    formatting (LWW winner bits, link url, comment-id sets) and the
    map-register table.  The reference's convergence oracles compare full
    formatted text (test/fuzz.ts:245-278), and cross-replica map state is
    part of the document too.  Interned identities enter only through the
    session content-hash tables (``sess_attr``/``sess_key``, flat (A,)/(K,)
    uint32, broadcast to rows HERE — shipping a pre-broadcast (D, A) table
    through a tunneled device link was the entire digest-stage cost) plus
    the sparse object-path overrides (``row_map``/``obj_attr``/``obj_key``),
    so digests are comparable across sessions with different intern orders.
    Masked or overflowed rows contribute ZERO (their host-side replay hash
    is summed in instead)."""
    from ..ops.packed import VK_DELETED, VK_STR
    from ..ops.resolve import COMMENT_TYPE, LINK_TYPE
    from .mesh import per_doc_format_digest, per_doc_register_digest, per_doc_text_digest

    d = row_map.shape[0]
    if obj_attr.shape[0]:  # static: compiled only when object docs exist
        safe = jnp.clip(row_map, 0, obj_attr.shape[0] - 1)
        is_obj = (row_map >= 0)[:, None]
        attr_hash = jnp.where(is_obj, obj_attr[safe], sess_attr[None, :])
        key_hash = jnp.where(is_obj, obj_key[safe], sess_key[None, :])
    else:
        attr_hash = jnp.broadcast_to(sess_attr[None, :], (d, sess_attr.shape[0]))
        key_hash = jnp.broadcast_to(sess_key[None, :], (d, sess_key.shape[0]))

    mask = row_mask & ~resolved.overflow
    per_doc = per_doc_text_digest(resolved.char, resolved.visible)
    per_doc = per_doc + per_doc_format_digest(
        resolved.visible, resolved.lww_active, resolved.link_attr,
        resolved.comment_bits, attr_hash, comment_hash,
        COMMENT_TYPE, LINK_TYPE,
    )
    per_doc = per_doc + per_doc_register_digest(
        state.r_obj, state.r_key, state.r_op, state.r_kind, state.r_val,
        key_hash, VK_DELETED, VK_STR,
    )
    return jnp.where(mask, per_doc, jnp.uint32(0))


@partial(jax.jit, static_argnums=1)
def _resolve_block_digest_jit(
    state: PackedDocs, comment_capacity: int, row_mask,
    sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
):
    """ONE program per block and round: span resolution (what every read
    path needs) PLUS the (D,) per-doc full-state hash vector (see
    :func:`_per_doc_full_digest`).  Returning both from one program means
    digest() and the read paths share the per-round resolution work (the
    block cache), and a digest-only sync point fetches just the per-doc
    vector + overflow — not the (D, S) planes.  The vector (not a scalar)
    comes back so the carried per-ROW digest plane can absorb it: later
    rounds re-hash only the rows they touch."""
    resolved = resolve(state, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        state, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    return resolved, per_doc


@jax.jit
def _concat_state_jit(*blocks: PackedDocs) -> PackedDocs:
    """Reassemble block-chunked apply outputs along the doc axis (one fused
    device program; compiled once per block count)."""
    return PackedDocs(*(jnp.concatenate(xs, axis=0) for xs in zip(*blocks)))


@partial(jax.jit, static_argnums=1)
def _split_blocks_jit(state: tuple, bounds: tuple):
    return tuple(
        tuple(x[lo:hi] for x in state) for lo, hi in bounds
    )


def _split_blocks(state: PackedDocs, bounds: tuple):
    """Slice session state into per-block states as ONE device program.

    The obvious `x[lo:hi]` per leaf per block dispatches n_blocks x 21
    separate slice programs — ~0.1 s each through the axon tunnel, which
    made the first chunked round's block-list construction cost ~4.5 s at
    16K docs (round-5 ingest profile) and ~27 s at 100K.  One jitted
    program (static bounds: compiled once per session shape) returns every
    block in a single dispatch."""
    return [PackedDocs(*b) for b in _split_blocks_jit(tuple(state), bounds)]




def _gather_rows(state: PackedDocs, rows_idx, mesh) -> PackedDocs:
    """K-row gather along the doc axis for the touched-rows digest.

    Meshless: one jitted fancy-index gather.  Mesh: an explicit shard_map —
    each device selects the rows its shard owns (zeros elsewhere) and a
    psum merges them — because the SPMD partitioner lowers a dynamic gather
    from a doc-sharded operand to an ALL-GATHER of the full operand, which
    made a 16-doc round's digest scale with total session docs.  Traffic
    here is K x row-bytes per device, independent of D (the analytic bound
    lives in DESIGN.md §10; tests/test_sharding.py pins the lowered HLO:
    psum all-reduces on (K, ...) shapes only, no all-gather of the (D, ...)
    operand)."""
    return PackedDocs(*gather_rows_fn(mesh)(tuple(state), rows_idx))


def gather_rows_fn(mesh):
    """The jitted K-row gather for ``mesh`` (cached through
    :func:`~.mesh_fused.mesh_fn` — bounded, keyed by mesh VALUE rather than
    the live object, so repeated test meshes share one compiled entry
    instead of accumulating stale ones).  Exposed as a function so the
    HLO-inspection test can ``.lower()`` exactly the program
    :func:`_gather_rows` dispatches."""
    from .mesh_fused import mesh_fn

    def build():
        if mesh is None:
            return jax.jit(lambda st, idx: tuple(x[idx] for x in st))
        from jax.experimental.shard_map import shard_map

        from .mesh import DOC_AXIS

        def per_shard(local, idx):
            d_local = local[0].shape[0]
            start = jax.lax.axis_index(DOC_AXIS) * d_local
            rel = idx - start
            inb = (rel >= 0) & (rel < d_local)
            safe = jnp.clip(rel, 0, d_local - 1)
            out = []
            for x in local:
                g = x[safe]
                m = inb.reshape((-1,) + (1,) * (g.ndim - 1))
                if g.dtype == jnp.bool_:
                    g = jax.lax.psum(
                        jnp.where(m, g.astype(jnp.int32), 0), DOC_AXIS
                    ).astype(jnp.bool_)
                else:
                    g = jax.lax.psum(jnp.where(m, g, 0), DOC_AXIS)
                out.append(g)
            return tuple(out)

        return jax.jit(shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(DOC_AXIS), P()), out_specs=P(),
        ))

    return mesh_fn(mesh, "gather_rows", build)


@partial(jax.jit, static_argnums=1)
def _rows_digest_jit(
    sub: PackedDocs, comment_capacity: int, row_mask,
    sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
):
    """Per-doc full-state hashes for a GATHERED row subset (see
    :func:`_gather_rows`): resolve and hash only the (power-of-two
    bucketed) rows a round touched, so the per-round digest cost scales
    with touched docs on every platform and mesh — the block program
    re-resolves docs/block (the whole batch, under a mesh) even for a
    one-doc round.  Padding rows (``row_mask`` False) hash to zero."""
    resolved = resolve(sub, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        sub, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    return per_doc, resolved.overflow


# -- drain-end digest chaining (fused pipeline, round 14) --------------------
#
# The FINAL staged batch of a pipelined drain chains the resolve+digest
# block program INTO its own donated program: the drain-end digest prefetch
# used to pre-dispatch _resolve_block_digest_jit as a SEPARATE program right
# after the final apply — one more dispatch than strictly needed per drain.
# The chained twins below return (state, resolved, per_doc) from ONE
# program; the dispatch seeds the per-round block cache with the result, so
# digest() and the read paths find the round's resolution exactly as if the
# separate prefetch had run (byte equality pinned in tests/test_fused.py).
# Only the genuinely fused multi-round forms chain ("flat" staged tensors
# and the static-rounds "stacked" form): the single-round "compact1"/
# "static1" fallbacks exist precisely to SHARE compiled programs with the
# per-round discipline, and welding a digest into them would mint the
# variant back.


def _staged_rounds_digest(
    state, counts_all, ins_all, del_all, mark_all, map_all,
    row_mask, sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    *, widths_seq, loop_slots_seq, ins_lens, del_lens, mark_lens, map_lens,
    insert_impl, comment_capacity,
):
    state = apply_batch_staged_rounds(
        state, counts_all, ins_all, del_all, mark_all, map_all,
        widths_seq=widths_seq, loop_slots_seq=loop_slots_seq,
        ins_lens=ins_lens, del_lens=del_lens, mark_lens=mark_lens,
        map_lens=map_lens, insert_impl=insert_impl,
    )
    resolved = resolve(state, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        state, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    return state, resolved, per_doc


_STAGED_DIGEST_STATICS = (
    "widths_seq", "loop_slots_seq", "ins_lens", "del_lens", "mark_lens",
    "map_lens", "insert_impl", "comment_capacity",
)
_staged_rounds_digest_jit = jax.jit(
    _staged_rounds_digest, static_argnames=_STAGED_DIGEST_STATICS,
    donate_argnums=0,
)
_staged_rounds_digest_jit_nodonate = jax.jit(
    _staged_rounds_digest, static_argnames=_STAGED_DIGEST_STATICS,
)


def _stacked_rounds_digest(
    state, stacked,
    row_mask, sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    *, loop_slots_seq, insert_impl, comment_capacity,
):
    state = apply_batch_stacked_rounds(
        state, stacked, loop_slots_seq=loop_slots_seq,
        insert_impl=insert_impl,
    )
    resolved = resolve(state, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        state, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    return state, resolved, per_doc


_STACKED_DIGEST_STATICS = ("loop_slots_seq", "insert_impl",
                           "comment_capacity")
_stacked_rounds_digest_jit = jax.jit(
    _stacked_rounds_digest, static_argnames=_STACKED_DIGEST_STATICS,
    donate_argnums=0,
)
_stacked_rounds_digest_jit_nodonate = jax.jit(
    _stacked_rounds_digest, static_argnames=_STACKED_DIGEST_STATICS,
)


@partial(jax.jit, static_argnums=2)
def _compact_packed_jit(resolved, elem_id, width: int):
    """Gather a resolved block's planes to a visible-prefix layout of static
    ``width`` columns (visible chars keep their slot order) and concatenate
    EVERYTHING into one (D, 2 + 4*width + words*width) int32 buffer:
    ``[n_vis | overflow | char | elem | link | lww | comment words]`` per
    row.  One buffer = ONE device->host transfer per block; through a
    tunneled link the sweep cost is per-RPC latency, not bytes (seven
    separate small fetches cost ~0.9 s/block against ~0.15 s for this one).
    The LWW type planes pack to one bitmask column group per char."""
    # bitmask column group: a 9th LWW mark type would silently vanish from
    # every sweep read — fail the trace instead (trace-time, free at run)
    assert resolved.lww_active.shape[1] <= 8, "lww bitmask plane is uint8"
    order = jnp.argsort(~resolved.visible, axis=1, stable=True)[:, :width]
    take = lambda x: jnp.take_along_axis(x, order, axis=1)  # noqa: E731
    n_vis = jnp.sum(resolved.visible, axis=1).astype(jnp.int32)
    lww_bits = jnp.zeros(resolved.char.shape, jnp.int32)
    for t in range(resolved.lww_active.shape[1]):
        lww_bits = lww_bits | (
            resolved.lww_active[:, t, :].astype(jnp.int32) << t
        )
    words = resolved.comment_bits.shape[1]
    parts = [
        n_vis[:, None],
        resolved.overflow.astype(jnp.int32)[:, None],
        take(resolved.char).astype(jnp.int32),
        take(elem_id).astype(jnp.int32),
        take(resolved.link_attr).astype(jnp.int32),
        take(lww_bits),
    ] + [
        jax.lax.bitcast_convert_type(
            take(resolved.comment_bits[:, w, :]), jnp.int32
        )
        for w in range(words)
    ]
    return jnp.concatenate(parts, axis=1)


def _unpack_compact(buf: np.ndarray, width: int, words: int):
    """Host-side CompactBlock view over one packed sweep buffer."""
    from ..ops.decode import CompactBlock

    w = width
    char = buf[:, 2:2 + w]
    elem = buf[:, 2 + w:2 + 2 * w]
    link = buf[:, 2 + 2 * w:2 + 3 * w]
    lww = buf[:, 2 + 3 * w:2 + 4 * w].astype(np.uint8)
    comment = (
        buf[:, 2 + 4 * w:].view(np.uint32).reshape(buf.shape[0], words, w)
        if words
        else np.zeros((buf.shape[0], 0, w), np.uint32)
    )
    return CompactBlock(
        buf[:, 0], char, elem, link, lww, comment, buf[:, 1].astype(bool)
    )


@jax.jit
def _max_visible_jit(visible):
    return jnp.max(jnp.sum(visible, axis=1))


class _BlockResolution:
    """Per-(round, block) resolution artifacts: the device-side resolved
    planes, the fused per-doc full-state hash vector, and a LAZY numpy
    conversion.  Digest-only sync points fetch the hash vector + overflow
    (D uint32 + D bools); only actual span/patch reads pay the (D, S) plane
    transfer — through a narrow device link that asymmetry is the
    difference between a ~ms and a ~second sync."""

    __slots__ = ("device", "digest_dev", "on_device", "_np", "_overflow",
                 "_digest_vec")

    def __init__(self, device, digest_dev, on_device):
        self.device = device
        self.digest_dev = digest_dev  # (D,) per-doc hash vector, device
        self.on_device = on_device  # fallback mask the digest was fused with
        self._np = None
        self._overflow = None
        self._digest_vec = None

    @property
    def digest_per_doc(self) -> np.ndarray:
        if self._digest_vec is None:
            self._digest_vec = np.asarray(self.digest_dev)
        return self._digest_vec

    @property
    def overflow(self) -> np.ndarray:
        if self._overflow is None:
            self._overflow = np.asarray(self.device.overflow)
        return self._overflow

    def to_np(self):
        if self._np is None:
            self._np = type(self.device)(*(np.asarray(x) for x in self.device))
            self._overflow = self._np.overflow
        return self._np


def _width_bucket(n: int) -> int:
    """Power-of-two table width so growing interners reuse compiled digests
    (canonical spelling: utils/shapes.next_pow2, floor 8)."""
    return next_pow2(n, floor=8)


#: byte budget for the per-(round, epoch) CompactBlock cache — 100K docs of
#: compacted planes is ~250 MB, comfortably inside it; sessions beyond the
#: budget degrade to one transfer per sweep instead of one per round
_COMPACT_CACHE_BYTES = int(
    os.environ.get("PT_COMPACT_CACHE_BYTES", 512 * 1024 * 1024)
)


#: quarantine reasons — the fault-domain vocabulary.  ``decode``: a wire
#: frame failed codec decode/validation (the doc's log has a gap until
#: anti-entropy re-ships it; device state is untouched).  ``capacity`` /
#: ``schedule`` / ``encode``: the doc left the device path for scalar
#: replay (degraded but correct).  ``device-round``: the supervisor rolled
#: a failed guarded round back and demoted the doc's pending work to
#: scalar replay.
REASON_DECODE = "decode"
REASON_CAPACITY = "capacity"
REASON_SCHEDULE = "schedule"
REASON_ENCODE = "encode"
REASON_DEVICE_ROUND = "device-round"


@dataclass
class QuarantineRecord:
    """Why one doc is quarantined (typed reason + free-form detail), and at
    which session round the quarantine was imposed."""

    reason: str
    detail: str = ""
    round: int = 0
    #: a clean delivery for the doc has arrived since the corrupt one — the
    #: first half of the ``decode`` re-admission condition (the second half
    #: is the doc draining with no stuck work; see _sweep_decode_quarantine)
    clean_delivery: bool = False


@dataclass
class _DocSession:
    encoder: Optional[DocEncoder] = None
    clock: Clock = field(default_factory=dict)
    pending: List[Change] = field(default_factory=list)
    log: List[Change] = field(default_factory=list)
    fallback: bool = False
    # frame-native mode (ops/frames.py): raw wire frames are the event source;
    # pending parsed ops live in the session-level pool (one flat array chunk
    # per bulk arrival, never per-doc Python objects), applied clocks live in
    # the session-level clock matrix, attr interning is session-level too.
    frame_mode: bool = False
    frames: List[bytes] = field(default_factory=list)
    text_obj: int = 0


class _RoundBuffers:
    """One round's padded device-stream staging arrays (host side).

    Fresh zeros each round: np.zeros is a calloc, so untouched rows cost no
    page writes; only rows with scheduled work are filled (object docs by the
    per-doc encoder, frame docs by the one-call native scheduler).  Duck-typed
    to what kernel.encoded_arrays_of consumes."""

    __slots__ = ("ins_ref", "ins_op", "ins_char", "del_target", "marks",
                 "map_ops", "ins_count", "del_count", "mark_count",
                 "map_count", "num_ops")

    def __init__(self, d: int, ki: int, kd: int, km: int, kp: int) -> None:
        self.ins_ref = np.zeros((d, ki), np.int32)
        self.ins_op = np.zeros((d, ki), np.int32)
        self.ins_char = np.zeros((d, ki), np.int32)
        self.del_target = np.zeros((d, kd), np.int32)
        self.marks = {col: np.zeros((d, km), np.int32) for col in MARK_COLS}
        self.map_ops = {col: np.zeros((d, kp), np.int32) for col in MAP_STREAM_COLS}
        self.ins_count = np.zeros(d, np.int32)
        self.del_count = np.zeros(d, np.int32)
        self.mark_count = np.zeros(d, np.int32)
        self.map_count = np.zeros(d, np.int32)
        self.num_ops = np.zeros(d, np.int32)


class StreamingMerge:
    """Incremental multi-round merge of up to ``num_docs`` documents.

    ``actors`` declares the replica set whose changes may arrive (needed up
    front: packed op-ID order requires a complete ordered actor table; an
    undeclared actor demotes that doc to scalar-replay fallback).

    ``layout`` selects the resident-state storage: ``"padded"`` (this
    class: one (D, S) element batch, every doc at the slot capacity),
    ``"paged"`` (store/session.PagedStreamingMerge: a global op-page pool
    + per-doc page tables, gathered per round at each doc's own size
    bucket), or ``"ragged"`` (store/session.RaggedStreamingMerge: the same
    pool applied IN PLACE by ops/ragged — no buckets, one compiled apply
    for any doc mix).  The constructor is the factory — ``StreamingMerge(
    ..., layout="paged")`` builds the matching subclass; the padded layout
    remains the byte-equality oracle.
    """

    #: storage layout of this class (the paged subclass overrides)
    _layout = "padded"

    def __new__(cls, *args, **kwargs):
        layout = kwargs.get("layout", "padded")
        if layout not in ("padded", "paged", "ragged"):
            raise ValueError(f"unknown layout: {layout!r}")
        if cls is StreamingMerge and layout == "paged":
            from ..store.session import PagedStreamingMerge

            return super().__new__(PagedStreamingMerge)
        if cls is StreamingMerge and layout == "ragged":
            from ..store.session import RaggedStreamingMerge

            return super().__new__(RaggedStreamingMerge)
        return super().__new__(cls)

    def __init__(
        self,
        num_docs: int,
        actors: Sequence[str],
        slot_capacity: int = 256,
        mark_capacity: int = 128,
        tomb_capacity: int = 128,
        round_insert_capacity: int = 64,
        round_delete_capacity: int = 32,
        round_mark_capacity: int = 32,
        round_map_capacity: int = 16,
        comment_capacity: int = 32,
        map_capacity: int = 32,
        read_chunk: int = 8192,
        mesh=None,
        tracer=None,
        static_rounds: bool = False,
        layout: str = "padded",
    ) -> None:
        self.num_docs = num_docs
        self.actors = list(actors)
        self.mesh = mesh
        # static capacities as plain attributes: the paged layout has no
        # (D, S) self.state to read shapes off, so every capacity consumer
        # (compact width caps, digest pad terms, config) uses these
        self._slot_capacity = int(slot_capacity)
        self._mark_capacity = int(mark_capacity)
        self._tomb_capacity = int(tomb_capacity)
        self._map_capacity = int(map_capacity)
        #: serving-tier shape discipline (serve/ SessionMux): commit every
        #: round through the PADDED (D, K) apply at the configured widths —
        #: one XLA apply shape for the session's whole lifetime (plus the
        #: log2 slot-window ladder) instead of the adaptive width / flat
        #: stream-bucket / fused-depth variant space.  Trickle rounds pay
        #: padded staging they don't fill, but a latency-SLO tier would
        #: rather waste bucket occupancy than eat a multi-second XLA
        #: compile inside a client's p99.  Meshless sessions only; sized
        #: for serving hosts (thousands of docs), not 100K-doc analytics
        #: sessions (whose block-chunked flat path exists for exactly the
        #: opposite trade).
        self.static_rounds = bool(static_rounds)
        #: pipeline-span producer (obs/spans.py).  Spans always measure, so
        #: per-round MergeStats work even with tracing off; they are only
        #: retained when the tracer is enabled or has sinks (e.g. the
        #: supervisor's flight recorder).
        self.tracer = tracer if tracer is not None else GLOBAL_TRACER
        #: optional FlightRecorder: quarantines land as fault records (and
        #: trigger its auto-dump) — the supervisor attaches one
        self.recorder = None
        #: MergeStats of the most recent committed round batch
        self.last_round_stats: Optional[MergeStats] = None
        #: per-drain span-duration accumulator for the serve tier's
        #: latency plane: reset by drain(), filled by _emit_round_stats
        #: with the drain's schedule/apply span sums.  Durations only —
        #: this module never reads a wall clock (PTL006 merge scope);
        #: the serve mux pairs these with ITS watermarks to split the
        #: drain wall into dispatch vs commit stages.
        self.last_drain_marks: Optional[Dict[str, float]] = None
        # cumulative padded-stream accounting behind health()'s
        # padding-efficiency readout
        self._pad_real_ops = 0
        self._pad_capacity = 0
        self.round_caps = (round_insert_capacity, round_delete_capacity,
                           round_mark_capacity, round_map_capacity)
        self.comment_capacity = comment_capacity
        # Sharding needs equal shards: pad the DEVICE doc axis up to a mesh
        # multiple; padded rows are permanently empty docs (all-zero streams
        # are no-ops) and are invisible in the public API (num_docs, reads).
        # Meshless sessions larger than a read block pad to a BLOCK multiple
        # instead, so every block-chunked program (apply, resolve, compact)
        # compiles exactly one doc shape — a ragged tail block would mint a
        # second XLA shape for each.
        if mesh is not None:
            self._padded_docs = -(-num_docs // mesh.size) * mesh.size
        elif num_docs > read_chunk:
            self._padded_docs = -(-num_docs // read_chunk) * read_chunk
        else:
            self._padded_docs = num_docs
        # reads resolve the doc axis in blocks of this size (see the
        # block-cached resolution section); meshed state is never sliced
        self._read_chunk_requested = read_chunk
        self._read_chunk = (
            self._padded_docs if mesh is not None else max(1, min(read_chunk, max(num_docs, 1)))
        )
        self.docs = [_DocSession() for _ in range(num_docs)]
        #: fault-domain registry: doc -> QuarantineRecord.  Quarantine is
        #: health METADATA — it never changes read routing by itself (a
        #: demoted doc is additionally in ``fallback``); a ``decode``
        #: quarantine auto-lifts once a later clean delivery arrives for the
        #: doc AND its pending work drains (anti-entropy repair; see
        #: _sweep_decode_quarantine).
        self._quarantine: Dict[int, QuarantineRecord] = {}
        self.rounds = 0
        #: cumulative wall seconds in the native wire parse (bench stage)
        self.host_parse_seconds = 0.0
        self._patch_base: Dict[int, list] = {}
        # per-round cache of numpy-resolved doc blocks: (rounds, {bi: resolved})
        self._resolved_cache = (-1, {})
        # Incremental convergence digest (VERDICT r3 task 2): per-ROW
        # full-state hashes CARRIED across rounds in host planes.  A round
        # invalidates only the rows it applied ops to; digest() re-hashes
        # heavily-dirty blocks through the fused block program (shared with
        # the read paths) and pools the remaining dirty rows into ONE
        # gathered sub-batch program, so the per-round digest cost scales
        # with TOUCHED docs on every platform and mesh (a block — the whole
        # batch, under a mesh — is never re-resolved for a one-doc round).
        # Fallback masking happens at SUM time from current flags, so
        # demotions never stale the carried hashes; per-doc hashes are
        # invariant under interner growth (tables are gathered by ids
        # present in the doc's own rows).  digest(refresh=True) is the
        # full-recompute verification path.
        self._digest_plane = np.zeros(self._padded_docs, np.uint32)
        self._digest_ov = np.zeros(self._padded_docs, bool)
        self._digest_row_valid = np.zeros(self._padded_docs, bool)
        # Physical placement indirection (SURVEY §5.8(c) re-sharding):
        # logical doc d lives in device row _row_of[d]; _doc_at is the
        # inverse (-1 = empty/pad row).  Identity until reshard() moves
        # rows; every device-facing site maps through it, every host
        # structure stays logical-doc-indexed.
        self._row_of = np.arange(num_docs, dtype=np.int64)
        self._doc_at = np.full(self._padded_docs, -1, np.int64)
        self._doc_at[:num_docs] = np.arange(num_docs)
        #: bumped by reshard(): in-flight async digests must neither write
        #: their pre-reshard scalars back into the carry nor map their
        #: schedule-time rows through the new placement
        self._placement_epoch = 0
        #: per-(lo, hi) device-resident digest hash tables, keyed by an
        #: interner/placement fingerprint (see _digest_tables)
        self._digest_tables_cache: Dict = {}
        #: fetched CompactBlocks for the current (round, epoch) — lets
        #: read_all + read_patches_all share one device transfer per block
        #: (bounded by _COMPACT_CACHE_BYTES; beyond it each sweep re-fetches)
        self._compact_cache: tuple = ((-1, -1), {}, 0)
        #: per-block visible-prefix widths (-1 = session-wide prior); see
        #: _compact_width_for
        self._compact_width: Dict[int, int] = {}
        #: last chunked-apply output blocks (next round's inputs); None
        #: whenever self.state was rebuilt outside _apply_compact
        self._apply_blocks: Optional[list] = None
        self._actor_table = OrderedActorTable(self.actors)
        # frame-native session state (bulk path, ops/frames.parse_frames_bulk):
        # parsed-but-unscheduled changes pool as (doc_of_change, ParsedChanges)
        # chunks; per-doc applied frontiers as one (D, A) clock matrix; attr
        # interning shared across frame docs (ids are per-session, append-only).
        self._pool: List = []
        self._frame_mode = np.zeros(num_docs, bool)
        self._clock_mat = np.zeros((num_docs, len(self._actor_table)), np.int32)
        self._frame_attrs = Interner()
        # map keys + string values share one session interner (read_root)
        self._map_keys = Interner()
        # comment-mark ids must be PER-DOC dense (they index the capacity-C
        # comment planes); link urls etc. stay in the session table
        self._doc_comment_ids: Dict[int, Interner] = {}
        # object-path docs with pending changes (so step() never scans all D)
        self._object_pending: set = set()
        # when a list, _apply_compact records each round's device-ready
        # inputs (engine-limit bench replay; see bench.py run_engine)
        self._capture_rounds: Optional[list] = None
        #: lazy double-buffered staging lane (parallel/staging.FrameStager):
        #: the pipelined drain flattens + uploads batch k's fused inputs on
        #: its worker while batch k+1 schedules here
        self._stager = None
        #: fused-pipeline digest accumulation: when True, a pipelined drain
        #: ends by PRE-DISPATCHING the fused resolve+digest block program
        #: (async, with an async host copy of the per-doc hash vector), so
        #: the next digest()/read is one readback of already-computed
        #: device results instead of a dispatch+compute sync.  Off by
        #: default: a drain whose caller neither digests nor reads before
        #: the next commit would pay a wasted resolve per drain.  The bench
        #: fused row and the serving mux (reads follow every pump) turn it
        #: on.
        self.prefetch_digest = False
        #: compat switch: False restores the pre-fusion per-round dispatch
        #: discipline (one compact apply dispatch per round, per-round
        #: device_put staging, unpipelined drain) — the bench fused row's
        #: comparison arm and the equivalence tests' oracle side
        self.fused_pipeline = True
        #: cross-tenant fusion window extents (plan/fusion.FusionGroup
        #: window_rows): ``(row_bases, block_docs)`` set by the serve
        #: tier's FusedMuxGroup around a drain whose window touched a
        #: SUBSET of the lane's tenants.  When set, static-round commits
        #: stage only the active tenants' row blocks and rebuild the full
        #: (D, K) planes in-program (kernel.apply_batch_stacked_rounds
        #: _multi: the offsets ride as DATA, so the active subset never
        #: recompiles).  None = whole-lane staging, the stacked form.
        self.fusion_rows = None
        # Per-ROW cumulative admitted inserts: a host-side upper bound on
        # device slot occupancy (slots only grow, one per admitted insert;
        # device-side convergence dedup can only make the true count
        # smaller).  max() of it bounds the pallas insert loop's slot
        # window (kernel insert_loop_slots) so early/steady rounds scan
        # the occupied prefix, not the whole slot capacity.  Maintained at
        # every admission site; reshard() permutes it with the rows.
        self._cum_ins = np.zeros(self._padded_docs, np.int64)
        if self._layout == "padded":
            state = empty_docs(self._padded_docs, slot_capacity, mark_capacity,
                               tomb_capacity, map_capacity=map_capacity)
            self.state: PackedDocs = shard_docs(state, mesh) if mesh is not None else state
        else:
            # paged layout: the element planes live in the page pool the
            # subclass builds after this init; there is no (D, S) batch
            self.state = None

    # -- ingestion ---------------------------------------------------------

    def ingest(self, doc_index: int, changes: Iterable[Change]) -> None:
        """Queue newly-arrived changes for one document (any order, dups ok)."""
        sess = self.docs[doc_index]
        changes = list(changes)
        if not changes:
            return  # a zero-change frame would only grow durable history
        if sess.frame_mode:
            # the doc's pending state lives as parsed arrays; route object
            # arrivals through the same (cheap) frame parse
            self.ingest_frame(doc_index, encode_frame(changes))
            return
        sess.pending.extend(changes)
        self._object_pending.add(doc_index)

    def ingest_frame(self, doc_index: int, data: bytes,
                     on_corrupt: str = "raise") -> None:
        """Queue one binary change frame (the wire format a peer host ships,
        parallel/codec.py) for one document.  Raises :class:`DecodeError`
        (a ValueError) on corrupt frames (nothing is queued) unless
        ``on_corrupt="quarantine"``.  This is the single-frame convenience
        form of :meth:`ingest_frames` — a host draining a DCN receive queue
        should hand the whole batch over at once."""
        self.ingest_frames([(doc_index, data)], on_corrupt=on_corrupt)

    def ingest_frames(self, items: Iterable, on_corrupt: str = "raise") -> None:
        """Bulk-queue binary change frames, many docs per call — the native
        fast path at pod scale: ONE C++ call parses every frame (header,
        string tables, varint payload, packed identifiers) straight into flat
        arrays; no per-frame Python, no ``Change`` objects unless a doc
        leaves the fast path.

        ``items`` is an iterable of ``(doc_index, frame_bytes)``.  Frames are
        processed in order; corrupt frames contribute nothing, quarantine
        their doc (typed reason ``decode``), and — per-doc fault isolation —
        never block the other docs' frames, which are all queued first.

        ``on_corrupt`` picks the failure surface: ``"raise"`` (default, the
        pre-supervisor contract) raises one :class:`DecodeError` naming the
        affected docs after everything parseable has been queued;
        ``"quarantine"`` absorbs the fault entirely — the quarantine registry
        plus counters are the only signal, and the quarantine lifts
        automatically once a later clean delivery for the doc arrives and
        its pending work drains (anti-entropy repair)."""
        if on_corrupt not in ("raise", "quarantine"):
            raise ValueError(f"unknown on_corrupt mode: {on_corrupt!r}")
        items = list(items)
        # Traced (v5) / checked (v6) transport frames normalize to the
        # self-contained v2 storage form here — durable history and the
        # native parser only ever see v1/v2 — and the wire-carried context
        # links this host's ingest span into the SENDING host's trace.  A
        # v6 frame whose CRC fails passes through UNCHANGED (identity) and
        # is rejected as corrupt by the per-doc decode below, preserving
        # per-doc fault isolation.
        ctx: Optional[TraceContext] = None
        for j, (d, data) in enumerate(items):
            c, plain = strip_trace_context(data)
            if plain is not data:
                items[j] = (d, plain)
            if c is not None and ctx is None:
                ctx = TraceContext(*c)
        with self.tracer.span("streaming.ingest", ctx=ctx, frames=len(items)):
            self._ingest_items(items, on_corrupt)

    def _ingest_items(self, items: List, on_corrupt: str) -> None:
        fast: List = []
        corrupt: List[int] = []
        use_native = native.available()
        for doc_index, data in items:
            sess = self.docs[doc_index]
            object_bound = sess.fallback or sess.encoder is not None or bool(
                sess.pending or sess.log
            )
            if (not sess.frame_mode and object_bound) or not use_native:
                try:
                    self.ingest(doc_index, decode_frame(data))
                except ValueError:
                    corrupt.append(doc_index)
            else:
                fast.append((doc_index, data))
        if fast:
            corrupt.extend(self._ingest_frames_native(fast))
        bad = set(corrupt)
        # anti-entropy repair, first half: note which decode-quarantined docs
        # saw a clean delivery; re-admission happens once the doc also drains
        # with no stuck work (_sweep_decode_quarantine)
        for d in {int(d) for d, _ in items} - bad:
            rec = self._quarantine.get(int(d))
            if rec is not None and rec.reason == REASON_DECODE:
                rec.clean_delivery = True
        if bad:
            GLOBAL_COUNTERS.add("streaming.corrupt_frames", len(corrupt))
            for d in sorted(bad):  # deterministic quarantine-registry order
                self.quarantine_doc(
                    int(d), REASON_DECODE, "corrupt wire frame discarded"
                )
            if on_corrupt == "raise":
                raise DecodeError(
                    f"corrupt frame(s) for doc(s) {sorted(bad)}"
                )

    def _ingest_frames_native(self, items: List) -> List[int]:
        """Bulk-parse eligible frames; returns doc indices of corrupt frames."""
        doc_ids = np.asarray([d for d, _ in items], np.int64)
        frames = [data for _, data in items]
        frame_off = np.concatenate(
            [[0], np.cumsum([len(f) for f in frames], dtype=np.int64)]
        ).astype(np.int64)
        text_objs: Dict[int, int] = {}
        for d in doc_ids:
            d = int(d)
            sess = self.docs[d]
            if not sess.frame_mode:
                sess.frame_mode = True
                self._frame_mode[d] = True
            text_objs.setdefault(d, sess.text_obj)

        t0 = time.perf_counter()
        out = parse_frames_bulk(
            b"".join(frames), frame_off, self._actor_table,
            self._frame_attrs, doc_ids, text_objs,
            keys=self._map_keys,
        )
        # host-parse share of ingest, surfaced by the bench streaming row
        # (VERDICT r4 task 3): the C++ wire parse + its Python finishing
        self.host_parse_seconds += time.perf_counter() - t0
        if out is None:  # pragma: no cover - native.available() checked
            corrupt = []
            for (d, data) in items:
                try:
                    self.ingest(int(d), decode_frame(data))
                except ValueError:
                    corrupt.append(int(d))
            return corrupt
        parsed, f_ch_off, status = out

        # Re-map comment-mark attr ids from the session table to PER-DOC
        # dense ids: comment ids index capacity-C resolution planes, and a
        # session-wide numbering would overflow every doc's capacity once
        # the session has seen more than C distinct ids anywhere.
        ops = parsed.ops
        sel = np.nonzero(
            (ops[:, 0] == KIND_MARK)
            & (ops[:, 4] == MARK_INDEX["comment"])
            & (ops[:, 9] > 0)
        )[0]
        if len(sel):
            ch_idx = np.searchsorted(parsed.ops_off, sel, side="right") - 1
            f_idx = np.searchsorted(f_ch_off, ch_idx, side="right") - 1
            # Intern only rows of frames that passed every corrupt/demote
            # check: rows of discarded frames never reach the device, and
            # interning their ids would let an adversarial peer exhaust the
            # doc's dense comment-id space (capacity C) with corrupt frames,
            # permanently routing its reads to scalar replay (advisor r2).
            ok = status[f_idx] == FRAME_OK
            sel, ch_idx, f_idx = sel[ok], ch_idx[ok], f_idx[ok]
        if len(sel):
            docs_of_rows = doc_ids[f_idx].astype(np.int64)
            keycode = (docs_of_rows << 32) | ops[sel, 9].astype(np.int64)
            uniq, inv = np.unique(keycode, return_inverse=True)
            local_ids = np.empty(len(uniq), np.int32)
            for j, kc in enumerate(uniq):
                doc, gid = int(kc >> 32), int(kc & 0xFFFFFFFF)
                table = self._doc_comment_ids.setdefault(doc, Interner())
                local_ids[j] = table.intern(self._frame_attrs.lookup(gid))
            ops[sel, 9] = local_ids[inv]

        # Per-frame bookkeeping in arrival order: a demotion mid-call routes
        # the same doc's later frames to the object path (its pooled changes
        # are dropped at gather time; the frame history replay covers them).
        corrupt: List[int] = []
        keep_frame = np.zeros(len(items), bool)
        for f, (d, data) in enumerate(items):
            d = int(d)
            sess = self.docs[d]
            if not sess.frame_mode:  # demoted earlier in this call
                try:
                    self.ingest(d, decode_frame(data))
                except ValueError:
                    corrupt.append(d)
                continue
            if status[f] == FRAME_CORRUPT:
                corrupt.append(d)
            elif status[f] == FRAME_DEMOTE:
                try:
                    extra = decode_frame(data)
                except ValueError:
                    # natively parseable but not object-decodable: corrupt
                    # semantics — contribute nothing, keep the doc's state
                    corrupt.append(d)
                    continue
                self._demote_frame_doc(
                    d, extra=extra, reason=REASON_SCHEDULE,
                    detail="frame parseable but not device-expressible",
                )
            else:
                sess.frames.append(data)
                sess.text_obj = text_objs[d]
                keep_frame[f] = True

        if keep_frame.all() and parsed.num_changes:
            self._pool.append(
                (np.repeat(doc_ids, np.diff(f_ch_off).astype(np.int64)), parsed)
            )
        elif parsed.num_changes:
            doc_of = np.repeat(doc_ids, np.diff(f_ch_off).astype(np.int64))
            sel = np.nonzero(np.repeat(keep_frame, np.diff(f_ch_off)))[0]
            if len(sel):
                self._pool.append((doc_of[sel], parsed.select(sel)))
        return corrupt

    # -- fault-domain quarantine -------------------------------------------

    def quarantine_doc(self, doc_index: int, reason: str,
                       detail: str = "") -> None:
        """Quarantine one doc with a typed reason.  Idempotent per doc with
        one escalation rule: a demotion-class reason OVERWRITES a ``decode``
        record (the doc's routing really changed — a later clean frame must
        not lift the record while the doc sits on the scalar path), while a
        repeated fault never re-labels an existing same-class record."""
        rec = self._quarantine.get(doc_index)
        if rec is None:
            self._quarantine[doc_index] = QuarantineRecord(
                reason=reason, detail=detail, round=self.rounds
            )
            GLOBAL_COUNTERS.add("streaming.quarantined_docs")
            if self.recorder is not None:
                # flight recorder: the quarantine becomes a post-mortem —
                # fault() auto-dumps the recent span/event ring as JSONL
                self.recorder.fault(
                    "quarantine", doc=doc_index, quarantine_reason=reason,
                    detail=detail, round=self.rounds,
                )
        elif rec.reason == REASON_DECODE and reason != REASON_DECODE:
            self._quarantine[doc_index] = QuarantineRecord(
                reason=reason, detail=detail, round=self.rounds
            )
        elif rec.reason == REASON_DECODE and reason == REASON_DECODE:
            # a fresh corrupt frame invalidates any earlier repair evidence
            rec.clean_delivery = False

    def readmit(self, doc_index: int) -> bool:
        """Lift a doc's quarantine (any reason); returns whether a record
        was present.  Demotion-class reasons leave the doc on the scalar
        path — re-admission clears the health flag, not the routing."""
        if self._quarantine.pop(doc_index, None) is not None:
            GLOBAL_COUNTERS.add("streaming.readmitted_docs")
            return True
        return False

    def _sweep_decode_quarantine(self) -> None:
        """Auto re-admission, second half: a ``decode``-quarantined doc
        lifts once a clean delivery has arrived AND the doc has no pending
        work left (a causal gap the corrupt frame tore keeps its dependents
        pending, so a stuck doc stays quarantined until anti-entropy really
        re-ships the missing changes).  Only ``decode`` records lift —
        demotion-class records describe device-path state that a new frame
        does not repair.  Note the limit: a gap with no local dependents is
        locally undetectable (the wire format has no checksum — see ROADMAP
        "Wire-frame checksum"); the frontier diff of the next anti-entropy
        round is what closes that window."""
        candidates = [
            d for d, r in sorted(self._quarantine.items())  # readmit in doc order
            if r.reason == REASON_DECODE and r.clean_delivery
        ]
        if not candidates:
            return
        pending = self.pending_docs()
        for d in candidates:
            if d not in pending:
                self.readmit(d)

    def quarantined(self) -> Dict[int, QuarantineRecord]:
        """Snapshot of the quarantine registry (doc -> record); sweeps any
        ``decode`` record whose re-admission condition is now met, so the
        snapshot never reports a repaired doc as sick."""
        self._sweep_decode_quarantine()
        return dict(self._quarantine)

    def pending_docs(self) -> set:
        """Docs with undelivered (pending or pooled) changes."""
        out = {d for d, s in enumerate(self.docs) if s.pending}
        for doc_of, _ in self._pool:
            out.update(int(x) for x in np.unique(doc_of))
        return out

    def force_fallback(self, doc_index: int,
                       reason: str = REASON_DEVICE_ROUND,
                       detail: str = "") -> None:
        """Demote one doc to scalar replay (degraded but correct) and
        quarantine it with ``reason`` — the supervisor's containment move
        after a failed guarded device round.  Frame docs replay their frame
        history; object docs fold pending work into the replay log."""
        sess = self.docs[doc_index]
        if sess.frame_mode:
            self._demote_frame_doc(doc_index, reason=reason, detail=detail)
            return
        if not sess.fallback:
            sess.fallback = True
            GLOBAL_COUNTERS.add("streaming.fallback_docs")
        sess.log.extend(sess.pending)
        sess.pending = []
        self._object_pending.discard(doc_index)
        self.quarantine_doc(doc_index, reason, detail)

    def health(self) -> Dict:
        """One structured snapshot of the session's fault-domain state —
        what a fleet health endpoint would export per session.  Includes
        the padding-efficiency readout of the LAST committed round batch
        and the session-cumulative ratio (real ops / padded stream
        capacity), so a fleet scrape can spot a session whose round widths
        are mis-sized for its workload."""
        last = self.last_round_stats
        return {
            "rounds": self.rounds,
            "num_docs": self.num_docs,
            "pending_changes": self.pending_count(),
            "fallback_docs": sum(1 for s in self.docs if s.fallback),
            "frame_docs": int(self._frame_mode.sum()),
            "round_padding_efficiency": (
                round(last.padding_efficiency, 4) if last is not None else None
            ),
            "padding_efficiency_cum": (
                round(self._pad_real_ops / self._pad_capacity, 4)
                if self._pad_capacity else None
            ),
            "quarantined": {
                d: {"reason": r.reason, "detail": r.detail, "round": r.round}
                for d, r in sorted(self.quarantined().items())
            },
        }

    def _demote_frame_doc(self, doc_index: int, extra: List[Change] = (),
                          reason: str = REASON_CAPACITY,
                          detail: str = "") -> None:
        """Leave the fast path: the doc becomes a scalar-replay fallback fed
        by its decoded frame history (its device rows may already hold applied
        ops, so only the oracle path is still correct for it).  The doc is
        quarantined with ``reason`` so health snapshots can attribute the
        demotion."""
        sess = self.docs[doc_index]
        changes = [ch for f in sess.frames for ch in decode_frame(f)]
        changes.extend(extra)
        sess.log.extend(changes)
        # fold the applied frontier into the object-path clock so frontier()
        # stays truthful across the demotion
        row = self._clock_mat[doc_index]
        for idx in np.nonzero(row)[0]:
            actor = self._actor_table.lookup(int(idx))
            sess.clock[actor] = max(sess.clock.get(actor, 0), int(row[idx]))
        self._clock_mat[doc_index] = 0
        sess.frame_mode = False
        self._frame_mode[doc_index] = False
        sess.frames = []
        sess.text_obj = 0
        sess.fallback = True
        GLOBAL_COUNTERS.add("streaming.fallback_docs")
        self.quarantine_doc(doc_index, reason, detail)

    # -- the incremental device round --------------------------------------

    def step(self) -> int:
        """Apply every admissible pending change in one device round.

        Returns the number of changes scheduled this round.  Device work is
        dispatched asynchronously; the caller may immediately ingest and
        schedule the next round while the TPU runs this one.
        """
        with self.tracer.span("streaming.round") as rsp:
            with self.tracer.span("streaming.schedule") as ssp:
                enc, widths, scheduled = self._schedule_round()
            if scheduled:
                with self.tracer.span("streaming.apply", rounds=1) as asp:
                    self._commit_rounds([(enc, widths)])
                self._emit_round_stats(
                    [(enc, widths)], scheduled, ssp.duration, asp.duration
                )
            rsp.args["scheduled"] = scheduled
        self._sweep_decode_quarantine()
        return scheduled

    def _emit_round_stats(self, batch, scheduled: int,
                          schedule_s: float, apply_s: float,
                          origin: str = "streaming.round") -> None:
        """Per-commit MergeStats + histograms: the streaming path's analog
        of ``DocBatch.merge``'s report — the slowest bench row is no longer
        the least instrumented.  ``apply_seconds`` is host DISPATCH wall
        (device work is async; reads are the sync points), which is exactly
        the quantity the per-dispatch-floor analysis needs.  ``origin``
        labels the devprof occupancy rows ("streaming.fused" for pipelined
        drain commits), so the observability stack attributes per-fused-
        round cost to the fused sites."""
        touched: set = set()
        real = 0
        capacity = 0
        for enc, widths in batch:
            round_real = int(enc.num_ops.sum())
            round_cap = self._padded_docs * sum(widths)
            touched.update(int(r) for r in np.nonzero(enc.num_ops)[0])
            real += round_real
            capacity += round_cap
            if GLOBAL_DEVPROF.enabled:
                # per-bucket occupancy (devprof): the round's real ops vs
                # its padded (doc x width) capacity, keyed by the width set
                # — the per-bucket generalization of padding_efficiency
                GLOBAL_DEVPROF.observe_round(
                    occupancy_key(self._padded_docs, *widths),
                    round_real, round_cap, origin=origin,
                )
        if GLOBAL_DEVPROF.enabled:
            # round-boundary device-memory watermark (one sample per
            # committed batch, not per fused round — bounded overhead)
            GLOBAL_DEVPROF.sample_memory()
        stats = MergeStats(
            docs=len(touched),
            device_docs=len(touched),
            device_ops=real,
            encode_seconds=schedule_s,
            apply_seconds=apply_s,
            padding_efficiency=real / capacity if capacity else 0.0,
            extras={"rounds": len(batch), "scheduled_changes": scheduled},
        )
        self.last_round_stats = stats
        if self.last_drain_marks is not None:
            # span-derived stage durations for the serve tier's latency
            # plane (clock-free: spans always measure)
            self.last_drain_marks["schedule_seconds"] += schedule_s
            self.last_drain_marks["apply_seconds"] += apply_s
            self.last_drain_marks["rounds"] += len(batch)
        self._pad_real_ops += real
        self._pad_capacity += capacity
        GLOBAL_HISTOGRAMS.observe("streaming.round_seconds", schedule_s + apply_s)
        GLOBAL_HISTOGRAMS.observe(
            "streaming.round_scheduled_changes", scheduled, buckets=SIZE_BUCKETS
        )

    def _schedule_round(self):
        """The HOST half of a round: causal admission into staging buffers
        (object-path encode + the C++ frame scheduler), width selection —
        no device dispatch.  Returns ``(enc, widths, scheduled)``;
        ``drain`` schedules several rounds back-to-back and commits them as
        one fused program (the scheduling state is host-only clocks, so
        admission never needs the previous round's device result)."""
        ki, kd, km, kp = self.round_caps
        scheduled = 0

        # ---- object-path docs (editor-style sessions): per-doc encode ----
        obj_streams: Dict[int, _DocStreams] = {}
        for i in sorted(self._object_pending):
            sess = self.docs[i]
            if sess.fallback:
                sess.log.extend(sess.pending)
                sess.pending = []
                self._object_pending.discard(i)
                continue
            if sess.encoder is None:
                sess.encoder = DocEncoder(self.actors)
            ordered, stuck = causal_schedule(sess.pending, sess.clock)
            # budget the round to the static stream widths: admit a prefix
            # whose stream usage fits; the rest waits (shapes stay constant,
            # docs just take extra rounds)
            admitted, deferred = self._budget(ordered, ki, kd, km, kp)
            if not admitted and ordered and self._never_fits(ordered[0], ki, kd, km, kp):
                # a single change larger than a round width can never be
                # admitted: demote instead of wedging the doc (and every
                # change behind it) forever — the frame path's batched
                # scheduler does the same via its demote status
                sess.fallback = True
                GLOBAL_COUNTERS.add("streaming.fallback_docs")
                self.quarantine_doc(
                    i, REASON_CAPACITY, "change exceeds round stream widths"
                )
            streams, ok = sess.encoder.encode_increment(admitted)
            if not ok:
                sess.fallback = True
                GLOBAL_COUNTERS.add("streaming.fallback_docs")
                self.quarantine_doc(
                    i, REASON_ENCODE, "change not device-expressible"
                )
            else:
                for ch in admitted:
                    sess.clock[ch.actor] = ch.seq
                scheduled += len(admitted)
                if streams.ins or streams.dels or streams.marks or streams.maps:
                    obj_streams[i] = streams
            sess.log.extend(admitted)
            sess.pending = deferred + stuck
            if sess.fallback:
                # keep full history for scalar replay; nothing on device
                sess.log.extend(sess.pending)
                sess.pending = []
            if not sess.pending:
                self._object_pending.discard(i)

        pool = self._gather_pool()
        if scheduled == 0 and pool is None:
            return None, None, 0

        # Adaptive round widths: the (D, K) staging buffers are a real cost
        # (host->device transfer every round), so trickle rounds shrink them.
        # One shared power-of-two shift keeps the apply-program variant count
        # logarithmic; any doc with large pending work keeps the full widths.
        # Block-chunked sessions keep the widths FIXED instead: the flat
        # streams already transfer only real ops, and at 100K-doc scale each
        # extra (width-set x stream-bucket) shape is a multi-second XLA
        # compile of the apply program — one shape amortizes across every
        # block and round.  static_rounds sessions (the serving tier) keep
        # them fixed too: their whole point is ONE apply shape.
        if self._padded_docs <= self._read_chunk and not self.static_rounds:
            ki, kd, km, kp = self._round_widths(pool, obj_streams, ki, kd, km, kp)

        enc = _RoundBuffers(self._padded_docs, ki, kd, km, kp)
        for i, streams in obj_streams.items():
            r = int(self._row_of[i])  # device staging rows are PHYSICAL
            if streams.ins:
                arr = np.asarray(streams.ins, np.int32)
                enc.ins_ref[r, : len(arr)] = arr[:, 0]
                enc.ins_op[r, : len(arr)] = arr[:, 1]
                enc.ins_char[r, : len(arr)] = arr[:, 2]
            if streams.dels:
                enc.del_target[r, : len(streams.dels)] = streams.dels
            if streams.marks:
                arr = np.asarray(streams.marks, np.int32)
                for c, col in enumerate(MARK_COLS):
                    enc.marks[col][r, : len(arr)] = arr[:, c]
                enc.mark_count[r] = len(arr)
            if streams.maps:
                arr = np.asarray(streams.maps, np.int32)
                for c, col in enumerate(MAP_STREAM_COLS):
                    enc.map_ops[col][r, : len(arr)] = arr[:, c]
                enc.map_count[r] = len(arr)
            enc.ins_count[r] = len(streams.ins)
            enc.del_count[r] = len(streams.dels)
            enc.num_ops[r] = (
                len(streams.ins) + len(streams.dels)
                + len(streams.marks) + len(streams.maps)
            )

        # Frame-native pass: ONE C++ call schedules + splits every frame-mode
        # doc's pooled parsed changes into its padded row (the per-doc Python
        # version is the no-native fallback).
        if pool is not None:
            scheduled += self._step_frame_docs(pool, enc, (ki, kd, km, kp))

        if scheduled == 0:
            return None, None, 0
        GLOBAL_COUNTERS.add("streaming.scheduled_changes", scheduled)
        return enc, (ki, kd, km, kp), scheduled

    #: max rounds chained into one fused dispatch by drain(); bounds both
    #: the compile-cache variant space and the staged host memory
    FUSE_MAX_ROUNDS = 8

    def _fused_eligible(self) -> bool:
        """Whether commits route through the fused device-resident round
        pipeline: single-block (the donated state program covers the whole
        doc axis — mesh sessions always qualify, their block IS the padded
        batch, and their batch commits as ONE shard_map'd staged program
        over the mesh) and not an engine-capture session (capture records
        per-ROUND device inputs, the replay contract
        bench.run_engine/engine_profile consume)."""
        return (
            self.fused_pipeline
            and self._capture_rounds is None
            and self._padded_docs <= self._read_chunk
        )

    def _commit_rounds(self, batch) -> None:
        """The DEVICE half: dispatch scheduled rounds ``[(enc, widths),
        ...]`` — for fused-eligible sessions as ONE donated device program
        per batch (kernel.apply_batch_staged_rounds: round state updates in
        place, the whole batch ships as one staged tensor set; static-round
        serving sessions chain through the stacked fixed-width twin so the
        one-shape discipline holds) — plus the per-round digest/round
        bookkeeping.  Mesh, block-chunked and engine-capture sessions
        commit per round (their dispatch paths are shape-disciplined
        differently; see kernel.apply_batch_compact_rounds for the replay
        fuse)."""
        if self._fused_eligible():
            statics = self._prep_fused_batch(batch)
            inputs = self._stage_fused_batch(batch, statics)
            self._dispatch_fused_batch(batch, statics, inputs)
            return
        for enc, widths in batch:
            self._cum_ins += enc.ins_count
            if self.mesh is not None:
                # sharded path: padded (D, K) rows partition over the mesh
                arrays = encoded_arrays_of(enc)
                arrays = shard_docs(arrays, self.mesh)
                self.state = apply_batch_jit(self.state, arrays)
            elif self.static_rounds:
                # serving-tier static path: the padded (D, K) staging at
                # the session's fixed widths — one apply shape forever
                # (the slot-window bound stays pow-2 bucketed, a log2(S)
                # ladder); see the __init__ note for the trade
                s_cap = int(self.state.elem_id.shape[1])
                bound = _width_bucket(int(self._cum_ins.max()))
                self._apply_blocks = None
                self.state = apply_batch_jit(
                    self.state, encoded_arrays_of(enc),
                    insert_loop_slots=bound if bound < s_cap else None,
                )
            else:
                # single-device path: flat streams proportional to real
                # ops, padded layout rebuilt on device (_pad_from_flat)
                self.state = self._apply_compact(enc, widths)
            # incremental digest bookkeeping: only the rows this round
            # wrote need their carried per-row hash recomputed
            self._digest_row_valid[np.nonzero(enc.num_ops)[0]] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")

    @staticmethod
    def _flatten_round(enc: _RoundBuffers, widths, lo: int, hi: int):
        """Doc-major flat streams + counts for rows [lo, hi) of a round."""
        ki, kd, km, kp = widths
        ic, dc = enc.ins_count[lo:hi], enc.del_count[lo:hi]
        mc, pc = enc.mark_count[lo:hi], enc.map_count[lo:hi]
        mi = np.arange(ki, dtype=np.int32)[None, :] < ic[:, None]
        md = np.arange(kd, dtype=np.int32)[None, :] < dc[:, None]
        mm = np.arange(km, dtype=np.int32)[None, :] < mc[:, None]
        mp = np.arange(kp, dtype=np.int32)[None, :] < pc[:, None]
        return (
            (ic, dc, mc, pc),
            (enc.ins_ref[lo:hi][mi], enc.ins_op[lo:hi][mi], enc.ins_char[lo:hi][mi]),
            enc.del_target[lo:hi][md],
            {col: enc.marks[col][lo:hi][mm] for col in MARK_COLS},
            {col: enc.map_ops[col][lo:hi][mp] for col in MAP_STREAM_COLS},
        )

    @staticmethod
    def _pad_put(v: np.ndarray, cap: Optional[int] = None):
        """Pow-of-two pad + ASYNC h2d: the copy streams while the host
        parses/schedules the next block (a jit call would otherwise block
        on each input)."""
        if cap is None:
            cap = _width_bucket(len(v))
        out = np.zeros(cap, np.int32)
        out[: len(v)] = v
        return jax.device_put(out)

    def _device_round_inputs(self, enc: _RoundBuffers, widths):
        """Whole-batch device inputs for one scheduled round: flatten, pow-2
        pad + async h2d, slot-window bound from the (already-updated)
        cumulative-insert plane, and the engine-bench capture hook — the
        ONE place the fused and per-round dispatch paths share, so the
        capture tuple and padding can never desync between them.
        Returns ``(round_inputs, loop_slots)``."""
        d = enc.ins_count.shape[0]
        s_cap = int(self.state.elem_id.shape[1])
        bound = _width_bucket(int(self._cum_ins.max()))
        loop_slots = bound if bound < s_cap else None
        counts, ins, dels, marks, maps = self._flatten_round(enc, widths, 0, d)
        round_inputs = (
            tuple(jax.device_put(np.ascontiguousarray(c)) for c in counts),
            tuple(self._pad_put(v) for v in ins),
            self._pad_put(dels),
            {c: self._pad_put(v) for c, v in marks.items()},
            {c: self._pad_put(v) for c, v in maps.items()},
        )
        if self._capture_rounds is not None:
            # engine-limit benchmarking (bench.py --mode engine): record the
            # round's device-ready inputs so a replay can time the pure
            # device engine with zero host parse/schedule/transfer
            self._capture_rounds.append((round_inputs, widths, loop_slots))
        return round_inputs, loop_slots

    # -- the fused device-resident round pipeline ---------------------------
    #
    # A committed batch is one donated device program: the per-round flat
    # streams concatenate into ONE staged tensor per stream kind (static
    # per-round slice boundaries), the 21-leaf resident state is donated so
    # XLA updates it in place, and under drain() the flatten+upload of
    # batch k runs on the staging lane's worker while batch k+1 schedules
    # on this thread and batch k-1 computes behind the async dispatch
    # queue.  Split into prep (main thread: mutates _cum_ins, derives the
    # static signature) / stage (worker-safe: pure reads of the batch's own
    # staging buffers + jax.device_put) / dispatch (main thread: the
    # donated jit call + round bookkeeping) so the pipelined drain can
    # overlap them.

    def _prep_fused_batch(self, batch):
        """Main-thread half of staging: advance the cumulative-insert
        plane, derive each round's slot-window bound and the fused
        program's static signature.  Returns the statics tuple handed to
        ``_stage_fused_batch``/``_dispatch_fused_batch`` (tagged with the
        program form: flat staged tensors, or the stacked fixed-width form
        for static-round serving sessions)."""
        from ..ops.kernel import resolve_state_donation

        s_cap = self._slot_capacity
        loop_seq = []
        for enc, _ in batch:
            self._cum_ins += enc.ins_count
            bound = _width_bucket(int(self._cum_ins.max()))
            loop_seq.append(bound if bound < s_cap else None)
        if self.mesh is not None:
            # mesh-sharded fused form: per-round (D, K) staging planes
            # stack on a leading round axis — zero-padded to the batch-max
            # width per stream kind first (zero op ids are no-op slots, so
            # rounds of different widths share one stacked shape) — and
            # the stacked program runs under shard_map on the doc axis:
            # the whole batch commits as ONE dispatch for the whole mesh.
            # fusion_rows is ignored here (full-lane staging): the
            # offset-plane subset form would need per-shard row bases, and
            # the mesh trades that staging saving for the single dispatch.
            ki = max(enc.ins_ref.shape[1] for enc, _ in batch)
            kd = max(enc.del_target.shape[1] for enc, _ in batch)
            km = max(enc.marks[MARK_COLS[0]].shape[1] for enc, _ in batch)
            kp = max(
                enc.map_ops[MAP_STREAM_COLS[0]].shape[1] for enc, _ in batch
            )
            return ("mesh_stacked", tuple(loop_seq), (ki, kd, km, kp))
        if self.static_rounds:
            if self.fusion_rows is not None:
                # cross-tenant fusion window: only the active tenants'
                # row blocks ship; T is pow-2 bucketed (zero pad blocks
                # are no-op rows wherever their row_base points) so the
                # static shape is a (T_bucket, block_docs) ladder while
                # the subset itself rides as data
                bases, block = self.fusion_rows
                return ("stacked_multi", tuple(loop_seq), tuple(bases),
                        int(block), _width_bucket(len(bases)))
            if (len(batch) == 1
                    and not resolve_state_donation(self.state.elem_id)):
                # single-round serving commit, non-donating platform: the
                # legacy one-shape padded apply IS the program (shared
                # compile with the pre-fusion static path)
                return ("static1", loop_seq[0])
            return ("stacked", tuple(loop_seq))
        if len(batch) == 1 and not resolve_state_donation(self.state.elem_id):
            # single-round commit on a non-donating platform: stage and
            # dispatch through the SAME compact apply program the
            # per-round discipline (and the capture/oracle paths) use —
            # K=1 chaining buys nothing without donation, and sharing the
            # compiled program keeps the suite-wide variant count where
            # the pre-fusion path left it.  Donating platforms route K=1
            # through the staged program so state still updates in place.
            return ("compact1", loop_seq[0], batch[0][1])
        widths_seq = tuple(widths for _, widths in batch)
        # SHARED per-kind stream buckets across the batch (the block-chunk
        # idiom): every round pads to the batch's max bucket, so the
        # compile signature carries ONE length per stream kind instead of
        # a per-round combination — the variant space stays (K x 4 bucket
        # scalars), not their product
        k = len(batch)
        ib = _width_bucket(max(int(enc.ins_count.sum()) for enc, _ in batch))
        db = _width_bucket(max(int(enc.del_count.sum()) for enc, _ in batch))
        mb = _width_bucket(max(int(enc.mark_count.sum()) for enc, _ in batch))
        pb = _width_bucket(max(int(enc.map_count.sum()) for enc, _ in batch))
        return ("flat", tuple(loop_seq), widths_seq,
                (ib,) * k, (db,) * k, (mb,) * k, (pb,) * k)

    def _stage_fused_batch(self, batch, statics):
        """Worker-safe half: flatten the batch into its single staged
        tensor set and upload everything with ONE ``jax.device_put`` of the
        whole pytree.  Touches only the batch's own staging buffers (never
        session state), so the pipelined drain may run it on the staging
        lane while this thread schedules the next batch."""
        d = self._padded_docs
        k = len(batch)
        if statics[0] == "compact1":
            # the shared-program single-round form: flat streams pow-2
            # padded exactly as _device_round_inputs stages them
            _, _, widths = statics
            enc = batch[0][0]
            counts, ins, dels, marks, maps = self._flatten_round(
                enc, widths, 0, d)

            def pad(v):
                out = np.zeros(_width_bucket(len(v)), np.int32)
                out[: len(v)] = v
                return out

            return jax.device_put((
                tuple(np.ascontiguousarray(c) for c in counts),
                tuple(pad(v) for v in ins),
                pad(dels),
                {c: pad(v) for c, v in marks.items()},
                {c: pad(v) for c, v in maps.items()},
            ))
        if statics[0] == "static1":
            enc = batch[0][0]
            return jax.device_put((
                enc.ins_ref, enc.ins_op, enc.ins_char, enc.del_target,
                {c: enc.marks[c] for c in MARK_COLS}, enc.mark_count,
                {c: enc.map_ops[c] for c in MAP_STREAM_COLS}, enc.map_count,
            ))
        if statics[0] == "stacked_multi":
            # cross-tenant fusion form: per-round, slice the ACTIVE
            # tenants' row blocks out of the (D, K) staging planes —
            # (T_bucket, block, K) per plane, zero pad blocks beyond the
            # active count — and stack along the round axis; the row_base
            # data plane ships alongside
            _, _, bases, block, t_pad = statics

            def blocks(plane):
                out = np.zeros((t_pad, block) + plane.shape[1:], plane.dtype)
                for t, b in enumerate(bases):
                    out[t] = plane[b:b + block]
                return out

            def round_tree(enc):
                return (
                    blocks(enc.ins_ref), blocks(enc.ins_op),
                    blocks(enc.ins_char), blocks(enc.del_target),
                    {c: blocks(enc.marks[c]) for c in MARK_COLS},
                    blocks(enc.mark_count),
                    {c: blocks(enc.map_ops[c]) for c in MAP_STREAM_COLS},
                    blocks(enc.map_count),
                )

            per_round = [round_tree(enc) for enc, _ in batch]
            stacked = jax.tree_util.tree_map(
                lambda *leaves: np.stack(leaves), *per_round,
            )
            row_base = np.zeros(t_pad, np.int32)
            row_base[: len(bases)] = bases
            return jax.device_put((stacked, row_base))
        if statics[0] == "mesh_stacked":
            # mesh-sharded form: per-round planes zero-pad to the batch-max
            # width per kind, stack on the round axis, and ship with ONE
            # sharded device_put — round axis replicated, doc axis
            # partitioned over the mesh (every shard receives only its own
            # rows of every round)
            ki, kd, km, kp = statics[2]

            def pad_to(a, w):
                if a.shape[1] == w:
                    return a
                out = np.zeros((a.shape[0], w) + a.shape[2:], a.dtype)
                out[:, : a.shape[1]] = a
                return out

            tree = (
                np.stack([pad_to(enc.ins_ref, ki) for enc, _ in batch]),
                np.stack([pad_to(enc.ins_op, ki) for enc, _ in batch]),
                np.stack([pad_to(enc.ins_char, ki) for enc, _ in batch]),
                np.stack([pad_to(enc.del_target, kd) for enc, _ in batch]),
                {col: np.stack([pad_to(enc.marks[col], km)
                                for enc, _ in batch]) for col in MARK_COLS},
                np.stack([enc.mark_count for enc, _ in batch]),
                {col: np.stack([pad_to(enc.map_ops[col], kp)
                                for enc, _ in batch])
                 for col in MAP_STREAM_COLS},
                np.stack([enc.map_count for enc, _ in batch]),
            )
            return jax.device_put(
                tree, NamedSharding(self.mesh, P(None, DOC_AXIS))
            )
        if statics[0] == "stacked":
            # static-round serving form: the padded (D, K) staging rows at
            # the session's fixed widths, stacked along a leading round axis
            ins_ref = np.stack([enc.ins_ref for enc, _ in batch])
            ins_op = np.stack([enc.ins_op for enc, _ in batch])
            ins_char = np.stack([enc.ins_char for enc, _ in batch])
            del_t = np.stack([enc.del_target for enc, _ in batch])
            marks = {
                col: np.stack([enc.marks[col] for enc, _ in batch])
                for col in MARK_COLS
            }
            mark_count = np.stack([enc.mark_count for enc, _ in batch])
            maps = {
                col: np.stack([enc.map_ops[col] for enc, _ in batch])
                for col in MAP_STREAM_COLS
            }
            map_count = np.stack([enc.map_count for enc, _ in batch])
            return jax.device_put(
                (ins_ref, ins_op, ins_char, del_t, marks, mark_count,
                 maps, map_count)
            )
        _, _, widths_seq, ins_lens, del_lens, mark_lens, map_lens = statics
        counts_all = np.zeros((k, 4, d), np.int32)
        ins_all = [np.zeros(sum(ins_lens), np.int32) for _ in range(3)]
        del_all = np.zeros(sum(del_lens), np.int32)
        mark_all = {col: np.zeros(sum(mark_lens), np.int32)
                    for col in MARK_COLS}
        map_all = {col: np.zeros(sum(map_lens), np.int32)
                   for col in MAP_STREAM_COLS}
        io = do = mo = po = 0
        for r, (enc, widths) in enumerate(batch):
            counts, ins, dels, marks, maps = self._flatten_round(
                enc, widths, 0, d)
            for j in range(4):
                counts_all[r, j] = counts[j]
            for a, v in zip(ins_all, ins):
                a[io:io + len(v)] = v
            del_all[do:do + len(dels)] = dels
            for col in MARK_COLS:
                mark_all[col][mo:mo + len(marks[col])] = marks[col]
            for col in MAP_STREAM_COLS:
                map_all[col][po:po + len(maps[col])] = maps[col]
            io += ins_lens[r]
            do += del_lens[r]
            mo += mark_lens[r]
            po += map_lens[r]
        return jax.device_put(
            (counts_all, tuple(ins_all), del_all, mark_all, map_all)
        )

    def _dispatch_fused_batch(self, batch, statics, inputs,
                              chain_digest: bool = False) -> bool:
        """Dispatch half: ONE donated program applies the whole batch (the
        old state buffer is consumed in place), then the per-round digest
        and round bookkeeping.  With ``chain_digest`` (the drain's FINAL
        batch, digest prefetch armed) the staged multi-round forms chain
        the resolve+digest block program INTO the same dispatch and seed
        the block cache with its result — returns True when that happened
        (the drain then skips the separate prefetch dispatch)."""
        self._apply_blocks = None
        # one staged device program is about to launch (the digest-chained
        # arm is still ONE program) — the serve tier's fusion accounting
        # and the multi-tenant bench row measure deltas of this counter
        GLOBAL_COUNTERS.add("streaming.fused_dispatches")
        if statics[0] in ("mesh_stacked", "mesh_paged", "mesh_ragged") \
                and GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_mesh(self._mesh_stats())
        if chain_digest and statics[0] in ("stacked", "flat", "mesh_stacked"):
            self._dispatch_fused_batch_digest(batch, statics, inputs)
            return True
        if statics[0] == "compact1":
            from ..ops.kernel import apply_batch_compact_jit

            _, loop_slots, widths = statics
            counts, ins, dels, marks, maps = inputs
            self.state = apply_batch_compact_jit(
                self.state, counts, ins, dels, marks, maps,
                widths=widths, insert_loop_slots=loop_slots,
            )
        elif statics[0] == "static1":
            self.state = apply_batch_jit(
                self.state, inputs, insert_loop_slots=statics[1],
            )
        elif statics[0] == "mesh_stacked":
            fn = self._mesh_stacked_fn(statics[1])
            if GLOBAL_DEVPROF.enabled:
                note_jit_dispatch(
                    "apply_batch_stacked_rounds.mesh", fn,
                    (self.state, inputs),
                )
            self.state = fn(self.state, inputs)
        elif statics[0] == "stacked":
            loop_seq = statics[1]
            self.state = apply_batch_stacked_rounds_jit(
                self.state, inputs, loop_slots_seq=loop_seq,
            )
        elif statics[0] == "stacked_multi":
            stacked, row_base = inputs
            self.state = apply_batch_stacked_rounds_multi_jit(
                self.state, stacked, row_base, loop_slots_seq=statics[1],
            )
        else:
            _, loop_seq, widths_seq, ins_lens, del_lens, mark_lens, \
                map_lens = statics
            counts_all, ins_all, del_all, mark_all, map_all = inputs
            self.state = apply_batch_staged_rounds_jit(
                self.state, counts_all, ins_all, del_all, mark_all, map_all,
                widths_seq=widths_seq, loop_slots_seq=loop_seq,
                ins_lens=ins_lens, del_lens=del_lens,
                mark_lens=mark_lens, map_lens=map_lens,
            )
        for enc, _ in batch:
            self._digest_row_valid[np.nonzero(enc.num_ops)[0]] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        return False

    def _dispatch_fused_batch_digest(self, batch, statics, inputs) -> None:
        """The chain_digest arm of :meth:`_dispatch_fused_batch`: apply the
        final staged batch AND the fused resolve+digest of the (single)
        block in one program, then seed the per-round block cache with the
        returned resolution — the drain-end digest costs zero extra
        dispatches, and digest()/read paths behave exactly as after the
        separate prefetch (same cache entry, same mask semantics)."""
        on_device = self._block_fallback_mask(0)
        digest_args = (jnp.asarray(on_device),
                       *self._digest_tables(0, self._padded_docs))
        insert_impl = resolve_insert_impl(self.state.elem_id)
        donate = resolve_state_donation(self.state.elem_id)
        if statics[0] == "mesh_stacked":
            # the shard_map twin: apply + resolve + per-doc digest in the
            # SAME sharded program — the per-shard digest vectors come back
            # doc-sharded and the host combine (digest()) sums them exactly
            # as it does meshless
            fn = self._mesh_stacked_digest_fn(statics[1])
            args = (self.state, inputs, *digest_args)
            kw = {}
        elif statics[0] == "stacked":
            fn = (_stacked_rounds_digest_jit if donate
                  else _stacked_rounds_digest_jit_nodonate)
            args = (self.state, inputs, *digest_args)
            kw = dict(loop_slots_seq=statics[1], insert_impl=insert_impl,
                      comment_capacity=self.comment_capacity)
        else:  # "flat"
            _, loop_seq, widths_seq, ins_lens, del_lens, mark_lens, \
                map_lens = statics
            counts_all, ins_all, del_all, mark_all, map_all = inputs
            fn = (_staged_rounds_digest_jit if donate
                  else _staged_rounds_digest_jit_nodonate)
            args = (self.state, counts_all, ins_all, del_all, mark_all,
                    map_all, *digest_args)
            kw = dict(widths_seq=widths_seq, loop_slots_seq=loop_seq,
                      ins_lens=ins_lens, del_lens=del_lens,
                      mark_lens=mark_lens, map_lens=map_lens,
                      insert_impl=insert_impl,
                      comment_capacity=self.comment_capacity)
        if GLOBAL_DEVPROF.enabled:
            note_jit_dispatch(
                "_fused_rounds_digest" if statics[0] == "flat"
                else "_stacked_rounds_digest", fn, args, kw,
            )
        self.state, resolved, digest_dev = fn(*args, **kw)
        for enc, _ in batch:
            self._digest_row_valid[np.nonzero(enc.num_ops)[0]] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        entry = _BlockResolution(resolved, digest_dev, on_device)
        self._resolved_cache = (self.rounds, {0: entry})
        self._start_digest_readback(entry)
        GLOBAL_COUNTERS.add("streaming.digest_chained")

    # -- the mesh-sharded fused programs (round 19) --------------------------
    #
    # The stacked fixed-shape form runs under shard_map on the doc axis:
    # every shard applies its own rows of every round from the one staged
    # tensor set, so a drain batch is ONE dispatch for the whole mesh.
    # Programs cache through mesh_fused.mesh_fn (bounded, mesh-VALUE keyed)
    # and close over statics only — plan planes and stream staging ride as
    # data, so repeat mesh drains compile nothing (sentinel-pinned).

    def _mesh_stacked_fn(self, loop_seq):
        from jax.experimental.shard_map import shard_map

        from .mesh_fused import mesh_fn

        mesh = self.mesh
        insert_impl = resolve_insert_impl(self.state.elem_id)
        donate = resolve_state_donation(self.state.elem_id)

        def build():
            def body(state, stacked):
                return apply_batch_stacked_rounds(
                    state, stacked, loop_slots_seq=loop_seq,
                    insert_impl=insert_impl,
                )

            sm = shard_map(
                body, mesh=mesh,
                in_specs=(P(DOC_AXIS), P(None, DOC_AXIS)),
                out_specs=P(DOC_AXIS),
            )
            return jax.jit(sm, donate_argnums=(0,) if donate else ())

        return mesh_fn(
            mesh, ("stacked_apply", loop_seq, insert_impl, donate), build
        )

    def _mesh_stacked_digest_fn(self, loop_seq):
        from jax.experimental.shard_map import shard_map

        from .mesh_fused import mesh_fn

        mesh = self.mesh
        insert_impl = resolve_insert_impl(self.state.elem_id)
        donate = resolve_state_donation(self.state.elem_id)
        cc = self.comment_capacity

        def build():
            def body(state, stacked, row_mask, sess_attr, sess_key,
                     comment_hash, row_map, obj_attr, obj_key):
                # row_map values are GLOBAL override indices into the
                # replicated obj_attr/obj_key tables, so the per-shard body
                # reads them unchanged
                return _stacked_rounds_digest(
                    state, stacked, row_mask, sess_attr, sess_key,
                    comment_hash, row_map, obj_attr, obj_key,
                    loop_slots_seq=loop_seq, insert_impl=insert_impl,
                    comment_capacity=cc,
                )

            sm = shard_map(
                body, mesh=mesh,
                in_specs=(P(DOC_AXIS), P(None, DOC_AXIS), P(DOC_AXIS),
                          P(), P(), P(DOC_AXIS), P(DOC_AXIS), P(), P()),
                out_specs=(P(DOC_AXIS), P(DOC_AXIS), P(DOC_AXIS)),
            )
            return jax.jit(sm, donate_argnums=(0,) if donate else ())

        return mesh_fn(
            mesh, ("stacked_digest", loop_seq, insert_impl, donate, cc),
            build,
        )

    def _mesh_stats(self) -> Dict:
        """Per-shard load snapshot behind the ``peritext_mesh_*`` gauges:
        shard count, per-shard cumulative admitted inserts (the padded
        layout's live-slot proxy) and the max/mean imbalance ratio.  The
        paged subclass overrides with real per-shard pool occupancy."""
        n = self.mesh.size
        rows = self._padded_docs // n
        per = np.asarray(self._cum_ins).reshape(n, rows).sum(axis=1)
        mean = float(per.mean())
        return {
            "shards": n,
            "rows_per_shard": rows,
            "shard_load": [int(x) for x in per],
            "shard_utilization": [
                round(float(x) / (rows * self._slot_capacity), 4)
                for x in per
            ],
            "imbalance_ratio": (
                round(float(per.max()) / mean, 4) if mean > 0 else 1.0
            ),
            "ici_page_moves": 0,
        }

    def _apply_compact(self, enc: _RoundBuffers, widths) -> PackedDocs:
        """Dispatch one round via kernel.apply_batch_compact_jit: the host
        link carries flat op streams (power-of-two padded) plus per-doc
        counts instead of the mostly-zero (D, K) staging rows.

        Sessions larger than a read block apply BLOCK-CHUNKED: the round's
        rows slice into read_chunk-doc blocks whose flat streams share one
        pow-of-two bucket per stream kind, so XLA compiles ONE block-shaped
        program reused across blocks and rounds — at 100K docs the
        whole-batch shape cost ~22 s of XLA compile PER ROUND (stream
        totals land in a different bucket each round) plus hundreds of MB
        of monolithic transfer; block shapes compile once in seconds, and
        per-block transfers overlap the next block's host flatten.  The
        per-block states concatenate back on device (one fused program)."""
        from ..ops.kernel import apply_batch_compact_jit

        d = enc.ins_count.shape[0]
        chunk = self._read_chunk
        if self._capture_rounds is not None or d <= chunk:
            round_inputs, loop_slots = self._device_round_inputs(enc, widths)
            # whole-batch apply rebuilds state outside the chunked path —
            # any carried blocks describe the PREVIOUS state
            self._apply_blocks = None
            return apply_batch_compact_jit(self.state, *round_inputs,
                                           widths=widths,
                                           insert_loop_slots=loop_slots)
        # Slot-window bound for the pallas insert loop: pow-2 bucketed so a
        # growing session mints at most log2(S) program shapes; None once
        # the bound reaches the slot capacity (full window).
        s_cap = int(self.state.elem_id.shape[1])
        bound = _width_bucket(int(self._cum_ins.max()))
        loop_slots = bound if bound < s_cap else None

        n_blocks = -(-d // chunk)
        touched = [
            bi for bi in range(n_blocks)
            if enc.num_ops[slice(*self._block_bounds(bi))].any()
        ]
        if not touched:
            return self.state
        flats = {
            bi: self._flatten_round(enc, widths, *self._block_bounds(bi))
            for bi in touched
        }
        # shared stream buckets: every touched block compiles to ONE shape
        b_ins = _width_bucket(max(len(f[1][0]) for f in flats.values()))
        b_del = _width_bucket(max(len(f[2]) for f in flats.values()))
        b_mark = _width_bucket(max(
            len(next(iter(f[3].values()))) for f in flats.values()
        ))
        b_map = _width_bucket(max(
            len(next(iter(f[4].values()))) for f in flats.values()
        ))
        # block inputs come from the PREVIOUS round's outputs — steady-state
        # rounds never slice the concatenated state (device slicing is a
        # compile per (leaf shape, start), and a traced-start dynamic slice
        # of the 22-leaf state compiled in ~28 s at 100K docs).  Keeping the
        # block list alongside the concatenated state costs a second device
        # copy of session state (~1 GB at 100K docs) — the price of never
        # re-slicing; untouched blocks pass through by reference.
        blocks_in = self._apply_blocks
        if blocks_in is None:
            blocks_in = _split_blocks(
                self.state,
                tuple(self._block_bounds(b) for b in range(n_blocks)),
            )
        new_blocks = list(blocks_in)
        for bi in touched:
            counts, ins, dels, marks, maps = flats[bi]
            new_blocks[bi] = apply_batch_compact_jit(
                blocks_in[bi],
                counts,
                tuple(self._pad_put(v, b_ins) for v in ins),
                self._pad_put(dels, b_del),
                {c: self._pad_put(v, b_mark) for c, v in marks.items()},
                {c: self._pad_put(v, b_map) for c, v in maps.items()},
                widths=widths,
                insert_loop_slots=loop_slots,
            )
        self._apply_blocks = new_blocks
        return _concat_state_jit(*new_blocks)

    #: fraction of frame-pool docs whose whole pending need must fit the
    #: round width; the skewed tail above it defers to later (cheap,
    #: mostly-idle) rounds instead of inflating every doc's padded width —
    #: the apply program's insert phase costs D x width x slot-window, so
    #: one heavy doc at width 256 made 2,048 docs pay 4-5x the p98 width
    #: (the measured 47x engine-vs-batch gap of VERDICT r4 task 2)
    ROUND_WIDTH_QUANTILE = 0.98

    def _round_widths(self, pool, obj_streams, ki: int, kd: int, km: int, kp: int):
        """Shrink this round's stream widths by a shared power-of-two shift.

        Object-path docs were already admitted at the full caps, so their
        exact usage is a hard floor.  Frame-pool docs defer un-admitted
        changes to the next round anyway (the C++ scheduler budgets a
        causal prefix per doc), so their widths follow the
        ROUND_WIDTH_QUANTILE of per-doc need — bounded below by the largest
        single change in the pool, which guarantees every doc still admits
        at least one change per round (no livelock, no demotion: the
        scheduler's never-fits check sees the same floor).

        Each stream kind buckets INDEPENDENTLY (round 5): the insert width
        drives the expensive sequential phase (cost ~ ki x slot window per
        doc), and under shuffle arrival the delete/mark backlogs grow
        faster than the insert need (targets must exist first), so the old
        shared power-of-two shift let a deep delete queue inflate the
        insert width 2-4x.  Worst-case program-variant count stays small:
        pow-2 buckets per kind, and consecutive rounds have similar
        needs."""
        need_i = max((len(s.ins) for s in obj_streams.values()), default=0)
        need_d = max((len(s.dels) for s in obj_streams.values()), default=0)
        need_m = max((len(s.marks) for s in obj_streams.values()), default=0)
        need_p = max((len(s.maps) for s in obj_streams.values()), default=0)
        if pool is not None:
            doc_of, parsed = pool
            starts = np.nonzero(
                np.concatenate([[True], doc_of[1:] != doc_of[:-1]])
            )[0]
            q = self.ROUND_WIDTH_QUANTILE
            wants = []
            for cap, cnt in ((ki, parsed.cnt_ins), (kd, parsed.cnt_del),
                             (km, parsed.cnt_mark), (kp, parsed.cnt_map)):
                per_doc = np.minimum(np.add.reduceat(cnt, starts), cap)
                floor = int(cnt.max()) if len(cnt) else 0  # largest single change
                want = max(floor,
                           int(np.quantile(per_doc, q)) if len(per_doc) else 0)
                wants.append(min(cap, want))
            need_i = max(need_i, wants[0])
            need_d = max(need_d, wants[1])
            need_m = max(need_m, wants[2])
            need_p = max(need_p, wants[3])
        return (
            min(ki, _width_bucket(max(need_i, 8))),
            min(kd, _width_bucket(max(need_d, 8))),
            min(km, _width_bucket(max(need_m, 8))),
            min(kp, _width_bucket(max(need_p, 8))),
        )

    def _gather_pool(self):
        """Merge pooled parsed-change chunks into one doc-grouped batch:
        ``(doc_of_change, ParsedChanges)`` sorted by doc, demoted docs'
        entries dropped (their frame-history replay covers those changes)."""
        if not self._pool:
            return None
        chunks = self._pool
        self._pool = []
        doc_of = (
            chunks[0][0] if len(chunks) == 1
            else np.concatenate([d for d, _ in chunks])
        )
        parsed = ParsedChanges.concat_many([p for _, p in chunks])
        keep = self._frame_mode[doc_of]
        if not keep.all():
            idx = np.nonzero(keep)[0]
            if not len(idx):
                return None
            doc_of, parsed = doc_of[idx], parsed.select(idx)
        if np.any(doc_of[:-1] > doc_of[1:]):
            order = np.argsort(doc_of, kind="stable")
            doc_of, parsed = doc_of[order], parsed.select(order)
        return doc_of, parsed

    def _step_frame_docs(self, pool, enc, caps) -> int:
        """Round-schedule every frame-mode doc's pooled changes into its
        padded row; deferred changes go back to the pool as one chunk."""
        doc_of, parsed = pool
        if not native.available():
            return self._step_frame_docs_python(pool, enc, caps)

        frame_docs = np.unique(doc_of)
        frame_rows = self._row_of[frame_docs]  # device staging is physical
        ch_off = np.concatenate(
            [np.searchsorted(doc_of, frame_docs), [len(doc_of)]]
        ).astype(np.int32)
        # gather the scheduled docs' clock rows; scatter back after the call
        clock = np.ascontiguousarray(self._clock_mat[frame_docs], np.int32)
        text_obj = np.asarray(
            [self.docs[int(i)].text_obj for i in frame_docs], np.int32
        )
        batch = native.schedule_split_batch(
            len(self._actor_table),
            ch_off,
            frame_rows.astype(np.int32),
            text_obj,
            (parsed.ch_actor, parsed.ch_seq, parsed.dep_off,
             parsed.dep_actor, parsed.dep_seq, parsed.ops_off, parsed.ops),
            clock,
            caps,
            (enc.ins_ref, enc.ins_op, enc.ins_char),
            enc.del_target,
            enc.marks,
            enc.map_ops,
        )
        if batch is None:  # pragma: no cover - available() checked above
            return self._step_frame_docs_python(pool, enc, caps)

        _, n_ins, n_del, n_mark, n_map, n_admitted, admitted, status = batch
        self._clock_mat[frame_docs] = clock
        enc.mark_count[frame_rows] = n_mark
        enc.map_count[frame_rows] = n_map
        enc.num_ops[frame_rows] = n_ins + n_del + n_mark + n_map
        scheduled = int(n_admitted.sum())

        enc.ins_count[frame_rows] = n_ins
        enc.del_count[frame_rows] = n_del

        demoted_docs = frame_docs[status != 0] if status.any() else None
        if demoted_docs is not None:
            for i in demoted_docs:  # rare: demote (rows zeroed natively)
                i = int(i)
                r = int(self._row_of[i])
                enc.ins_count[r] = 0
                enc.del_count[r] = 0
                enc.mark_count[r] = 0
                enc.map_count[r] = 0
                enc.num_ops[r] = 0
                # folds + zeroes the doc's clock row
                self._demote_frame_doc(
                    i, reason=REASON_SCHEDULE,
                    detail="batched scheduler demoted the doc's round",
                )

        defer = admitted == 0
        if demoted_docs is not None:
            defer &= ~np.isin(doc_of, demoted_docs)
        if defer.any():
            idx = np.nonzero(defer)[0]
            self._pool.append((doc_of[idx], parsed.select(idx)))
        return scheduled

    def _step_frame_docs_python(self, pool, enc, caps) -> int:
        """Per-doc Python fallback (no native library)."""
        doc_of, parsed = pool
        ki, kd, km, kp = caps
        scheduled = 0
        frame_docs = np.unique(doc_of)
        bounds = np.concatenate(
            [np.searchsorted(doc_of, frame_docs), [len(doc_of)]]
        )
        for j, i in enumerate(frame_docs):
            i = int(i)
            r = int(self._row_of[i])  # device staging rows are PHYSICAL
            sess = self.docs[i]
            doc_parsed = parsed.select(
                np.arange(bounds[j], bounds[j + 1], dtype=np.int64)
            )
            try:
                nch, (ni, nd, nm, np_), deferred = schedule_split(
                    doc_parsed,
                    self._clock_mat[i],  # row view: advanced in place
                    sess.text_obj,
                    (ki, kd, km, kp),
                    (enc.ins_ref[r], enc.ins_op[r], enc.ins_char[r]),
                    enc.del_target[r],
                    {col: enc.marks[col][r] for col in sorted(enc.marks)},
                    {col: enc.map_ops[col][r] for col in sorted(enc.map_ops)},
                    len(self._actor_table),
                )
            except FrameIngestError:
                for col in sorted(enc.marks):  # discard any partial row writes
                    enc.marks[col][r] = 0
                for col in sorted(enc.map_ops):
                    enc.map_ops[col][r] = 0
                enc.ins_ref[r] = 0
                enc.ins_op[r] = 0
                enc.ins_char[r] = 0
                enc.del_target[r] = 0
                self._demote_frame_doc(
                    i, reason=REASON_SCHEDULE,
                    detail="scalar scheduler rejected the doc's round",
                )
                continue
            if deferred.num_changes:
                self._pool.append(
                    (np.full(deferred.num_changes, i, np.int64), deferred)
                )
            enc.ins_count[r] = ni
            enc.del_count[r] = nd
            enc.mark_count[r] = nm
            enc.map_count[r] = np_
            enc.num_ops[r] = ni + nd + nm + np_
            scheduled += nch
        return scheduled

    def drain(self, max_rounds: int = 1_000) -> int:
        """Drain all admissible pending work; returns rounds run.

        Scheduling is host-only (causal clocks), so drain schedules every
        pending round FIRST and commits them as fused device programs (up
        to FUSE_MAX_ROUNDS per dispatch) — a deep queue pays the
        ~11 ms/dispatch platform floor once instead of once per round.

        Fused-eligible sessions (meshless, single-block) run the PIPELINED
        form: batch k's flatten + host→device upload happens on the
        double-buffered staging lane while batch k+1 schedules on this
        thread and batch k-1's donated program computes behind the async
        dispatch queue — the host parse/transfer wall hides behind device
        math instead of serializing with it.  With
        :attr:`prefetch_digest`, the drain ends by pre-dispatching the
        fused resolve+digest block program so the caller's next digest or
        sweep read is one readback.  Byte equality with the per-round
        ``step`` discipline is pinned by test on every path."""
        # fresh per-drain accumulator: after the drain returns, the serve
        # tier reads this drain's schedule/apply span sums (stage durations
        # for the latency plane — durations, never clocks, in merge scope)
        self.last_drain_marks = {
            "schedule_seconds": 0.0, "apply_seconds": 0.0, "rounds": 0,
        }
        if not self._fused_eligible():
            return self._drain_serial(max_rounds)
        rounds = 0
        committed = False
        chained = False
        pending = None  # (handle, batch, statics, scheduled, schedule_span)
        while True:
            batch, scheduled_total, ssp = self._schedule_batch(
                rounds, max_rounds
            )
            if pending is not None:
                # an empty schedule means the staged batch in flight is the
                # drain's FINAL one: with the prefetch armed, its dispatch
                # chains the resolve+digest into the same program (the
                # staged forms), saving the separate prefetch dispatch
                chained = self._commit_pending(
                    pending,
                    chain_digest=self.prefetch_digest and not batch,
                )
                committed = True
                pending = None
            if not batch:
                break
            statics = self._prep_fused_batch(batch)
            handle = self._ensure_stager().submit(
                self._stage_fused_batch, batch, statics
            )
            pending = (handle, batch, statics, scheduled_total, ssp)
            rounds += len(batch)
        if committed and self.prefetch_digest and not chained:
            # single-round compat forms (compact1/static1) and the paged
            # subclass keep the separate pre-dispatch
            self._prefetch_digest()
        self._sweep_decode_quarantine()
        return rounds

    def _commit_pending(self, pending, chain_digest: bool = False) -> bool:
        """Land one staged batch: wait its staging handle (a staging fault
        surfaces HERE, inside whatever guard wraps the drain) and dispatch
        the donated program.  ``chain_digest`` marks the drain's final
        batch with the digest prefetch armed; returns whether the dispatch
        actually chained the resolve+digest in."""
        handle, batch, statics, scheduled, ssp = pending
        with self.tracer.span("streaming.apply", rounds=len(batch)) as asp:
            inputs = handle.wait()
            chained = bool(self._dispatch_fused_batch(
                batch, statics, inputs, chain_digest=chain_digest,
            ))
        self._emit_round_stats(
            batch, scheduled, ssp.duration, asp.duration,
            origin="streaming.fused",
        )
        return chained

    def _ensure_stager(self):
        """The session's staging lane (lazy; respawned if closed).  The
        lane's jobs run under a ``staging.stage`` span so the stage wall
        is measured on the worker thread (timing telemetry stays the
        caller's, per the staging module's contract)."""
        from .staging import FrameStager

        if self._stager is None or self._stager._closed:
            self._stager = FrameStager()
            self._stager.span_factory = (
                lambda: self.tracer.span("staging.stage")
            )
        return self._stager

    def _prefetch_digest(self) -> None:
        """Fused-pipeline digest accumulation: dispatch the fused
        resolve+digest program for the (single) block NOW — async, with an
        async device→host copy of the per-doc hash vector — so digest()
        (and, via the shared block cache, the sweep reads) find the round's
        resolution already computed: one readback per committed drain
        instead of a dispatch+compute sync at the read point."""
        self._start_digest_readback(self._digest_resolution(0))

    @staticmethod
    def _start_digest_readback(entry) -> None:
        """Start the async device→host copy of a resolved block's digest
        planes — the ONE spelling shared by the drain-end prefetch and the
        heavy-block sweep's lookahead (no-op on platforms without async
        copy)."""
        for a in (entry.digest_dev, entry.device.overflow):
            try:
                a.copy_to_host_async()
            except AttributeError:  # platform without async copy
                pass

    def _schedule_batch(self, rounds: int, max_rounds: int):
        """Schedule the next fused batch (host-only causal admission): up
        to ``FUSE_MAX_ROUNDS`` rounds within the drain's ``max_rounds``
        bound.  ONE spelling of the batching policy — both the pipelined
        and serial drain disciplines call it, so the fused_pipeline=False
        equality oracle can never diverge on scheduling."""
        batch = []
        scheduled_total = 0
        with self.tracer.span("streaming.schedule") as ssp:
            while (len(batch) < self.FUSE_MAX_ROUNDS
                   and rounds + len(batch) < max_rounds):
                enc, widths, scheduled = self._schedule_round()
                if not scheduled:
                    break
                batch.append((enc, widths))
                scheduled_total += scheduled
        return batch, scheduled_total, ssp

    def _drain_serial(self, max_rounds: int) -> int:
        """Unpipelined drain for mesh / block-chunked / engine-capture
        sessions: schedule-then-commit per batch through the session's
        per-round dispatch discipline."""
        rounds = 0
        while rounds < max_rounds:
            batch, scheduled_total, ssp = self._schedule_batch(
                rounds, max_rounds
            )
            if not batch:
                break
            with self.tracer.span("streaming.apply", rounds=len(batch)) as asp:
                self._commit_rounds(batch)
            self._emit_round_stats(
                batch, scheduled_total, ssp.duration, asp.duration
            )
            rounds += len(batch)
        self._sweep_decode_quarantine()
        return rounds

    @staticmethod
    def _op_counts(change: Change) -> tuple:
        """(inserts, deletes, marks, map-register ops) — the round-width cost
        model shared by admission budgeting and the never-fits check."""
        ci = cd = cm = cp = 0
        for op in change.ops:
            if op.action == "set" and op.insert:
                ci += 1
            elif op.action == "del" and op.elem_id is not None:
                cd += 1
            elif op.action in ("addMark", "removeMark"):
                cm += 1
            else:  # map set/del/makeMap/makeList -> one register row
                cp += 1
        return ci, cd, cm, cp

    @classmethod
    def _never_fits(cls, change: Change, ki: int, kd: int, km: int, kp: int) -> bool:
        ci, cd, cm, cp = cls._op_counts(change)
        return ci > ki or cd > kd or cm > km or cp > kp

    @classmethod
    def _budget(cls, ordered: List[Change], ki: int, kd: int, km: int, kp: int):
        """Admit the longest causal prefix whose op streams fit the static
        round widths."""
        ins = dels = marks = maps = 0
        admitted: List[Change] = []
        for idx, ch in enumerate(ordered):
            ci, cd, cm, cp = cls._op_counts(ch)
            if ins + ci > ki or dels + cd > kd or marks + cm > km or maps + cp > kp:
                return admitted, ordered[idx:]
            ins, dels, marks, maps = ins + ci, dels + cd, marks + cm, maps + cp
            admitted.append(ch)
        return admitted, []

    # -- reads (synchronization points) ------------------------------------

    @staticmethod
    def _replay_changes(sess: _DocSession) -> List[Change]:
        """A doc's full change history for scalar replay: decoded wire frames
        in frame mode, the object log otherwise."""
        if sess.frame_mode:
            return [ch for f in sess.frames for ch in decode_frame(f)]
        return sess.log + sess.pending

    def _attr_tables(self, sess: _DocSession, doc_index: int):
        """(link/general attr table, per-doc comment-id table) for decode."""
        if sess.frame_mode:
            return self._frame_attrs, self._doc_comment_ids.get(doc_index)
        attrs = sess.encoder.attrs if sess.encoder else None
        return attrs, attrs  # object path interns per doc already

    # -- block-cached resolution ------------------------------------------
    #
    # Reads resolve the doc axis in fixed-size BLOCKS: at 100K docs a full-
    # batch span resolution materializes multi-GB comment planes and OOMs
    # HBM, while any single read only needs its own block.  Blocks are
    # cached per round (the hot pattern: many per-doc reads between steps)
    # with at most two blocks resident.  Mesh sessions use one whole-batch
    # block: state is sharded across devices there, and slicing would
    # gather across shards.

    def _block_bounds(self, block_index: int):
        lo = block_index * self._read_chunk
        return lo, min(lo + self._read_chunk, self._padded_docs)

    def _state_block(self, block_index: int) -> PackedDocs:
        lo, hi = self._block_bounds(block_index)
        if lo == 0 and hi == self._padded_docs:
            return self.state
        if self._apply_blocks is None:
            # one dispatch splits EVERY block (and the list is kept: it is
            # exactly the "blocks match state" invariant _apply_compact
            # maintains), instead of 21 per-leaf slice programs per block
            n_blocks = -(-self._padded_docs // self._read_chunk)
            self._apply_blocks = _split_blocks(
                self.state,
                tuple(self._block_bounds(b) for b in range(n_blocks)),
            )
        return self._apply_blocks[block_index]

    def _block_fallback_mask(self, block_index: int) -> np.ndarray:
        """(block,) bool: rows currently served by the device (a real doc's
        row, and that doc not fallback)."""
        lo, hi = self._block_bounds(block_index)
        on_device = np.zeros(hi - lo, bool)
        for local, d in enumerate(self._doc_at[lo:hi]):
            if d >= 0:
                on_device[local] = not self.docs[d].fallback
        return on_device

    def _resolution(self, block_index: int) -> _BlockResolution:
        """Per-round cached resolution + fused digest of one doc block (ONE
        device program for both — digest() and the read paths share it).

        Cache hits are O(1): the fused digest's doc mask is validated only
        by the digest consumers (:meth:`digest` / :meth:`digest_async`, via
        ``fresh_mask=True``) — the read paths route each doc on its CURRENT
        ``fallback`` flag before consulting the cache, so a stale mask can
        only ever affect the digest scalar, never a read."""
        stamp, cache = self._resolved_cache
        if stamp != self.rounds:
            cache = {}
            self._resolved_cache = (self.rounds, cache)
        if block_index in cache:
            entry = cache.pop(block_index)  # re-insert: LRU, not FIFO
            cache[block_index] = entry
            return entry
        lo, hi = self._block_bounds(block_index)
        on_device = self._block_fallback_mask(block_index)
        with self.tracer.span("streaming.resolve", block=block_index):
            dispatch_args = (
                self._state_block(block_index), self.comment_capacity,
                jnp.asarray(on_device), *self._digest_tables(lo, hi),
            )
            if GLOBAL_DEVPROF.enabled:
                note_jit_dispatch(
                    "_resolve_block_digest_jit", _resolve_block_digest_jit,
                    dispatch_args,
                )
            resolved, digest_dev = _resolve_block_digest_jit(*dispatch_args)
        entry = _BlockResolution(resolved, digest_dev, on_device)
        if len(cache) >= 2:  # bound host/device memory at large scale
            cache.pop(next(iter(cache)))  # least-recently-used
        cache[block_index] = entry
        return entry

    def _digest_resolution(self, block_index: int) -> _BlockResolution:
        """_resolution plus doc-mask freshness: a fallback transition without
        a round bump (demotion at read time, or a test flipping the flag)
        invalidates the fused digest's mask — recompute the block then."""
        entry = self._resolution(block_index)
        current = self._block_fallback_mask(block_index)
        if not np.array_equal(entry.on_device, current):
            stamp, cache = self._resolved_cache
            cache.pop(block_index, None)
            entry = self._resolution(block_index)
        return entry

    def _resolved_block(self, block_index: int):
        """Numpy-converted span resolution of one doc block (read paths)."""
        return self._resolution(block_index).to_np()

    def _resolved_doc(self, doc_index: int):
        """(resolved block, index of the doc within it)."""
        row = int(self._row_of[doc_index])
        bi = row // self._read_chunk
        return self._resolved_block(bi), row - bi * self._read_chunk

    def read(self, doc_index: int) -> List[FormatSpan]:
        sess = self.docs[doc_index]
        if sess.fallback:
            return _replay_spans(self._replay_changes(sess))
        resolved, local = self._resolved_doc(doc_index)
        if bool(resolved.overflow[local]):
            return _replay_spans(self._replay_changes(sess))
        attrs, comments = self._attr_tables(sess, doc_index)
        return decode_doc_spans(resolved, local, attrs, comments)

    def read_patches(self, doc_index: int) -> List:
        """Incremental reference-shaped patches since this doc's previous
        ``read_patches`` call (the first call builds the doc from empty) —
        config 5's "async patch scatter": device state is diffed host-side
        between reads (ops/patches.py), keyed on stable element identities,
        so editors receive the same patch vocabulary the scalar path emits
        (insert/delete/addMark/removeMark, testing/accumulate.py model)."""
        from ..ops.patches import diff_patches

        chars = self._doc_chars(doc_index)
        base = self._patch_base.get(doc_index, [])
        patches = diff_patches(base, chars)
        self._patch_base[doc_index] = chars
        return patches

    def _doc_chars(self, doc_index: int):
        from ..ops.patches import doc_chars_device, doc_chars_scalar

        sess = self.docs[doc_index]
        if sess.fallback:
            return doc_chars_scalar(_replay_doc(self._replay_changes(sess)))
        resolved, local = self._resolved_doc(doc_index)
        if bool(resolved.overflow[local]):
            return doc_chars_scalar(_replay_doc(self._replay_changes(sess)))
        attrs, comments = self._attr_tables(sess, doc_index)
        # the doc's element row comes from the same BLOCK the resolution
        # used (layout-independent: the paged backend materializes blocks
        # at their page-bucketed width, and the elem row must align with
        # the resolved planes' slot axis)
        bi = int(self._row_of[doc_index]) // self._read_chunk
        return doc_chars_device(
            resolved,
            local,
            attrs,
            np.asarray(self._state_block(bi).elem_id[local]),
            self._actor_table,
            comments,
        )

    def resolve_cursors(self, doc_index: int, cursors) -> List[int]:
        """Resolve stable cursors (reference ``Cursor`` dicts, src/
        micromerge.ts:859-870) for one doc; see resolve_cursors_batch."""
        return self.resolve_cursors_batch({doc_index: list(cursors)})[doc_index]

    def resolve_cursors_batch(self, cursor_map) -> Dict[int, List[int]]:
        """Resolve cursors for many docs in ONE batched device call
        (ops/resolve.resolve_cursors; width bucketed so varying counts reuse
        one compiled program).  ``cursor_map``: {doc_index: [Cursor, ...]}.
        Fallback and overflowed docs resolve via scalar replay.  Returns
        visible indices per doc, -1 for absent elements."""
        from ..ops.resolve import (
            oracle_cursor_positions,
            pack_cursor_rows,
            resolve_cursors_jit,
        )

        # Route on the per-block RESOLVED overflow (apply-time overflow plus
        # resolve-time mark/comment errors) so cursor fallback matches
        # read()/read_all() exactly; blocks are cached per round.
        device_map, replay_docs = {}, []
        for d, cursors in cursor_map.items():
            if self.docs[d].fallback:
                replay_docs.append(d)
                continue
            row = int(self._row_of[d])
            bi = row // self._read_chunk
            # overflow routing needs only the (D,) vector, not the planes
            if bool(self._resolution(bi).overflow[row - bi * self._read_chunk]):
                replay_docs.append(d)
            else:
                device_map[d] = cursors

        out: Dict[int, List[int]] = {}
        by_block: Dict[int, Dict[int, list]] = {}
        for d, cursors in device_map.items():
            by_block.setdefault(int(self._row_of[d]) // self._read_chunk, {})[d] = cursors
        for bi, block_map in by_block.items():
            lo, hi = self._block_bounds(bi)
            local_map = {
                int(self._row_of[d]) - lo: c for d, c in block_map.items()
            }
            cursor_elem = pack_cursor_rows(
                local_map, hi - lo, lambda d: self._actor_table
            )
            visible_dev = self._resolution(bi).device.visible  # stays on device
            positions = np.asarray(
                resolve_cursors_jit(
                    self._state_block(bi), visible_dev, cursor_elem
                )
            )
            for d, cursors in block_map.items():
                row = int(self._row_of[d])
                out[d] = [int(p) for p in positions[row - lo, : len(cursors)]]
        for d in replay_docs:
            doc = _replay_doc(self._replay_changes(self.docs[d]))
            out[d] = oracle_cursor_positions(doc, cursor_map[d])
        return out

    def read_root(self, doc_index: int) -> dict:
        """Materialize one doc's root map (nested maps + the text character
        list) — the streaming twin of MergeReport.roots: device docs decode
        their LWW register table (ops/decode.decode_doc_root; both ingest
        paths emit a VK_TEXT register for the makeList, so text placement
        resolves through register LWW), fallback docs replay through the
        oracle."""
        from ..ops.decode import decode_doc_root

        sess = self.docs[doc_index]
        if sess.fallback:
            return _replay_doc(self._replay_changes(sess)).root
        resolved, local = self._resolved_doc(doc_index)
        if bool(resolved.overflow[local]):
            return _replay_doc(self._replay_changes(sess)).root
        lo = (doc_index // self._read_chunk) * self._read_chunk
        block_state = self._state_block(doc_index // self._read_chunk)
        keys = (
            self._map_keys if sess.frame_mode
            else (sess.encoder.keys if sess.encoder else self._map_keys)
        )
        # both ingest paths emit a VK_TEXT register for the makeList, so the
        # text placement resolves through register LWW like any other key
        return decode_doc_root(block_state, resolved, doc_index - lo, keys)

    def _block_tables(self, lo: int):
        """(attr_of, comment_of) accessors for block-local ROW indices."""
        def attr_of(local: int):
            d = int(self._doc_at[lo + local])
            return self._attr_tables(self.docs[d], d)[0]

        def comment_of(local: int):
            d = int(self._doc_at[lo + local])
            table = self._attr_tables(self.docs[d], d)[1]
            return table if table is not None else Interner()

        return attr_of, comment_of

    def _block_device_mask(self, resolved, lo: int, hi: int) -> np.ndarray:
        """Rows of a block served from device state (not fallback/overflow)."""
        return self._block_fallback_mask(
            lo // self._read_chunk
        ) & ~np.asarray(resolved.overflow)[: hi - lo]

    def _compact_cached(self, block_index: int):
        """CompactBlock cache lookup for the current (round, epoch)."""
        stamp = (self.rounds, self._placement_epoch)
        if self._compact_cache[0] != stamp:
            self._compact_cache = (stamp, {}, 0)
        return self._compact_cache[1].get(block_index)

    def _compact_store(self, block_index: int, c):
        stamp, cache, nbytes = self._compact_cache
        if nbytes + c.nbytes <= _COMPACT_CACHE_BYTES:
            cache[block_index] = c
            self._compact_cache = (stamp, cache, nbytes + c.nbytes)

    def _compact_width_for(self, block_index: int, entry) -> int:
        """Visible-prefix width for a block's packed transfer.  The first
        block of a session pays one device round-trip for its max visible
        count; later blocks start from the session-wide prior (docs are
        statistically alike across blocks) and the post-transfer validation
        in _finish_compact widens on the rare miss — steady-state sweeps
        make ZERO width round-trips."""
        width = self._compact_width.get(block_index) or self._compact_width.get(-1)
        if width is None:
            width = min(
                _width_bucket(int(_max_visible_jit(entry.device.visible))),
                self._slot_capacity,
            )
            self._compact_width[-1] = width
        self._compact_width[block_index] = width
        return width

    def _dispatch_compact(self, block_index: int):
        """Dispatch (async) one block's packed visible-prefix transfer;
        returns ``(device_buf, width)`` for :meth:`_finish_compact`."""
        entry = self._resolution(block_index)
        width = self._compact_width_for(block_index, entry)
        buf = _compact_packed_jit(
            entry.device, self._state_block(block_index).elem_id, width
        )
        return buf, width

    def _finish_compact(self, block_index: int, buf, width: int):
        """Fetch + unpack a dispatched packed buffer, re-fetching wider if
        any live row's visible count outgrew the cached width (truncation
        would otherwise drop characters silently)."""
        words = (buf.shape[1] - 2 - 4 * width) // max(width, 1)
        c = _unpack_compact(np.asarray(buf), width, words)
        live = ~c.overflow & self._block_fallback_mask(block_index)
        if live.any():
            need = int(c.n_vis[live].max())
            if need > width:
                wide = min(_width_bucket(need), self._slot_capacity)
                entry = self._resolution(block_index)
                # never wider than the block's resolved planes: the paged
                # backend materializes blocks below slot capacity, and an
                # over-wide take would silently truncate the packed layout
                wide = min(wide, int(entry.device.char.shape[1]))
                self._compact_width[block_index] = wide
                self._compact_width[-1] = max(self._compact_width.get(-1) or 0, wide)
                buf = _compact_packed_jit(
                    entry.device,
                    self._state_block(block_index).elem_id, wide,
                )
                c = _unpack_compact(np.asarray(buf), wide, words)
        return c

    def _compact_block(self, block_index: int):
        """Fetched visible-prefix planes of one block (ops/decode.
        CompactBlock): the resolution's (D, S) planes gathered device-side
        to bucketed visible-prefix width and transferred as ONE packed
        buffer — the sweep paths decode from this instead of the full
        planes (~5x fewer bytes, one RPC), and a (round, epoch)-scoped
        byte-bounded cache lets a spans sweep and a patches sweep share
        the transfer."""
        hit = self._compact_cached(block_index)
        if hit is not None:
            return hit
        buf, width = self._dispatch_compact(block_index)
        c = self._finish_compact(block_index, buf, width)
        self._compact_store(block_index, c)
        return c

    def _sweep_compact(self, blocks=None, lookahead: int = 1):
        """Iterate ``(block_index, CompactBlock)`` over the session's live
        (non-pad-only) blocks — or an explicit list — with the next block's
        device work dispatched (and its packed buffer copying to host
        asynchronously) while the caller decodes the current one: the
        sweep's device/link time hides behind its Python decode time."""
        if blocks is None:
            blocks = [
                bi for bi in range(-(-self._padded_docs // self._read_chunk))
                if (self._doc_at[slice(*self._block_bounds(bi))] >= 0).any()
            ]
        blocks = list(blocks)
        inflight: Dict[int, tuple] = {}
        nxt = 0
        for j, bi in enumerate(blocks):
            while nxt < len(blocks) and nxt <= j + lookahead:
                b = blocks[nxt]
                if self._compact_cached(b) is None and b not in inflight:
                    buf, width = self._dispatch_compact(b)
                    try:
                        buf.copy_to_host_async()
                    except AttributeError:  # platform without async copy
                        pass
                    inflight[b] = (buf, width)
                nxt += 1
            hit = self._compact_cached(bi)
            if hit is None:
                buf, width = inflight.pop(bi)
                hit = self._finish_compact(bi, buf, width)
                self._compact_store(bi, hit)
            else:
                inflight.pop(bi, None)
            yield bi, hit

    def read_all(self) -> List[List[FormatSpan]]:
        """Span sweep over every doc: device docs decode in ONE vectorized
        pass per block (ops/decode.decode_block_spans_compact — Python
        touches only mark-run segments, the device link only visible-prefix
        planes), fallback/overflow docs replay."""
        with self.tracer.span("streaming.decode", docs=self.num_docs):
            return self._read_all()

    def _read_all(self) -> List[List[FormatSpan]]:
        from ..ops.decode import decode_block_spans_compact

        out: List[Optional[List[FormatSpan]]] = [None] * self.num_docs
        for bi, compact in self._sweep_compact():
            lo, hi = self._block_bounds(bi)
            docs_here = self._doc_at[lo:hi]
            mask = self._block_device_mask(compact, lo, hi)
            attr_of, comment_of = self._block_tables(lo)
            spans = decode_block_spans_compact(
                compact, attr_of, comment_of, doc_mask=mask
            )
            for local, d in enumerate(docs_here):
                if d < 0:
                    continue
                if mask[local]:
                    out[d] = spans[local]
                else:
                    out[d] = _replay_spans(self._replay_changes(self.docs[d]))
        return out

    def read_patches_all(self) -> List[List]:
        """Batched incremental-patch sweep: one vectorized char-state
        extraction per block (ops/decode.block_char_states_compact), then
        the per-doc identity diff — config 5's async patch scatter for a
        whole-session sweep (the per-doc ``read_patches`` stays for point
        reads).  Shares the per-block compact transfer with read_all via
        the (round, epoch) cache."""
        with self.tracer.span("streaming.patch-scatter", docs=self.num_docs):
            return self._read_patches_all()

    def _read_patches_all(self) -> List[List]:
        from ..ops.decode import block_char_states_compact
        from ..ops.patches import diff_patches, doc_chars_scalar

        out: List[List] = [None] * self.num_docs
        for bi, compact in self._sweep_compact():
            lo, hi = self._block_bounds(bi)
            docs_here = self._doc_at[lo:hi]
            mask = self._block_device_mask(compact, lo, hi)
            attr_of, comment_of = self._block_tables(lo)
            chars_block = block_char_states_compact(
                compact, self._actor_table, attr_of, comment_of, doc_mask=mask
            )
            for local, d in enumerate(docs_here):
                if d < 0:
                    continue
                if mask[local]:
                    chars = chars_block[local]
                else:
                    chars = doc_chars_scalar(
                        _replay_doc(self._replay_changes(self.docs[d]))
                    )
                base = self._patch_base.get(d, [])
                out[d] = diff_patches(base, chars)
                self._patch_base[d] = chars
        return out

    # -- cross-shard reductions (the ICI/DCN collectives) ------------------

    def reshard(self, assignment: Optional[Sequence[int]] = None) -> dict:
        """Load-balance doc placement across shards (SURVEY §5.8(c)).

        Streaming sessions place docs at first sight and never move them
        (``rebalance`` is placement-time only), so skewed arrival leaves hot
        shards bounding round latency.  This moves packed doc rows between
        shards as ONE gather over the doc axis — under a mesh XLA lowers
        the cross-shard row movement to collective permutes over ICI (the
        all-to-all) — while every logical doc id, clock, interner, pending
        queue and fallback flag stays put: placement is an internal detail
        behind ``_row_of``/``_doc_at``, so reads, ingest and digests are
        unchanged (digest is a doc-sum — permutation-invariant by
        construction; tests assert it bit-equal across a reshard).

        ``assignment`` maps each logical doc to a target shard (len
        ``num_docs``); default balances per-shard LIVE SLOT load greedily
        (largest doc first onto the least-loaded shard with a free row),
        with quarantine-aware placement: quarantined/fallback docs are
        HOST-BOUND (scalar replay runs on the shard's host CPU, not its
        chip), so the default assignment additionally spreads their load —
        a host-bound doc goes to the shard carrying the least host-bound
        load first, slot load second, while device docs weigh slot load
        first — keeping a burst of scalar-replay docs from crowding one
        shard's host.  Shards are ``mesh.size`` for mesh sessions, else the
        read-block count (balancing per-block read/digest latency).
        Returns ``{"moved": n, "shard_load": [...],
        "host_bound_load": [...]}``."""
        n_blocks = -(-self._padded_docs // self._read_chunk)
        n_shards = self.mesh.size if self.mesh is not None else n_blocks
        if n_shards <= 1 or self.num_docs == 0:
            return {"moved": 0, "shard_load": [0] * max(n_shards, 1),
                    "host_bound_load": [0] * max(n_shards, 1)}
        if self._padded_docs % n_shards:
            raise ValueError("padded doc axis must divide the shard count")
        rows_per_shard = self._padded_docs // n_shards
        sizes = self._reshard_sizes()
        host_bound = {
            d for d in range(self.num_docs)
            if self.docs[d].fallback or d in self._quarantine
        }
        if assignment is None:
            # host-bound docs place FIRST (they are the scarce dimension:
            # row capacity must not strand the last of them on a crowded
            # host), then device docs, each group largest-first
            order = sorted(
                range(self.num_docs),
                key=lambda d: (d not in host_bound, -int(sizes[d])),
            )
            load = [0] * n_shards
            hb_load = [0] * n_shards
            free = [rows_per_shard] * n_shards
            assignment = [0] * self.num_docs
            for d in order:
                # host-bound (quarantined/fallback scalar-replay) docs cost
                # the shard's HOST, not its chip: balance that dimension
                # first for them, second for device docs, so neither the
                # chips nor one host's CPU becomes the round bound
                key = (
                    (lambda s: (hb_load[s], load[s])) if d in host_bound
                    else (lambda s: (load[s], hb_load[s]))
                )
                s = min((s for s in range(n_shards) if free[s] > 0), key=key)
                assignment[d] = s
                load[s] += int(sizes[d])
                if d in host_bound:
                    hb_load[s] += int(sizes[d])
                free[s] -= 1
        else:
            assignment = [int(s) for s in assignment]
            if len(assignment) != self.num_docs:
                raise ValueError("assignment must cover every doc")
            for s, count in zip(*np.unique(assignment, return_counts=True)):
                if not 0 <= s < n_shards:
                    raise ValueError(f"shard {s} out of range")
                if count > rows_per_shard:
                    raise ValueError(f"shard {s} over capacity: {count} docs")

        next_row = [s * rows_per_shard for s in range(n_shards)]
        new_row = np.empty(self.num_docs, np.int64)
        for d, s in enumerate(assignment):
            new_row[d] = next_row[s]
            next_row[s] += 1
        moved = int((new_row != self._row_of).sum())
        if moved == 0:
            pass
        else:
            # permutation: new physical row r carries old row src[r]; rows
            # not holding a doc recycle the old empty rows (zeros), so src
            # is a full permutation and pad rows stay no-op
            src = np.full(self._padded_docs, -1, np.int64)
            src[new_row] = self._row_of
            spare = iter(sorted(
                set(range(self._padded_docs)) - set(int(r) for r in self._row_of)
            ))
            for r in range(self._padded_docs):
                if src[r] < 0:
                    src[r] = next(spare)
            self._permute_rows(src)
            self._cum_ins = self._cum_ins[src]  # occupancy bound rides the rows
            self._row_of = new_row
            self._doc_at = np.full(self._padded_docs, -1, np.int64)
            self._doc_at[new_row] = np.arange(self.num_docs)
            # placement changed: every physically-keyed cache is stale, and
            # in-flight async digests must not write back (epoch guard)
            self._resolved_cache = (-1, {})
            self._digest_row_valid[:] = False
            self._apply_blocks = None
            self._placement_epoch += 1
        shard_load = [0] * n_shards
        host_bound_load = [0] * n_shards
        for d, s in enumerate(assignment):
            shard_load[s] += int(sizes[d])
            if d in host_bound:
                host_bound_load[s] += int(sizes[d])
        return {"moved": moved, "shard_load": shard_load,
                "host_bound_load": host_bound_load}

    def _reshard_sizes(self) -> np.ndarray:
        """(num_docs,) per-doc load for reshard's balancing — live device
        slots under the padded layout; the paged subclass balances PAGES
        (the resource its pool actually spends)."""
        return np.asarray(self.state.num_slots)[self._row_of[: self.num_docs]]

    def _permute_rows(self, src: np.ndarray) -> None:
        """Move physical doc rows per ``src`` (new row r takes old row
        src[r]) — one gather over the padded layout's doc axis; the paged
        subclass permutes page TABLES and aux rows instead."""
        idx = jnp.asarray(src)
        state = PackedDocs(*(jnp.take(x, idx, axis=0) for x in self.state))
        self.state = shard_docs(state, self.mesh) if self.mesh is not None else state

    def _digest_tables_rows(self, rows: np.ndarray, n_real: int):
        """Digest hash tables for a GATHERED row subset (the sub-batch
        program) — same shapes/semantics as :meth:`_digest_tables` but
        row-indexed by position in ``rows``; small, so uncached.  Only the
        first ``n_real`` positions are real (the rest is power-of-two
        padding that repeats row 0 — its table entries must stay zero, and
        building them would also let the padding shadow the REAL row 0);
        everything here is O(n_real), not O(session)."""
        k = len(rows)
        sess_attr = self._frame_attrs.content_hashes()
        sess_keys = self._map_keys.content_hashes()
        enc = {}
        for i in range(n_real):
            d = int(self._doc_at[rows[i]])
            if d >= 0 and not self.docs[d].frame_mode and \
                    self.docs[d].encoder is not None:
                enc[i] = self.docs[d].encoder
        a_w = _width_bucket(max(
            [len(sess_attr)] + [len(e.attrs.content_hashes()) for e in enc.values()]
        ))
        k_w = _width_bucket(max(
            [len(sess_keys)] + [len(e.keys.content_hashes()) for e in enc.values()]
        ))
        c_w = self.comment_capacity
        sess_attr_t = np.zeros(a_w, np.uint32)
        sess_attr_t[: len(sess_attr)] = sess_attr
        sess_key_t = np.zeros(k_w, np.uint32)
        sess_key_t[: len(sess_keys)] = sess_keys
        row_map = np.full(k, -1, np.int32)
        obj_attr = np.zeros((_width_bucket(len(enc)) if enc else 0, a_w), np.uint32)
        obj_key = np.zeros((obj_attr.shape[0], k_w), np.uint32)
        comment_hash = np.zeros((k, c_w), np.uint32)
        # sorted: override-row assignment order must be a function of the
        # row set (it feeds row_map and the digest tables), never of dict
        # insertion history
        for j, (i, e) in enumerate(sorted(enc.items())):
            ah = e.attrs.content_hashes()
            kh = e.keys.content_hashes()
            row_map[i] = j
            obj_attr[j, : len(ah)] = ah
            obj_key[j, : len(kh)] = kh
            comment_hash[i, : min(c_w, len(ah))] = ah[:min(c_w, len(ah))]
        for i in range(n_real):
            d = int(self._doc_at[rows[i]])
            table = self._doc_comment_ids.get(d) if d >= 0 else None
            if table is not None and self.docs[d].frame_mode:
                ch = table.content_hashes()
                comment_hash[i, : min(c_w, len(ch))] = ch[:min(c_w, len(ch))]
        return (jnp.asarray(sess_attr_t), jnp.asarray(sess_key_t),
                jnp.asarray(comment_hash), jnp.asarray(row_map),
                jnp.asarray(obj_attr), jnp.asarray(obj_key))

    def _on_device_mask(self) -> np.ndarray:
        """(padded,) bool: rows currently backed by device state (their doc
        not fallback); placement goes through ``_row_of``."""
        on_dev = np.zeros(self._padded_docs, bool)
        for d, s in enumerate(self.docs):
            if not s.fallback:
                on_dev[self._row_of[d]] = True
        return on_dev

    def _schedule_rows_digest(self, rest: np.ndarray):
        """Dispatch the gathered sub-batch hash program for dirty rows
        ``rest`` (shared by digest() and digest_async()); returns the
        device refs ``(per_doc_dev, ov_dev)`` — callers slice the first
        ``len(rest)`` entries after fetching."""
        k = _width_bucket(len(rest))
        rows_idx = np.zeros(k, np.int32)
        rows_idx[: len(rest)] = rest
        mask = np.zeros(k, bool)
        mask[: len(rest)] = True
        sub = _gather_rows(self.state, jnp.asarray(rows_idx), self.mesh)
        dispatch_args = (
            sub, self.comment_capacity, jnp.asarray(mask),
            *self._digest_tables_rows(rows_idx, len(rest)),
        )
        if GLOBAL_DEVPROF.enabled:
            note_jit_dispatch(
                "_rows_digest_jit", _rows_digest_jit, dispatch_args,
            )
        return _rows_digest_jit(*dispatch_args)

    def _refresh_digest_rows(self):
        """Bring the carried per-row hash plane current for every on-device
        real-doc row, re-hashing only invalid rows: heavily-dirty blocks go
        through the fused block program (lookahead-pipelined, shared with
        the read paths); the remaining dirty rows pool into ONE gathered
        sub-batch program regardless of how many blocks they span."""
        on_dev = self._on_device_mask()
        need = ~self._digest_row_valid & on_dev & (self._doc_at >= 0)
        if not need.any():
            return on_dev
        n_blocks = -(-self._padded_docs // self._read_chunk)
        heavy = []
        for bi in range(n_blocks):
            lo, hi = self._block_bounds(bi)
            if int(need[lo:hi].sum()) > (hi - lo) // 4:
                heavy.append(bi)
        # heavy blocks: fused resolve+hash, lookahead-1 pipelined
        pending: Dict[int, object] = {}
        nxt = 0
        for j, bi in enumerate(heavy):
            while nxt < len(heavy) and nxt <= j + 1:
                entry = self._digest_resolution(heavy[nxt])
                self._start_digest_readback(entry)
                pending[heavy[nxt]] = entry
                nxt += 1
            entry = pending.pop(bi)
            lo, hi = self._block_bounds(bi)
            self._digest_plane[lo:hi] = entry.digest_per_doc
            self._digest_ov[lo:hi] = entry.overflow
            self._digest_row_valid[lo:hi] = on_dev[lo:hi] & (self._doc_at[lo:hi] >= 0)
            need[lo:hi] = False
        # the long tail: one gathered sub-batch program
        rest = np.nonzero(need)[0]
        if len(rest):
            per_doc_dev, ov_dev = self._schedule_rows_digest(rest)
            self._digest_plane[rest] = np.asarray(per_doc_dev)[: len(rest)]
            self._digest_ov[rest] = np.asarray(ov_dev)[: len(rest)]
            self._digest_row_valid[rest] = True
        return on_dev

    def digest(self, full: bool = True, refresh: bool = False) -> int:
        """Global convergence digest: with a mesh, XLA lowers the cross-doc
        reduction to an all-reduce over ICI.  Two sessions that converged
        hold equal digests.

        ``full=True`` (default) digests the COMPLETE document state — visible
        text, resolved formatting (LWW winner bits, link urls, comment-id
        sets) and map registers — matching the scope of the reference's
        convergence oracles (test/fuzz.ts:245-278 compare formatted text, not
        characters).  Interned identities are folded as content hashes, so
        two sessions that interned attrs/keys/values in different orders
        still agree.  ``full=False`` is the cheaper text-only digest (the
        comment planes compile away entirely — resolve.py ``with_comments``).

        Device-resident docs hash on device; fallback and overflowed docs —
        the ones the read paths route to scalar replay — are masked out of
        the device sum and hashed HOST-SIDE with the bit-identical per-doc
        formula (mesh.doc_digest_host and the format/register mirrors), so
        two converged peers agree even when their demotion histories differ.
        (The equivalence needs the replayed doc to fit the device capacities;
        a doc too large for any device row hashes consistently between
        fallback peers only.)

        The digest is a doc-sum of per-doc hashes carried in a host-side
        per-row plane; a call re-hashes only rows invalidated since the
        last one (see :meth:`_refresh_digest_rows`), then sums the plane
        mod 2^32 — identical to a whole-batch recompute while keeping the
        per-round cost proportional to touched docs.  ``refresh=True`` is
        the verification path: every row re-hashes from current device
        state, ignoring (and rebuilding) the carried plane."""
        with self.tracer.span("streaming.digest", full=full, refresh=refresh):
            return self._digest(full, refresh)

    def _digest(self, full: bool, refresh: bool) -> int:
        from .mesh import doc_digest_host

        if refresh:
            self._digest_row_valid[:] = False
            self._resolved_cache = (-1, {})

        replay_docs = [i for i, s in enumerate(self.docs) if s.fallback]
        if full:
            on_device_all = self._refresh_digest_rows()
            ok = (self._digest_row_valid & on_device_all & ~self._digest_ov
                  & (self._doc_at >= 0))
            total = int(self._digest_plane[ok].sum(dtype=np.uint32))
            replay_docs.extend(
                int(self._doc_at[r])
                for r in np.nonzero(self._digest_ov & on_device_all
                                    & (self._doc_at >= 0))[0]
            )
        else:
            on_device_all = self._on_device_mask()
            total = 0
            n_blocks = -(-self._padded_docs // self._read_chunk)
            for bi in range(n_blocks):
                lo, hi = self._block_bounds(bi)
                digest, overflow = _resolve_digest_jit(
                    self._state_block(bi), self.comment_capacity,
                    jnp.asarray(on_device_all[lo:hi]),
                )
                digest, ov = int(digest), np.asarray(overflow)
                total = (total + digest) & 0xFFFFFFFF
                replay_docs.extend(
                    int(self._doc_at[int(r) + lo])
                    for r in np.nonzero(ov & on_device_all[lo:hi])[0]
                    if int(self._doc_at[int(r) + lo]) >= 0
                )
        s_cap = self._slot_capacity
        for i in replay_docs:
            doc = _replay_doc(self._replay_changes(self.docs[i]))
            cps, slots = _doc_char_slots(doc)
            part = doc_digest_host(cps, slots, s_cap)
            if full:
                part = (part + _doc_full_extras_host(doc, slots, self._actor_table)) & 0xFFFFFFFF
            total = (total + part) & 0xFFFFFFFF
        return total

    def doc_digest(self, doc_index: int) -> int:
        """ONE doc's full-state convergence hash — exactly the per-doc term
        :meth:`digest` sums (device rows read the carried per-row hash
        plane; fallback/overflowed docs hash host-side with the
        bit-identical formula), so ``sum(doc_digest(i)) mod 2^32 ==
        digest()`` on an all-real-doc session (pinned by test).

        Interned identities fold as content hashes, so two SESSIONS that
        interned attrs/keys in different orders still agree per doc — this
        is the fleet tier's migration-cutover oracle: a doc shipped to a
        new host must hash byte-equal there before the old slot is
        released."""
        from .mesh import doc_digest_host

        sess = self.docs[doc_index]
        if not sess.fallback:
            on_device_all = self._refresh_digest_rows()
            row = int(self._row_of[doc_index])
            if on_device_all[row] and not self._digest_ov[row]:
                return int(self._digest_plane[row])
        doc = _replay_doc(self._replay_changes(sess))
        cps, slots = _doc_char_slots(doc)
        part = doc_digest_host(cps, slots, self._slot_capacity)
        return (part + _doc_full_extras_host(
            doc, slots, self._actor_table
        )) & 0xFFFFFFFF

    def digest_async(self) -> "_PendingDigest":
        """Schedule the full-state convergence digest WITHOUT synchronizing:
        the fused resolve+digest programs are enqueued (device work proceeds
        behind the queue) and the returned handle's ``wait()`` fetches only
        the per-block scalars + overflow vectors.  A per-round sync point
        then costs one enqueue (~ms) instead of a blocking device
        round-trip, and the digest overlaps the next round's host-side
        ingest parsing (VERDICT r2 weak #7).

        Semantics: the device hashes snapshot the state AT SCHEDULING time
        (the per-round block cache / carried row plane).  Docs that were
        already fallback — or that the overflow vectors route to scalar
        replay — are hashed at ``wait()`` time from their CURRENT change
        history, so call ``wait()`` before further ingestion whenever such
        docs exist (sessions with zero fallbacks/overflows may wait at any
        time)."""
        on_dev = self._on_device_mask()
        need = ~self._digest_row_valid & on_dev & (self._doc_at >= 0)
        parts = []
        n_blocks = -(-self._padded_docs // self._read_chunk)
        for bi in range(n_blocks):
            lo, hi = self._block_bounds(bi)
            if int(need[lo:hi].sum()) > (hi - lo) // 4:
                entry = self._digest_resolution(bi)
                # keep ONLY the hash-vector + overflow device refs — not
                # the _BlockResolution itself, whose resolved (D, S) planes
                # would otherwise stay pinned on device across the handle's
                # lifetime, defeating the block-cache memory bound
                parts.append(("block", lo, hi, entry.digest_dev,
                              entry.device.overflow))
                need[lo:hi] = False
        rest = np.nonzero(need)[0]
        if len(rest):
            per_doc, ov = self._schedule_rows_digest(rest)
            parts.append(("rows", rest, per_doc, ov))
        snapshot = (
            self._digest_plane.copy(), self._digest_ov.copy(),
            self._digest_row_valid.copy(), on_dev, self._doc_at.copy(),
            [i for i, s in enumerate(self.docs) if s.fallback],
        )
        return _PendingDigest(self, parts, snapshot, self.rounds,
                              self._placement_epoch)

    def _digest_tables(self, lo: int, hi: int):
        """Compact content-hash tables for the full digest: interned-id ->
        FNV-1a hash for link/mark attrs, per-doc dense comment ids, and map
        keys/string-values.

        Frame-mode docs all share the SESSION tables, so those ship as flat
        ``(A,)`` / ``(K,)`` arrays and are broadcast to rows on DEVICE — a
        host-side ``(D, A)`` materialization was ~A*4 bytes per doc per
        digest through the device link (128 MB/call at 2K docs x 16K attrs,
        the whole streaming digest stage cost on a tunneled chip).  Only
        object-path docs carry genuinely per-doc id spaces: their encoder
        tables ride in a sparse ``(n_obj, A)`` override matrix addressed by
        ``row_map`` (-1 = session tables).  Per-doc comment-id tables stay
        dense — (D, comment_capacity) is small.  Fallback rows are masked
        out device-side so their contents are irrelevant."""
        d_block = hi - lo
        sess_attr = self._frame_attrs.content_hashes()
        sess_keys = self._map_keys.content_hashes()
        # rows hold docs through the placement indirection: table row r-lo
        # describes the doc at physical row r (identity until reshard)
        enc = {
            row: self.docs[d].encoder
            for row in range(lo, hi)
            if (d := int(self._doc_at[row])) >= 0
            and not self.docs[d].frame_mode and self.docs[d].encoder is not None
        }
        # interner/placement fingerprint: tables only change when an interner
        # grows, object-doc membership shifts, or docs move rows — reuse the
        # device-resident copies otherwise (repeat transfers, and under a
        # mesh the replicated device_put, are the cost being avoided here)
        key = (
            len(sess_attr), len(sess_keys), self._placement_epoch,
            tuple((row, len(e.attrs.content_hashes()), len(e.keys.content_hashes()))
                  for row, e in sorted(enc.items())),
            tuple(sorted(
                (d, len(t)) for d, t in self._doc_comment_ids.items()
                if lo <= int(self._row_of[d]) < hi and self.docs[d].frame_mode
            )),
        )
        cached = self._digest_tables_cache.get((lo, hi))
        if cached is not None and cached[0] == key:
            return cached[1]
        a_w = _width_bucket(max(
            [len(sess_attr)] + [len(e.attrs.content_hashes()) for e in enc.values()]
        ))
        k_w = _width_bucket(max(
            [len(sess_keys)] + [len(e.keys.content_hashes()) for e in enc.values()]
        ))
        c_w = self.comment_capacity
        # override row count is bucketed like the widths: each new object-path
        # doc must not mint a fresh (n_obj, ·) shape -> XLA recompile
        n_obj_w = _width_bucket(len(enc)) if enc else 0
        sess_attr_t = np.zeros(a_w, np.uint32)
        sess_attr_t[: len(sess_attr)] = sess_attr
        sess_key_t = np.zeros(k_w, np.uint32)
        sess_key_t[: len(sess_keys)] = sess_keys
        row_map = np.full(d_block, -1, np.int32)
        obj_attr = np.zeros((n_obj_w, a_w), np.uint32)
        obj_key = np.zeros((n_obj_w, k_w), np.uint32)
        comment_hash = np.zeros((d_block, c_w), np.uint32)
        # sorted for the same reason as the cache key above: the override
        # matrix row order (and therefore row_map) must depend only on
        # which rows hold object docs
        for i, (row, e) in enumerate(sorted(enc.items())):
            ah = e.attrs.content_hashes()
            kh = e.keys.content_hashes()
            row_map[row - lo] = i
            obj_attr[i, : len(ah)] = ah
            obj_key[i, : len(kh)] = kh
            # object-path comment marks index the same per-doc attr interner
            comment_hash[row - lo, : min(c_w, len(ah))] = ah[:min(c_w, len(ah))]
        for d, table in sorted(self._doc_comment_ids.items()):
            row = int(self._row_of[d])
            if lo <= row < hi and self.docs[d].frame_mode:
                ch = table.content_hashes()
                comment_hash[row - lo, : min(c_w, len(ch))] = ch[:min(c_w, len(ch))]
        comment_hash_d = jnp.asarray(comment_hash)
        row_map_d = jnp.asarray(row_map)
        sess_attr_d = jnp.asarray(sess_attr_t)
        sess_key_d = jnp.asarray(sess_key_t)
        obj_attr_d = jnp.asarray(obj_attr)
        obj_key_d = jnp.asarray(obj_key)
        if self.mesh is not None:
            comment_hash_d, row_map_d = shard_docs(
                (comment_hash_d, row_map_d), self.mesh
            )
            repl = NamedSharding(self.mesh, P())  # session/override tables
            sess_attr_d, sess_key_d, obj_attr_d, obj_key_d = (
                jax.device_put(x, repl)
                for x in (sess_attr_d, sess_key_d, obj_attr_d, obj_key_d)
            )
        tables = (sess_attr_d, sess_key_d, comment_hash_d, row_map_d,
                  obj_attr_d, obj_key_d)
        self._digest_tables_cache[(lo, hi)] = (key, tables)
        return tables

    # -- checkpoint support (peritext_tpu.checkpoint.save_session) ----------

    def doc_history_frames(self, doc_index: int) -> List[bytes]:
        """The doc's full ingested history as wire frames — the durable,
        event-sourced form (re-ingesting them reconstructs the doc exactly;
        duplicate-tolerant, so crash-replay overlap is safe).  Frame-mode
        docs return their raw frames; object/fallback docs re-encode their
        log (lossless: the codec JSON-spills anything exotic)."""
        sess = self.docs[doc_index]
        if sess.frame_mode:
            return list(sess.frames)
        changes = self._replay_changes(sess)
        return [encode_frame(changes)] if changes else []

    @property
    def config(self) -> Dict[str, int]:
        """Constructor-shape configuration (for checkpoint restore)."""
        return {
            "num_docs": self.num_docs,
            "slot_capacity": self._slot_capacity,
            "mark_capacity": self._mark_capacity,
            "tomb_capacity": self._tomb_capacity,
            "round_insert_capacity": self.round_caps[0],
            "round_delete_capacity": self.round_caps[1],
            "round_mark_capacity": self.round_caps[2],
            "round_map_capacity": self.round_caps[3],
            "comment_capacity": self.comment_capacity,
            "map_capacity": self._map_capacity,
            # the REQUESTED value: a mesh session's effective block is its
            # whole padded batch, but a meshless restore must block reads
            "read_chunk": self._read_chunk_requested,
            # the storage layout rides in the config so checkpoint restore
            # (and serve snapshots) rebuild the same backend
            "layout": self._layout,
        }

    def frontier(self) -> Clock:
        """Merged vector-clock frontier across all docs (host-side metadata)."""
        merged: Clock = {}
        if self._clock_mat.size:
            col_max = self._clock_mat.max(axis=0)  # frame docs, vectorized
            for idx in np.nonzero(col_max)[0]:
                merged[self._actor_table.lookup(int(idx))] = int(col_max[idx])
        for sess in self.docs:
            for actor, seq in sorted(sess.clock.items()):
                merged[actor] = max(merged.get(actor, 0), seq)
        # sorted at the END so the frontier's key order (which reaches wire
        # frames via json) is a function of the actor set alone — the
        # clock-matrix loop above inserts in actor-table interning (arrival)
        # order, which is replica-local
        return dict(sorted(merged.items()))

    def overflow_count(self) -> int:
        """Docs the device read path cannot serve: apply-time capacity
        overflow OR resolve-time errors (mark anchor not found, comment attr
        beyond capacity) — exactly the docs read() routes to scalar replay
        and digest() masks.  A nonzero count on a converged session means
        capacities should be raised for the workload (correctness is
        preserved via replay either way)."""
        n_blocks = -(-self._padded_docs // self._read_chunk)
        return sum(
            int(self._resolution(bi).overflow.sum()) for bi in range(n_blocks)
        )

    def pending_count(self) -> int:
        pooled = sum(int(self._frame_mode[d].sum()) for d, _ in self._pool)
        return pooled + sum(len(s.pending) for s in self.docs)

    def pending_rounds_estimate(self) -> int:
        """Upper-bound estimate of the device rounds a full ``drain()``
        needs: the deepest per-doc pending queue.  Docs drain in parallel
        and causal admission feeds each doc at least one change per round
        it participates in, so the deepest queue bounds the round count —
        the supervisor scales its fused-drain watchdog budget by this so a
        legitimately deep backlog is not mistaken for a hung device."""
        if not self.num_docs:
            return 0
        per_doc = np.zeros(self.num_docs, np.int64)
        for doc_of, _ in self._pool:
            live = np.asarray(doc_of)[self._frame_mode[doc_of]]
            if live.size:
                per_doc += np.bincount(live, minlength=self.num_docs)
        for d, sess in enumerate(self.docs):
            per_doc[d] += len(sess.pending)
        return int(per_doc.max())

    @property
    def layout(self) -> str:
        """Resident-state storage layout ("padded", "paged" or "ragged")."""
        return self._layout

    def sync_device(self) -> None:
        """Block until all dispatched device work has completed (a cheap
        host fetch of one per-doc scalar plane) — the layout-independent
        sync point the supervisor's guarded rounds use."""
        np.asarray(self.state.num_slots)


def _doc_char_slots(doc: Doc):
    """(visible codepoints, their slot positions in full element order incl.
    tombstones) for a scalar replica's text list — the inputs the device
    digest formula needs (mesh.doc_digest_host).

    The text list is located by OBJECT, not by the literal ``["text"]``
    path: encode_doc/the frame parser accept a makeList under any key, and
    the device path adopts whichever list the doc created — so a fallback
    doc whose list key isn't "text" must still hash the same list a
    device-resident peer adopted (advisor r2: the path-keyed lookup hashed
    such docs as empty, silently breaking digest parity across demotion
    sets).  With several lists (device peers demote such docs, but both
    sides of the comparison must stay deterministic) the earliest-created
    one — minimum (ctr, actor) opid, the same total order compareOpIds
    defines — is hashed."""
    list_id = _doc_text_list_id(doc)
    if list_id is None:
        return [], []
    meta = doc._metadata[list_id]
    text = doc._objects[list_id]
    cps, slots, vis = [], [], 0
    for i, el in enumerate(meta):
        if not el.deleted:
            cps.append(ord(text[vis]))
            slots.append(i)
            vis += 1
    return cps, slots


class _PendingDigest:
    """Deferred digest handle from :meth:`StreamingMerge.digest_async`.

    Holds references to the scheduled per-doc hash VECTORS and overflow
    vectors only (safe across cache eviction — never the resolved planes)
    plus a scheduling-time snapshot of the carried row plane and masks;
    ``wait`` merges the fetched vectors into the snapshot, folds host-side
    replay hashes exactly as ``digest()`` does, writes the fresh hashes
    back into the live plane when no round/reshard intervened, then
    releases the device refs."""

    __slots__ = ("_session", "_parts", "_snapshot", "_value", "_stamp",
                 "_epoch")

    def __init__(self, session: "StreamingMerge", parts, snapshot,
                 stamp: int, epoch: int) -> None:
        self._session = session
        self._parts = parts
        self._snapshot = snapshot
        self._value: Optional[int] = None
        self._stamp = stamp  # session round at scheduling time
        self._epoch = epoch  # placement epoch at scheduling time

    def wait(self) -> int:
        if self._value is not None:
            return self._value
        s = self._session
        plane, ovp, valid, on_dev, doc_at, fallback_docs = self._snapshot
        writeback = (s.rounds == self._stamp
                     and s._placement_epoch == self._epoch)
        for part in self._parts:
            if part[0] == "block":
                _, lo, hi, vec_dev, ov_dev = part
                vec, ov = np.asarray(vec_dev), np.asarray(ov_dev)
                rows = np.arange(lo, hi)
            else:
                _, rows, vec_dev, ov_dev = part
                vec = np.asarray(vec_dev)[: len(rows)]
                ov = np.asarray(ov_dev)[: len(rows)]
            plane[rows], ovp[rows] = vec, ov
            valid[rows] = on_dev[rows] & (doc_at[rows] >= 0)
            if writeback:
                # a round/reshard in between makes these hashes describe
                # rows that no longer hold the same content — never write
                # back then (the snapshot math above still answers for
                # scheduling time)
                s._digest_plane[rows] = vec
                s._digest_ov[rows] = ov
                s._digest_row_valid[rows] = valid[rows]
        ok = valid & on_dev & ~ovp & (doc_at >= 0)
        total = int(plane[ok].sum(dtype=np.uint32))
        replay_docs = list(fallback_docs)
        replay_docs.extend(
            int(doc_at[r]) for r in np.nonzero(ovp & on_dev & (doc_at >= 0))[0]
        )
        from .mesh import doc_digest_host

        s_cap = s._slot_capacity
        for i in replay_docs:
            doc = _replay_doc(s._replay_changes(s.docs[i]))
            cps, slots = _doc_char_slots(doc)
            part = doc_digest_host(cps, slots, s_cap)
            part = (part + _doc_full_extras_host(doc, slots, s._actor_table)) & 0xFFFFFFFF
            total = (total + part) & 0xFFFFFFFF
        self._value = total
        self._parts = ()  # release the device refs once folded
        self._snapshot = None
        return total


def _doc_text_list_id(doc: Doc):
    """The doc's text list object id, or None (see _doc_char_slots)."""
    list_ids = [
        oid for oid, meta in doc._metadata.items()
        if isinstance(meta, list) and oid in doc._objects
    ]
    if not list_ids:
        return None
    return min(list_ids)  # OpId tuples order exactly as compareOpIds


def _doc_path_of_object(doc: Doc, target) -> Optional[list]:
    """Key path from the root map to ``target`` (BFS over map children)."""
    from ..core.doc import MapMeta
    from ..core.opids import ROOT

    queue = [(ROOT, [])]
    seen = set()
    while queue:
        oid, path = queue.pop(0)
        if oid in seen:
            continue
        seen.add(oid)
        meta = doc._metadata.get(oid)
        if not isinstance(meta, MapMeta):
            continue
        for key, child in sorted(meta.children.items()):  # deterministic BFS path
            if child == target:
                return path + [key]
            queue.append((child, path + [key]))
    return None


def _doc_full_extras_host(doc: Doc, slot_positions, actor_table) -> int:
    """Formatting + map-register digest contribution of ONE scalar-replay
    doc, bit-identical to the device sums in _resolve_full_digest_jit (the
    mirrors live in mesh.format_digest_host / register_digest_host).
    ``slot_positions`` are the visible characters' element-order slots from
    :func:`_doc_char_slots`."""
    import json as _json

    from ..core.doc import MapMeta
    from ..core.opids import ROOT
    from ..ops.packed import (
        MAX_CTR,
        OBJ_ROOT,
        VK_FALSE,
        VK_INT,
        VK_NULL,
        VK_OBJ,
        VK_STR,
        VK_TEXT,
        VK_TRUE,
        pack_id,
    )
    from ..ops.resolve import COMMENT_TYPE
    from ..schema import ALL_MARKS
    from ..utils.interning import content_hash32
    from .mesh import format_digest_host, register_digest_host

    # -- formatting: expand spans to per-visible-char mark maps -------------
    marks_per_char: list = []
    list_id = _doc_text_list_id(doc)
    if list_id is not None and slot_positions:
        path = _doc_path_of_object(doc, list_id)
        if path is not None:
            for span in doc.get_text_with_formatting(path):
                marks_per_char.extend([span["marks"]] * len(span["text"]))
    if len(marks_per_char) != len(slot_positions):
        # degenerate doc (unreachable list) — formatting contributes nothing,
        # deterministically on every peer applying the same rule
        marks_per_char = [{}] * len(slot_positions)
    total = format_digest_host(
        slot_positions, marks_per_char, ALL_MARKS, COMMENT_TYPE
    )

    # -- map registers: LWW winner per (object, key), live keys only --------
    def packed_u32(opid) -> int:
        ctr, actor = opid
        idx = actor_table.get(actor)
        if idx is None or ctr > MAX_CTR:
            # undeclared actor / over-wide counter: no device peer can hold
            # this doc; a deterministic stand-in keeps fallback peers equal
            return content_hash32(f"{ctr}@{actor}")
        return pack_id(ctr, idx) & 0xFFFFFFFF

    rows = []
    for oid, meta in doc._metadata.items():
        if not isinstance(meta, MapMeta):
            continue
        obj_u32 = (OBJ_ROOT & 0xFFFFFFFF) if oid is ROOT else packed_u32(oid)
        obj = doc._objects.get(oid, {})
        for key, value in obj.items():
            if isinstance(value, bool):
                kind, val = (VK_TRUE, 0) if value else (VK_FALSE, 0)
            elif isinstance(value, int):
                kind, val = VK_INT, value & 0xFFFFFFFF
            elif isinstance(value, str):
                kind, val = VK_STR, content_hash32(value)
            elif value is None:
                kind, val = VK_NULL, 0
            elif isinstance(value, dict):
                kind, val = VK_OBJ, packed_u32(meta.children[key])
            elif isinstance(value, list):
                kind, val = VK_TEXT, packed_u32(meta.children[key])
            else:
                # device-inexpressible value (float/containers): the doc is
                # in fallback on every peer; hash a canonical JSON form
                kind = 255
                val = content_hash32(_json.dumps(value, sort_keys=True))
            rows.append((obj_u32, content_hash32(key), kind, val))
    return (total + register_digest_host(rows)) & 0xFFFFFFFF


def _replay_doc(changes: List[Change]) -> Doc:
    doc = Doc("streaming-fallback")
    ordered, stuck = causal_schedule(changes)
    for ch in ordered:
        doc.apply_change(ch)
    return doc


def _replay_spans(changes: List[Change]) -> List[FormatSpan]:
    return _replay_doc(changes).get_text_with_formatting(["text"])


def rebalance(workload_sizes: Sequence[int], num_shards: int) -> List[List[int]]:
    """Greedy load-balance: assign doc indices to shards equalizing total op
    counts (host-side placement; docs are independent so no device
    all-to-all is needed — placement happens before transfer)."""
    order = sorted(range(len(workload_sizes)), key=lambda i: -workload_sizes[i])
    shards: List[List[int]] = [[] for _ in range(num_shards)]
    loads = [0] * num_shards
    for i in order:
        target = loads.index(min(loads))
        shards[target].append(i)
        loads[target] += workload_sizes[i]
    return shards
