"""Fault-domain supervisor: guarded device rounds over a streaming session.

The streaming engine already contains faults to the smallest doc-level unit
(per-doc quarantine, scalar-replay fallback) — this module supervises the
one fault domain a doc cannot contain by itself: the DEVICE ROUND.  A hung
XLA dispatch, a poisoned compiled program, or a runtime device error takes
out the whole session's round, so the supervisor wraps every ``step`` in a
wall-clock watchdog and, on deadline or device error, walks the degradation
ladder (DESIGN.md "Fault domains & degradation ladder"):

1. **guarded round** — ``step`` runs on a watchdog thread; a round that
   overruns ``deadline`` seconds raises :class:`DeviceRoundError` instead of
   wedging the caller (the stuck dispatch is abandoned with its session
   object — JAX owns the thread, we own the state).
2. **checkpoint rollback** — the session is rebuilt from the last good
   checkpoint (``checkpoint.CheckpointManager``: atomic staging+rename, so
   a crash mid-save can never corrupt it), and every frame ingested since
   that checkpoint is replayed from the supervisor's journal (frames are
   duplicate-tolerant, so journal/checkpoint overlap is harmless).
3. **guarded re-drain** — the restored session drains on device under the
   same watchdog; a transient fault (one bad round) fully recovers here.
4. **scalar degradation** — if the device path is still failing, every doc
   with pending work is demoted to scalar replay
   (``StreamingMerge.force_fallback``) and quarantined with reason
   ``device-round``: degraded throughput, byte-identical convergence.

Callers above the ``ingest_frame``/``step`` boundary never see a device
fault — ``step`` returns 0 for a rolled-back round, and the health snapshot
carries the evidence (rollback count, quarantine registry).
"""

from __future__ import annotations

import threading
from functools import partial
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..core.errors import DeviceRoundError
from ..obs import (
    FlightRecorder,
    GLOBAL_COUNTERS,
    GLOBAL_DEVPROF,
    GLOBAL_HISTOGRAMS,
    Histogram,
    Tracer,
    ambient_parent,
    current_span,
)
from .streaming import REASON_DEVICE_ROUND, StreamingMerge


class GuardedSession:
    """A :class:`StreamingMerge` under fault-domain supervision.

    ``factory`` builds a fresh, empty session (used at construction and as
    the last-resort restore when no checkpoint exists yet).  All ingest must
    flow through the supervisor so its journal stays complete; reads (and
    any other method) pass through to ``self.session``.

    ``deadline`` is the per-round wall-clock watchdog in seconds —
    AUTOTUNED by default (ROADMAP "supervisor deadline autotuning"): the
    effective deadline is ``clamp(margin * rolling_p{quantile}(round
    latency), floor, ceiling)`` over the last ``deadline_window`` rounds,
    so slow-compile first rounds no longer force a generous global
    constant.  ``deadline`` doubles as the ceiling (and, /4, the floor)
    when no explicit bound is given; the first ``deadline_warmup``
    successful rounds are EXEMPT — they run against the ceiling and their
    (compile-dominated) latencies never enter the window.  Rollback drains
    always run against the ceiling: a restore replays and may recompile.
    Set ``autotune=False`` for the pre-round-7 static behavior.

    ``checkpoint_every`` counts successful guarded rounds between automatic
    checkpoints — under the per-round ``step()`` discipline the rollback
    replay window is at most that many rounds of journal.  ``drain()`` is
    DIFFERENT by design (ISSUE 9, chaos-pinned): the whole fused drain is
    one atomic commit that checkpoints at its end, so its replay window is
    the drained backlog — rollback lands on the pre-fuse boundary, never
    mid-fuse, and the watchdog budget scales with the same backlog
    (``_drain_deadline``).  Callers needing a tighter replay bound on a
    deep backlog can drain in ``max_rounds`` slices.

    Observability: the supervisor owns a :class:`~..obs.Tracer` (unless
    given one) and a :class:`~..obs.FlightRecorder` ring dumping JSONL
    under ``<checkpoint_root>/flight`` on quarantine and rollback; both are
    attached to the supervised session (and re-attached across restores)
    so round/stage spans land in the ring.
    """

    def __init__(
        self,
        factory: Callable[[], StreamingMerge],
        checkpoint_root: str | Path,
        deadline: float = 30.0,
        checkpoint_every: int = 8,
        keep: int = 3,
        mesh=None,
        tracer=None,
        recorder=None,
        autotune: bool = True,
        deadline_floor: Optional[float] = None,
        deadline_ceiling: Optional[float] = None,
        deadline_quantile: float = 0.99,
        deadline_margin: float = 6.0,
        deadline_window: int = 64,
        deadline_warmup: int = 1,
    ) -> None:
        from ..checkpoint import CheckpointManager

        self._factory = factory
        self.tracer = tracer if tracer is not None else Tracer()
        self.recorder = recorder if recorder is not None else FlightRecorder(
            capacity=1024, dump_dir=Path(checkpoint_root) / "flight"
        )
        self.tracer.add_sink(self.recorder.record_span)
        self.manager = CheckpointManager(checkpoint_root, keep=keep)
        self.deadline = deadline
        self.autotune = autotune
        self._deadline_floor = deadline_floor
        self._deadline_ceiling = deadline_ceiling
        self.deadline_quantile = deadline_quantile
        self.deadline_margin = deadline_margin
        self.deadline_warmup = deadline_warmup
        #: rolling round-latency window (successful guarded rounds, warmup
        #: exempt) — the percentile source for the effective deadline
        self.round_latency = Histogram(window=deadline_window)
        self._rounds_total = 0
        self.checkpoint_every = checkpoint_every
        self.mesh = mesh
        self.session = factory()
        self._attach_session(self.session)
        #: everything ingested since the last checkpoint, in order — the
        #: rollback replay source (duplicate-tolerant, so overlap with the
        #: checkpoint's own frame histories is safe).  Entries are
        #: ``(doc, frame_bytes)`` or ``(doc, [Change, ...])`` — the object
        #: path journals too, so no accepted ingest can vanish in a rollback
        self._journal: List[Tuple[int, object]] = []
        self._rounds_since_checkpoint = 0
        # resume numbering above any existing checkpoint: starting at 0 over
        # a pre-crash root would mint already-used low step numbers that
        # retention immediately prunes, leaving latest() stuck on stale state
        self._checkpoint_step = max(self.manager.steps(), default=0)
        self.rollbacks = 0
        self.checkpoints = 0
        #: one-shot fault injection queues (chaos harness / tests)
        self._inject_failures: List[Exception] = []
        self._inject_delays: List[float] = []

    # -- deadline autotuning -------------------------------------------------

    @property
    def deadline_floor(self) -> float:
        """Autotune lower clamp (explicit, else ``deadline / 4`` — a tuned
        deadline may tighten, but never below a quarter of the configured
        budget, so a mid-session compile burst cannot trip the watchdog)."""
        return (self._deadline_floor if self._deadline_floor is not None
                else self.deadline / 4)

    @property
    def deadline_ceiling(self) -> float:
        """Autotune upper clamp (explicit, else the configured ``deadline``
        — mutating ``self.deadline`` keeps working as the static control)."""
        return (self._deadline_ceiling if self._deadline_ceiling is not None
                else self.deadline)

    def effective_deadline(self) -> float:
        """The watchdog deadline the NEXT guarded round runs against:
        ``clamp(margin * rolling-percentile, floor, ceiling)`` once the
        warmup-exempt window has data, the ceiling before (first-round
        compiles run against the full budget) and with ``autotune=False``."""
        if not self.autotune or self.round_latency.count == 0:
            return float(self.deadline_ceiling)
        tuned = self.round_latency.percentile(self.deadline_quantile)
        tuned *= self.deadline_margin
        return float(min(self.deadline_ceiling, max(self.deadline_floor, tuned)))

    # -- session attachment --------------------------------------------------

    def _attach_session(self, session) -> None:
        """Point the session's telemetry at the supervisor's tracer and
        flight recorder (round/stage spans land in the dump ring; a
        quarantine inside the session triggers the recorder's auto-dump)."""
        session.tracer = self.tracer
        session.recorder = self.recorder

    def adopt_session(self, session) -> None:
        """Install an externally-restored session (crash-restore path) with
        the telemetry attachment a factory-built session would get."""
        self.session = session
        self._attach_session(session)

    def close(self) -> None:
        """Detach this supervisor's flight-recorder sink from the tracer.
        Matters when the tracer is SHARED (caller-supplied, outliving the
        supervisor): without the detach, every future span keeps feeding
        this dead supervisor's recorder ring forever.  Idempotent."""
        self.tracer.remove_sink(self.recorder.record_span)

    # -- ingest (journalled) ------------------------------------------------

    def ingest_frame(self, doc_index: int, data: bytes) -> None:
        self.ingest_frames([(doc_index, data)])

    def ingest_frames(self, items: Iterable) -> None:
        """Journal + quarantine-mode ingest: corrupt frames are contained to
        their doc (typed ``decode`` quarantine), never raised — the
        supervisor's contract is that callers see no fault."""
        items = list(items)
        self._journal.extend(items)
        self.session.ingest_frames(items, on_corrupt="quarantine")

    def ingest(self, doc_index: int, changes: Iterable) -> None:
        """Journalled object-change ingest (the editor/bridge surface) —
        same completeness contract as frames: a rollback replays these too,
        so changes the caller saw accepted can never silently vanish."""
        changes = list(changes)
        if not changes:
            return
        self._journal.append((doc_index, changes))
        self.session.ingest(doc_index, changes)

    # -- guarded rounds -----------------------------------------------------

    def inject_failure(self, exc: Exception) -> None:
        """Queue one device-round failure for the next :meth:`step` (chaos
        harness hook — a real deployment gets these from XLA for free).
        ``Exception`` only: step()'s containment handler deliberately lets
        BaseException (KeyboardInterrupt, SystemExit) through."""
        if not isinstance(exc, Exception):
            raise TypeError(f"inject_failure wants an Exception, got {exc!r}")
        self._inject_failures.append(exc)

    def inject_delay(self, seconds: float) -> None:
        """Queue one artificial round delay (deadline-path chaos hook)."""
        self._inject_delays.append(seconds)

    def _round(self) -> int:
        # bind the session NOW: if the watchdog abandons this thread and the
        # supervisor rolls back, a late-waking zombie must keep touching the
        # abandoned session object, never the freshly restored one
        session = self.session
        if self._inject_delays:
            import time

            time.sleep(self._inject_delays.pop(0))
        scheduled = session.step()
        # Periodic guarded sync (a cheap device fetch), not per-round: step's
        # async dispatch overlap is the streaming engine's whole throughput
        # story, and containment doesn't need a sync every round — an async
        # device error from round N surfaces inside round N+1's guarded
        # dispatch (or here, before the next checkpoint), and rollback
        # restores the same checkpoint+journal state either way.
        if self._rounds_since_checkpoint + 1 >= self.checkpoint_every:
            session.sync_device()
        return scheduled

    def _run_guarded(self, fn: Callable[[], int],
                     deadline: Optional[float] = None) -> int:
        deadline = self.effective_deadline() if deadline is None else deadline
        box: Dict[str, object] = {}
        # the round body runs on the watchdog thread; carry the caller's
        # open span (supervisor.round) across so the session's stage spans
        # nest under it in the timeline instead of rooting parentless
        parent = current_span()

        def run() -> None:
            try:
                with ambient_parent(parent):
                    box["value"] = fn()
            except BaseException as exc:  # noqa: BLE001 - re-raised below
                box["error"] = exc

        worker = threading.Thread(target=run, daemon=True)
        worker.start()
        worker.join(deadline)
        if worker.is_alive():
            # the dispatch is wedged; abandon it (state is rebuilt from the
            # checkpoint — the stuck thread can no longer corrupt anything
            # the supervisor will use)
            raise DeviceRoundError(
                f"device round exceeded its {deadline:.4g}s deadline"
            )
        if "error" in box:
            exc = box["error"]
            if isinstance(exc, DeviceRoundError):
                raise exc
            raise DeviceRoundError(f"device round failed: {exc!r}") from exc
        return int(box["value"])  # type: ignore[arg-type]

    def step(self) -> int:
        """One guarded device round.  Returns the changes scheduled, or 0
        when the round was rolled back (the work is not lost: it recovered
        on device during rollback, or was demoted to scalar replay)."""
        sp = None
        try:
            if self._inject_failures:
                raise self._inject_failures.pop(0)
            with self.tracer.span(
                "supervisor.round",
                deadline=round(self.effective_deadline(), 4),
            ) as sp:
                scheduled = self._run_guarded(self._round)
        except Exception as exc:  # graftlint: boundary(degradation ladder root: ANY round failure rolls back to the last good checkpoint)
            if sp is not None:
                # failed/deadline-hit rounds are the worst case the exported
                # histogram exists to show — they must land too (the span's
                # duration is set before the exception propagates)
                GLOBAL_HISTOGRAMS.observe("supervisor.round_seconds", sp.duration)
            self._rollback(exc)
            return 0
        self._rounds_total += 1
        # the exported histogram sees EVERY round — an operator sizing the
        # static ceiling needs the true worst case, compile rounds included
        GLOBAL_HISTOGRAMS.observe("supervisor.round_seconds", sp.duration)
        if GLOBAL_DEVPROF.enabled:
            # guarded-round boundary: device-memory watermark AFTER the
            # round's dispatches (the periodic sync above means the sample
            # near a checkpoint reflects settled, not queued, allocations)
            GLOBAL_DEVPROF.sample_memory()
        if self._rounds_total > self.deadline_warmup:
            # warmup exemption: the first round(s) are compile-dominated and
            # must not seed the rolling percentile the deadline derives from
            self.round_latency.observe(sp.duration)
        self._rounds_since_checkpoint += 1
        if self._rounds_since_checkpoint >= self.checkpoint_every:
            try:
                self.checkpoint()
            except Exception:  # graftlint: boundary(checkpoint save failure tolerated; next round retries)
                # a failed save (disk full, permissions) must not breach the
                # no-fault contract of step(); the journal was only truncated
                # after a successful save, so rollback state stays complete —
                # the next round simply retries the checkpoint
                GLOBAL_COUNTERS.add("supervisor.checkpoint_failures")
        return scheduled

    def drain(self, max_rounds: int = 1000) -> int:
        """Guarded FUSED drain: the session's whole multi-round pipelined
        drain — staged multi-round commits plus the device-error-surfacing
        sync — runs as ONE atomic guarded unit against the deadline
        CEILING (a fused commit is not a single round; the tuned per-round
        percentile does not describe it).  On watchdog deadline or any
        device fault anywhere in the fused pipeline, rollback restores the
        last checkpoint and replays the journal — the event-sourced ingest
        history — so the recovered session lands on the pre-fuse round
        boundary, never on a half-applied fused batch (chaos-pinned:
        testing/chaos.run_fused_drain_kill).  Returns the device rounds the
        drain committed, 0 when it rolled back (the work recovered on
        device during rollback, or was demoted to scalar replay)."""
        sp = None
        try:
            if self._inject_failures:
                raise self._inject_failures.pop(0)
            deadline = self._drain_deadline(max_rounds)
            with self.tracer.span(
                "supervisor.drain",
                deadline=round(float(deadline), 4),
            ) as sp:
                rounds = self._run_guarded(
                    partial(self._drain_once, max_rounds),
                    deadline=deadline,
                )
        except Exception as exc:  # graftlint: boundary(fused drain is one containment unit: ANY failure inside it rolls the whole commit back to the pre-fuse checkpoint boundary)
            if sp is not None:
                # a multi-round drain wall is NOT a round wall: it exports
                # under its own key so the fleet round-latency distribution
                # stays honest when step() and drain() usage mix
                GLOBAL_HISTOGRAMS.observe("supervisor.drain_seconds", sp.duration)
            self._rollback(exc)
            return 0
        GLOBAL_HISTOGRAMS.observe("supervisor.drain_seconds", sp.duration)
        if rounds:
            self._rounds_total += rounds
            self._rounds_since_checkpoint += rounds
            if self._rounds_since_checkpoint >= self.checkpoint_every:
                try:
                    self.checkpoint()
                except Exception:  # graftlint: boundary(checkpoint save failure tolerated; next round retries)
                    GLOBAL_COUNTERS.add("supervisor.checkpoint_failures")
        return rounds

    def _drain_deadline(self, max_rounds: int) -> float:
        """Watchdog budget for one fused drain: ``deadline_ceiling`` per
        staged batch (each batch is one dispatch of up to FUSE_MAX_ROUNDS
        rounds, and the tuned ceiling already covers a full round including
        its dispatch), scaled by the session's backlog estimate.  A deep
        but healthy drain gets a proportional budget instead of tripping
        the per-round ceiling and cascading into scalar degradation; a
        hung device is still caught within one ceiling per pending batch."""
        session = self.session
        fuse = int(getattr(session, "FUSE_MAX_ROUNDS", 1) or 1)
        est = getattr(session, "pending_rounds_estimate", None)
        rounds = min(max_rounds, est()) if est is not None else 1
        batches = max(1, -(-rounds // fuse))
        return self.deadline_ceiling * batches

    def _drain_once(self, max_rounds: int) -> int:
        """The guarded fused-drain body (watchdog thread): one session
        drain — every fused batch dispatch — plus the sync that surfaces
        async device errors INSIDE this guarded unit, so a poisoned fused
        program can never leak its fault past the atomic commit."""
        session = self.session  # zombie-safety: see _round
        if self._inject_delays:
            import time

            time.sleep(self._inject_delays.pop(0))
        rounds = session.drain(max_rounds)
        session.sync_device()
        return rounds

    # -- checkpoint / rollback ---------------------------------------------

    def checkpoint(self) -> Path:
        """Persist the session (event-sourced frame histories) and truncate
        the journal — this becomes the rollback target."""
        self._checkpoint_step += 1
        path = self.manager.save(step=self._checkpoint_step, session=self.session)
        self._journal = []
        self._rounds_since_checkpoint = 0
        self.checkpoints += 1
        GLOBAL_COUNTERS.add("supervisor.checkpoints")
        return path

    def _restore_base(self) -> StreamingMerge:
        """Last good checkpoint (drain=False: draining happens under the
        watchdog) + journal replay; a fresh session when no checkpoint
        exists yet (the journal then holds the complete history)."""
        latest = self.manager.latest()
        restored: Optional[StreamingMerge] = None
        if latest is not None:
            restored = latest.session(mesh=self.mesh, drain=False)
        if restored is None:
            restored = self._factory()
        # replay in journal order; consecutive frame entries batch through
        # the native fast path, object entries replay via ingest so the
        # doc keeps the routing mode the caller established
        run: List[Tuple[int, bytes]] = []
        for d, payload in self._journal:
            if isinstance(payload, (bytes, bytearray)):
                run.append((d, payload))
                continue
            if run:
                restored.ingest_frames(run, on_corrupt="quarantine")
                run = []
            restored.ingest(d, list(payload))
        if run:
            restored.ingest_frames(run, on_corrupt="quarantine")
        self._attach_session(restored)
        return restored

    def _rollback(self, error: BaseException) -> None:
        """Degradation ladder steps 2-4 (see module docstring).  Rollback
        drains run against the deadline CEILING, not the tuned value — a
        restore replays the journal and may recompile, exactly the slow
        path the warmup exemption exists for — scaled by the restored
        backlog (``_drain_deadline``): the re-drain is at least as deep as
        the drain that faulted, so a flat ceiling would trip the watchdog
        on a healthy replay and cascade to scalar degradation."""
        self.rollbacks += 1
        GLOBAL_COUNTERS.add("supervisor.rollbacks")
        self.recorder.fault(
            "rollback", error=repr(error), rollbacks=self.rollbacks,
            journal_frames=len(self._journal),
        )
        self.session = self._restore_base()
        try:
            self._run_guarded(self._drain_device,
                              deadline=self._drain_deadline(1_000))
        except Exception as exc:  # graftlint: boundary(second-strike containment: a still-sick device path falls back to scalar replay)
            # the device path is still sick: rebuild once more from durable
            # state (a deadline here may have left a zombie thread draining
            # the object we just restored — abandon it too), then contain:
            # every doc with pending work replays on the scalar path
            restored = self._restore_base()
            self.session = restored
            for d in sorted(restored.pending_docs()):
                restored.force_fallback(
                    d, REASON_DEVICE_ROUND,
                    detail=f"rollback after {error!r}; re-drain failed: {exc!r}",
                )
            GLOBAL_COUNTERS.add("supervisor.scalar_degradations")

    def _drain_device(self) -> int:
        session = self.session  # zombie-safety: see _round
        rounds = 0
        while session.drain() > 0:
            rounds += 1
        session.sync_device()
        return rounds

    # -- pass-throughs ------------------------------------------------------

    def read(self, doc_index: int):
        return self.session.read(doc_index)

    def read_all(self):
        return self.session.read_all()

    def digest(self, **kw) -> int:
        return self.session.digest(**kw)

    def quarantined(self):
        return self.session.quarantined()

    def __getattr__(self, name: str):
        # every other PUBLIC session method (read_patches, pending_count,
        # frontier, ...) passes through; private names stay local so a
        # half-constructed supervisor can never recurse here
        session = self.__dict__.get("session")
        if session is not None and not name.startswith("_"):
            return getattr(session, name)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def health(self) -> Dict:
        """Session health plus the supervisor's own fault evidence and the
        deadline-autotune state (the effective value, its clamps, and the
        rolling round-latency percentiles it derives from)."""
        out = self.session.health()
        out.update(
            rollbacks=self.rollbacks,
            checkpoints=self.checkpoints,
            journal_frames=len(self._journal),
            deadline_seconds=self.effective_deadline(),
            deadline_static=self.deadline,
            deadline_floor=self.deadline_floor,
            deadline_ceiling=self.deadline_ceiling,
            deadline_autotuned=bool(
                self.autotune and self.round_latency.count > 0
            ),
            round_latency=self.round_latency.snapshot(),
            flight_recorder=self.recorder.snapshot(),
        )
        return out
