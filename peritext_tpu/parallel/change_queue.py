"""Outbound change batching (reference ``src/changeQueue.ts``).

Buffers locally-generated changes and flushes them in batches — either
manually (deterministic tests, simulated latency) or on a wall-clock interval
via a background timer thread (interactive demos).  Flush failures requeue the
batch at the front so no change is lost (the reference left this as a TODO,
src/changeQueue.ts:38).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..core.types import Change


class ChangeQueue:
    def __init__(
        self,
        handle_flush: Callable[[List[Change]], None],
        interval: float = 0.01,
        on_error: Optional[Callable[[Exception], None]] = None,
        max_backoff: float = 1.0,
    ) -> None:
        self._changes: List[Change] = []
        self._handle_flush = handle_flush
        self._interval = interval
        self._timer: Optional[threading.Timer] = None
        self._lock = threading.Lock()
        self._running = False
        #: called with the exception when a timer-driven flush fails
        self._on_error = on_error
        self._max_backoff = max_backoff
        self._current_interval = interval

    def enqueue(self, *changes: Change) -> None:
        with self._lock:
            self._changes.extend(changes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._changes)

    def flush(self) -> None:
        with self._lock:
            batch, self._changes = self._changes, []
        if not batch:
            return
        try:
            self._handle_flush(batch)
        except Exception:  # graftlint: boundary(requeue-then-reraise: the batch must survive ANY flush failure; the exception propagates unchanged)
            with self._lock:  # requeue at the front; nothing is dropped
                self._changes = batch + self._changes
            raise

    def start(self) -> None:
        """Begin periodic flushing on a daemon timer."""
        with self._lock:
            self._running = True
            self._current_interval = self._interval  # forget stale backoff
        self._schedule()

    def _schedule(self) -> None:
        # Check _running and start the timer under the lock so a concurrent
        # drop() can never observe "stopped" yet leave a fresh timer running.
        with self._lock:
            if not self._running:
                return
            self._timer = threading.Timer(self._current_interval, self._tick)
            self._timer.daemon = True
            self._timer.start()

    def _tick(self) -> None:
        # Timer-driven flushes must not leak exceptions into the timer thread;
        # failures back off exponentially (changes stay queued) and are
        # reported through on_error.
        try:
            self.flush()
            self._current_interval = self._interval
        except Exception as exc:  # noqa: BLE001 - deliberate boundary
            self._current_interval = min(self._current_interval * 2, self._max_backoff)
            if self._on_error is not None:
                self._on_error(exc)
        finally:
            self._schedule()

    def drop(self) -> None:
        """Stop the timer (simulates a network partition; reference
        ``queue.drop()``, src/index.ts:117-119)."""
        with self._lock:
            self._running = False
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
