"""Sharded doc placement across a serving fleet.

The serving tier's placement question — "which host should carry this new
doc, and which docs should move when a host degrades" — is answered HERE,
in merge scope, as a deterministic function of the observed fleet state:
same observations in, same placement out, on every replica that runs the
router.  That determinism is load-bearing (two frontends placing the same
doc must agree without coordination) and machine-checked: graftlint's
PTL006 forbids wall-clock/RNG reads in ``parallel/``, and the corpus
carries a router-shaped true positive proving the rule fires on exactly
the "stamp the placement with time.monotonic()" mistake.

Load model (the dimensions ``StreamingMerge.reshard()`` established):

* **slot load** — live device slots a host's docs occupy (device cost);
* **host-bound load** — quarantined/fallback docs replaying on the host's
  CPU (the scalar-replay rung of the degradation ladder costs the HOST,
  not the chip), balanced as its own dimension exactly as ``reshard()``
  balances it within one session;
* **lag** — the host's replication lag in ops
  (:class:`~..obs.convergence.ConvergenceMonitor` watermarks, folded in
  via :meth:`FleetRouter.observe`): a behind host charges a placement
  penalty, because a doc placed there serves stale reads until the gossip
  scheduler drains the lag.

Placement is least-loaded-first over the relevant dimension ordering
(host-bound docs weigh host-bound load first; device docs weigh
device+lag load first), name-tiebroken — the same greedy shape as
``reshard()``'s assignment, lifted from rows-within-a-session to
docs-across-a-fleet.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class HostSlot:
    """One serving host's tracked placement state."""

    name: str
    #: doc slots this host's mux can still open (capacity bound)
    capacity: int
    docs: int = 0
    slot_load: int = 0
    host_bound_load: int = 0
    #: replication lag (ops behind the observing frontier) — the
    #: ConvergenceMonitor watermark, folded in by :meth:`FleetRouter.observe`
    lag_ops: int = 0
    #: paged-storage load (store/): pages the host's pool holds, from a
    #: paged session's ``reshard()["page_load"]`` / ``health()["page_pool"]``.
    #: Once observed — even at 0, a fresh empty pool — the host is marked
    #: ``paged`` and pages ARE its device dimension: a paged host's scarce
    #: resource is pool pages, and slot-unit estimates would overweight
    #: long docs that actually share pages with nobody.  Unit contract:
    #: placement ``size`` for a paged host is in PAGES, and a MIXED fleet
    #: (paged + padded hosts in one router) must feed page-normalized
    #: sizes/loads on the padded side too — the greedy compares the
    #: dimensions directly and never converts units.
    page_load: int = 0
    #: latched by the first ``observe(page_load=...)`` — see above
    paged: bool = False
    #: a draining host accepts no new docs (operator decommission, or the
    #: serving tier reacting to sustained overload)
    draining: bool = False
    #: per-doc placed sizes (doc_key -> size), the rebalance input
    placed: Dict[str, int] = field(default_factory=dict)
    #: doc_keys whose placed size was counted into ``page_load`` (placed
    #: AFTER the paged latch): _unassign must only subtract from the
    #: dimension the size was added to, or a pre-latch slot-unit doc would
    #: wipe the page estimate on eviction
    page_counted: set = field(default_factory=set)
    #: doc_keys currently host-bound (quarantined/fallback) on this host
    bound_docs: Dict[str, int] = field(default_factory=dict)

    def device_load(self) -> int:
        """The device-dimension load: reported pool pages for paged hosts
        (a fresh empty pool counts as 0, not as "fall back to slots"),
        slot load otherwise (see ``page_load``)."""
        return self.page_load if self.paged else self.slot_load

    def effective_load(self, lag_weight: int) -> int:
        """Device-dimension placement load: device load plus the lag penalty
        (a behind host is 'fuller' — new docs would read stale there)."""
        return self.device_load() + lag_weight * self.lag_ops

    def to_json(self) -> Dict:
        return {
            "capacity": self.capacity,
            "docs": self.docs,
            "slot_load": self.slot_load,
            "page_load": self.page_load,
            "paged": self.paged,
            "host_bound_load": self.host_bound_load,
            "lag_ops": self.lag_ops,
            "draining": self.draining,
        }


class PlacementError(ValueError):
    """No host can accept the doc (every host full or draining)."""


class FleetRouter:
    """Places and re-places docs across N serving hosts (see module doc).

    ``lag_weight`` scales the lag penalty in slot-load units per op behind
    (integer, so placement stays exact-arithmetic deterministic).  All
    iteration orders are sorted; ties break on host name, then doc key.
    """

    def __init__(self, lag_weight: int = 1) -> None:
        self.lag_weight = int(lag_weight)
        self._hosts: Dict[str, HostSlot] = {}
        self._doc_host: Dict[str, str] = {}
        self.placements = 0
        self.moves = 0

    # -- fleet membership -----------------------------------------------------

    def add_host(self, name: str, capacity: int) -> None:
        if capacity <= 0:
            raise ValueError(f"host {name!r} needs positive capacity")
        if name in self._hosts:
            raise ValueError(f"host {name!r} already registered")
        self._hosts[name] = HostSlot(name=name, capacity=int(capacity))

    def remove_host(self, name: str) -> None:
        """Deregister a host (the fleet's dead-host re-admission path).
        Refuses while placements remain — a dead host's are forgotten by
        :meth:`fail_host` first, so a refusal here means the caller is
        removing a host that still serves docs."""
        host = self._hosts[name]
        if host.placed:
            raise PlacementError(
                f"host {name!r} still places {len(host.placed)} doc(s)"
            )
        del self._hosts[name]

    def hosts(self) -> List[str]:
        return sorted(self._hosts)

    def host(self, name: str) -> HostSlot:
        return self._hosts[name]

    def set_draining(self, name: str, draining: bool = True) -> None:
        self._hosts[name].draining = bool(draining)

    # -- observation ingestion (reshard load dims + monitor watermarks) ------

    def observe(
        self,
        name: str,
        slot_load: Optional[int] = None,
        host_bound_load: Optional[int] = None,
        lag_ops: Optional[int] = None,
        page_load: Optional[int] = None,
    ) -> None:
        """Fold one host's measured state in: ``slot_load`` /
        ``host_bound_load`` from its session's ``reshard()`` dimensions or
        health snapshot, ``lag_ops`` from a ConvergenceMonitor watermark
        (``peers()[host].ops_behind`` as observed by the routing frontend),
        ``page_load`` from a paged session's ``reshard()["page_load"]`` sum
        (pages become the device dimension — see ``HostSlot.page_load``).
        Measurements REPLACE the router's accumulated estimates — the
        estimate is only the prior between observations."""
        rec = self._hosts[name]
        if slot_load is not None:
            rec.slot_load = int(slot_load)
        if host_bound_load is not None:
            rec.host_bound_load = int(host_bound_load)
        if lag_ops is not None:
            rec.lag_ops = int(lag_ops)
        if page_load is not None:
            rec.page_load = int(page_load)
            rec.paged = True

    def observe_monitor(self, monitor) -> None:
        """Fold every registered host's lag watermark from one
        :class:`~..obs.convergence.ConvergenceMonitor` (hosts the monitor
        has never exchanged with keep their current estimate)."""
        peers = monitor.peers()
        for name in sorted(self._hosts):
            rec = peers.get(name)
            if rec is not None:
                self._hosts[name].lag_ops = int(rec.ops_behind)

    # -- placement ------------------------------------------------------------

    def _placement_key(self, host: HostSlot, host_bound: bool) -> Tuple:
        if host_bound:
            # scalar-replay docs cost the host CPU: balance that dimension
            # first, device load second (reshard()'s exact ordering)
            return (host.host_bound_load,
                    host.effective_load(self.lag_weight), host.name)
        return (host.effective_load(self.lag_weight),
                host.host_bound_load, host.name)

    def _eligible(self) -> List[HostSlot]:
        return [
            h for h in (self._hosts[n] for n in sorted(self._hosts))
            if not h.draining and h.docs < h.capacity
        ]

    def place(self, doc_key: str, size: int = 1,
              host_bound: bool = False) -> str:
        """Place one doc; returns the chosen host name.  ``size`` is the
        doc's slot-load estimate; ``host_bound`` places a doc already known
        to need scalar replay.  Raises :class:`PlacementError` when every
        host is full or draining (the caller's typed ``capacity`` shed)."""
        if doc_key in self._doc_host:
            return self._doc_host[doc_key]
        hosts = self._eligible()
        if not hosts:
            raise PlacementError(
                f"no serving host can accept doc {doc_key!r}"
            )
        best = min(hosts, key=lambda h: self._placement_key(h, host_bound))
        self._assign(doc_key, best, int(size), host_bound)
        self.placements += 1
        return best.name

    def _assign(self, doc_key: str, host: HostSlot, size: int,
                host_bound: bool) -> None:
        self._doc_host[doc_key] = host.name
        host.docs += 1
        host.slot_load += size
        if host.paged:
            # paged host: size is in PAGES (the caller sizes docs off the
            # paged reshard dimensions); keep the active dimension moving
            # between observations so the greedy stays monotone
            host.page_load += size
            host.page_counted.add(doc_key)
        host.placed[doc_key] = size
        if host_bound:
            host.host_bound_load += size
            host.bound_docs[doc_key] = size

    def _unassign(self, doc_key: str) -> Tuple[HostSlot, int, bool]:
        name = self._doc_host.pop(doc_key)
        host = self._hosts[name]
        size = host.placed.pop(doc_key)
        host.docs -= 1
        host.slot_load -= size
        if doc_key in host.page_counted:
            host.page_counted.discard(doc_key)
            host.page_load = max(0, host.page_load - size)
        bound = doc_key in host.bound_docs
        if bound:
            host.host_bound_load -= host.bound_docs.pop(doc_key)
        return host, size, bound

    def host_of(self, doc_key: str) -> Optional[str]:
        return self._doc_host.get(doc_key)

    def placement(self) -> Dict[str, str]:
        return dict(sorted(self._doc_host.items()))

    # -- re-placement ---------------------------------------------------------

    def mark_host_bound(self, doc_key: str, bound: bool = True) -> None:
        """A placed doc entered (or left) the quarantine/fallback rung:
        shift its size between the device and host-bound load dimensions
        in place (no move — degradation alone never migrates a doc; the
        next :meth:`rebalance` decides whether it should)."""
        name = self._doc_host[doc_key]
        host = self._hosts[name]
        size = host.placed[doc_key]
        if bound and doc_key not in host.bound_docs:
            host.bound_docs[doc_key] = size
            host.host_bound_load += size
        elif not bound and doc_key in host.bound_docs:
            host.host_bound_load -= host.bound_docs.pop(doc_key)

    def evacuate(self, name: str) -> List[Tuple[str, str, str]]:
        """Drain one host: re-place every doc it carries onto the rest of
        the fleet (largest first, host-bound docs first — reshard()'s
        scarcity ordering).  Returns the move plan
        ``[(doc_key, from_host, to_host), ...]`` in plan order; the host
        stays registered and draining.  ATOMIC: if the fleet lacks
        capacity mid-plan, every move already made is rolled back before
        :class:`PlacementError` raises — the caller acts on the whole
        returned plan or none of it, so router state never disagrees with
        where doc state physically lives."""
        host = self._hosts[name]
        host.draining = True
        moves: List[Tuple[str, str, str]] = []
        done: List[Tuple[str, int, bool]] = []  # (doc, size, bound) undo log
        order = sorted(
            host.placed,
            key=lambda dk: (dk not in host.bound_docs,
                            -host.placed[dk], dk),
        )
        for doc_key in order:
            _, size, bound = self._unassign(doc_key)
            hosts = self._eligible()
            if not hosts:
                # nowhere to go: restore this doc AND every earlier move
                self._assign(doc_key, host, size, bound)
                for undo_key, undo_size, undo_bound in reversed(done):
                    self._unassign(undo_key)
                    self._assign(undo_key, host, undo_size, undo_bound)
                self.moves -= len(done)
                raise PlacementError(
                    f"evacuating {name!r}: no capacity for doc {doc_key!r}"
                )
            best = min(hosts, key=lambda h: self._placement_key(h, bound))
            self._assign(doc_key, best, size, bound)
            moves.append((doc_key, name, best.name))
            done.append((doc_key, size, bound))
            self.moves += 1
        return moves

    def release(self, doc_key: str) -> None:
        """Forget one doc's placement — the execution layer failed to
        realize it (target mux out of slots) or the doc was deleted.  A
        no-op for unplaced docs."""
        if doc_key in self._doc_host:
            self._unassign(doc_key)

    def move(self, doc_key: str, to: str) -> None:
        """Directed single-doc move (the execution layer's manual-migration
        bookkeeping): re-assign ``doc_key`` to host ``to`` if it has room.
        Raises :class:`PlacementError` without touching state otherwise."""
        host = self._hosts[to]
        if host.draining or host.docs >= host.capacity:
            raise PlacementError(
                f"host {to!r} cannot accept doc {doc_key!r}"
            )
        _, size, bound = self._unassign(doc_key)
        self._assign(doc_key, host, size, bound)
        self.moves += 1

    def fail_host(self, name: str) -> List[Tuple[str, int, bool]]:
        """A host DIED (heartbeat lease expired): its doc state is gone, so
        — unlike :meth:`evacuate`, which plans moves of live state — its
        placements are simply forgotten and returned for failover
        re-placement from durable state (checkpoint + journal).  The host
        stays registered and draining so a zombie coming back cannot
        receive placements until it re-registers.  Returns
        ``[(doc_key, size, host_bound), ...]`` in the evacuation scarcity
        order (host-bound first, largest first, key tiebreak) — the order
        failover re-placement should run in."""
        host = self._hosts[name]
        host.draining = True
        order = sorted(
            host.placed,
            key=lambda dk: (dk not in host.bound_docs,
                            -host.placed[dk], dk),
        )
        lost: List[Tuple[str, int, bool]] = []
        for doc_key in order:
            _, size, bound = self._unassign(doc_key)
            lost.append((doc_key, size, bound))
        return lost

    def rollback_moves(self, moves: List[Tuple[str, str, str]]) -> None:
        """Reverse an executed move plan (``[(doc_key, from, to), ...]``
        from :meth:`evacuate` / :meth:`rebalance`), newest first — the
        execution layer's atomic-cutover escape hatch: when a move plan's
        PHYSICAL execution fails partway (a cutover digest mismatch), the
        router's bookkeeping must return to the pre-plan placement so it
        never disagrees with where doc state actually serves."""
        for doc_key, from_host, _ in reversed(moves):
            _, size, bound = self._unassign(doc_key)
            self._assign(doc_key, self._hosts[from_host], size, bound)
            self.moves -= 1

    def rebalance(self, max_moves: int = 8) -> List[Tuple[str, str, str]]:
        """Bounded greedy re-placement: while the most- and least-loaded
        hosts (device dimension, lag-penalized) differ by more than the
        moved doc's size, move the largest doc that shrinks the spread.
        Deterministic and monotone: every accepted move strictly reduces
        the max-min spread, so the plan cannot oscillate.  Returns the
        move plan (may be empty)."""
        moves: List[Tuple[str, str, str]] = []
        for _ in range(max_moves):
            hosts = [self._hosts[n] for n in sorted(self._hosts)
                     if not self._hosts[n].draining]
            if len(hosts) < 2:
                break
            hot = max(hosts, key=lambda h: (h.effective_load(self.lag_weight), h.name))
            cold = min(
                (h for h in hosts if h.docs < h.capacity),
                key=lambda h: (h.effective_load(self.lag_weight), h.name),
                default=None,
            )
            if cold is None or hot.name == cold.name:
                break
            spread = (hot.effective_load(self.lag_weight)
                      - cold.effective_load(self.lag_weight))
            candidates = sorted(
                ((size, dk) for dk, size in hot.placed.items()
                 if 0 < size < spread),
                key=lambda sd: (-sd[0], sd[1]),
            )
            if not candidates:
                break
            size, doc_key = candidates[0]
            _, _, bound = self._unassign(doc_key)
            self._assign(doc_key, cold, size, bound)
            moves.append((doc_key, hot.name, cold.name))
            self.moves += 1
        return moves

    def snapshot(self) -> Dict:
        """JSON-serializable fleet placement state (composes into the
        serve exporter surfaces)."""
        return {
            "hosts": {
                name: self._hosts[name].to_json()
                for name in sorted(self._hosts)
            },
            "docs": len(self._doc_host),
            "placements": self.placements,
            "moves": self.moves,
            "lag_weight": self.lag_weight,
        }
