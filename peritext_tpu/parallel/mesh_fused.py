"""Mesh-fused dispatch plumbing: value-keyed program cache + ICI page mover.

The mesh-sharded fused commit path (round 19) runs the staged K-round
programs under ``shard_map`` on the doc axis.  Two pieces of machinery are
shared by every arm (padded stacked, paged group chain, ragged per-round,
the K-row digest gather) and live here:

* :func:`mesh_fn` — a bounded, VALUE-keyed cache of mesh-specialized
  compiled callables.  ``jax.Mesh`` objects hash by identity, so a cache
  keyed by the live mesh (the pre-round-19 ``_GATHER_ROWS_CACHE``) grew one
  stale compiled entry per test-suite mesh and could never share programs
  between two meshes over the same devices.  :func:`mesh_fingerprint` keys
  by (axis names, device grid shape, device ids) instead — the exact value
  identity under which a compiled program is reusable.
* :func:`page_mover_fn` — the collective reshard protocol: pages move
  between per-shard pools over ICI via ``ppermute`` (one program, a static
  ring-offset loop), never through host round-trips.  The caller
  (store/sharded.ShardedPagedDocStore.permute_rows) owns the allocate-first
  discipline that makes the in-place scatter sound: destination local ids
  are drawn from the complement of (pages staying + pages leaving) per
  shard, so a shard's incoming pages can never land on a slot whose payload
  has not yet been gathered.

Programs built THROUGH :func:`mesh_fn` close over static shapes only; all
per-round variation (plan planes, stream staging, page tables) rides as
data — the recompile-sentinel pin for repeat mesh drains depends on it.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from .mesh import DOC_AXIS

#: Cache bound for :func:`mesh_fn`.  One mesh serving session needs several
#: live programs at once (stacked apply + digest chain + a paged group
#: ladder + the row gather); 64 keeps every program of a handful of
#: concurrent meshes resident — so the steady-state zero-compile pin holds
#: — while still bounding a test suite that builds hundreds of throwaway
#: meshes.
MESH_FN_CACHE_BOUND = 64

_MESH_FN_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()


def mesh_fingerprint(mesh) -> Tuple:
    """Value identity of a mesh: (axis names, device grid shape, device
    ids).  Two ``Mesh`` objects agreeing on all three compile to identical
    programs, so cache entries key on this — never on the live object."""
    if mesh is None:
        return ("meshless",)
    return (
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(int(d.id) for d in mesh.devices.flat),
    )


def mesh_fn(mesh, key, build: Callable[[], Callable]) -> Callable:
    """The compiled callable for ``(mesh, key)``, building it via
    ``build()`` on first use.  ``key`` must carry every static the built
    program closes over (widths, bucket ladders, impl names) — the cache
    returns an existing entry on key equality alone."""
    cache_key = (mesh_fingerprint(mesh), key)
    fn = _MESH_FN_CACHE.get(cache_key)
    if fn is None:
        fn = build()
        _MESH_FN_CACHE[cache_key] = fn
        while len(_MESH_FN_CACHE) > MESH_FN_CACHE_BOUND:
            _MESH_FN_CACHE.popitem(last=False)
    else:
        _MESH_FN_CACHE.move_to_end(cache_key)
    return fn


def mesh_fn_cache_size() -> int:
    """Current entry count (the bound test reads it)."""
    return len(_MESH_FN_CACHE)


def shard_leading(tree, mesh):
    """Device-put a host pytree with every leaf's LEADING axis sharded over
    the doc axis — the per-shard plan-plane staging idiom: host stacks
    per-shard planes on a fresh ``(n_shards, ...)`` axis, this ships each
    shard its own slice."""
    return jax.device_put(
        tree, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(DOC_AXIS))
    )


def page_mover_fn(mesh, m_pages: int, m_zero: int) -> Callable:
    """The ICI page-move program for ``mesh``: one ``shard_map`` dispatch
    moves up to ``m_pages`` pool pages between every ordered shard pair
    (a static ring-offset loop of ``ppermute``) and re-zeroes up to
    ``m_zero`` vacated source pages per shard — the free-page all-zero
    invariant survives the move.

    Operands (global shapes; ``n`` = mesh size, ``Ps`` = per-shard pool
    pages, ``P`` = page width):

    * ``pool_elem`` / ``pool_char`` — ``(n * Ps, P)``, page axis sharded.
    * ``send_idx`` — ``(n, n - 1, m_pages)`` int32: shard ``s`` row ``d-1``
      holds the LOCAL page ids it sends at ring offset ``d`` (to shard
      ``(s + d) % n``); pad = 0, the per-shard null page, which gathers
      zeros.
    * ``recv_idx`` — ``(n, n - 1, m_pages)`` int32: shard ``s`` row ``d-1``
      holds the LOCAL destination ids for pages arriving at offset ``d``
      (from shard ``(s - d) % n``); pad = ``Ps`` (out of bounds — the
      scatter drops it).
    * ``zero_idx`` — ``(n, m_zero)`` int32: each shard's vacated source
      ids to re-zero after the scatters; pad = ``Ps`` (dropped).

    Returns the updated ``(pool_elem, pool_char)``.  Cache through
    :func:`mesh_fn` with key ``("page_mover", m_pages, m_zero)``."""
    from jax.experimental.shard_map import shard_map

    from jax.sharding import PartitionSpec as P

    n = mesh.size

    def body(pool_elem, pool_char, send_idx, recv_idx, zero_idx):
        send_idx = send_idx[0]
        recv_idx = recv_idx[0]
        zero_idx = zero_idx[0]
        # gather every outgoing payload BEFORE any scatter lands: with the
        # caller's src/dst disjointness this makes the in-place move sound
        payload_e = pool_elem[send_idx]  # (n-1, m_pages, P)
        payload_c = pool_char[send_idx]
        for d in range(1, n):
            perm = [(i, (i + d) % n) for i in range(n)]
            pe = jax.lax.ppermute(payload_e[d - 1], DOC_AXIS, perm)
            pc = jax.lax.ppermute(payload_c[d - 1], DOC_AXIS, perm)
            idx = recv_idx[d - 1]
            pool_elem = pool_elem.at[idx].set(pe, mode="drop")
            pool_char = pool_char.at[idx].set(pc, mode="drop")
        zeros = jnp.zeros(
            (zero_idx.shape[0], pool_elem.shape[1]), pool_elem.dtype
        )
        pool_elem = pool_elem.at[zero_idx].set(zeros, mode="drop")
        pool_char = pool_char.at[zero_idx].set(zeros, mode="drop")
        return pool_elem, pool_char

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(DOC_AXIS), P(DOC_AXIS), P(DOC_AXIS), P(DOC_AXIS),
                  P(DOC_AXIS)),
        out_specs=(P(DOC_AXIS), P(DOC_AXIS)),
    ))
