"""Delivery fault injection (SURVEY §5.3 *Build* item).

The reference's only "fault tolerance" artifacts are the causal retry loop
(test/merge.ts:4-23) and manually dropping the sync timer (src/index.ts:117).
This module injects the full space of delivery faults the replication layer
must survive:

* **reorder** — arbitrary permutation of a delivery batch (the causal layer
  must hold back / resequence);
* **duplication** — redelivered changes must be idempotent;
* **drop** — lost changes must be repaired by a later anti-entropy round
  (vector-clock diffs re-ship anything missing, so drops delay but never
  prevent convergence);
* **payload corruption** — truncated or bit-flipped wire frames must be
  rejected at the codec (:class:`~..core.errors.DecodeError`) and contained
  to the affected doc (per-doc quarantine), never applied as garbage.

Entry points: :func:`perturb_delivery` for harnesses that move changes by
hand (the fuzzer's sync step), :func:`perturb_frame` for harnesses that move
raw wire bytes (the chaos harness's codec-surface faults), and
:class:`FaultyPublisher`, a drop-in ``Publisher`` that applies
per-subscriber faults and records what it lost so tests can assert repair
actually happened.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.errors import DecodeError
from ..core.types import Change
from .pubsub import Publisher


@dataclass(frozen=True)
class FaultSpec:
    """Probabilities for one delivery hop.

    ``drop_p``/``dup_p``/``reorder`` act on whole changes (delivery faults);
    ``truncate_p``/``bitflip_p`` act on the encoded FRAME BYTES (payload
    faults) — they model a corrupting link or store, and exercise the codec's
    :class:`DecodeError` surface rather than the causal layer."""

    drop_p: float = 0.0
    dup_p: float = 0.0
    reorder: bool = True
    #: per-frame probability the frame arrives truncated at a random cut
    truncate_p: float = 0.0
    #: per-frame probability 1..4 random bits arrive flipped
    bitflip_p: float = 0.0

    def any_faults(self) -> bool:
        return (self.drop_p > 0 or self.dup_p > 0 or self.reorder
                or self.any_payload_faults())

    def any_payload_faults(self) -> bool:
        return self.truncate_p > 0 or self.bitflip_p > 0


def perturb_delivery(
    changes: List[Change], rng: random.Random, spec: FaultSpec
) -> List[Change]:
    """Apply drop / duplicate / reorder faults to one delivery batch.

    Returns the perturbed batch; dropped changes are simply absent (the
    caller's next anti-entropy round will re-ship them)."""
    delivered: List[Change] = []
    for change in changes:
        if rng.random() < spec.drop_p:
            continue
        delivered.append(change)
        while rng.random() < spec.dup_p:
            delivered.append(change)
    if spec.reorder:
        rng.shuffle(delivered)
    return delivered


def perturb_frame(data: bytes, rng: random.Random, spec: FaultSpec) -> bytes:
    """Apply payload faults (truncation, bit flips) to one encoded wire
    frame; returns the (possibly corrupted) bytes.  The result may or may
    not decode — that is the point: the codec must reject corruption with
    :class:`DecodeError`, and the ingest layer must quarantine the affected
    doc without crashing.  With no payload faults configured (or an empty
    frame) the bytes pass through untouched."""
    if not data or not spec.any_payload_faults():
        return data
    out = data
    if rng.random() < spec.truncate_p:
        out = out[: rng.randrange(len(out))]
    if out and rng.random() < spec.bitflip_p:
        buf = bytearray(out)
        for _ in range(rng.randint(1, 4)):
            pos = rng.randrange(len(buf))
            buf[pos] ^= 1 << rng.randrange(8)
        out = bytes(buf)
    return out


def corrupt_detectably(
    frame: bytes, rng: random.Random, spec: FaultSpec,
) -> Optional[bytes]:
    """Apply payload faults to one encoded frame and return the corrupted
    bytes ONLY when the codec can detect the damage (:class:`DecodeError`);
    returns None when no corruption fired or when the corruption is
    UNDETECTABLE (the mutated frame still decodes — the wire format carries
    no checksum).  Undetectable corruption models as clean delivery: link-
    level integrity (TCP/TLS) is assumed to catch what application-level
    validation cannot, and delivering decoded garbage would make replicas
    diverge by design.  THE single definition of that policy — harnesses
    (FaultyPublisher, testing/chaos.py) share it so a future wire-frame
    checksum (ROADMAP) changes it in one place."""
    from .codec import decode_frame

    bad = perturb_frame(frame, rng, spec)
    if bad is frame:
        return None
    try:
        decode_frame(bad)
    except DecodeError:
        return bad
    return None


class FaultyPublisher(Publisher):
    """A ``Publisher`` whose deliveries suffer per-subscriber faults.

    Dropped updates are recorded per subscriber; :meth:`redeliver_lost`
    models the transport-level retransmission that a real deployment gets
    from anti-entropy, letting tests assert convergence-after-repair.

    With payload faults configured (``truncate_p``/``bitflip_p``) every
    delivery round-trips through the real wire codec — encode, corrupt the
    bytes, decode — so the :class:`DecodeError` surface is exercised, not
    just delivery ordering.  A batch whose corrupted frame fails decode is
    counted as lost in full (the transport analog: a corrupt frame
    contributes nothing, anti-entropy re-ships it later).
    """

    def __init__(self, spec: FaultSpec, seed: int = 0, monitor=None) -> None:
        super().__init__(monitor=monitor)
        self.spec = spec
        self.rng = random.Random(seed)
        self.lost: Dict[str, List[List[Change]]] = {}
        self.delivered_count = 0
        self.dropped_count = 0
        #: deliveries whose frame failed decode after payload corruption
        self.corrupt_count = 0

    def _through_codec(self, batch: List[Change]) -> Optional[List[Change]]:
        """Encode → corrupt → decode one delivery batch; None = frame lost
        to DETECTABLE corruption (the whole batch, like a dropped TCP
        message); undetectable corruption models as clean delivery (the
        :func:`corrupt_detectably` policy)."""
        from .codec import decode_frame, encode_frame

        if not batch:
            return batch
        frame = encode_frame(batch)
        if corrupt_detectably(frame, self.rng, self.spec) is not None:
            return None
        return decode_frame(frame)

    def publish(self, sender: str, update: List[Change]) -> None:
        # sorted, not subscription order: fault draws consume the rng in
        # subscriber-key order, so a run is reproducible from (seed, spec)
        # alone regardless of subscription timing (PTL001)
        for key, callback in sorted(self._subscribers.items()):
            if key == sender:
                continue
            perturbed = perturb_delivery(list(update), self.rng, self.spec)
            if self.spec.any_payload_faults():
                decoded = self._through_codec(perturbed)
                if decoded is None:
                    self.corrupt_count += 1
                    perturbed = []
                else:
                    perturbed = decoded
            dropped = [
                c for c in update
                if not any(d.actor == c.actor and d.seq == c.seq for d in perturbed)
            ]
            if dropped:
                self.lost.setdefault(key, []).append(dropped)
                self.dropped_count += len(dropped)
                if self.monitor is not None:
                    # the lossy hop surfaces like a failed exchange: the
                    # subscriber's failure count grows until redelivery
                    self.monitor.observe_failure(
                        key, error=f"dropped {len(dropped)} change(s)"
                    )
            self.delivered_count += len(perturbed)
            if perturbed:
                callback(perturbed)
                if self.monitor is not None and not dropped:
                    self.monitor.observe_success(key, pulled=len(perturbed))

    def redeliver_lost(self) -> int:
        """Re-deliver every recorded drop (faithfully, no new faults);
        returns how many changes were retransmitted."""
        count = 0
        for key, batches in sorted(self.lost.items()):  # deterministic repair order
            callback = self._subscribers.get(key)
            if callback is None:
                continue
            redelivered = 0
            for batch in batches:
                callback(list(batch))
                redelivered += len(batch)
            count += redelivered
            if batches and self.monitor is not None:
                # repair delivered: the subscriber's failure streak clears
                self.monitor.observe_success(key, pulled=redelivered)
            self.lost[key] = []
        return count
