"""Delivery fault injection (SURVEY §5.3 *Build* item).

The reference's only "fault tolerance" artifacts are the causal retry loop
(test/merge.ts:4-23) and manually dropping the sync timer (src/index.ts:117).
This module injects the full space of delivery faults the replication layer
must survive:

* **reorder** — arbitrary permutation of a delivery batch (the causal layer
  must hold back / resequence);
* **duplication** — redelivered changes must be idempotent;
* **drop** — lost changes must be repaired by a later anti-entropy round
  (vector-clock diffs re-ship anything missing, so drops delay but never
  prevent convergence).

Two entry points: :func:`perturb_delivery` for harnesses that move changes by
hand (the fuzzer's sync step), and :class:`FaultyPublisher`, a drop-in
``Publisher`` that applies per-subscriber faults and records what it lost so
tests can assert repair actually happened.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List

from ..core.types import Change
from .pubsub import Publisher


@dataclass(frozen=True)
class FaultSpec:
    """Probabilities for one delivery hop."""

    drop_p: float = 0.0
    dup_p: float = 0.0
    reorder: bool = True

    def any_faults(self) -> bool:
        return self.drop_p > 0 or self.dup_p > 0 or self.reorder


def perturb_delivery(
    changes: List[Change], rng: random.Random, spec: FaultSpec
) -> List[Change]:
    """Apply drop / duplicate / reorder faults to one delivery batch.

    Returns the perturbed batch; dropped changes are simply absent (the
    caller's next anti-entropy round will re-ship them)."""
    delivered: List[Change] = []
    for change in changes:
        if rng.random() < spec.drop_p:
            continue
        delivered.append(change)
        while rng.random() < spec.dup_p:
            delivered.append(change)
    if spec.reorder:
        rng.shuffle(delivered)
    return delivered


class FaultyPublisher(Publisher):
    """A ``Publisher`` whose deliveries suffer per-subscriber faults.

    Dropped updates are recorded per subscriber; :meth:`redeliver_lost`
    models the transport-level retransmission that a real deployment gets
    from anti-entropy, letting tests assert convergence-after-repair.
    """

    def __init__(self, spec: FaultSpec, seed: int = 0) -> None:
        super().__init__()
        self.spec = spec
        self.rng = random.Random(seed)
        self.lost: Dict[str, List[List[Change]]] = {}
        self.delivered_count = 0
        self.dropped_count = 0

    def publish(self, sender: str, update: List[Change]) -> None:
        for key, callback in list(self._subscribers.items()):
            if key == sender:
                continue
            perturbed = perturb_delivery(list(update), self.rng, self.spec)
            dropped = [c for c in update if c not in perturbed]
            if dropped:
                self.lost.setdefault(key, []).append(dropped)
                self.dropped_count += len(dropped)
            self.delivered_count += len(perturbed)
            if perturbed:
                callback(perturbed)

    def redeliver_lost(self) -> int:
        """Re-deliver every recorded drop (faithfully, no new faults);
        returns how many changes were retransmitted."""
        count = 0
        for key, batches in list(self.lost.items()):
            callback = self._subscribers.get(key)
            if callback is None:
                continue
            for batch in batches:
                callback(list(batch))
                count += len(batch)
            self.lost[key] = []
        return count
