"""Lag-driven gossip scheduling: the healing control loop over the
convergence monitor's watermarks.

Before this module, a host repaired partitions by round-robining
``try_sync_with`` over its peer list — every peer cost one round-trip per
round whether it was 10,000 ops behind or fully converged, and an
unreachable peer was re-dialed (and re-timed-out) every single round.  The
:class:`GossipScheduler` owns a :class:`~.multihost.ReplicaServer`'s peer
set and turns the :class:`~..obs.convergence.ConvergenceMonitor`'s
behind-states into a round plan:

* **most-behind-first** — peers sort by ``(ops_behind, staleness)``
  descending, so after a partition heals the backlog drains in lag order
  (the peers holding the most missing work are reached first);
* **per-peer backoff** — a peer that keeps failing is skipped for
  ``2^failures`` rounds (capped), so a dead peer costs one timeout every
  backoff window instead of one per round, while the rest of the fleet
  keeps gossiping at full cadence;
* **divergent peers still sync** — divergence is an incident to surface
  (flight recorder + counter), not a reason to stop exchanging; the sync
  keeps the lag picture current while operators investigate.

Determinism: the scheduler holds no wall clock and no RNG (PTL006 merge
scope) — backoff is counted in ROUNDS, ties break on the peer name — so a
fleet harness replay reproduces the exact round order from the same
observation sequence.  All entropy (retry jitter, socket timing) stays in
the transport layer below.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs import GLOBAL_COUNTERS
from .multihost import RetryPolicy, SyncOutcome


@dataclass
class GossipPeer:
    """One peer slot: where to dial it, and its backoff state."""

    name: str
    host: str
    port: int
    #: consecutive failed rounds (mirrors the monitor's failure count but
    #: kept locally so backoff state survives a monitor swap)
    failures: int = 0
    #: scheduler round number before which this peer is skipped
    skip_until: int = 0


class GossipScheduler:
    """Schedules a ReplicaServer's anti-entropy rounds by behind-ness.

    ``peers`` may be seeded at construction or via :meth:`add_peer`; each
    peer has a logical ``name`` (default ``host:port``) — the key the
    monitor tracks it under, which may differ from the dialed address when
    traffic rides a proxy/gateway.  ``backoff_cap`` bounds the skip window
    (rounds); ``retry`` is handed to every ``try_sync_with``.
    """

    def __init__(
        self,
        server,
        peers: Optional[List[Tuple[str, int]]] = None,
        monitor=None,
        retry: Optional[RetryPolicy] = None,
        backoff_cap: int = 8,
    ) -> None:
        self.server = server
        self.monitor = monitor if monitor is not None else server.monitor
        self.retry = retry
        self.backoff_cap = int(backoff_cap)
        self._peers: Dict[str, GossipPeer] = {}
        self.round_no = 0
        #: the peer order of the most recent :meth:`round` (telemetry and
        #: the chaos harness's priority-order oracle)
        self.last_round_order: List[str] = []
        for addr in peers or []:
            self.add_peer(*addr)

    # -- peer-set ownership -------------------------------------------------

    def add_peer(self, host: str, port: int,
                 name: Optional[str] = None) -> str:
        """Register a peer; returns its logical name.  ``name`` defaults to
        ``host:port`` and is how the monitor's watermarks key it — pass the
        peer's canonical identity when dialing through a proxy."""
        name = name or f"{host}:{port}"
        self._peers[name] = GossipPeer(name=name, host=host, port=int(port))
        return name

    def remove_peer(self, name: str) -> bool:
        return self._peers.pop(name, None) is not None

    def peers(self) -> List[str]:
        return sorted(self._peers)

    # -- scheduling ---------------------------------------------------------

    def priority(self, name: str) -> Tuple[int, int]:
        """(ops_behind, staleness) for one peer — higher = more urgent."""
        return self.monitor.behindness(name)

    def plan(self) -> List[str]:
        """This round's peer order: eligible (not backed-off) peers sorted
        most-behind-first — ops_behind desc, then staleness desc, then name
        (the deterministic tiebreak)."""
        eligible = [
            self._peers[n] for n in sorted(self._peers)
            if self._peers[n].skip_until <= self.round_no
        ]
        keyed = [(self.priority(p.name), p.name) for p in eligible]
        keyed.sort(key=lambda kv: (-kv[0][0], -kv[0][1], kv[1]))
        return [name for _, name in keyed]

    def round(self) -> List[Tuple[str, SyncOutcome]]:
        """Run one gossip round: sync eligible peers in behind-ness order,
        applying per-peer exponential backoff to the ones that fail.
        Returns ``[(peer_name, outcome), ...]`` in execution order."""
        self.round_no += 1
        self.monitor.advance_round()
        order = self.plan()
        self.last_round_order = list(order)
        results: List[Tuple[str, SyncOutcome]] = []
        for name in order:
            peer = self._peers[name]
            outcome = self.server.try_sync_with(
                peer.host, peer.port, retry=self.retry, peer_name=name
            )
            if outcome.behind:
                peer.failures += 1
                # exponential skip window, in rounds: 2, 4, ... capped —
                # a dead peer costs one timeout per window, not per round
                window = min(self.backoff_cap, 2 ** peer.failures)
                peer.skip_until = self.round_no + window
                GLOBAL_COUNTERS.add("convergence.gossip_backoffs")
            else:
                peer.failures = 0
                peer.skip_until = 0
            results.append((name, outcome))
        GLOBAL_COUNTERS.add("convergence.gossip_rounds")
        return results

    def wake(self, name: Optional[str] = None) -> None:
        """Clear backoff state — for one peer, or (default) all of them.
        The heal hook: when something above the scheduler learns a
        partition lifted (a failure detector, an operator, the chaos
        harness), waking skips the remaining backoff windows so the next
        round retries immediately, in behind-ness order."""
        peers = (
            [self._peers[name]] if name
            else [self._peers[n] for n in sorted(self._peers)]
        )
        for p in peers:
            p.failures = 0
            p.skip_until = 0

    def drain(self, max_rounds: int = 64) -> int:
        """Gossip until no tracked peer reports lag, staleness stops
        advancing the picture, and a full round completes with every
        eligible exchange clean — or ``max_rounds`` elapse.  Returns the
        number of rounds run.  The post-heal entry point: a caller that
        knows a partition just lifted calls ``drain()`` and gets lag-ordered
        convergence."""
        for i in range(1, max_rounds + 1):
            results = self.round()
            all_clean = all(not out.behind for _, out in results)
            if all_clean and results and self.monitor.total_lag_ops() == 0:
                return i
        return max_rounds

    def snapshot(self) -> Dict:
        """JSON-serializable scheduler state (composes into fleet views)."""
        return {
            "round": self.round_no,
            "peers": {
                name: {
                    "host": p.host,
                    "port": p.port,
                    "failures": p.failures,
                    "backed_off": p.skip_until > self.round_no,
                    "priority": list(self.priority(name)),
                }
                for name, p in sorted(self._peers.items())
            },
            "last_round_order": list(self.last_round_order),
        }
