"""Multi-host replication transport: vector-clock anti-entropy over TCP.

The reference's replication never leaves one process — ``Publisher`` is an
in-memory fan-out (reference src/pubsub.ts:4-25) and the anti-entropy clock
diff runs between two in-process replicas (test/merge.ts:25-38).  This module
is the multi-host equivalent: each host exposes its append-only
:class:`~.anti_entropy.ChangeStore` on a TCP endpoint, and one
``sync_with`` round performs a full bidirectional anti-entropy exchange —
frontiers are swapped, and each side ships exactly the changes the other is
missing, packed as binary codec frames (:mod:`.codec`, the DCN wire format).

Division of labour with the device path: this transport only converges the
*change logs* across hosts (cheap, irregular, host-side).  Each host then
feeds its converged logs to its own device mesh via the normal batched path
(api.DocBatch / parallel.streaming) — cross-host traffic rides DCN once per
change, while all per-op work stays on the chips.

Protocol (all messages length-prefixed: 4-byte big-endian length, 1-byte
type, body):

* ``F`` frontier — JSON vector clock ``{actor: seq}``.
* ``C`` changes  — one binary codec frame.

Exchange, from the client's side::

    connect -> send F(mine) -> recv C(what I lack) + F(theirs)
            -> send C(what they lack) -> close

Both sides merge with :func:`merge_changes`, which tolerates duplicates and
out-of-order arrival (per-actor seq ordering restores log order), so repeated
or concurrent syncs against many peers are safe — the store is a CRDT of
append-only logs.

Fault domains (the supervisor layer): every socket operation runs under a
per-socket deadline — a stalled peer raises :class:`TransportError` (via
``socket.timeout``) instead of hanging ``_recv_exact`` forever.  The retry
layer (:class:`RetryPolicy`) wraps one anti-entropy round in bounded
exponential backoff with jitter; :func:`try_sync_with` absorbs terminal
transport failures into a :class:`SyncOutcome` whose ``behind`` flag simply
means "this peer's changes are still missing" — exactly the state a later
anti-entropy round repairs, because the store is append-only and
duplicate-tolerant.  Callers above the transport never need to see a
transport exception to stay correct.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..core.errors import DecodeError, PeritextError, TransportError
from ..core.types import Change, Clock
from ..obs import GLOBAL_COUNTERS, GLOBAL_HISTOGRAMS, GLOBAL_TRACER, TraceContext
from .anti_entropy import ChangeStore
from .codec import (
    WIRE_CAPS,
    WireSession,
    decode_frame,
    encode_frame,
    encode_frame_checked,
    encode_frame_chunks,
    encode_frame_traced,
    iter_frames,
    strip_trace_context,
)

_LEN = struct.Struct(">I")
_MAX_MESSAGE = 1 << 28  # 256 MiB: far above any sane frame, guards corrupt peers

MSG_FRONTIER = b"F"
MSG_CHANGES = b"C"
#: multi-frame change payload (concatenated encode_frame_chunks output).
#: A DISTINCT kind, not MSG_CHANGES with trailing frames: a pre-chunking
#: peer's decoder read one frame and silently IGNORED trailing bytes, so
#: reusing "C" would truncate large backlogs against old peers without any
#: error.  Old peers reject the unknown kind loudly (sync aborts, store
#: untouched); small backlogs still ride "C" for full compatibility.
MSG_CHANGES_MULTI = b"M"
#: checkpoint-frame ship (the fleet tier's doc-state migration leg): body =
#: 4-byte big-endian JSON-header length + header + packed frame blob
#: (checkpoint.pack_doc_frames).  The header names the doc key and carries
#: the sender's ``base`` (how many frames it believes the receiver already
#: holds — the frame-count frontier of this anti-entropy-shaped exchange).
#: A peer without a ship handler rejects the kind loudly; nothing about the
#: frontier/changes exchange changes.
MSG_SHIP = b"S"
#: ship acknowledgement: JSON ``{"doc": key, "have": n}`` — the receiver's
#: post-merge frame count, so the shipper can diff and re-ship a tail that
#: landed while this leg was in flight (the catch-up round).
MSG_SHIP_ACK = b"A"


# -- retry policy ------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + jitter for one transport leg.

    ``attempts`` counts TOTAL tries (1 = no retry).  Delay before try k+1 is
    ``min(max_delay, base_delay * 2**k)`` scaled by a uniform jitter in
    ``[1, 1 + jitter]`` — jitter desynchronizes a fleet of peers retrying
    against the same recovered host.  ``timeout`` is the per-SOCKET deadline
    applied to connect and every send/recv of the attempt, so one stalled
    peer costs at most ``attempts * timeout`` wall-clock, never forever."""

    attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5
    timeout: float = 30.0

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.max_delay, self.base_delay * (2 ** attempt))
        return base * (1.0 + self.jitter * rng.random())


#: single-attempt policy — the pre-supervisor behavior, minus the hangs
NO_RETRY = RetryPolicy(attempts=1)


@dataclass
class SyncOutcome:
    """Result of one :func:`try_sync_with` round.  ``behind=True`` means the
    peer could not be reached within the retry budget: nothing was lost (the
    store is untouched or merely partially ahead), the local frontier is
    simply behind that peer until a later anti-entropy round succeeds."""

    pulled: int = 0
    pushed: int = 0
    ok: bool = True
    error: Optional[str] = None

    @property
    def behind(self) -> bool:
        return not self.ok


# -- framing ----------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout as exc:
            raise TransportError(
                f"peer stalled: recv deadline exceeded with {n - len(buf)} "
                "bytes outstanding"
            ) from exc
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def _send_message(sock: socket.socket, kind: bytes, body: bytes) -> None:
    try:
        sock.sendall(_LEN.pack(len(body) + 1) + kind + body)
    except socket.timeout as exc:
        raise TransportError("peer stalled: send deadline exceeded") from exc


def _recv_message(sock: socket.socket) -> Tuple[bytes, bytes]:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if not 1 <= length <= _MAX_MESSAGE:
        raise ConnectionError(f"bad message length {length}")
    payload = _recv_exact(sock, length)
    return payload[:1], payload[1:]


# Frontier metadata sentinels (observability, round 3 of the wire): the
# frontier stays a ``{str: int}`` JSON map — exactly what every deployed
# peer validates — and capability/trace metadata rides as keys that can
# never collide with an actor id (actor ids are printable; these start with
# NUL).  An OLD peer accepts them as unknown "actors" whose seqs it never
# looks up (``missing_changes`` iterates only the SOURCE clock), so the
# negotiation is invisible to it; a NEW peer strips them before any clock
# math.  ``caps`` advertises the sender's max decodable wire version —
# trace-context (v5) frames are sent only to a peer that advertised
# ``caps >= WIRE_CAPS``, which is how old peers keep decoding everything.
_META_CAPS = "\x00caps"
_META_TRACE = "\x00trace"
_META_SPAN = "\x00span"
#: convergence observability (round 4 of the wire): the sender's COMMUTATIVE
#: store digest at the advertised frontier (ChangeStore.digest — the
#: divergence probe: equal frontiers must carry equal digests), and the
#: sender's own replica LISTENING port so the serving side can attribute the
#: observation to a stable peer identity (peer-IP + advertised port) for its
#: ConvergenceMonitor.  Both are ints, so old peers' {str: int} frontier
#: validation accepts-and-ignores them like every other sentinel.
_META_DIGEST = "\x00digest"
_META_PORT = "\x00port"
#: incident observability (round 5 of the wire): the sender's compact
#: incident summary (``IncidentMonitor.wire_summary`` — open count packed
#: above a 32-bit digest of the observation-derived incident view), so two
#: frontends can tell whether they AGREE on what is broken before the
#: ROADMAP's death-verdict gossip acts on it.  An int, so old peers'
#: {str: int} frontier validation accepts-and-ignores it like every other
#: sentinel.
_META_INCIDENTS = "\x00incidents"
_META_KEYS = {_META_CAPS: "caps", _META_TRACE: "trace", _META_SPAN: "span",
              _META_DIGEST: "digest", _META_PORT: "port",
              _META_INCIDENTS: "incidents"}


def _frontier_meta(tracer, span, digest=None, port=None,
                   incidents=None) -> dict:
    """The metadata this endpoint attaches to an outbound frontier: always
    its wire caps; the current span's trace context when tracing is live,
    so the peer's handler span can join OUR trace; the store digest at the
    advertised frontier (divergence probe); for endpoints that serve a
    replica socket, the listening port (peer attribution); and, when an
    incident monitor is armed, its packed incident summary (fleet incident
    agreement)."""
    meta = {_META_CAPS: WIRE_CAPS}
    if span is not None and tracer is not None and tracer.active():
        meta[_META_TRACE] = int(span.trace_id)
        meta[_META_SPAN] = int(span.span_id)
    if digest is not None:
        meta[_META_DIGEST] = int(digest)
    if port is not None:
        meta[_META_PORT] = int(port)
    if incidents is not None:
        meta[_META_INCIDENTS] = int(incidents)
    return meta


def _send_frontier(sock: socket.socket, clock: Clock,
                   meta: Optional[dict] = None) -> None:
    payload = dict(clock)
    if meta:
        payload.update(meta)
    _send_message(sock, MSG_FRONTIER, json.dumps(payload).encode("utf-8"))


def _parse_frontier(body: bytes) -> Tuple[Clock, dict]:
    """Decode and validate a frontier message: must be ``{actor: seq}`` with
    string keys and int seqs — anything else is a protocol error, typed as
    :class:`DecodeError` (a ValueError) so both endpoints' error contracts
    stay uniform and ``try_sync_with`` can absorb a corrupt peer as a
    ``behind`` outcome.  Returns ``(clock, meta)`` with the metadata
    sentinels (caps / trace context) stripped out of the clock."""
    try:
        clock = json.loads(body)
    except json.JSONDecodeError as exc:
        raise DecodeError(f"bad frontier: {exc}") from exc
    if not isinstance(clock, dict) or not all(
        isinstance(k, str) and isinstance(v, int) and not isinstance(v, bool)
        for k, v in clock.items()
    ):
        raise DecodeError("bad frontier: expected {actor: seq}")
    meta = {
        name: clock.pop(key)
        for key, name in _META_KEYS.items()
        if key in clock
    }
    return clock, meta


def _meta_ctx(meta: dict) -> Optional[TraceContext]:
    """The peer's wire-carried trace context, when its frontier sent one."""
    if "trace" in meta and "span" in meta:
        return TraceContext(meta["trace"], meta["span"])
    return None


def _expect(sock: socket.socket, expected: bytes) -> bytes:
    kind, body = _recv_message(sock)
    if kind != expected:
        raise ConnectionError(f"expected message {expected!r}, got {kind!r}")
    return body


def _send_changes(sock: socket.socket, changes: List[Change],
                  peer_caps: int = 0,
                  ctx: Optional[TraceContext] = None) -> None:
    """One MSG_CHANGES frame when the backlog fits a single frame's decode
    budget (the overwhelmingly common case, wire-identical to old peers),
    else MSG_CHANGES_MULTI: session-scoped (v4) chunks sharing one string
    dictionary + deflate — the string table and repeated attrs are paid once
    per backlog, not once per chunk.  Single-frame version negotiation, by
    the peer's advertised caps: ``caps >= 6`` rides wire v6 (CRC32-checked,
    trace context embedded when one is live); a ``caps == 5`` peer with a
    live trace context gets v5 (traced, unchecked — its maximum); everyone
    else gets plain v2.  Large MULTI backlogs fall back to untraced v3/v4
    chunks — the frontier already carried the context."""
    from .codec import _ENCODE_CHUNK_CHARGE, _VERSION_TRACED

    if sum(1 + len(c.deps or {}) for c in changes) <= _ENCODE_CHUNK_CHARGE:
        if peer_caps >= WIRE_CAPS:
            frame = encode_frame_checked(
                changes, *(ctx if ctx is not None else (0, 0))
            )
        elif ctx is not None and peer_caps >= _VERSION_TRACED:
            frame = encode_frame_traced(changes, ctx.trace_id, ctx.span_id)
        else:
            frame = encode_frame(changes)
        _send_message(sock, MSG_CHANGES, frame)
        return
    chunks = encode_frame_chunks(changes, session=WireSession(compress=True))
    _send_message(sock, MSG_CHANGES_MULTI, b"".join(chunks))


def _recv_changes(
    sock: socket.socket, want_frames: bool = True,
) -> Tuple[List[Change], List[bytes], Optional[TraceContext]]:
    """Receive either changes kind; returns (changes, self-contained frames
    for ``on_frame`` consumers — MULTI chunks are normalized to v2 so a
    consumer can store or re-ingest each frame independently, and a traced
    v5 single frame is stripped the same way — plus the frame-carried trace
    context when there was one).  Pass ``want_frames=False`` when no
    on_frame consumer exists: normalization is a full re-encode of the
    backlog, wasted on discarded output."""
    kind, body = _recv_message(sock)
    if kind == MSG_CHANGES:
        ctx, plain = strip_trace_context(body)
        return (
            decode_frame(plain),
            [plain] if want_frames else [],
            TraceContext(*ctx) if ctx is not None else None,
        )
    if kind == MSG_CHANGES_MULTI:
        sess = WireSession()
        changes: List[Change] = []
        frames: List[bytes] = []
        for raw in iter_frames(body):
            if want_frames:
                part, v2 = sess.decode_frame_normalized(raw)
                frames.append(v2)
            else:
                part = sess.decode_frame(raw)
            changes.extend(part)
        return changes, frames, None
    raise ConnectionError(f"expected changes message, got {kind!r}")


# -- store merge ------------------------------------------------------------


def merge_changes(store: ChangeStore, changes: List[Change]) -> List[Change]:
    """Merge remotely-received changes into ``store``; returns the changes
    that were actually new.  Duplicates (seq already present) are skipped;
    per-actor seq sorting restores append order, so arbitrary arrival order
    is fine as long as each actor's suffix is contiguous — which the clock
    diff guarantees (reference getMissingChanges ships ``log[have:seq]``)."""
    fresh: List[Change] = []
    for change in sorted(changes, key=lambda c: (c.actor, c.seq)):
        have = len(store.log(change.actor))
        if change.seq <= have:
            continue  # duplicate from a concurrent sync
        store.append(change)  # raises on a genuine gap
        fresh.append(change)
    return fresh


# -- server -----------------------------------------------------------------


class ReplicaServer:
    """Serves one host's ChangeStore for anti-entropy pulls from peers.

    ``on_changes`` (optional) is invoked with each batch of newly-merged
    remote changes — the hook where a host forwards fresh changes into its
    device pipeline (e.g. ``StreamingMerge.ingest``).  It runs on the
    connection-handler thread; keep it quick or hand off to a queue.
    """

    def __init__(
        self,
        store: ChangeStore,
        host: str = "127.0.0.1",
        port: int = 0,
        on_changes: Optional[Callable[[List[Change]], None]] = None,
        on_frame: Optional[Callable[[bytes], None]] = None,
        timeout: float = 30.0,
        tracer=None,
        recorder=None,
        metrics_port: Optional[int] = None,
        monitor=None,
        serve=None,
        on_ship: Optional[Callable[[str, List[bytes], int], int]] = None,
        fleet=None,
        incidents=None,
    ) -> None:
        """``on_changes`` receives each batch of newly-merged decoded
        changes; ``on_frame`` receives the RAW inbound frame bytes whenever
        it carried anything new — the zero-copy hook for feeding a device
        session's ``ingest_frame`` (frames are duplicate-tolerant, so
        redelivered changes inside the frame are harmless).  ``timeout`` is
        the per-connection socket deadline: a peer that stalls mid-exchange
        holds a handler thread for at most this long.

        Observability: ``tracer`` (default the process tracer) produces
        anti-entropy spans that join a traced peer's trace via the
        wire-carried context; ``recorder`` gets a ``fault`` record on
        transport give-ups (``try_sync_with``) and divergence incidents;
        ``monitor`` (default: a fresh
        :class:`~..obs.convergence.ConvergenceMonitor`) ingests every
        frontier this server exchanges, inbound and outbound, maintaining
        per-peer lag watermarks and divergence probes; ``metrics_port``
        (0 = ephemeral) mounts an :class:`~..obs.MetricsServer` exposing
        ``/metrics`` (Prometheus, with ``peritext_convergence_*`` gauges),
        ``/health.json``, ``/convergence.json`` and ``/trace.json`` — its
        bound address is :attr:`metrics_address` after :meth:`start`;
        ``serve`` (a :class:`~..serve.SessionMux`) additionally mounts
        ``/serve.json`` and the ``peritext_serve_*`` gauges, so a serving
        host's replica endpoint and serving telemetry share one scrape;
        ``fleet`` (a :class:`~..serve.fleet.FleetFrontend`) mounts
        ``/fleet.json`` + the ``peritext_fleet_*`` gauges the same way.

        ``on_ship`` is the checkpoint-ship receiver
        ``(doc_key, frames, base) -> total frame count now held``: the
        fleet tier's doc-state migration lands here (frames are
        duplicate-tolerant, so a retried or overlapping ship is
        idempotent).  Without a handler, MSG_SHIP connections are refused
        loudly — this endpoint does not accept migrations."""
        from ..obs import ConvergenceMonitor

        #: optional :class:`~..obs.incidents.IncidentMonitor`: when armed,
        #: outbound frontiers carry its packed summary (the
        #: ``"\x00incidents"`` sentinel) and inbound ones feed
        #: ``observe_peer_summary`` — the fleet incident-agreement view
        self.incidents = incidents
        self.store = store
        self.on_changes = on_changes
        self.on_frame = on_frame
        self.on_ship = on_ship
        self.timeout = timeout
        self.tracer = tracer if tracer is not None else GLOBAL_TRACER
        self.recorder = recorder
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address: Tuple[str, int] = self._sock.getsockname()
        self.monitor = monitor if monitor is not None else ConvergenceMonitor(
            host=f"{self.address[0]}:{self.address[1]}", recorder=recorder,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.metrics = None
        self.metrics_address: Optional[Tuple[str, int]] = None
        if metrics_port is not None:
            from ..obs import GLOBAL_DEVPROF, MetricsServer

            try:
                self.metrics = MetricsServer(
                    host=host, port=metrics_port,
                    tracer=self.tracer, recorder=self.recorder,
                    convergence=self.monitor,
                    # the process profiler is mounted even while disabled:
                    # /devprof.json answers (enabled: false) and the gauges
                    # appear the moment an operator arms GLOBAL_DEVPROF
                    devprof=GLOBAL_DEVPROF,
                    serve=serve,
                    fleet=fleet,
                )
            except OSError:
                # metrics port unavailable: release the already-bound
                # replica socket too, or a caller's retry loop finds its
                # replica port intermittently held by this dead instance
                self._sock.close()
                raise
            self.metrics_address = self.metrics.address

    def start(self) -> Tuple[str, int]:
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        if self.metrics is not None:
            self.metrics_address = self.metrics.start()
        return self.address

    def stop(self) -> None:
        self._stop.set()
        # shutdown() BEFORE close(): closing a listening socket does not
        # wake a thread already blocked in accept() on Linux — the accept
        # loop would strand until the join timeout below (a flat 5 s per
        # server teardown, multiplied across every test/chaos episode that
        # builds a fleet).  shutdown() fails accept() immediately.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.metrics is not None:
            self.metrics.stop()

    # internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            threading.Thread(
                target=self._serve_one, args=(conn,), daemon=True
            ).start()

    def sync_with(
        self, host: str, port: int, timeout: Optional[float] = None,
        retry: Optional[RetryPolicy] = None,
        peer_name: Optional[str] = None,
    ) -> Tuple[int, int]:
        """Outbound anti-entropy round sharing this server's store lock, so a
        node that serves peers and pulls from peers concurrently stays
        consistent."""
        return sync_with(
            self.store, host, port,
            on_changes=self.on_changes, timeout=timeout, lock=self._lock,
            on_frame=self.on_frame, retry=retry, tracer=self.tracer,
            monitor=self.monitor, advertise_port=self.address[1],
            peer_name=peer_name, incidents=self.incidents,
        )

    def try_sync_with(
        self, host: str, port: int, retry: Optional[RetryPolicy] = None,
        peer_name: Optional[str] = None,
    ) -> SyncOutcome:
        """Non-raising outbound round: terminal transport failure becomes a
        ``behind`` outcome for the next anti-entropy pass."""
        return try_sync_with(
            self.store, host, port,
            on_changes=self.on_changes, lock=self._lock,
            on_frame=self.on_frame, retry=retry, tracer=self.tracer,
            recorder=self.recorder, monitor=self.monitor,
            advertise_port=self.address[1], peer_name=peer_name,
            incidents=self.incidents,
        )

    def _handle_ship(self, conn: socket.socket, body: bytes) -> None:
        """One inbound checkpoint ship: parse the header + packed frames,
        hand them to ``on_ship`` (which merges idempotently and returns the
        doc's total frame count), and ack with that count — the shipper's
        catch-up input."""
        from ..checkpoint import unpack_doc_frames

        if self.on_ship is None:
            raise ConnectionError("this endpoint accepts no checkpoint ships")
        try:
            (hlen,) = _LEN.unpack(body[:_LEN.size])
            header = json.loads(body[_LEN.size:_LEN.size + hlen])
            doc_key = str(header["doc"])
            base = int(header.get("base", 0))
            frames = unpack_doc_frames(body[_LEN.size + hlen:])
        except (ValueError, KeyError, TypeError, struct.error) as exc:
            # struct.error (short body), KeyError/TypeError (header not a
            # dict / missing "doc"), json/unpack ValueError: all must stay
            # inside _serve_one's bad-peer guard — a malformed ship is a
            # counted, swallowed protocol error, never a dead thread
            raise DecodeError(f"malformed checkpoint ship: {exc!r}") from exc
        with self.tracer.span(
            "fleet.ship.receive", doc=doc_key, frames=len(frames),
        ):
            have = int(self.on_ship(doc_key, frames, base))
        GLOBAL_COUNTERS.add("fleet.ship_frames_received", len(frames))
        _send_message(
            conn, MSG_SHIP_ACK,
            json.dumps({"doc": doc_key, "have": have}).encode("utf-8"),
        )

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(self.timeout)
                kind, first = _recv_message(conn)
                if kind == MSG_SHIP:
                    self._handle_ship(conn, first)
                    return
                if kind != MSG_FRONTIER:
                    raise ConnectionError(
                        f"expected message {MSG_FRONTIER!r} or {MSG_SHIP!r}, "
                        f"got {kind!r}"
                    )
                peer_clock, meta = _parse_frontier(first)
                # peer attribution for the convergence monitor: a frontier
                # that advertised the sender's replica port names a stable
                # identity (peer IP + that port); bare clients (no replica
                # socket) stay anonymous and are not tracked
                peer_name = None
                if "port" in meta:
                    try:
                        peer_name = (
                            f"{conn.getpeername()[0]}:{int(meta['port'])}"
                        )
                    except OSError:
                        peer_name = None
                # the peer's frontier carried its trace context: this
                # handler's span (and every child span it opens — ingest,
                # merge) joins the PEER's trace, so a two-host exchange
                # renders as one timeline in the merged Perfetto trace
                with self.tracer.span(
                    "anti-entropy.serve", ctx=_meta_ctx(meta),
                ) as sp:
                    with self._lock:
                        my_clock = self.store.clock()
                        my_digest = self.store.digest(my_clock)
                        outbound = self.store.missing_changes(my_clock, peer_clock)
                    if peer_name is not None and self.monitor is not None:
                        # inbound frontiers count too: under an asymmetric
                        # partition (we can hear but not dial), this is how
                        # the host still learns how far behind it is
                        self.monitor.observe_frontier(
                            peer_name, my_clock, peer_clock,
                            local_digest=my_digest,
                            peer_digest=meta.get("digest"),
                        )
                    if (peer_name is not None and self.incidents is not None
                            and "incidents" in meta):
                        self.incidents.observe_peer_summary(
                            peer_name, meta["incidents"]
                        )
                    # chunked: a large backlog splits into multiple frames so
                    # no single frame approaches the peer's decode dep budget
                    _send_changes(
                        conn, outbound, peer_caps=int(meta.get("caps", 0)),
                        ctx=sp.context if self.tracer.active() else None,
                    )
                    _send_frontier(
                        conn, my_clock, meta=_frontier_meta(
                            self.tracer, sp, digest=my_digest,
                            port=self.address[1],
                            incidents=(self.incidents.wire_summary()
                                       if self.incidents is not None
                                       else None),
                        )
                    )
                    # the frame-level ctx is redundant HERE: this handler
                    # span already adopted the same context from the peer's
                    # frontier, and the on_frame/on_changes delivery below
                    # runs inside it (the client side of the exchange is
                    # where the frame field is load-bearing — sync_with)
                    inbound, frames, _ = _recv_changes(
                        conn, want_frames=self.on_frame is not None
                    )
                    with self._lock:
                        fresh = merge_changes(self.store, inbound)
                    if peer_name is not None and self.monitor is not None:
                        self.monitor.observe_success(
                            peer_name, pulled=len(fresh),
                            pushed=len(outbound),
                        )
                    sp.args.update(pulled=len(fresh), pushed=len(outbound))
                    if fresh:
                        # on_frame first: consumers that ingest via on_frame
                        # and account via on_changes must never observe the
                        # count ahead of the ingestion
                        if self.on_frame is not None:
                            for one in frames:
                                self.on_frame(one)
                        if self.on_changes is not None:
                            self.on_changes(fresh)
                GLOBAL_HISTOGRAMS.observe("transport.serve_seconds", sp.duration)
        except (ConnectionError, ValueError, OSError, PeritextError):
            # a bad peer (bad framing, corrupt frame, malformed frontier, or a
            # change batch with log gaps) must not take the server down
            GLOBAL_COUNTERS.add("transport.server_errors")
            return


# -- client -----------------------------------------------------------------


def _sync_once(
    store: ChangeStore,
    host: str,
    port: int,
    timeout: float,
    lock: threading.Lock,
    want_frames: bool,
    tracer,
    monitor=None,
    advertise_port: Optional[int] = None,
    peer_name: Optional[str] = None,
    incidents=None,
) -> Tuple[List[Change], int, List[bytes], Optional[TraceContext]]:
    """One attempt of the bidirectional exchange (see :func:`sync_with`).
    The store mutates only AFTER the socket closes cleanly, so a failed
    attempt is side-effect free and safe to retry.  Returns the freshly
    merged changes, the pushed count, the raw inbound frames, and the
    peer's frame-carried trace context — on_frame/on_changes delivery
    happens in the CALLER, outside the retried region: a callback failure
    after a successful merge is a local error, and retrying it would skip
    the callbacks entirely (the reconnect pulls only duplicates).

    A ``monitor`` (:class:`~..obs.convergence.ConvergenceMonitor`) ingests
    the peer's frontier AS SOON AS IT PARSES — before the exchange
    completes — so an attempt that dies mid-transfer (slow link, stall)
    still updates the peer's lag watermark with what the frontier taught
    us."""
    with tracer.span("anti-entropy.sync", peer=f"{host}:{port}") as sp:
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.settimeout(timeout)  # per-socket deadline on every send/recv
            with lock:
                my_clock = store.clock()
                my_digest = store.digest(my_clock)
            # the frontier carries our caps + this span's trace context, so
            # the peer's handler span joins THIS trace (cross-host spans);
            # plus the store digest at this frontier (divergence probe) and
            # our replica port when we serve one (peer attribution)
            _send_frontier(sock, my_clock, meta=_frontier_meta(
                tracer, sp, digest=my_digest, port=advertise_port,
                incidents=(incidents.wire_summary()
                           if incidents is not None else None),
            ))
            inbound, frames, in_ctx = _recv_changes(sock, want_frames=want_frames)
            peer_clock, meta = _parse_frontier(_expect(sock, MSG_FRONTIER))
            if incidents is not None and "incidents" in meta:
                incidents.observe_peer_summary(
                    peer_name or f"{host}:{port}", meta["incidents"]
                )
            if monitor is not None:
                # telemetry only, observed against the PRE-merge snapshot:
                # both frontiers are pre-exchange positions, so the
                # clock-delta sums are this round's true lag watermarks
                monitor.observe_frontier(
                    peer_name or f"{host}:{port}", my_clock, peer_clock,
                    local_digest=my_digest, peer_digest=meta.get("digest"),
                )
            with lock:
                outbound = store.missing_changes(store.clock(), peer_clock)
            _send_changes(
                sock, outbound, peer_caps=int(meta.get("caps", 0)),
                ctx=sp.context if tracer.active() else None,
            )
        with lock:
            fresh = merge_changes(store, inbound)
        sp.args.update(pulled=len(fresh), pushed=len(outbound))
    GLOBAL_HISTOGRAMS.observe("transport.sync_seconds", sp.duration)
    return fresh, len(outbound), frames, in_ctx


#: what a retry may absorb: connect/stall/teardown (OSError family, incl.
#: socket.timeout and our TransportError) and protocol corruption
#: (ValueError, incl. DecodeError).  A CausalityError from merge_changes is
#: NOT transport — a genuine log gap propagates to the caller.
_RETRYABLE = (OSError, ValueError)


def sync_with(
    store: ChangeStore,
    host: str,
    port: int,
    on_changes: Optional[Callable[[List[Change]], None]] = None,
    timeout: Optional[float] = None,
    lock: Optional[threading.Lock] = None,
    on_frame: Optional[Callable[[bytes], None]] = None,
    retry: Optional[RetryPolicy] = None,
    tracer=None,
    monitor=None,
    advertise_port: Optional[int] = None,
    peer_name: Optional[str] = None,
    incidents=None,
) -> Tuple[int, int]:
    """One full bidirectional anti-entropy round against a peer.

    Returns ``(pulled, pushed)`` change counts.  Every socket operation runs
    under a per-socket deadline — an explicitly-passed ``timeout`` wins,
    else the retry policy's ``timeout`` (30 s with no policy) — so a stalled
    peer raises :class:`TransportError` instead of hanging.  With a
    :class:`RetryPolicy`, transport-level failures (connect refused, stall,
    teardown, corrupt protocol bytes) retry with exponential backoff +
    jitter; a terminal connect/stall/teardown failure raises
    :class:`TransportError`, while terminal protocol corruption keeps its
    typed :class:`~..core.errors.DecodeError`/ValueError surface (the
    pre-retry contract).  Retrying is always safe: the store mutates only
    after a complete exchange, logs are append-only, and merge_changes
    skips duplicates.  ``on_frame``/``on_changes`` run once, after the
    successful attempt — an exception they raise propagates unwrapped (it
    is a local failure, not transport).  Pass ``lock`` when other threads
    (e.g. a ReplicaServer on the same store) mutate the store concurrently.
    """
    lock = lock or threading.Lock()
    policy = retry or NO_RETRY
    tracer = tracer if tracer is not None else GLOBAL_TRACER
    deadline = timeout if timeout is not None else policy.timeout
    rng = random.Random()
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.attempts)):
        if attempt:
            GLOBAL_COUNTERS.add("transport.retries")
            time.sleep(policy.delay(attempt - 1, rng))
        try:
            fresh, pushed, frames, in_ctx = _sync_once(
                store, host, port, deadline, lock, on_frame is not None,
                tracer, monitor=monitor, advertise_port=advertise_port,
                peer_name=peer_name, incidents=incidents,
            )
        except _RETRYABLE as exc:
            last = exc
            continue
        if monitor is not None:
            # the pull merged: the observed lag drained, staleness resets
            monitor.observe_success(
                peer_name or f"{host}:{port}", pulled=len(fresh),
                pushed=pushed,
            )
        if fresh:
            # delivery runs after the sync span closed (outside the retried
            # region), so the peer's FRAME-carried context is what links the
            # consumer's ingest spans into the exchange's trace — this is
            # the client-side consumer of wire v5 (the serve side's ingest
            # nests under its handler span, which adopted the frontier ctx)
            with tracer.span(
                "anti-entropy.deliver", ctx=in_ctx, pulled=len(fresh),
            ):
                if on_frame is not None:  # before on_changes; see ReplicaServer
                    for one in frames:
                        on_frame(one)
                if on_changes is not None:
                    on_changes(fresh)
        return len(fresh), pushed
    if isinstance(last, ValueError) and not isinstance(last, OSError):
        raise last  # protocol corruption: keep the typed DecodeError surface
    raise TransportError(
        f"sync with {host}:{port} failed after {max(1, policy.attempts)} "
        f"attempt(s): {last!r}"
    ) from last


def try_sync_with(
    store: ChangeStore,
    host: str,
    port: int,
    on_changes: Optional[Callable[[List[Change]], None]] = None,
    lock: Optional[threading.Lock] = None,
    on_frame: Optional[Callable[[bytes], None]] = None,
    retry: Optional[RetryPolicy] = None,
    tracer=None,
    recorder=None,
    monitor=None,
    advertise_port: Optional[int] = None,
    peer_name: Optional[str] = None,
    incidents=None,
) -> SyncOutcome:
    """Anti-entropy round that NEVER raises on transport failure: a peer
    that stays unreachable through the retry budget yields a ``behind``
    :class:`SyncOutcome` — the local store is simply behind that peer's
    frontier, and the next successful round repairs it (append-only,
    duplicate-tolerant).  A peer shipping corrupt protocol bytes through
    the retry budget (:class:`DecodeError`) is the same state — behind
    until a clean round.  Non-transport errors (e.g. a genuine log gap, or
    a failure inside the caller's own on_frame/on_changes callback) still
    propagate: they indicate local problems a retry cannot fix."""
    policy = retry or RetryPolicy()

    # fence the caller's callbacks off from the exchange's own error space:
    # a DecodeError raised INSIDE on_frame/on_changes is a local delivery
    # failure (the store already merged the pull — "behind" would be a lie
    # no later round repairs), so it must propagate, while the same type
    # from the exchange itself is a corrupt peer and absorbs as behind
    class _CallbackFailed(Exception):
        pass

    def _fenced(cb):
        if cb is None:
            return None

        def run(arg):
            try:
                cb(arg)
            except Exception as exc:  # graftlint: boundary(fences caller callbacks out of the exchange's retry/error space; rewrapped and re-raised)
                raise _CallbackFailed() from exc

        return run

    try:
        pulled, pushed = sync_with(
            store, host, port, on_changes=_fenced(on_changes),
            lock=lock, on_frame=_fenced(on_frame), retry=policy,
            tracer=tracer, monitor=monitor, advertise_port=advertise_port,
            peer_name=peer_name, incidents=incidents,
        )
    except _CallbackFailed as exc:
        raise exc.__cause__
    except (TransportError, DecodeError) as exc:
        GLOBAL_COUNTERS.add("transport.behind_peers")
        if monitor is not None:
            # the behind state is no longer forgotten: the monitor keeps the
            # peer's last lag estimate and grows its staleness/failure
            # counts — the gossip scheduler's healing priority inputs
            monitor.observe_failure(
                peer_name or f"{host}:{port}", error=str(exc)
            )
        if recorder is not None:
            # transport give-up: the flight recorder turns "that peer was
            # behind all soak" into a post-mortem with the attempts' spans
            recorder.fault(
                "transport-give-up", peer=f"{host}:{port}", error=str(exc)
            )
        return SyncOutcome(ok=False, error=str(exc))
    return SyncOutcome(pulled=pulled, pushed=pushed)


# -- checkpoint ship (the fleet tier's doc-state migration leg) --------------


def ship_frames(
    host: str,
    port: int,
    doc_key: str,
    frames: List[bytes],
    base: int = 0,
    retry: Optional[RetryPolicy] = None,
    timeout: Optional[float] = None,
    tracer=None,
) -> int:
    """Ship one doc's checkpoint frame history to a peer's ship endpoint
    (``ReplicaServer(on_ship=...)``) and return the peer's post-merge frame
    count — the frame-count frontier of this anti-entropy-shaped exchange,
    which the caller diffs against its own history to ship the tail that
    landed mid-move (the catch-up round).

    Rides the SAME bounded-retry transport discipline as the anti-entropy
    sync: per-socket deadlines (a stalled peer raises
    :class:`TransportError`, never hangs), exponential backoff + jitter
    between attempts.  Retrying is always safe: the receiver's merge is
    idempotent (frames are duplicate-tolerant), so a ship that died after
    partial delivery simply re-ships.  ``base`` advertises how many frames
    the sender believes the receiver already holds — a fresh target gets 0,
    a catch-up leg gets the previous ack's ``have``."""
    from ..checkpoint import pack_doc_frames

    policy = retry or NO_RETRY
    deadline = timeout if timeout is not None else policy.timeout
    tracer = tracer if tracer is not None else GLOBAL_TRACER
    header = json.dumps({"doc": doc_key, "base": int(base)}).encode("utf-8")
    body = _LEN.pack(len(header)) + header + pack_doc_frames(frames)
    rng = random.Random()
    last: Optional[BaseException] = None
    for attempt in range(max(1, policy.attempts)):
        if attempt:
            GLOBAL_COUNTERS.add("transport.retries")
            time.sleep(policy.delay(attempt - 1, rng))
        try:
            with tracer.span(
                "fleet.ship", peer=f"{host}:{port}", doc=doc_key,
                frames=len(frames),
            ):
                with socket.create_connection((host, port), timeout=deadline) as sock:
                    sock.settimeout(deadline)
                    _send_message(sock, MSG_SHIP, body)
                    ack = json.loads(_expect(sock, MSG_SHIP_ACK))
        except _RETRYABLE as exc:
            last = exc
            continue
        if str(ack.get("doc")) != doc_key:
            raise DecodeError(
                f"ship ack names doc {ack.get('doc')!r}, shipped {doc_key!r}"
            )
        GLOBAL_COUNTERS.add("fleet.ship_frames_sent", len(frames))
        return int(ack["have"])
    if isinstance(last, ValueError) and not isinstance(last, OSError):
        raise last
    raise TransportError(
        f"ship to {host}:{port} failed after {max(1, policy.attempts)} "
        f"attempt(s): {last!r}"
    ) from last
