"""Checkpoint / resume.

The reference has no checkpointing; its durable state is the per-actor change
logs themselves — any replica is reconstructible by replaying changes (event
sourcing; reference ``queues`` test/fuzz.ts:160-163, failed-state traces
test/fuzz.ts:16-20).  This module makes that durability real and adds a fast
path for the device state:

* **Change-log persistence** — the source of truth.  A :class:`ChangeStore`
  round-trips through JSON-lines in the reference's exact change wire format,
  so checkpoints interoperate with recorded reference traces.
* **Replica restore by replay** — rebuild any ``Doc`` from the log.
* **Packed-state snapshots** — the batched device state (``PackedDocs``) is a
  NamedTuple of int tensors; it serializes to one ``.npz``.  Restoring a
  snapshot skips replaying history for long-lived batches; the change log
  still guards against snapshot loss.
* **CheckpointManager** — step-tagged checkpoint directories with atomic
  publish (write to temp, rename) and retention, so a long streaming run can
  resume after a failure (SURVEY §5.4 *Build* item).

Failed fuzz states serialize via :func:`save_failed_trace` in the same
queues-plus-evidence shape the reference writes to ``traces/*.json``
(test/fuzz.ts:16-20), replayable by ``testing/traces.py``.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from .core.doc import Doc
from .core.types import Change
from .ops.packed import PackedDocs
from .parallel.anti_entropy import ChangeStore, apply_changes


# ---------------------------------------------------------------------------
# Change-log persistence (the durable source of truth)
# ---------------------------------------------------------------------------


def save_change_log(store: ChangeStore, path: str | Path) -> int:
    """Write every change as one JSON line (wire format); returns the count."""
    path = Path(path)
    count = 0
    with open(path, "w") as f:
        for actor in sorted(store.actors()):
            for change in store.log(actor):
                f.write(json.dumps(change.to_json()) + "\n")
                count += 1
    return count


def load_change_log(path: str | Path) -> ChangeStore:
    store = ChangeStore()
    by_actor: Dict[str, List[Change]] = {}
    with open(path) as f:
        for line in f:
            if line.strip():
                change = Change.from_json(json.loads(line))
                by_actor.setdefault(change.actor, []).append(change)
    # logs must append in seq order regardless of file order
    for changes in by_actor.values():
        for change in sorted(changes, key=lambda c: c.seq):
            store.append(change)
    return store


def doc_from_store(store: ChangeStore, actor_id: str = "restored") -> Doc:
    """Rebuild a replica by replaying the full log (event-sourcing restore)."""
    doc = Doc(actor_id)
    changes = [ch for actor in store.actors() for ch in store.log(actor)]
    apply_changes(doc, changes)
    return doc


# ---------------------------------------------------------------------------
# Packed device-state snapshots
# ---------------------------------------------------------------------------


def save_packed(state: PackedDocs, path: str | Path) -> None:
    """Snapshot the batched device state to one ``.npz`` (host transfer of
    every field, then a single file write)."""
    arrays = {name: np.asarray(x) for name, x in zip(PackedDocs._fields, state)}
    with open(path, "wb") as f:
        np.savez_compressed(f, **arrays)


def load_packed(path: str | Path) -> PackedDocs:
    """Load a packed snapshot.  Fields absent from the file (snapshots
    written before the schema gained them, e.g. the map-register table)
    default to empty: zeros are exactly the state a doc without those ops
    holds, so old snapshots stay loadable."""
    with np.load(path) as data:
        num_docs = data["elem_id"].shape[0]

        def field(name: str) -> np.ndarray:
            if name in data:
                return data[name]
            if name == "overflow":
                return np.zeros((num_docs,), bool)
            if name in ("num_slots", "num_tombs", "num_marks", "num_regs"):
                return np.zeros((num_docs,), np.int32)
            return np.zeros((num_docs, 32), np.int32)  # table default width

        return PackedDocs(*(field(name) for name in PackedDocs._fields))


# ---------------------------------------------------------------------------
# Streaming-session checkpoints (event-sourced: the frame log IS the state)
# ---------------------------------------------------------------------------

_LEN = "<I"


def _write_frames(path: Path, frames: List[bytes]) -> None:
    path.write_bytes(pack_doc_frames(frames))


def _read_frames(path: Path) -> List[bytes]:
    try:
        return unpack_doc_frames(path.read_bytes())
    except ValueError as exc:
        raise ValueError(f"truncated frame file: {path}") from exc


def pack_doc_frames(frames: List[bytes]) -> bytes:
    """One doc's checkpoint frame history as a single SHIPPABLE blob —
    the unit the fleet tier's checkpoint ship moves over the multihost
    transport (:func:`~.parallel.multihost.ship_frames`).  Same
    length-prefix framing as the on-disk ``doc_*.frames`` files, so a
    shipped checkpoint and a saved one are byte-interchangeable.
    Re-ingesting the unpacked frames reconstructs the doc exactly
    (event sourcing), and frames are duplicate-tolerant, so overlap
    between a shipped checkpoint and later journal redelivery is
    harmless."""
    import struct

    out = bytearray()
    for frame in frames:
        out += struct.pack(_LEN, len(frame))
        out += frame
    return bytes(out)


def unpack_doc_frames(data: bytes) -> List[bytes]:
    """Inverse of :func:`pack_doc_frames`; raises ``ValueError`` on a
    truncated blob (a partial ship must fail loudly, never ingest a
    half-frame)."""
    import struct

    frames: List[bytes] = []
    pos = 0
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("truncated doc-frame blob")
        (length,) = struct.unpack_from(_LEN, data, pos)
        pos += 4
        if pos + length > len(data):
            raise ValueError("truncated doc-frame blob")
        frames.append(data[pos:pos + length])
        pos += length
    return frames


def save_session(session, directory: str | Path) -> Dict[str, Any]:
    """Checkpoint a :class:`~.parallel.streaming.StreamingMerge` session.

    Durable form = per-doc wire-frame histories (event sourcing): restoring
    re-ingests the frames, which reconstructs device state, clocks, attr
    tables, and fallback routing exactly — no device-state serialization to
    keep consistent.  Frames are duplicate-tolerant, so overlap between a
    checkpoint and post-checkpoint redelivery is harmless.

    Layout-agnostic by the same token: a paged session (store/) checkpoints
    as the identical frame history — pages, page tables and the pool are
    derived state the restore rebuilds — and the ``layout`` (plus
    ``page_size``) rides in the config, so restore constructs the same
    backend.  The top-level ``layout`` key mirrors it for scrapers that
    read checkpoint metadata without parsing the config.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    total = 0
    for i in range(session.num_docs):
        frames = session.doc_history_frames(i)
        if not frames:
            continue  # untouched doc: no file (restore treats absent as empty)
        total += len(frames)
        _write_frames(directory / f"doc_{i:06d}.frames", frames)
    meta = {
        "kind": "streaming-session",
        "actors": list(session.actors),
        "rounds": session.rounds,
        "frames": total,
        "layout": getattr(session, "layout", "padded"),
        "config": session.config,
    }
    (directory / "session.json").write_text(json.dumps(meta, indent=2))
    return meta


def restore_session(directory: str | Path, mesh=None, drain: bool = True):
    """Rebuild a session from :func:`save_session` output by re-ingesting
    every doc's frame history (and draining, unless ``drain=False``)."""
    from .parallel.streaming import StreamingMerge

    directory = Path(directory)
    meta = json.loads((directory / "session.json").read_text())
    # the config dict is written verbatim from StreamingMerge.config, so the
    # key set can never drift between save and restore
    session = StreamingMerge(actors=meta["actors"], mesh=mesh, **meta["config"])
    for i in range(session.num_docs):
        path = directory / f"doc_{i:06d}.frames"
        if path.exists():
            for frame in _read_frames(path):
                session.ingest_frame(i, frame)
    if drain:
        # drain() caps rounds per call; keep draining until no admissible
        # work remains so a huge history never silently restores truncated.
        # Changes still pending after that are causally stuck — normal for a
        # mid-stream checkpoint (their deps had not arrived at save time);
        # they stay pending exactly as they did in the saved session.
        while session.drain() > 0:
            pass
    return session


# ---------------------------------------------------------------------------
# Step-tagged checkpoints with atomic publish + retention
# ---------------------------------------------------------------------------

_STEP_PREFIX = "step_"

#: staging dirs older than this are crash leftovers; younger ones may be a
#: concurrent saver's live staging (see CheckpointManager.__init__)
_STAGING_STALE_SECONDS = 3600.0


@dataclass
class Checkpoint:
    step: int
    directory: Path
    meta: Dict[str, Any]

    @property
    def store(self) -> ChangeStore:
        return load_change_log(self.directory / "changes.jsonl")

    @property
    def packed(self) -> Optional[PackedDocs]:
        path = self.directory / "packed.npz"
        return load_packed(path) if path.exists() else None

    def session(self, mesh=None, drain: bool = True):
        """Restore the streaming session saved in this checkpoint (None if
        the checkpoint holds no session)."""
        path = self.directory / "session"
        return restore_session(path, mesh=mesh, drain=drain) if path.exists() else None


class CheckpointManager:
    """Directory of step-tagged checkpoints.

    Each checkpoint is staged in a temp dir and published with an atomic
    rename, so a crash mid-save never corrupts the latest good checkpoint.
    ``keep`` bounds how many checkpoints are retained (oldest pruned).
    """

    def __init__(self, root: str | Path, keep: int = 3) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        # a crash mid-save (kill -9 between mkdtemp and the atomic rename)
        # leaves a staging dir behind; it was never published, so it is
        # garbage — sweep it rather than leak one per crash.  Only STALE
        # dirs are swept: a freshly-modified one may belong to a live saver
        # on the same root (supervisor restart racing the old process's
        # in-flight save), whose rename must not be sabotaged.
        import time

        cutoff = time.time() - _STAGING_STALE_SECONDS
        for stale in self.root.glob(".staging_*"):
            try:
                if stale.stat().st_mtime < cutoff:
                    shutil.rmtree(stale, ignore_errors=True)
            except OSError:
                pass  # raced with its owner's rename/cleanup

    def save(
        self,
        step: int,
        store: Optional[ChangeStore] = None,
        packed: Optional[PackedDocs] = None,
        session=None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> Path:
        if store is None and packed is None and session is None:
            raise ValueError(
                "nothing to checkpoint: need a store, packed state, or session"
            )
        final = self.root / f"{_STEP_PREFIX}{step:012d}"
        staging = Path(tempfile.mkdtemp(prefix=".staging_", dir=self.root))
        try:
            payload_meta = dict(meta or {})
            payload_meta["step"] = step
            if store is not None:
                payload_meta["changes"] = save_change_log(store, staging / "changes.jsonl")
            if packed is not None:
                save_packed(packed, staging / "packed.npz")
                payload_meta["num_docs"] = int(packed.num_docs)
            if session is not None:
                payload_meta["session"] = save_session(session, staging / "session")
            (staging / "meta.json").write_text(json.dumps(payload_meta, indent=2))
            if final.exists():
                shutil.rmtree(final)
            os.rename(staging, final)
        except BaseException:  # graftlint: boundary(staging cleanup then re-raise; KeyboardInterrupt must not leak a half-written checkpoint)
            shutil.rmtree(staging, ignore_errors=True)
            raise
        self._prune()
        return final

    def steps(self) -> List[int]:
        # only PUBLISHED checkpoints count: the atomic rename guarantees a
        # step_* dir is complete, but a meta.json check keeps a manually
        # damaged (or foreign) directory from masking the last good one
        return sorted(
            int(p.name[len(_STEP_PREFIX):])
            for p in self.root.iterdir()
            if p.is_dir() and p.name.startswith(_STEP_PREFIX)
            and (p / "meta.json").exists()
        )

    def latest(self) -> Optional[Checkpoint]:
        steps = self.steps()
        return self.restore(steps[-1]) if steps else None

    def restore(self, step: int) -> Checkpoint:
        directory = self.root / f"{_STEP_PREFIX}{step:012d}"
        meta = json.loads((directory / "meta.json").read_text())
        return Checkpoint(step=step, directory=directory, meta=meta)

    def _prune(self) -> None:
        steps = self.steps()
        for step in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self.root / f"{_STEP_PREFIX}{step:012d}")


# ---------------------------------------------------------------------------
# Failed-state traces (reference saveFailedTrace, test/fuzz.ts:16-20)
# ---------------------------------------------------------------------------


def save_failed_trace(
    path: str | Path,
    store: ChangeStore,
    evidence: Optional[Dict[str, Any]] = None,
) -> None:
    """Serialize a failing multi-replica state: replayable per-actor change
    ``queues`` plus free-form divergence evidence.  The ``queues`` are ground
    truth; evidence fields are diagnostics only (the reference's trace files
    carry divergent final texts — SURVEY §2.15's oracle caution)."""
    payload: Dict[str, Any] = {
        "queues": {
            actor: [ch.to_json() for ch in store.log(actor)]
            for actor in sorted(store.actors())
        }
    }
    if evidence:
        payload.update(evidence)
    Path(path).write_text(json.dumps(payload, indent=2))
