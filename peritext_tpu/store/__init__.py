"""peritext_tpu.store — paged document storage.

The padded ``(D docs x S slots)`` layout pays the widest doc's cost for
every doc: one 500K-op essay among 100K tweets forces every row to the
essay's slot capacity, and the PR-5 bucket-occupancy tables measure exactly
how much compute and memory that burns.  This package replaces the padded
element planes with the TPU-native recipe Ragged Paged Attention uses for
ragged KV caches: a device-resident global pool of FIXED-SIZE op pages
plus a per-doc page table, gathered into dense work groups at dispatch
time — so resident memory and per-round device work scale with real ops,
not with the widest doc's bucket.

Pieces:

* :mod:`.alloc` — :class:`PageAllocator`: the deterministic free-list
  allocator (lowest-page-id-first, sorted walks, no wall clock/RNG —
  ``store/`` is graftlint merge scope ON PURPOSE: two replicas allocating
  for the same ingest order must build identical page tables) with
  ``grow`` / ``compact`` / ``evacuate`` and the typed
  :class:`PoolExhausted` error.
* :mod:`.paged` — :class:`PagedDocStore`: the device pool (element planes
  paged; the small per-doc aux tables — tombstones, marks, registers —
  stay dense rows), page-table bookkeeping, bucketed group planning, and
  the materialize/apply plumbing over :func:`ops.kernel.apply_batch_paged`.
* :mod:`.session` — :class:`PagedStreamingMerge`: ``StreamingMerge``
  with the paged store as its resident state (selected via
  ``StreamingMerge(layout="paged")``); commits gather only the touched
  docs at their size bucket, reads/digests materialize per block at
  page-bucketed width with the pad-term corrected so digests stay
  bit-equal to a padded session.

The padded layout remains the default AND the byte-equality oracle: every
fuzz seed and recorded trace must produce identical docs, patches and
digests under both layouts (tests/test_store.py).
"""

from .alloc import PageAllocator, PoolExhausted
from .paged import DEFAULT_PAGE_SIZE, PagedDocStore, plan_page_groups

__all__ = [
    "DEFAULT_PAGE_SIZE",
    "PageAllocator",
    "PagedDocStore",
    "PoolExhausted",
    "plan_page_groups",
]
