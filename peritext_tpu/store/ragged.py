"""Ragged plan: the flat doc-index + page-table view of the page pool.

The paged dispatch plan (store/paged.plan_page_groups) buckets touched rows
by power-of-two page count and pads each group's row axis to a power of two
— the compile-cache discipline that keeps the gather/apply/scatter variant
family logarithmic.  The ragged apply (ops/ragged.py) needs none of that:
it walks the pool IN PLACE, so the only shapes the compiled program sees
are the pool itself and the round's stream staging — per-doc true op and
page counts ride in as *data* (traced loop bounds and plan planes), never
as shapes.

This module builds that plan: three ``(N_pages,)`` planes over the pool —

* ``owner``      — which batch-local row each pool page belongs to
  (``num_rows`` = unowned: the null page, free pages, and pages of docs
  outside the batch — the apply's inert segment),
* ``pos_base``   — the page's first slot position within its doc
  (``page_index_within_doc * page_size``),
* ``prev_page``  — the preceding page in the same doc (first pages point at
  the null page 0, whose lanes are always zero),

plus the per-row ``page_count`` (true allocation, no rounding) and the flat
``row_idx``.  Everything is a pure function of the allocator state; the
plan snapshots at build time exactly like ``PagedDocStore.group_plan``, so
later growth never leaks into a planned dispatch.

Deliberately bucket-free: no import of ``_pow2`` / ``next_pow2`` /
``_width_bucket`` may appear here or in ops/ragged.py — enforced by
graftlint rule PTL007.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class RaggedPlan:
    """One ragged dispatch's host-side plan (module doc)."""

    #: batch rows (B,) — the ``owner`` sentinel is ``num_rows``
    row_idx: np.ndarray
    #: (N_pages,) batch-local owner per pool page (num_rows = unowned)
    owner: np.ndarray
    #: (N_pages,) first slot position of the page within its doc
    pos_base: np.ndarray
    #: (N_pages,) previous page of the same doc (0 = null page)
    prev_page: np.ndarray
    #: (B,) true allocated page count per row — no pow-2 rounding
    page_count: np.ndarray
    #: (B, max_doc_pages) pool page per (row, doc-page); 0 (the null page)
    #: pads beyond each row's true count — the ragged Pallas kernel's
    #: scalar-prefetch plane (its second axis is config-static, so it pins
    #: no data-dependent shape)
    page_table: np.ndarray
    #: pool size the plan was built against (shape pin for the dispatch)
    pool_pages: int
    #: grid stats for the ``peritext_ragged_*`` gauges
    docs_walked: int
    pages_walked: int

    @property
    def num_rows(self) -> int:
        return int(self.row_idx.shape[0])


def ragged_plan(store, rows: Optional[Sequence[int]] = None) -> RaggedPlan:
    """Build the ragged pool view for ``rows`` (default: every doc row of
    ``store``).  Rows must already hold their allocation
    (``ensure_rows``); rows with no pages are legal — they simply own no
    pool segment, and any live op for them overflows exactly as the padded
    oracle's zero-width doc would."""
    if rows is None:
        rows = np.arange(store.num_docs, dtype=np.int64)
    row_idx = np.asarray(rows, np.int64)
    b = int(row_idx.shape[0])
    n = int(store.pool_elem.shape[0])
    p = int(store.page_size)
    owner = np.full(n, b, np.int32)
    pos_base = np.zeros(n, np.int32)
    prev_page = np.zeros(n, np.int32)
    page_count = np.zeros(b, np.int32)
    page_table = np.zeros((b, store.max_doc_pages), np.int32)
    pages_walked = 0
    for i, row in enumerate(row_idx):
        pages = store.alloc.pages_of(int(row))
        page_count[i] = len(pages)
        pages_walked += len(pages)
        for k, pg in enumerate(pages):
            owner[pg] = i
            pos_base[pg] = k * p
            prev_page[pg] = pages[k - 1] if k else 0
            page_table[i, k] = pg
    return RaggedPlan(
        row_idx=row_idx,
        owner=owner,
        pos_base=pos_base,
        prev_page=prev_page,
        page_count=page_count,
        page_table=page_table,
        pool_pages=n,
        docs_walked=b,
        pages_walked=pages_walked,
    )
