"""PagedDocStore: the device-resident page pool + per-doc page tables.

Layout (module doc of :mod:`peritext_tpu.store`): the ELEMENT planes —
``elem_id`` / ``char``, the tensors whose padded ``(D, S)`` form carries
essentially all of the padded layout's waste — live as fixed-size pages in
a global ``(N_pages, P)`` pool, addressed per doc through a page table
(page ``k`` of a doc backs slots ``[k*P, (k+1)*P)``).  The small per-doc
aux tables (tombstones, mark rows, LWW registers, scalars) stay dense
``(D, ·)`` device rows: a 500K-op essay needs ~8K element pages but the
same 128-row tombstone table as a tweet, so paging them would buy nothing
and cost a second indirection.

Invariants the rest of the subsystem leans on:

* **Page 0 is the null page and every free page is all-zero.**  Gathers
  through padding page-table entries read zeros; a page handed out by the
  allocator reads as empty slots (elem_id 0) exactly like a fresh padded
  row.  Frees and compaction re-zero, the apply program re-zeroes page 0
  after its scatter.
* **Allocation is deterministic** (:class:`~.alloc.PageAllocator`): page
  tables are a pure function of the admission sequence.
* **Group widths are power-of-two page counts** capped at the doc slot
  capacity, so the apply/materialize programs compile once per
  (rows-bucket, pages-bucket, stream widths) triple — the paged analog of
  the padded path's width buckets, pinned by the recompile-sentinel test.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..ops.kernel import (
    PAGED_AUX_FIELDS,
    apply_batch_paged_jit,
    gather_paged_state_jit,
)
from ..ops.packed import PackedDocs, empty_docs
from ..utils.shapes import next_pow2
from .alloc import PageAllocator, PoolExhausted

#: Default op-page width.  Chosen from the PR-5 devprof cost snapshots (see
#: DESIGN.md "Paged storage"): the insert phase is HBM-bound on two (B, W)
#: planes, and modeled bytes-accessed flattens once rows are >= ~256 B
#: (64 int32 lanes) while internal fragmentation grows linearly with P —
#: 64 slots/page keeps worst-case per-doc fragmentation under one tweet
#: and the page tables tiny.
DEFAULT_PAGE_SIZE = 64


def _pow2(n: int) -> int:
    """Smallest power of two >= n (floor 1 — page counts, not stream
    widths).  Delegates to the one canonical spelling
    (:func:`peritext_tpu.utils.shapes.next_pow2`); kept under its
    historical name because the session/batch layers import it from here."""
    return next_pow2(n, floor=1)


def plan_page_groups(
    rows: Sequence[int], pages_of_row, max_doc_pages: int
) -> List[Tuple[int, np.ndarray]]:
    """Bucket ``rows`` by power-of-two page count (capped at
    ``max_doc_pages``): returns ``[(bucket_pages, rows_array), ...]`` in
    ascending bucket order, rows sorted within each bucket — the dispatch
    plan every paged apply/materialize shares, deterministic by
    construction."""
    buckets: Dict[int, List[int]] = {}
    for row in rows:
        g = min(_pow2(max(1, int(pages_of_row(row)))), max_doc_pages)
        buckets.setdefault(g, []).append(int(row))
    return [
        (g, np.asarray(sorted(buckets[g]), np.int64))
        for g in sorted(buckets)
    ]


def group_stream_arrays(enc, rows, b: int):
    """One paged group's device stream tensors (the apply_batch 8-tuple):
    rows sliced out of any EncodedBatch-shaped staging object (the batch
    EncodedBatch and streaming's _RoundBuffers share the field names) and
    zero-padded to the power-of-two row bucket ``b`` — padding rows are
    all-zero no-ops.  ``rows=None`` takes every row (a group-encoded
    batch).  The ONE shared helper: the batch and streaming paged paths
    must never drift on the stream tuple's field order."""
    def take(a):
        a = np.asarray(a)
        src = a if rows is None else a[rows]
        out = np.zeros((b,) + a.shape[1:], a.dtype)
        out[: src.shape[0]] = src
        return jnp.asarray(out)

    return (
        take(enc.ins_ref), take(enc.ins_op), take(enc.ins_char),
        take(enc.del_target),
        {c: take(enc.marks[c]) for c in sorted(enc.marks)},
        take(enc.mark_count),
        {c: take(enc.map_ops[c]) for c in sorted(enc.map_ops)},
        take(enc.map_count),
    )


class PagedDocStore:
    """Page pool + page tables + dense aux rows for ``num_docs`` doc rows."""

    def __init__(
        self,
        num_docs: int,
        slot_capacity: int,
        mark_capacity: int,
        tomb_capacity: Optional[int] = None,
        map_capacity: int = 32,
        page_size: int = DEFAULT_PAGE_SIZE,
        initial_pages: Optional[int] = None,
        max_pool_pages: Optional[int] = None,
    ) -> None:
        if slot_capacity % page_size:
            raise ValueError(
                f"slot_capacity {slot_capacity} must be a multiple of the "
                f"page size {page_size} (digest pad-term parity needs W <= S)"
            )
        self.num_docs = int(num_docs)
        self.page_size = int(page_size)
        self.slot_capacity = int(slot_capacity)
        self.max_doc_pages = slot_capacity // page_size
        # hard ceiling: every doc fully grown, plus the null page — beyond
        # it ensure_rows raises the typed PoolExhausted instead of growing
        self.max_pool_pages = int(
            max_pool_pages
            if max_pool_pages is not None
            else 1 + self.num_docs * self.max_doc_pages
        )
        start = initial_pages or min(
            self.max_pool_pages, _pow2(1 + max(self.num_docs, 8))
        )
        start = max(2, min(int(start), self.max_pool_pages))
        self.alloc = PageAllocator(start)
        self.pool_elem = jnp.zeros((start, page_size), jnp.int32)
        self.pool_char = jnp.zeros((start, page_size), jnp.int32)
        # aux rows share empty_docs' field construction (schema single
        # source): build at elem width 1 and keep everything but elem/char.
        # tomb default mirrors the padded layout's (empty_docs defaults an
        # omitted tomb table to the SLOT width — which here is the proto's
        # width-1 element axis, so the default must be made explicit or a
        # second delete would overflow every doc)
        proto = empty_docs(
            num_docs, 1, mark_capacity,
            tomb_capacity=(
                tomb_capacity if tomb_capacity is not None else slot_capacity
            ),
            map_capacity=map_capacity,
        )
        self.aux = tuple(getattr(proto, f) for f in PAGED_AUX_FIELDS)
        self._num_pages = np.zeros(num_docs, np.int32)
        #: host-side upper bound on per-row used slots (the session/batch
        #: layer's cumulative admitted inserts) — drives allocation AND the
        #: internal-fragmentation telemetry
        self._used_hint = np.zeros(num_docs, np.int64)
        #: pool growths so far (each one is a fresh device allocation and a
        #: new program shape — telemetry wants to see them)
        self.growths = 0
        #: bumped whenever any page table (or the pool size) changes —
        #: ragged callers key their plan caches on (alloc_epoch, pool size)
        #: so stale owner/pos_base planes can never reach a dispatch
        self.alloc_epoch = 0

    # -- sizing --------------------------------------------------------------

    @property
    def aux_capacities(self) -> Dict[str, int]:
        aux = dict(zip(PAGED_AUX_FIELDS, self.aux))
        return {
            "tomb_capacity": int(aux["tomb_id"].shape[1]),
            "mark_capacity": int(aux["m_action"].shape[1]),
            "map_capacity": int(aux["r_obj"].shape[1]),
        }

    def num_pages(self, row: int) -> int:
        return int(self._num_pages[row])

    def aux_field(self, name: str):
        """One dense aux plane by PackedDocs field name (e.g. "num_slots")."""
        return self.aux[PAGED_AUX_FIELDS.index(name)]

    def pages_needed(self, used_slots: int) -> int:
        used = min(int(used_slots), self.slot_capacity)
        return max(1, -(-used // self.page_size))

    def width_for_rows(self, rows: Sequence[int]) -> int:
        """Power-of-two page bucket covering every row's allocation (>= 1,
        capped at the doc slot capacity)."""
        top = int(self._num_pages[np.asarray(rows, np.int64)].max()) if len(rows) else 1
        return min(_pow2(max(1, top)), self.max_doc_pages)

    # -- allocation ----------------------------------------------------------

    def ensure_rows(self, rows: Sequence[int], used_slots: Sequence[int]) -> None:
        """Grow each row's page table to cover ``used_slots`` (its cumulative
        admitted inserts), growing the device pool (doubling, up to
        ``max_pool_pages``) when the free list runs dry.  Deterministic:
        rows walk in sorted order; raises :class:`PoolExhausted` past the
        ceiling."""
        order = np.argsort(np.asarray(rows, np.int64), kind="stable")
        rows_arr = np.asarray(rows, np.int64)[order]
        used_arr = np.asarray(used_slots, np.int64)[order]
        for row, used in zip(rows_arr, used_arr):
            row = int(row)
            need = self.pages_needed(int(used))
            delta = need - self.alloc.num_pages(row)
            if delta > 0 and delta > self.alloc.free_pages:
                self._grow_pool(self.alloc.pages_in_use + self.alloc.reserved + delta)
            self.alloc.ensure(row, need)
            if delta > 0:
                self.alloc_epoch += 1
            self._num_pages[row] = self.alloc.num_pages(row)
            self._used_hint[row] = max(self._used_hint[row], int(used))

    def _grow_pool(self, min_total: int) -> None:
        target = _pow2(max(min_total, 2 * self.alloc.total_pages))
        target = min(target, self.max_pool_pages)
        if target < min_total:
            raise PoolExhausted(
                min_total - self.alloc.total_pages,
                self.alloc.free_pages, self.alloc.total_pages,
            )
        added = self.alloc.grow(target)
        if added:
            pad = jnp.zeros((added, self.page_size), jnp.int32)
            self.pool_elem = jnp.concatenate([self.pool_elem, pad], axis=0)
            self.pool_char = jnp.concatenate([self.pool_char, pad], axis=0)
            self.growths += 1
            self.alloc_epoch += 1

    def page_rows(self, rows: Sequence[int], bucket_pages: int,
                  pad_rows_to: Optional[int] = None) -> np.ndarray:
        """(B, bucket_pages) int32 page-table slab for ``rows`` — padding
        entries (beyond a doc's allocation, and whole padding rows) point
        at the null page 0."""
        b = pad_rows_to if pad_rows_to is not None else len(rows)
        table = np.zeros((b, bucket_pages), np.int32)
        for i, row in enumerate(rows):
            pages = self.alloc.pages_of(int(row))
            table[i, : len(pages)] = pages
        return table

    # -- device plumbing -----------------------------------------------------

    def materialize_rows(
        self, rows: Sequence[int], bucket_pages: Optional[int] = None,
        pad_rows_to: Optional[int] = None,
    ) -> PackedDocs:
        """Dense PackedDocs view of ``rows`` gathered from the pool at
        ``bucket_pages * page_size`` slots (default: the rows' own bucket).
        Padding rows (up to ``pad_rows_to``) gather null pages and clamp
        into the aux tables; callers mask them."""
        g = bucket_pages or self.width_for_rows(rows)
        b = pad_rows_to if pad_rows_to is not None else len(rows)
        row_idx = np.full(b, self.num_docs, np.int64)
        row_idx[: len(rows)] = np.asarray(rows, np.int64)
        table = self.page_rows(rows, g, pad_rows_to=b)
        return gather_paged_state_jit(
            self.pool_elem, self.pool_char, self.aux,
            jnp.asarray(row_idx), jnp.asarray(table),
        )

    def apply_rows(
        self, rows: Sequence[int], bucket_pages: int, encoded_arrays,
        pad_rows_to: Optional[int] = None,
        insert_impl: str = "auto",
        insert_loop_slots: Optional[int] = None,
    ) -> None:
        """Dispatch one gather-apply-scatter group (ops/kernel.
        apply_batch_paged) and adopt the updated pool/aux arrays.  The
        stream tensors in ``encoded_arrays`` carry the (possibly padded)
        group row axis; padding rows must be all-zero no-ops."""
        b = pad_rows_to if pad_rows_to is not None else len(rows)
        row_idx = np.full(b, self.num_docs, np.int64)
        row_idx[: len(rows)] = np.asarray(rows, np.int64)
        table = self.page_rows(rows, bucket_pages, pad_rows_to=b)
        self.pool_elem, self.pool_char, self.aux = apply_batch_paged_jit(
            self.pool_elem, self.pool_char, self.aux,
            jnp.asarray(row_idx), jnp.asarray(table), encoded_arrays,
            insert_impl=insert_impl, insert_loop_slots=insert_loop_slots,
        )

    def group_plan(self, rows: Sequence[int], bucket_pages: int,
                   pad_rows_to: Optional[int] = None):
        """One group's host-side plan pair for the fused group chain
        (kernel.apply_batch_paged_groups): the padded row-index vector and
        a SNAPSHOT of the group's page-table slab — taken at plan time so
        a later round's ``ensure_rows`` growth can never leak into an
        already-planned group."""
        b = pad_rows_to if pad_rows_to is not None else len(rows)
        row_idx = np.full(b, self.num_docs, np.int64)
        row_idx[: len(rows)] = np.asarray(rows, np.int64)
        return row_idx, self.page_rows(rows, bucket_pages, pad_rows_to=b)

    # -- lifecycle: evacuate / compact / permute -----------------------------

    def evacuate_row(self, row: int) -> int:
        """Release one row's pages back to the (zeroed) free list and clear
        its aux row — the doc's state has moved elsewhere (host move, or
        demotion with history replay).  Returns the page count released."""
        pages = self.alloc.evacuate(int(row))
        if pages:
            # scalar-broadcast scatter: a len(pages)-shaped zeros tensor
            # would mint one XLA shape per distinct page count (PTL004)
            idx = jnp.asarray(np.asarray(pages, np.int32))
            self.pool_elem = self.pool_elem.at[idx].set(0)
            self.pool_char = self.pool_char.at[idx].set(0)
        r = int(row)
        self.aux = tuple(
            a.at[r].set(jnp.zeros((), a.dtype)) for a in self.aux
        )
        if pages:
            self.alloc_epoch += 1
        self._num_pages[r] = 0
        self._used_hint[r] = 0
        return len(pages)

    def compact(self) -> int:
        """Pack every held page into the lowest pool ids (one device gather;
        free tail reads from the null page, so it comes back zeroed).
        Returns the number of pages that moved.  Page tables stay
        deterministic: the plan walks docs in sorted row order."""
        mapping = self.alloc.compact_plan()
        moved = sum(1 for old, new in sorted(mapping.items()) if old != new)
        if moved:
            src = np.zeros(self.alloc.total_pages, np.int32)  # default: null
            for old, new in sorted(mapping.items()):
                src[new] = old
            idx = jnp.asarray(src)
            self.pool_elem = jnp.take(self.pool_elem, idx, axis=0)
            self.pool_char = jnp.take(self.pool_char, idx, axis=0)
        self.alloc.apply_compact(mapping)
        if moved:
            self.alloc_epoch += 1
        self._num_pages[:] = 0
        for doc in self.alloc.docs():
            self._num_pages[doc] = self.alloc.num_pages(doc)
        return moved

    def permute_rows(self, src: np.ndarray) -> None:
        """Re-home doc rows: new row ``r`` takes old row ``src[r]`` (a full
        permutation — reshard()'s contract).  Pages do NOT move; page
        TABLES do, plus the dense aux rows (one device gather)."""
        src = np.asarray(src, np.int64)
        old_pages = {r: self.alloc.pages_of(r) for r in self.alloc.docs()}
        self.alloc.reseat({
            int(r): old_pages[int(src[r])]
            for r in range(len(src))
            if int(src[r]) in old_pages
        })
        idx = jnp.asarray(src)
        self.aux = tuple(jnp.take(a, idx, axis=0) for a in self.aux)
        self._num_pages = self._num_pages[src]
        self._used_hint = self._used_hint[src]
        self.alloc_epoch += 1

    # -- telemetry -----------------------------------------------------------

    def page_loads(self) -> np.ndarray:
        """(num_docs,) pages held per row — the paged load dimension
        reshard()/FleetRouter balance on."""
        return self._num_pages.copy()

    def pool_stats(self) -> Dict:
        """The ``peritext_page_*`` snapshot: pool occupancy, internal
        fragmentation (allocated-but-unused slots) overall and per
        doc-size decile — the paged layout's waste is fragmentation inside
        the last page of each doc, and this table is how a mis-chosen page
        size shows up."""
        total = self.alloc.total_pages - self.alloc.reserved
        in_use = self.alloc.pages_in_use
        live = np.nonzero(self._num_pages > 0)[0]
        alloc_slots = self._num_pages[live].astype(np.int64) * self.page_size
        used_slots = np.minimum(self._used_hint[live], alloc_slots)
        frag = alloc_slots - used_slots
        deciles = {}
        if len(live):
            order = np.argsort(alloc_slots, kind="stable")
            chunks = np.array_split(order, 10)
            for i, chunk in enumerate(chunks):
                if not len(chunk):
                    deciles[f"d{i}"] = 0.0
                    continue
                a = int(alloc_slots[chunk].sum())
                f = int(frag[chunk].sum())
                deciles[f"d{i}"] = round(f / a, 4) if a else 0.0
        return {
            "page_size": self.page_size,
            "pool_pages": total,
            "pages_in_use": in_use,
            "pages_free": total - in_use,
            "pool_utilization": round(in_use / total, 4) if total else 0.0,
            "growths": self.growths,
            "docs_resident": int(len(live)),
            "allocated_slots": int(alloc_slots.sum()),
            "used_slots": int(used_slots.sum()),
            "internal_frag_slots": int(frag.sum()),
            "internal_frag_ratio": (
                round(int(frag.sum()) / int(alloc_slots.sum()), 4)
                if len(live) and int(alloc_slots.sum()) else 0.0
            ),
            "frag_by_decile": deciles,
        }
