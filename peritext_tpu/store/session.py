"""PagedStreamingMerge: StreamingMerge over the page pool.

Selected via ``StreamingMerge(layout="paged")``.  The host half of every
round (causal admission, frame scheduling, staging buffers) is shared with
the padded engine verbatim; what changes is WHERE device state lives and
WHAT each round dispatches:

* **Commit** — instead of applying all D docs at the session slot capacity,
  the round's touched rows group into power-of-two page-count buckets and
  each group dispatches one gather→apply→scatter program
  (ops/kernel.apply_batch_paged) at its own width, so per-round device work
  is ``sum(touched docs x their bucket)`` — one 500K-op essay among 100K
  tweets costs its own pages, not everyone's.
* **Reads/digests** — blocks materialize on demand from the pool at the
  block's page-bucketed width (cached per round like the padded block
  cache).  The per-doc full-state hash includes a pad-slot term
  (mesh.per_doc_text_digest hashes ``slot_capacity - n_visible`` pad
  slots), so every paged digest program adds the missing
  ``(S - W) * avalanche(PAD_SEED)`` per live doc — digests are BIT-EQUAL
  to a padded session's, which is what lets mixed-layout fleets compare
  frontiers and the byte-equality oracle pin the layouts against each
  other.
* **reshard()** — balances PAGES (the resource the pool actually spends):
  page tables and aux rows permute, pages never move.  The return gains a
  ``page_load`` dimension for the FleetRouter.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import (
    GLOBAL_COUNTERS,
    GLOBAL_DEVPROF,
    GLOBAL_HISTOGRAMS,
    MergeStats,
    SIZE_BUCKETS,
    note_jit_dispatch,
    occupancy_key,
)
from ..ops.packed import PackedDocs
from ..ops.resolve import resolve
from ..parallel import mesh as _mesh
from ..parallel.streaming import (
    StreamingMerge,
    _BlockResolution,
    _doc_char_slots,
    _per_doc_full_digest,
    _replay_doc,
    _width_bucket,
)
from .paged import (
    DEFAULT_PAGE_SIZE,
    PagedDocStore,
    _pow2,
    group_stream_arrays,
    plan_page_groups,
)


def _pad_unit() -> int:
    """Host value of one pad slot's digest contribution —
    avalanche(PAD_SEED), the same constant mesh.per_doc_text_digest folds
    per non-visible slot (and doc_digest_host multiplies by the pad
    count)."""
    x = (_mesh._PAD_SEED * _mesh._KF) & 0xFFFFFFFF
    return x ^ (x >> 15)


_PAD_UNIT = _pad_unit()


@partial(jax.jit, static_argnums=1)
def _resolve_block_digest_paged_jit(
    state: PackedDocs, comment_capacity: int, row_mask, pad_slots,
    sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
):
    """The paged twin of streaming._resolve_block_digest_jit: resolution at
    the block's materialized width W plus the per-doc full-state hash, with
    the ``pad_slots = S - W`` pad-term correction folded in so the hash
    equals what the padded layout computes at width S."""
    resolved = resolve(state, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        state, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    mask = row_mask & ~resolved.overflow
    per_doc = jnp.where(
        mask, per_doc + pad_slots * jnp.uint32(_PAD_UNIT), jnp.uint32(0)
    )
    return resolved, per_doc


@partial(jax.jit, static_argnums=1)
def _rows_digest_paged_jit(
    sub: PackedDocs, comment_capacity: int, row_mask, pad_slots,
    sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
):
    """Paged twin of streaming._rows_digest_jit (gathered dirty-row
    sub-batch), pad-term corrected."""
    resolved = resolve(sub, comment_capacity, with_comments=True)
    per_doc = _per_doc_full_digest(
        sub, resolved, row_mask,
        sess_attr, sess_key, comment_hash, row_map, obj_attr, obj_key,
    )
    mask = row_mask & ~resolved.overflow
    per_doc = jnp.where(
        mask, per_doc + pad_slots * jnp.uint32(_PAD_UNIT), jnp.uint32(0)
    )
    return per_doc, resolved.overflow


@partial(jax.jit, static_argnums=1)
def _resolve_digest_paged_jit(
    state: PackedDocs, comment_capacity: int, row_mask, pad_slots
):
    """Paged twin of streaming._resolve_digest_jit (TEXT-ONLY digest),
    pad-term corrected per contributing doc."""
    resolved = resolve(state, comment_capacity, with_comments=False)
    mask = row_mask & ~resolved.overflow
    per_doc = _mesh.per_doc_text_digest(resolved.char, resolved.visible)
    per_doc = jnp.where(
        mask, per_doc + pad_slots * jnp.uint32(_PAD_UNIT), jnp.uint32(0)
    )
    return jnp.sum(per_doc, dtype=jnp.uint32), resolved.overflow


class PagedStreamingMerge(StreamingMerge):
    """StreamingMerge whose resident element planes live in a page pool
    (module doc).  Under ``mesh=`` the pool shards per shard
    (store/sharded.ShardedPagedDocStore) and the fused commit runs the
    whole drain batch's group chain as ONE ``shard_map`` program with
    per-shard plan planes as data (round 19); ``static_rounds`` (the
    serving tier's one-shape discipline) stays on the padded layout."""

    _layout = "paged"

    def __init__(self, num_docs, actors, *args,
                 layout: str = "paged",
                 page_size: int = DEFAULT_PAGE_SIZE,
                 pool_pages: Optional[int] = None,
                 max_pool_pages: Optional[int] = None,
                 **kwargs) -> None:
        if layout != "paged":
            raise ValueError(f"PagedStreamingMerge is layout='paged', got {layout!r}")
        if kwargs.get("static_rounds"):
            raise ValueError(
                "layout='paged' is incompatible with static_rounds: the "
                "serving shape discipline is exactly the padded one-shape "
                "apply; use the padded layout for static-round serving"
            )
        self.page_size = int(page_size)
        super().__init__(num_docs, actors, *args, layout="paged", **kwargs)
        if self._slot_capacity % self.page_size:
            raise ValueError(
                f"slot_capacity {self._slot_capacity} must be a multiple of "
                f"page_size {self.page_size} under layout='paged'"
            )
        if self.mesh is not None:
            from .sharded import ShardedPagedDocStore

            self._store = ShardedPagedDocStore(
                self._padded_docs, self.mesh,
                slot_capacity=self._slot_capacity,
                mark_capacity=self._mark_capacity,
                tomb_capacity=self._tomb_capacity,
                map_capacity=self._map_capacity,
                page_size=self.page_size,
                initial_pages=pool_pages,
                max_pool_pages=max_pool_pages,
            )
        else:
            self._store = PagedDocStore(
                self._padded_docs,
                slot_capacity=self._slot_capacity,
                mark_capacity=self._mark_capacity,
                tomb_capacity=self._tomb_capacity,
                map_capacity=self._map_capacity,
                page_size=self.page_size,
                initial_pages=pool_pages,
                max_pool_pages=max_pool_pages,
            )
        #: per-(round, epoch) materialized-block cache (<= 2 blocks, the
        #: paged analog of the padded path's _apply_blocks reuse)
        self._mat_cache: tuple = ((-1, -1), {})
        #: per-round-buffer dispatched stream capacity (feeds the stats
        #: override: padded capacity is what the GROUPS paid, not D x K)
        self._commit_caps: Dict[int, int] = {}

    # -- store access --------------------------------------------------------

    @property
    def store(self) -> PagedDocStore:
        return self._store

    @property
    def config(self) -> Dict[str, int]:
        cfg = dict(StreamingMerge.config.fget(self))
        cfg["page_size"] = self.page_size
        return cfg

    def sync_device(self) -> None:
        np.asarray(self._store.aux_field("num_slots"))

    def health(self) -> Dict:
        h = super().health()
        h["layout"] = "paged"
        h["page_pool"] = self._store.pool_stats()
        return h

    # -- the paged device half of a round ------------------------------------

    def _commit_rounds(self, batch) -> None:
        """Dispatch scheduled rounds through the page pool as ONE donated
        fused program: per round, the touched rows (and only them) group by
        page bucket; every (round, group) gather-apply-scatter chains
        inside the program in causal order, with the pool operands donated
        so XLA updates pages in place instead of copying the whole pool per
        group (the fused round pipeline's paged form).  Page growth
        (``ensure_rows``) stays a per-round HOST decision made in prep, and
        each group's page-table slab snapshots at plan time, so grouping
        and gather widths are byte-identical to the per-round discipline."""
        if not self.fused_pipeline:
            self._commit_rounds_serial(batch)
            return
        statics = self._prep_fused_batch(batch)
        inputs = self._stage_fused_batch(batch, statics)
        self._dispatch_fused_batch(batch, statics, inputs)

    def _commit_rounds_serial(self, batch) -> None:
        """Pre-fusion discipline (``fused_pipeline=False``): one
        gather-apply-scatter dispatch per (round, group), each paying its
        own whole-pool copy — the bench fused row's comparison arm and the
        equivalence tests' oracle side."""
        for enc, widths in batch:
            self._cum_ins += enc.ins_count
            rows = np.nonzero(enc.num_ops)[0]
            if len(rows):
                self._store.ensure_rows(rows, self._cum_ins[rows])
                groups = plan_page_groups(
                    rows, self._store.num_pages, self._store.max_doc_pages
                )
                cap_total = 0
                for g, g_rows in groups:
                    b = _pow2(len(g_rows))
                    self._store.apply_rows(
                        g_rows, g, group_stream_arrays(enc, g_rows, b),
                        pad_rows_to=b,
                    )
                    cap = b * sum(widths)
                    cap_total += cap
                    if GLOBAL_DEVPROF.enabled:
                        GLOBAL_DEVPROF.observe_round(
                            occupancy_key(b, *widths),
                            int(enc.num_ops[g_rows].sum()), cap,
                            origin="streaming.paged",
                        )
                self._commit_caps[id(enc)] = cap_total
                self._digest_row_valid[rows] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(self._store.pool_stats())

    def _prep_fused_batch(self, batch):
        """Main-thread prep: advance cum-inserts, grow/allocate pages per
        round, plan that round's page groups and SNAPSHOT their page-table
        slabs (``PagedDocStore.group_plan``) — everything that reads or
        mutates allocator state happens here, in round order."""
        if self.mesh is not None:
            return self._prep_mesh_fused_batch(batch)
        plans = []
        for enc, widths in batch:
            self._cum_ins += enc.ins_count
            rows = np.nonzero(enc.num_ops)[0]
            if not len(rows):
                plans.append((widths, []))
                continue
            self._store.ensure_rows(rows, self._cum_ins[rows])
            groups = plan_page_groups(
                rows, self._store.num_pages, self._store.max_doc_pages
            )
            plan = []
            for g, g_rows in groups:
                b = _pow2(len(g_rows))
                row_idx, table = self._store.group_plan(g_rows, g,
                                                        pad_rows_to=b)
                plan.append((g_rows, b, row_idx, table))
            plans.append((widths, plan))
        return ("paged", tuple(plans))

    def _stage_fused_batch(self, batch, statics):
        """Worker-safe staging: slice each group's stream tensors out of
        its round's staging buffers and upload the whole (round, group)
        input sequence with one ``jax.device_put``."""
        if statics[0] == "mesh_paged":
            return self._stage_mesh_fused_batch(batch, statics)
        _, plans = statics
        group_inputs = []
        for (enc, _), (widths, plan) in zip(batch, plans):
            for g_rows, b, row_idx, table in plan:
                group_inputs.append(
                    (row_idx, table, group_stream_arrays(enc, g_rows, b))
                )
        return jax.device_put(tuple(group_inputs))

    def _dispatch_fused_batch(self, batch, statics, inputs,
                              chain_digest: bool = False) -> bool:
        """Dispatch the donated group chain + per-round bookkeeping and
        the fused-site occupancy telemetry.  ``chain_digest`` is accepted
        for drain-loop compatibility but never chains here (returns
        False): a paged digest twin of the group-chain program is an open
        rung — the drain keeps the separate prefetch dispatch instead."""
        if statics[0] == "mesh_paged":
            return self._dispatch_mesh_fused_batch(batch, statics, inputs)
        from ..ops.kernel import apply_batch_paged_groups_jit

        from ..ops.kernel import (
            apply_batch_paged_jit,
            resolve_state_donation,
        )

        _, plans = statics
        store = self._store
        if len(inputs) == 1 and not resolve_state_donation(store.pool_elem):
            # single-group commit on a non-donating platform: the legacy
            # per-group program IS the dispatch (shared compile with the
            # pre-fusion path — group chaining buys nothing at length 1)
            row_idx, table, enc_arrays = inputs[0]
            store.pool_elem, store.pool_char, store.aux = (
                apply_batch_paged_jit(
                    store.pool_elem, store.pool_char, store.aux,
                    row_idx, table, enc_arrays,
                )
            )
        elif inputs:
            store.pool_elem, store.pool_char, store.aux = (
                apply_batch_paged_groups_jit(
                    store.pool_elem, store.pool_char, store.aux, inputs,
                    loop_slots_seq=(None,) * len(inputs),
                )
            )
        for (enc, _), (widths, plan) in zip(batch, plans):
            cap_total = 0
            rows = np.nonzero(enc.num_ops)[0]
            for g_rows, b, _, _ in plan:
                cap = b * sum(widths)
                cap_total += cap
                if GLOBAL_DEVPROF.enabled:
                    GLOBAL_DEVPROF.observe_round(
                        occupancy_key(b, *widths),
                        int(enc.num_ops[g_rows].sum()), cap,
                        origin="streaming.paged.fused",
                    )
            self._commit_caps[id(enc)] = cap_total
            if len(rows):
                self._digest_row_valid[rows] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(self._store.pool_stats())
        return False

    # -- mesh-sharded fused commit (round 19) --------------------------------

    def _prep_mesh_fused_batch(self, batch):
        """The meshless prep's round walk, but groups are planned PER SHARD
        with LOCAL row ids (pad = rows_per_shard, the locally-OOB drop
        sentinel) and LOCAL page tables built straight off the per-shard
        allocators — never by translating global page ids, so pad entries
        are each shard's OWN null page.  The bucket ladder unifies across
        shards: one (round, bucket) group spans the whole mesh at the
        max-shard row bucket; shards short of rows ride as all-pad no-op
        lanes (zero streams + null tables are free by the same argument as
        padding rows)."""
        store = self._store
        n = store.n_shards
        rps = store.rows_per_shard
        plans = []
        for enc, widths in batch:
            self._cum_ins += enc.ins_count
            rows = np.nonzero(enc.num_ops)[0]
            if not len(rows):
                plans.append((widths, []))
                continue
            store.ensure_rows(rows, self._cum_ins[rows])
            buckets: Dict[int, Dict[int, list]] = {}
            for row in rows:
                row = int(row)
                g = min(_pow2(max(1, store.num_pages(row))),
                        store.max_doc_pages)
                buckets.setdefault(g, {}).setdefault(row // rps, []).append(row)
            plan = []
            for g in sorted(buckets):
                by_shard = buckets[g]
                b = _pow2(max(len(v) for v in by_shard.values()))
                shard_rows = [sorted(by_shard.get(s, ())) for s in range(n)]
                row_idx = np.full((n, b), rps, np.int64)
                table = np.zeros((n, b, g), np.int32)
                for s in range(n):
                    alloc = store.alloc.shards[s]
                    for i, r in enumerate(shard_rows[s]):
                        row_idx[s, i] = r - s * rps
                        pages = alloc.pages_of(r)
                        table[s, i, : len(pages)] = pages
                plan.append((shard_rows, g, b, row_idx, table))
            plans.append((widths, plan))
        return ("mesh_paged", tuple(plans))

    def _stage_mesh_fused_batch(self, batch, statics):
        """Every (round, group) input grows a leading ``(n_shards,)`` axis
        — shard ``s``'s local row ids, local page-table slab and stream
        slice — and the whole chain ships with ONE sharded device_put, so
        each shard receives exactly its own planes and the dispatch below
        needs no in-program resharding."""
        from ..parallel.mesh_fused import shard_leading

        _, plans = statics
        n = self._store.n_shards

        def stack(a, shard_rows, b):
            a = np.asarray(a)
            out = np.zeros((n, b) + a.shape[1:], a.dtype)
            for s in range(n):
                rows = shard_rows[s]
                if len(rows):
                    out[s, : len(rows)] = a[rows]
            return out

        group_inputs = []
        for (enc, _), (widths, plan) in zip(batch, plans):
            for shard_rows, g, b, row_idx, table in plan:
                streams = (
                    stack(enc.ins_ref, shard_rows, b),
                    stack(enc.ins_op, shard_rows, b),
                    stack(enc.ins_char, shard_rows, b),
                    stack(enc.del_target, shard_rows, b),
                    {c: stack(enc.marks[c], shard_rows, b)
                     for c in sorted(enc.marks)},
                    stack(enc.mark_count, shard_rows, b),
                    {c: stack(enc.map_ops[c], shard_rows, b)
                     for c in sorted(enc.map_ops)},
                    stack(enc.map_count, shard_rows, b),
                )
                group_inputs.append((row_idx, table, streams))
        return shard_leading(tuple(group_inputs), self.mesh)

    def _mesh_paged_fn(self):
        """The drain batch's whole (round, group) chain as ONE compiled
        ``shard_map`` program: each shard runs
        ops/kernel.apply_batch_paged_groups over its local pool block with
        its own plan planes (sliced off the staged leading shard axis).
        The jit retraces per chain structure exactly like the meshless
        bucket ladder — one executable per (group shapes, widths) chain,
        shared across the mesh and cached per mesh fingerprint."""
        from ..ops.kernel import (
            apply_batch_paged_groups,
            resolve_insert_impl,
            resolve_state_donation,
        )
        from ..parallel.mesh_fused import mesh_fn

        mesh = self.mesh
        impl = resolve_insert_impl(self._store.pool_elem)
        donate = resolve_state_donation(self._store.pool_elem)

        def build():
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def body(pool_elem, pool_char, aux, group_inputs):
                local = jax.tree_util.tree_map(lambda x: x[0], group_inputs)
                return apply_batch_paged_groups(
                    pool_elem, pool_char, aux, local,
                    loop_slots_seq=(None,) * len(local),
                    insert_impl=impl,
                )

            wrapped = shard_map(
                body, mesh=mesh,
                in_specs=(P(_mesh.DOC_AXIS),) * 4,
                out_specs=(P(_mesh.DOC_AXIS),) * 3,
            )
            return jax.jit(
                wrapped, donate_argnums=(0, 1, 2) if donate else ())

        return mesh_fn(mesh, ("paged_groups", impl, donate), build)

    def _dispatch_mesh_fused_batch(self, batch, statics, inputs) -> bool:
        """One program for the whole mesh drain batch + the same per-round
        bookkeeping as the meshless dispatch.  Returns False (the paged
        digest twin stays an open rung under the mesh too — the drain
        keeps the separate prefetch dispatch)."""
        _, plans = statics
        store = self._store
        if inputs:
            fn = self._mesh_paged_fn()
            if GLOBAL_DEVPROF.enabled:
                note_jit_dispatch(
                    "apply_batch_paged_groups.mesh", fn,
                    (store.pool_elem, store.pool_char, store.aux, inputs),
                )
            store.pool_elem, store.pool_char, store.aux = fn(
                store.pool_elem, store.pool_char, store.aux, inputs
            )
            GLOBAL_COUNTERS.add("streaming.fused_dispatches")
        for (enc, _), (widths, plan) in zip(batch, plans):
            cap_total = 0
            rows = np.nonzero(enc.num_ops)[0]
            for shard_rows, g, b, _, _ in plan:
                cap = b * store.n_shards * sum(widths)
                cap_total += cap
                if GLOBAL_DEVPROF.enabled:
                    g_rows = [r for sr in shard_rows for r in sr]
                    GLOBAL_DEVPROF.observe_round(
                        occupancy_key(b * store.n_shards, *widths),
                        int(enc.num_ops[g_rows].sum()), cap,
                        origin="streaming.paged.fused",
                    )
            self._commit_caps[id(enc)] = cap_total
            if len(rows):
                self._digest_row_valid[rows] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(store.pool_stats())
            GLOBAL_DEVPROF.observe_mesh(self._mesh_stats())
        return False

    def _mesh_stats(self) -> Dict:
        """Real per-shard pool occupancy (the padded base reports the
        cum-insert proxy) plus the ICI page-move counter."""
        return dict(self._store.shard_stats())

    def _emit_round_stats(self, batch, scheduled: int,
                          schedule_s: float, apply_s: float,
                          origin: str = "streaming.paged") -> None:
        """Padded capacity under the paged layout is what the dispatched
        GROUPS paid (rows-bucket x widths per bucket), recorded at commit
        time — the base accounting's D x widths would charge the whole
        session for every trickle round."""
        touched: set = set()
        real = 0
        capacity = 0
        for enc, _ in batch:
            touched.update(int(r) for r in np.nonzero(enc.num_ops)[0])
            real += int(enc.num_ops.sum())
            capacity += self._commit_caps.pop(id(enc), 0)
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.sample_memory()
        stats = MergeStats(
            docs=len(touched),
            device_docs=len(touched),
            device_ops=real,
            encode_seconds=schedule_s,
            apply_seconds=apply_s,
            padding_efficiency=real / capacity if capacity else 0.0,
            extras={"rounds": len(batch), "scheduled_changes": scheduled,
                    "layout_paged": 1.0},
        )
        self.last_round_stats = stats
        self._pad_real_ops += real
        self._pad_capacity += capacity
        GLOBAL_HISTOGRAMS.observe("streaming.round_seconds", schedule_s + apply_s)
        GLOBAL_HISTOGRAMS.observe(
            "streaming.round_scheduled_changes", scheduled, buckets=SIZE_BUCKETS
        )

    # -- reads: block materialization ----------------------------------------

    def _state_block(self, block_index: int) -> PackedDocs:
        """Materialize one read block from the pool at the block's
        page-bucketed width (cached per (round, epoch), <= 2 resident)."""
        stamp = (self.rounds, self._placement_epoch)
        key_stamp, cache = self._mat_cache
        if key_stamp != stamp:
            cache = {}
            self._mat_cache = (stamp, cache)
        hit = cache.get(block_index)
        if hit is not None:
            return hit
        lo, hi = self._block_bounds(block_index)
        rows = np.arange(lo, hi)
        state = self._store.materialize_rows(
            rows, self._store.width_for_rows(rows)
        )
        if len(cache) >= 2:
            cache.pop(next(iter(cache)))
        cache[block_index] = state
        return state

    def _resolution(self, block_index: int) -> _BlockResolution:
        """Base _resolution with the paged fused program: resolution at the
        block's width plus the pad-corrected per-doc hash vector."""
        stamp, cache = self._resolved_cache
        if stamp != self.rounds:
            cache = {}
            self._resolved_cache = (self.rounds, cache)
        if block_index in cache:
            entry = cache.pop(block_index)  # re-insert: LRU, not FIFO
            cache[block_index] = entry
            return entry
        lo, hi = self._block_bounds(block_index)
        on_device = self._block_fallback_mask(block_index)
        with self.tracer.span("streaming.resolve", block=block_index):
            state = self._state_block(block_index)
            pad_slots = self._slot_capacity - int(state.elem_id.shape[1])
            dispatch_args = (
                state, self.comment_capacity,
                jnp.asarray(on_device), jnp.uint32(pad_slots),
                *self._digest_tables(lo, hi),
            )
            if GLOBAL_DEVPROF.enabled:
                note_jit_dispatch(
                    "_resolve_block_digest_paged_jit",
                    _resolve_block_digest_paged_jit, dispatch_args,
                )
            resolved, digest_dev = _resolve_block_digest_paged_jit(*dispatch_args)
        entry = _BlockResolution(resolved, digest_dev, on_device)
        if len(cache) >= 2:
            cache.pop(next(iter(cache)))
        cache[block_index] = entry
        return entry

    def _dispatch_compact(self, block_index: int):
        """Base _dispatch_compact with the visible-prefix width capped at
        the block's MATERIALIZED width: the session-wide width prior may
        come from a wider block, and an over-wide take_along_axis would
        silently truncate the packed buffer's layout math."""
        from ..parallel.streaming import _compact_packed_jit

        entry = self._resolution(block_index)
        width = self._compact_width_for(block_index, entry)
        width = min(width, int(entry.device.char.shape[1]))
        buf = _compact_packed_jit(
            entry.device, self._state_block(block_index).elem_id, width
        )
        return buf, width

    # -- digests -------------------------------------------------------------

    def _schedule_rows_digest(self, rest: np.ndarray):
        k = _width_bucket(len(rest))
        rows_idx = np.zeros(k, np.int32)
        rows_idx[: len(rest)] = rest
        mask = np.zeros(k, bool)
        mask[: len(rest)] = True
        g = self._store.width_for_rows(rest)
        sub = self._store.materialize_rows(rest, g, pad_rows_to=k)
        pad_slots = self._slot_capacity - g * self.page_size
        dispatch_args = (
            sub, self.comment_capacity, jnp.asarray(mask),
            jnp.uint32(pad_slots),
            *self._digest_tables_rows(rows_idx, len(rest)),
        )
        if GLOBAL_DEVPROF.enabled:
            note_jit_dispatch(
                "_rows_digest_paged_jit", _rows_digest_paged_jit, dispatch_args,
            )
        return _rows_digest_paged_jit(*dispatch_args)

    def _digest(self, full: bool, refresh: bool) -> int:
        if full:
            # the carried-plane path: _resolution/_schedule_rows_digest above
            # already fold the pad correction into every hash they produce
            return super()._digest(True, refresh)
        from ..parallel.mesh import doc_digest_host

        if refresh:
            self._digest_row_valid[:] = False
            self._resolved_cache = (-1, {})
        replay_docs = [i for i, s in enumerate(self.docs) if s.fallback]
        on_device_all = self._on_device_mask()
        total = 0
        n_blocks = -(-self._padded_docs // self._read_chunk)
        for bi in range(n_blocks):
            lo, hi = self._block_bounds(bi)
            state = self._state_block(bi)
            pad_slots = self._slot_capacity - int(state.elem_id.shape[1])
            digest, overflow = _resolve_digest_paged_jit(
                state, self.comment_capacity,
                jnp.asarray(on_device_all[lo:hi]), jnp.uint32(pad_slots),
            )
            total = (total + int(digest)) & 0xFFFFFFFF
            ov = np.asarray(overflow)
            replay_docs.extend(
                int(self._doc_at[int(r) + lo])
                for r in np.nonzero(ov & on_device_all[lo:hi])[0]
                if int(self._doc_at[int(r) + lo]) >= 0
            )
        s_cap = self._slot_capacity
        for i in replay_docs:
            doc = _replay_doc(self._replay_changes(self.docs[i]))
            cps, slots = _doc_char_slots(doc)
            total = (total + doc_digest_host(cps, slots, s_cap)) & 0xFFFFFFFF
        return total

    # -- placement: pages are the load dimension -----------------------------

    def _reshard_sizes(self) -> np.ndarray:
        """Balance PAGES: the pool spends pages, so a shard's load is the
        pages its docs hold (a host-bound doc's replay cost still balances
        through the host_bound dimension exactly as in the base)."""
        return self._store.page_loads()[self._row_of[: self.num_docs]]

    def _permute_rows(self, src: np.ndarray) -> None:
        self._store.permute_rows(src)

    def reshard(self, assignment=None) -> dict:
        out = super().reshard(assignment)
        n_shards = max(len(out["shard_load"]), 1)
        rows_per_shard = max(self._padded_docs // n_shards, 1)
        page_load = [0] * n_shards
        pages = self._store.page_loads()
        for d in range(self.num_docs):
            row = int(self._row_of[d])
            page_load[min(row // rows_per_shard, n_shards - 1)] += int(pages[row])
        out["page_load"] = page_load
        return out


class RaggedStreamingMerge(PagedStreamingMerge):
    """StreamingMerge over the page pool with the RAGGED apply: every round
    is ONE ``ops/ragged.apply_batch_ragged`` dispatch straight against pool
    pages — no page-count buckets, no row-bucket pad, no gather/scatter,
    and therefore exactly one compiled apply executable per session
    regardless of the doc-size mix (tests/test_recompile_sentinel.py pins
    a tweet-fleet + essay + book drain to one program where the paged
    engine compiles a bucket ladder).

    Storage, reads, digests, compaction and resharding are inherited from
    :class:`PagedStreamingMerge` unchanged — the pool IS the paged pool,
    so materialized blocks and the pad-term-corrected digests stay
    bit-equal to both other layouts.  What changes is only the commit
    half: the round's streams dispatch over ALL ``D`` doc rows (a static
    batch axis; untouched rows carry all-zero streams, which the traced
    per-doc loop bounds make genuinely free, not just masked), with the
    plan planes (store/ragged.ragged_plan) cached per
    ``(alloc_epoch, pool size)`` so steady-state rounds re-upload
    nothing."""

    _layout = "ragged"

    def __init__(self, num_docs, actors, *args,
                 layout: str = "ragged", **kwargs) -> None:
        if layout != "ragged":
            raise ValueError(
                f"RaggedStreamingMerge is layout='ragged', got {layout!r}"
            )
        super().__init__(num_docs, actors, *args, layout="paged", **kwargs)
        #: (alloc_epoch, pool_pages) -> (RaggedPlan, device plane tuple)
        self._ragged_cache: tuple = ((-1, -1), None)
        #: mesh twin: (alloc_epoch, pages_per_shard) -> ((docs_walked,
        #: pages_walked), stacked per-shard device planes)
        self._mesh_ragged_cache: tuple = ((-1, -1), None)

    def health(self) -> Dict:
        h = super().health()
        h["layout"] = "ragged"
        return h

    def _round_widths(self, pool, obj_streams, ki, kd, km, kp):
        """Keep round stream widths FIXED at the session caps (the
        block-chunked/static_rounds discipline): the ragged apply's trip
        counts are data, so padded stream slots cost transfer bytes but
        zero compute — while a shrunk width is a brand-new apply shape.
        One width set x one pool shape = the ONE executable the recompile
        sentinel pins."""
        return ki, kd, km, kp

    # -- the ragged device half of a round -----------------------------------

    def _ragged_planes(self):
        """The whole-session ragged plan, rebuilt only when the allocator
        state it snapshots actually changed (ensure growth, evacuation,
        compaction, permutation, pool growth — anything that bumps
        ``PagedDocStore.alloc_epoch``)."""
        from ..ops.ragged import plan_arrays
        from .ragged import ragged_plan

        store = self._store
        key = (store.alloc_epoch, int(store.pool_elem.shape[0]))
        cached_key, cached = self._ragged_cache
        if cached_key != key:
            plan = ragged_plan(store)
            cached = (plan, plan_arrays(plan))
            self._ragged_cache = (key, cached)
        return cached

    def _commit_round_ragged(self, enc, widths) -> None:
        """One round = one ragged dispatch over the whole pool."""
        from ..ops.ragged import apply_batch_ragged_jit

        store = self._store
        d = self._padded_docs
        rows = np.nonzero(enc.num_ops)[0]
        real = int(enc.num_ops.sum())
        if len(rows):
            store.ensure_rows(rows, self._cum_ins[rows])
        plan, planes = self._ragged_planes()
        row_idx, owner, pos_base, prev_page, page_count, page_table = planes
        store.pool_elem, store.pool_char, store.aux = apply_batch_ragged_jit(
            store.pool_elem, store.pool_char, store.aux,
            row_idx, owner, pos_base, prev_page, page_count, page_table,
            group_stream_arrays(enc, None, d),
            jnp.asarray(enc.ins_count, jnp.int32),
            jnp.asarray(enc.del_count, jnp.int32),
        )
        # ragged pays real ops only: no bucket pad rows, no padded slots —
        # capacity IS the real work, so padding_efficiency reads 1.0
        self._commit_caps[id(enc)] = real
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_round(
                occupancy_key(d, *widths), real, max(real, 1),
                origin="streaming.ragged",
            )
            GLOBAL_DEVPROF.observe_ragged(
                docs_walked=plan.docs_walked,
                pages_walked=plan.pages_walked,
                real_ops=real,
            )
        if len(rows):
            self._digest_row_valid[rows] = False
        self.rounds += 1
        GLOBAL_COUNTERS.add("streaming.rounds")

    def _commit_rounds(self, batch) -> None:
        """Per-round ragged dispatches (a Python loop, ONE executable): a
        rounds-chained fused program would mint one shape per drain depth,
        which is exactly the ladder this layout exists to kill.  The fused
        staged-drain hooks below reuse this same discipline, so serving
        drains and direct commits share the single compiled apply."""
        for enc, widths in batch:
            self._cum_ins += enc.ins_count
            self._commit_round_ragged(enc, widths)
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(self._store.pool_stats())

    def _commit_rounds_serial(self, batch) -> None:
        self._commit_rounds(batch)

    # -- fused staged-drain hooks (serve/mux.py drains) ----------------------
    #
    # The drain loop stages rounds through the prep/stage/dispatch trio so
    # host staging overlaps device work.  The ragged prep is allocation
    # only (the plan planes are cached device-side), the stage uploads each
    # round's stream tensors, and the dispatch is the same per-round
    # program as a direct commit — shapes never depend on the drain depth.

    def _prep_fused_batch(self, batch):
        for enc, _ in batch:
            self._cum_ins += enc.ins_count
            rows = np.nonzero(enc.num_ops)[0]
            if len(rows):
                self._store.ensure_rows(rows, self._cum_ins[rows])
        if self.mesh is not None:
            return ("mesh_ragged", len(batch))
        return ("ragged", len(batch))

    def _stage_fused_batch(self, batch, statics):
        d = self._padded_docs
        inputs = tuple(
            (
                group_stream_arrays(enc, None, d),
                jnp.asarray(enc.ins_count, jnp.int32),
                jnp.asarray(enc.del_count, jnp.int32),
            )
            for enc, _ in batch
        )
        if statics[0] == "mesh_ragged":
            from ..parallel.mesh_fused import shard_leading

            return shard_leading(inputs, self.mesh)
        return jax.device_put(inputs)

    def _mesh_ragged_planes(self):
        """Per-shard ragged plans — LOCAL row ids over each shard's local
        pool block, built straight off the per-shard allocators (the
        owner sentinel is ``rows_per_shard``, the prev-page sentinel each
        shard's OWN null page 0) — stacked on a leading shard axis and
        cached device-side keyed by (alloc_epoch, per-shard pool size):
        the meshless ``_ragged_planes`` discipline, one plane set per
        shard, re-uploaded only when the allocator state changes."""
        from ..parallel.mesh_fused import shard_leading

        store = self._store
        key = (store.alloc_epoch, store.pages_per_shard)
        cached_key, cached = self._mesh_ragged_cache
        if cached_key != key:
            n, rps = store.n_shards, store.rows_per_shard
            ps = store.pages_per_shard
            p = store.page_size
            row_idx = np.tile(np.arange(rps, dtype=np.int64), (n, 1))
            owner = np.full((n, ps), rps, np.int32)
            pos_base = np.zeros((n, ps), np.int32)
            prev_page = np.zeros((n, ps), np.int32)
            page_count = np.zeros((n, rps), np.int32)
            page_table = np.zeros((n, rps, store.max_doc_pages), np.int32)
            pages_walked = 0
            for s in range(n):
                alloc = store.alloc.shards[s]
                for doc in alloc.docs():
                    row = doc - s * rps
                    pages = alloc.pages_of(doc)
                    page_count[s, row] = len(pages)
                    pages_walked += len(pages)
                    for k, pg in enumerate(pages):
                        owner[s, pg] = row
                        pos_base[s, pg] = k * p
                        prev_page[s, pg] = pages[k - 1] if k else 0
                        page_table[s, row, k] = pg
            planes = shard_leading(
                (row_idx, owner, pos_base, prev_page, page_count,
                 page_table),
                self.mesh,
            )
            cached = ((self._padded_docs, pages_walked), planes)
            self._mesh_ragged_cache = (key, cached)
        return cached

    def _mesh_ragged_fn(self):
        """The ONE mesh ragged apply executable: per-round ``shard_map``
        dispatch whose body walks each shard's local pool with its own
        plan planes.  Like the meshless ragged engine, rounds dispatch one
        at a time against the same compiled program — chaining a drain's
        rounds into one program would mint one XLA shape per drain depth,
        the ladder this layout exists to kill."""
        from ..ops.kernel import resolve_ragged_impl, resolve_state_donation
        from ..ops.ragged import apply_batch_ragged
        from ..parallel.mesh_fused import mesh_fn

        mesh = self.mesh
        impl = resolve_ragged_impl(self._store.pool_elem)
        donate = resolve_state_donation(self._store.pool_elem)

        def build():
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            def body(pool_elem, pool_char, aux, planes, earrays,
                     ins_counts, del_counts):
                (row_idx, owner, pos_base, prev_page, page_count,
                 page_table) = jax.tree_util.tree_map(
                    lambda x: x[0], planes)
                return apply_batch_ragged(
                    pool_elem, pool_char, aux, row_idx, owner, pos_base,
                    prev_page, page_count, page_table, earrays,
                    ins_counts, del_counts, ragged_impl=impl,
                )

            # check_rep=False: the ragged pool walk is lax.fori_loop-based
            # and shard_map has no replication rule for while — every
            # operand and result is explicitly doc-axis-sharded anyway
            wrapped = shard_map(
                body, mesh=mesh,
                in_specs=(P(_mesh.DOC_AXIS),) * 7,
                out_specs=(P(_mesh.DOC_AXIS),) * 3,
                check_rep=False,
            )
            return jax.jit(
                wrapped, donate_argnums=(0, 1, 2) if donate else ())

        return mesh_fn(mesh, ("ragged_apply", impl, donate), build)

    def _dispatch_mesh_fused_batch(self, batch, statics, inputs) -> bool:
        store = self._store
        (docs_walked, pages_walked), planes = self._mesh_ragged_planes()
        fn = self._mesh_ragged_fn()
        GLOBAL_COUNTERS.add("streaming.fused_dispatches")
        for (enc, widths), (earrays, ins_counts, del_counts) in zip(
            batch, inputs
        ):
            rows = np.nonzero(enc.num_ops)[0]
            real = int(enc.num_ops.sum())
            if GLOBAL_DEVPROF.enabled:
                note_jit_dispatch(
                    "apply_batch_ragged.mesh", fn,
                    (store.pool_elem, store.pool_char, store.aux, planes,
                     earrays, ins_counts, del_counts),
                )
            store.pool_elem, store.pool_char, store.aux = fn(
                store.pool_elem, store.pool_char, store.aux, planes,
                earrays, ins_counts, del_counts,
            )
            self._commit_caps[id(enc)] = real
            if GLOBAL_DEVPROF.enabled:
                GLOBAL_DEVPROF.observe_round(
                    occupancy_key(self._padded_docs, *widths), real,
                    max(real, 1), origin="streaming.ragged",
                )
                GLOBAL_DEVPROF.observe_ragged(
                    docs_walked=docs_walked, pages_walked=pages_walked,
                    real_ops=real,
                )
            if len(rows):
                self._digest_row_valid[rows] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(store.pool_stats())
            GLOBAL_DEVPROF.observe_mesh(self._mesh_stats())
        return False

    def _dispatch_fused_batch(self, batch, statics, inputs,
                              chain_digest: bool = False) -> bool:
        if statics[0] == "mesh_ragged":
            return self._dispatch_mesh_fused_batch(batch, statics, inputs)
        from ..ops.ragged import apply_batch_ragged_jit

        store = self._store
        plan, planes = self._ragged_planes()
        row_idx, owner, pos_base, prev_page, page_count, page_table = planes
        for (enc, widths), (earrays, ins_counts, del_counts) in zip(
            batch, inputs
        ):
            rows = np.nonzero(enc.num_ops)[0]
            real = int(enc.num_ops.sum())
            store.pool_elem, store.pool_char, store.aux = (
                apply_batch_ragged_jit(
                    store.pool_elem, store.pool_char, store.aux,
                    row_idx, owner, pos_base, prev_page, page_count,
                    page_table, earrays, ins_counts, del_counts,
                )
            )
            self._commit_caps[id(enc)] = real
            if GLOBAL_DEVPROF.enabled:
                GLOBAL_DEVPROF.observe_round(
                    occupancy_key(self._padded_docs, *widths), real,
                    max(real, 1), origin="streaming.ragged",
                )
                GLOBAL_DEVPROF.observe_ragged(
                    docs_walked=plan.docs_walked,
                    pages_walked=plan.pages_walked,
                    real_ops=real,
                )
            if len(rows):
                self._digest_row_valid[rows] = False
            self.rounds += 1
            GLOBAL_COUNTERS.add("streaming.rounds")
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.observe_page_pool(store.pool_stats())
        return False

    def _emit_round_stats(self, batch, scheduled: int,
                          schedule_s: float, apply_s: float,
                          origin: str = "streaming.ragged") -> None:
        touched: set = set()
        real = 0
        capacity = 0
        for enc, _ in batch:
            touched.update(int(r) for r in np.nonzero(enc.num_ops)[0])
            real += int(enc.num_ops.sum())
            capacity += self._commit_caps.pop(id(enc), 0)
        if GLOBAL_DEVPROF.enabled:
            GLOBAL_DEVPROF.sample_memory()
        stats = MergeStats(
            docs=len(touched),
            device_docs=len(touched),
            device_ops=real,
            encode_seconds=schedule_s,
            apply_seconds=apply_s,
            padding_efficiency=real / capacity if capacity else 0.0,
            extras={"rounds": len(batch), "scheduled_changes": scheduled,
                    "layout_ragged": 1.0},
        )
        self.last_round_stats = stats
        self._pad_real_ops += real
        self._pad_capacity += capacity
        GLOBAL_HISTOGRAMS.observe("streaming.round_seconds", schedule_s + apply_s)
        GLOBAL_HISTOGRAMS.observe(
            "streaming.round_scheduled_changes", scheduled, buckets=SIZE_BUCKETS
        )
