"""ShardedPagedDocStore: the page pool split into per-shard pools (round 19).

One mesh host runs ONE logical page pool, physically split into ``n`` equal
per-shard pools along the page axis — shard ``s`` owns global pages
``[s * Ps, (s + 1) * Ps)``.  Placement keeps every doc's pages on the shard
that owns the doc's row range (``shard_of_row = row // rows_per_shard``), so
the ragged kernel's per-doc ``(max_doc_pages, P)`` window — the shard unit
the pool was designed around — never straddles an ICI link and the fused
mesh commits (store/session.py) can run each shard's groups entirely
locally under ``shard_map``.

Invariants on top of :class:`~.paged.PagedDocStore`'s:

* **Every shard has its own null page** (local page 0 = global ``s * Ps``,
  reserved and permanently all-zero).  The per-shard apply programs re-zero
  their LOCAL page 0 after the scatter, which is exactly the base
  program's null-page discipline seen through ``shard_map``.
* **Per-doc placement**: a doc's pages live on its row's shard, always.
  ``ensure_rows`` allocates from the row's shard; when any shard runs dry
  EVERY shard grows to the same per-shard size (the pool must stay ``n``
  equal blocks for the global-id arithmetic and the sharded device layout).
* **reshard() moves pages over ICI, not through the host**: the row
  permutation first allocates destination locals in each receiving shard
  (lowest-free-first, disjoint from both the pages staying and the pages
  leaving, so the one-program gather→ppermute→scatter in
  parallel/mesh_fused.page_mover_fn is sound), then runs the mover, then
  reseats the per-shard allocators and re-zeroes the vacated sources
  inside the same program.

The facade (:class:`ShardedAllocator`) presents the per-shard allocators
under the base allocator interface with GLOBAL page ids, so every
inherited read/digest/evacuate/compact path works unchanged; only the
allocation, growth and permutation verbs needed shard-aware overrides.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from ..obs import GLOBAL_COUNTERS
from ..parallel.mesh import shard_docs
from ..parallel.mesh_fused import mesh_fn, page_mover_fn, shard_leading
from .alloc import PageAllocator, PoolExhausted
from .paged import DEFAULT_PAGE_SIZE, PagedDocStore, _pow2


class ShardedAllocator:
    """Per-shard :class:`PageAllocator` bank behind the base allocator
    interface.  Doc rows are GLOBAL; page ids returned by query verbs are
    GLOBAL (``s * pages_per_shard + local``); each shard's allocator holds
    LOCAL ids and is keyed by global doc rows (a row lives on exactly one
    shard).  Mutating verbs route to the owning shard — cross-shard
    requests are placement-invariant violations and raise."""

    def __init__(self, n_shards: int, pages_per_shard: int,
                 rows_per_shard: int) -> None:
        self.n_shards = int(n_shards)
        self.pages_per_shard = int(pages_per_shard)
        self.rows_per_shard = int(rows_per_shard)
        self.shards: List[PageAllocator] = [
            PageAllocator(pages_per_shard) for _ in range(n_shards)
        ]

    # -- shard arithmetic ----------------------------------------------------

    def shard_of_row(self, row: int) -> int:
        return int(row) // self.rows_per_shard

    def _to_global(self, shard: int, locals_: Sequence[int]) -> List[int]:
        base = shard * self.pages_per_shard
        return [base + int(p) for p in locals_]

    # -- base allocator interface (global view) ------------------------------

    @property
    def total_pages(self) -> int:
        return self.n_shards * self.pages_per_shard

    @property
    def reserved(self) -> int:
        return sum(a.reserved for a in self.shards)

    @property
    def free_pages(self) -> int:
        return sum(a.free_pages for a in self.shards)

    @property
    def pages_in_use(self) -> int:
        return sum(a.pages_in_use for a in self.shards)

    def pages_of(self, doc: int) -> List[int]:
        s = self.shard_of_row(doc)
        return self._to_global(s, self.shards[s].pages_of(doc))

    def num_pages(self, doc: int) -> int:
        return self.shards[self.shard_of_row(doc)].num_pages(doc)

    def docs(self) -> List[int]:
        out: List[int] = []
        for a in self.shards:
            out.extend(a.docs())
        return sorted(out)

    def ensure(self, doc: int, num_pages: int) -> List[int]:
        s = self.shard_of_row(doc)
        return self._to_global(s, self.shards[s].ensure(doc, num_pages))

    def free_doc(self, doc: int) -> List[int]:
        s = self.shard_of_row(doc)
        return self._to_global(s, self.shards[s].free_doc(doc))

    def evacuate(self, doc: int) -> List[int]:
        return self.free_doc(doc)

    def grow(self, new_total: int) -> int:
        raise NotImplementedError(
            "sharded pools grow per shard (ShardedPagedDocStore._grow_pool)"
        )

    def compact_plan(self) -> Dict[int, int]:
        """Per-shard compaction expressed in global ids — every move stays
        inside its shard, so the pool's sharded device layout survives the
        gather unchanged."""
        mapping: Dict[int, int] = {}
        for s, a in enumerate(self.shards):
            base = s * self.pages_per_shard
            for old, new in a.compact_plan().items():
                mapping[base + old] = base + new
        return mapping

    def apply_compact(self, mapping: Dict[int, int]) -> None:
        per_shard: List[Dict[int, int]] = [{} for _ in self.shards]
        ps = self.pages_per_shard
        for old, new in mapping.items():
            if old // ps != new // ps:
                raise ValueError("sharded compact must not cross shards")
            per_shard[old // ps][old % ps] = new % ps
        for a, m in zip(self.shards, per_shard):
            a.apply_compact(m)

    def reseat(self, pages_by_doc: Dict[int, List[int]]) -> None:
        ps = self.pages_per_shard
        per_shard: List[Dict[int, List[int]]] = [{} for _ in self.shards]
        for doc, pages in pages_by_doc.items():
            s = self.shard_of_row(doc)
            locals_ = [int(p) - s * ps for p in pages]
            if any(p < 0 or p >= ps for p in locals_):
                raise ValueError(
                    f"doc {doc} reseated with pages outside shard {s}"
                )
            per_shard[s][int(doc)] = locals_
        for a, m in zip(self.shards, per_shard):
            a.reseat(m)


class ShardedPagedDocStore(PagedDocStore):
    """Doc-axis-sharded :class:`PagedDocStore` over ``mesh`` (module doc).

    Device arrays: ``pool_elem`` / ``pool_char`` are ``(n * Ps, P)`` with
    the PAGE axis sharded over the doc axis (each shard holds its own
    ``(Ps, P)`` block); the dense aux rows shard on the DOC axis.  Both
    therefore enter the fused ``shard_map`` commit programs with
    ``P(DOC_AXIS)`` specs and zero resharding."""

    def __init__(
        self,
        num_docs: int,
        mesh,
        slot_capacity: int,
        mark_capacity: int,
        tomb_capacity: Optional[int] = None,
        map_capacity: int = 32,
        page_size: int = DEFAULT_PAGE_SIZE,
        initial_pages: Optional[int] = None,
        max_pool_pages: Optional[int] = None,
    ) -> None:
        n = mesh.size
        if num_docs % n:
            raise ValueError(
                f"num_docs {num_docs} must be a multiple of the mesh size {n}"
            )
        # build the base store at its meshless shape first (allocator and
        # device arrays are replaced below; the aux schema, capacities and
        # host planes are exactly the base's)
        super().__init__(
            num_docs, slot_capacity, mark_capacity,
            tomb_capacity=tomb_capacity, map_capacity=map_capacity,
            page_size=page_size,
        )
        self.mesh = mesh
        self.n_shards = n
        self.rows_per_shard = num_docs // n
        # per-shard ceiling: every resident doc of the shard fully grown,
        # plus the shard's null page (the base's ceiling seen per shard)
        ceil = 1 + self.rows_per_shard * self.max_doc_pages
        if max_pool_pages is not None:
            ceil = min(ceil, max(2, int(max_pool_pages) // n))
        self.max_shard_pages = ceil
        self.max_pool_pages = n * ceil
        start = initial_pages or min(
            ceil, _pow2(1 + max(self.rows_per_shard, 8))
        )
        start = max(2, min(int(start), ceil))
        self.pages_per_shard = start
        self.alloc = ShardedAllocator(n, start, self.rows_per_shard)
        self.pool_elem = self._put_pages(
            jnp.zeros((n * start, page_size), jnp.int32))
        self.pool_char = self._put_pages(
            jnp.zeros((n * start, page_size), jnp.int32))
        self.aux = shard_docs(self.aux, mesh)
        #: pages moved between shards over ICI so far (reshard telemetry)
        self.ici_page_moves = 0

    def _put_pages(self, pool):
        return shard_leading(pool, self.mesh)

    # -- allocation: per-shard free lists, uniform growth --------------------

    def ensure_rows(self, rows: Sequence[int], used_slots: Sequence[int]) -> None:
        """Base contract, but a row can only draw from ITS shard's free
        list — the global count being ample does not help a dry shard, so
        the dry-shard check is per row and growth is all-shards-uniform."""
        order = np.argsort(np.asarray(rows, np.int64), kind="stable")
        rows_arr = np.asarray(rows, np.int64)[order]
        used_arr = np.asarray(used_slots, np.int64)[order]
        for row, used in zip(rows_arr, used_arr):
            row = int(row)
            shard = self.alloc.shards[self.alloc.shard_of_row(row)]
            need = self.pages_needed(int(used))
            delta = need - shard.num_pages(row)
            if delta > 0 and delta > shard.free_pages:
                self._grow_pool(
                    shard.pages_in_use + shard.reserved + delta
                )
            self.alloc.ensure(row, need)
            if delta > 0:
                self.alloc_epoch += 1
            self._num_pages[row] = self.alloc.num_pages(row)
            self._used_hint[row] = max(self._used_hint[row], int(used))

    def _grow_pool(self, min_shard_pages: int) -> None:
        """Grow EVERY shard to the same new per-shard size (>= the base's
        doubling curve).  The device remap keeps each shard's block
        contiguous — ``(n*Ps, P) -> (n, Ps, P) -> pad -> (n*Ps', P)`` — so
        local page ids survive and only the global-id base shifts."""
        ps = self.pages_per_shard
        target = _pow2(max(int(min_shard_pages), 2 * ps))
        target = min(target, self.max_shard_pages)
        if target < min_shard_pages:
            raise PoolExhausted(
                min_shard_pages - ps,
                min(a.free_pages for a in self.alloc.shards),
                self.alloc.total_pages,
            )
        added = target - ps
        if added <= 0:
            return
        n = self.n_shards
        pad = jnp.zeros((n, added, self.page_size), jnp.int32)

        def regrow(pool):
            blocks = pool.reshape(n, ps, self.page_size)
            wide = jnp.concatenate([blocks, pad], axis=1)
            return self._put_pages(wide.reshape(n * target, self.page_size))

        self.pool_elem = regrow(self.pool_elem)
        self.pool_char = regrow(self.pool_char)
        for a in self.alloc.shards:
            a.grow(target)
        self.pages_per_shard = target
        self.alloc.pages_per_shard = target
        self.growths += 1
        self.alloc_epoch += 1

    # -- lifecycle -----------------------------------------------------------

    def compact(self) -> int:
        """Base compaction, intra-shard by construction (the facade's plan
        never crosses shards); the gather uses an IDENTITY default so free
        pages keep their (all-zero) content without a cross-shard read of
        shard 0's null, and the result re-pins the sharded layout."""
        mapping = self.alloc.compact_plan()
        moved = sum(1 for old, new in sorted(mapping.items()) if old != new)
        if moved:
            src = np.arange(self.alloc.total_pages, dtype=np.int32)
            for old, new in sorted(mapping.items()):
                src[new] = old
            idx = jnp.asarray(src)
            self.pool_elem = self._put_pages(
                jnp.take(self.pool_elem, idx, axis=0))
            self.pool_char = self._put_pages(
                jnp.take(self.pool_char, idx, axis=0))
        self.alloc.apply_compact(mapping)
        if moved:
            self.alloc_epoch += 1
        self._num_pages[:] = 0
        for doc in self.alloc.docs():
            self._num_pages[doc] = self.alloc.num_pages(doc)
        return moved

    def permute_rows(self, src: np.ndarray) -> None:
        """The collective reshard protocol: new row ``r`` takes old row
        ``src[r]``.  Rows that stay on their shard move tables only (the
        base discipline); rows that change shard move their PAGES over ICI
        in one :func:`~..parallel.mesh_fused.page_mover_fn` program —
        destination locals allocated first (disjoint from pages staying
        AND leaving), vacated sources re-zeroed in-program."""
        src = np.asarray(src, np.int64)
        n, ps, rps = self.n_shards, self.pages_per_shard, self.rows_per_shard
        alloc = self.alloc
        old_pages = {
            d: alloc.shards[alloc.shard_of_row(d)].pages_of(d)
            for a in alloc.shards for d in a.docs()
        }
        staying: List[set] = [set() for _ in range(n)]
        leaving: List[set] = [set() for _ in range(n)]
        new_maps: List[Dict[int, List[int]]] = [{} for _ in range(n)]
        cross = []  # (src_shard, dst_shard, new_row, src_locals)
        for r in range(len(src)):
            o = int(src[r])
            pages = old_pages.get(o)
            if not pages:
                continue
            so, sn = alloc.shard_of_row(o), alloc.shard_of_row(r)
            if so == sn:
                new_maps[sn][r] = pages
                staying[sn].update(pages)
            else:
                cross.append((so, sn, r, pages))
                leaving[so].update(pages)
        if cross:
            # capacity: each receiving shard needs dst locals outside
            # (staying + leaving); grow all shards first if any is short
            need_in = [0] * n
            for _, sn, _, pages in cross:
                need_in[sn] += len(pages)
            worst = max(
                1 + len(staying[s]) + len(leaving[s]) + need_in[s]
                for s in range(n)
            )
            if worst > ps:
                self._grow_pool(worst)
                ps = self.pages_per_shard
            free: List[List[int]] = [
                sorted(set(range(1, ps)) - staying[s] - leaving[s])
                for s in range(n)
            ]
            send: Dict[tuple, List[int]] = {}
            recv: Dict[tuple, List[int]] = {}
            moved = 0
            for so, sn, r, pages in sorted(cross, key=lambda c: (c[1], c[2])):
                dst = free[sn][: len(pages)]
                del free[sn][: len(pages)]
                new_maps[sn][r] = dst
                d = (sn - so) % n
                send.setdefault((so, d), []).extend(pages)
                recv.setdefault((sn, d), []).extend(dst)
                moved += len(pages)
            m_pages = max(len(v) for v in send.values())
            m_zero = max((len(leaving[s]) for s in range(n)), default=1)
            m_zero = max(m_zero, 1)
            send_idx = np.zeros((n, n - 1, m_pages), np.int32)
            recv_idx = np.full((n, n - 1, m_pages), ps, np.int32)
            zero_idx = np.full((n, m_zero), ps, np.int32)
            for (s, d), v in send.items():
                send_idx[s, d - 1, : len(v)] = v
            for (s, d), v in recv.items():
                recv_idx[s, d - 1, : len(v)] = v
            for s in range(n):
                vac = sorted(leaving[s])
                zero_idx[s, : len(vac)] = vac
            fn = mesh_fn(
                self.mesh, ("page_mover", m_pages, m_zero),
                lambda: page_mover_fn(self.mesh, m_pages, m_zero),
            )
            idx_tree = shard_leading(
                (send_idx, recv_idx, zero_idx), self.mesh)
            self.pool_elem, self.pool_char = fn(
                self.pool_elem, self.pool_char, *idx_tree)
            self.ici_page_moves += moved
            GLOBAL_COUNTERS.add("store.ici_page_moves", moved)
        for a, m in zip(alloc.shards, new_maps):
            a.reseat(m)
        idx = jnp.asarray(src)
        self.aux = shard_docs(
            tuple(jnp.take(a, idx, axis=0) for a in self.aux), self.mesh)
        self._num_pages = self._num_pages[src]
        self._used_hint = self._used_hint[src]
        self.alloc_epoch += 1

    # -- telemetry -----------------------------------------------------------

    def shard_stats(self) -> Dict:
        """Per-shard pool snapshot behind the ``peritext_mesh_*`` gauges."""
        per_use = [a.pages_in_use for a in self.alloc.shards]
        cap = self.pages_per_shard - 1
        mean = sum(per_use) / len(per_use) if per_use else 0.0
        return {
            "shards": self.n_shards,
            "rows_per_shard": self.rows_per_shard,
            "pages_per_shard": self.pages_per_shard,
            "shard_load": per_use,
            "shard_utilization": [
                round(u / cap, 4) if cap else 0.0 for u in per_use
            ],
            "imbalance_ratio": (
                round(max(per_use) / mean, 4) if mean else 1.0
            ),
            "ici_page_moves": self.ici_page_moves,
        }
