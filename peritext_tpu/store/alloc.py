"""Deterministic page allocator for the global op-page pool.

Placement is merge-scope state: two replicas that ingest the same frames in
the same order must end up with IDENTICAL page tables (the paged digest
and the recompile-shape discipline both depend on it), so allocation is a
pure function of the request sequence — lowest-free-page-id first via a
heap (a sorted free-list walk), no wall clock, no RNG, no id churn from
dict/set iteration order.

Page 0 is permanently reserved as the NULL page: page-table padding slots
point at it, gathers read zeros from it, and the apply program re-zeroes it
after every scatter (ops/kernel.apply_batch_paged), so a shared padding
target can never leak state between docs.
"""

from __future__ import annotations

import heapq
from typing import Dict, List


class PoolExhausted(RuntimeError):
    """Typed pool-exhaustion error: the allocator cannot satisfy a request
    and the pool is not allowed to grow further.  Carries the sizing facts
    a supervisor needs to decide between shedding and resizing."""

    def __init__(self, requested: int, free: int, total: int) -> None:
        self.requested = int(requested)
        self.free = int(free)
        self.total = int(total)
        super().__init__(
            f"page pool exhausted: requested {requested} page(s), "
            f"{free} free of {total} total"
        )


class PageAllocator:
    """Free-list allocator over ``total_pages`` fixed-size pages.

    ``owner_of[page]`` maps a page to the doc row holding it (-1 = free);
    ``pages_of(doc)`` returns the doc's pages in TABLE ORDER (page k of a
    doc backs slots ``[k*P, (k+1)*P)``), which is allocation order — the
    order is part of the deterministic contract, not a convenience.
    """

    def __init__(self, total_pages: int, reserved: int = 1) -> None:
        if total_pages <= reserved:
            raise ValueError(
                f"pool needs more than {reserved} page(s), got {total_pages}"
            )
        self.total_pages = int(total_pages)
        self.reserved = int(reserved)
        # heap of free page ids: pop order == sorted order (deterministic)
        self._free: List[int] = list(range(reserved, total_pages))
        heapq.heapify(self._free)
        self._pages: Dict[int, List[int]] = {}  # doc row -> page ids

    # -- queries -------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.total_pages - self.reserved - len(self._free)

    def pages_of(self, doc: int) -> List[int]:
        return list(self._pages.get(doc, ()))

    def num_pages(self, doc: int) -> int:
        return len(self._pages.get(doc, ()))

    def docs(self) -> List[int]:
        return sorted(self._pages)

    # -- mutation ------------------------------------------------------------

    def ensure(self, doc: int, num_pages: int) -> List[int]:
        """Grow ``doc``'s page table to ``num_pages`` entries (no-op when it
        already holds at least that many).  Returns the newly-assigned page
        ids (allocation order).  Raises :class:`PoolExhausted` when the free
        list cannot cover the delta — atomically: a failed ensure assigns
        nothing."""
        held = self._pages.setdefault(doc, [])
        delta = int(num_pages) - len(held)
        if delta <= 0:
            return []
        if delta > len(self._free):
            raise PoolExhausted(delta, len(self._free), self.total_pages)
        fresh = [heapq.heappop(self._free) for _ in range(delta)]
        held.extend(fresh)
        return fresh

    def free_doc(self, doc: int) -> List[int]:
        """Release every page ``doc`` holds; returns them (table order)."""
        held = self._pages.pop(doc, [])
        for page in held:
            heapq.heappush(self._free, page)
        return held

    def evacuate(self, doc: int) -> List[int]:
        """Evacuation form of :meth:`free_doc`: the caller has materialized
        the doc's state (to ship it to another host / another pool) and the
        pages go back to the free list.  Kept as its own verb so call sites
        read as the host-move they are."""
        return self.free_doc(doc)

    def grow(self, new_total: int) -> int:
        """Extend the pool to ``new_total`` pages (the new page ids join the
        free list); returns the number of pages added.  The device arrays
        grow in :class:`~.paged.PagedDocStore` — this is the bookkeeping
        half."""
        added = int(new_total) - self.total_pages
        if added <= 0:
            return 0
        for page in range(self.total_pages, int(new_total)):
            heapq.heappush(self._free, page)
        self.total_pages = int(new_total)
        return added

    def compact_plan(self) -> Dict[int, int]:
        """Old-page -> new-page mapping that packs every held page into the
        lowest ids (docs walked in sorted row order, each doc's pages in
        table order), leaving the free list one contiguous tail.  Pure
        planning: :meth:`apply_compact` commits it, the store moves the
        device rows."""
        mapping: Dict[int, int] = {}
        nxt = self.reserved
        for doc in sorted(self._pages):
            for page in self._pages[doc]:
                mapping[page] = nxt
                nxt += 1
        return mapping

    def reseat(self, pages_by_doc: Dict[int, List[int]]) -> None:
        """Atomically replace the whole page-table map — the reshard row
        permutation: the same pages under new doc rows.  Pages must be
        disjoint; the free list rebuilds as the sorted complement, so the
        allocator state after a reseat is a pure function of the new map."""
        held: List[int] = []
        self._pages = {}
        for doc in sorted(pages_by_doc):
            pages = list(pages_by_doc[doc])
            if pages:
                self._pages[int(doc)] = pages
                held.extend(pages)
        held_set = set(held)
        if len(held) != len(held_set):
            raise ValueError("reseat pages must be disjoint")
        self._free = [
            p for p in range(self.reserved, self.total_pages)
            if p not in held_set
        ]
        heapq.heapify(self._free)

    def apply_compact(self, mapping: Dict[int, int]) -> None:
        """Commit a :meth:`compact_plan`: rewrite every page table through
        ``mapping`` and rebuild the free list as the tail above the packed
        prefix."""
        for doc in sorted(self._pages):
            self._pages[doc] = [mapping[p] for p in self._pages[doc]]
        used = self.reserved + len(mapping)
        self._free = list(range(used, self.total_pages))
        heapq.heapify(self._free)
