"""Declarative mark schema.

Mirrors the semantics the reference derives from its ProseMirror ``markSpec``
(reference ``src/schema.ts:45-96``): per-mark-type behavior flags that the CRDT
core consults.  The CRDT reads only:

* ``inclusive`` — whether the *end* of a span grows to absorb characters
  inserted at its right boundary (``src/micromerge.ts:651``).  Span starts never
  grow (``:650``).
* ``allow_multiple`` — whether concurrent marks of this type form a set
  (comments) or resolve last-writer-wins (strong/em/link)
  (``src/micromerge.ts:403-405``).

For the device path each mark type is interned to a stable small integer and
the flags become traced-constant arrays (:func:`mark_flags_arrays`), so the
schema compiles into the kernel rather than being branched on at runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple


@dataclass(frozen=True)
class MarkSchema:
    """Behavior of one mark type."""

    #: Does the span end grow to include text inserted at its right edge?
    inclusive: bool
    #: Multiple concurrent values coexist (set semantics) vs last-writer-wins.
    allow_multiple: bool
    #: Names of data attributes carried by the mark ("url", "id", ...).
    attr_keys: Tuple[str, ...] = field(default=())
    #: PRESENTATION half of the reference markSpec (src/schema.ts:45-96):
    #: which mark types adding this one replaces in a set.  ``None`` is
    #: ProseMirror's default — a mark excludes its own type (same-type add
    #: replaces); ``()`` is schema.ts's ``excludes: ""`` on comments —
    #: nothing is excluded, so same-type marks coexist (keyed by id).
    excludes: "Tuple[str, ...] | None" = None
    #: DOM rendering tag for :func:`mark_to_dom` (markSpec ``toDOM``).
    dom_tag: str = "span"


#: The default schema, matching the reference's four mark types.
MARK_SPEC: Dict[str, MarkSchema] = {
    "strong": MarkSchema(inclusive=True, allow_multiple=False, dom_tag="strong"),
    "em": MarkSchema(inclusive=True, allow_multiple=False, dom_tag="em"),
    "comment": MarkSchema(inclusive=False, allow_multiple=True,
                          attr_keys=("id",), excludes=(), dom_tag="span"),
    "link": MarkSchema(inclusive=False, allow_multiple=False,
                       attr_keys=("url",), dom_tag="a"),
}

#: Stable ordering for device-side integer encoding of mark types.
ALL_MARKS: Tuple[str, ...] = ("strong", "em", "comment", "link")

MARK_INDEX: Dict[str, int] = {name: i for i, name in enumerate(ALL_MARKS)}


def is_mark_type(s: str) -> bool:
    return s in MARK_SPEC


def excludes_of(mark_type: str) -> Tuple[str, ...]:
    """Resolved exclusion set: ProseMirror's ``Mark.addToSet`` consults the
    schema's ``excludes`` to decide replacement; the default (None) is the
    mark's own type (reference markSpec relies on it for strong/em/link,
    and overrides it to "" for comments, src/schema.ts:77)."""
    spec = MARK_SPEC.get(mark_type)
    if spec is None:
        return (mark_type,)
    return (mark_type,) if spec.excludes is None else spec.excludes


def _link_color(url: str) -> str:
    """Deterministic per-url color (stand-in for the reference demo's
    colorHash, src/schema.ts:86 — any stable mapping works; peers render
    the same url the same color).  Reuses the interning content hash so
    there is exactly one FNV implementation in the tree."""
    from .utils.interning import content_hash32

    return f"#{(content_hash32(url) >> 8) & 0xFFFFFF:06x}"


def mark_to_dom(mark_type: str, attrs=None):
    """DOMOutputSpec-shaped rendering of one mark (markSpec ``toDOM``,
    reference src/schema.ts:45-96): ``["strong"]``, ``["em"]``,
    ``["a", {href, style}]``, ``["span", {data-mark, data-comment-id}]``.
    Tags come from the spec's ``dom_tag``; the attr shapes mirror the
    reference's per-type toDOM closures.  Consumed by presentation layers
    (the web demos inline an equivalent); exposed so a real PM schema can
    be built from this spec."""
    attrs = attrs or {}
    spec = MARK_SPEC.get(mark_type)
    tag = spec.dom_tag if spec else "span"
    if mark_type == "link":
        url = attrs.get("url") or ""
        return [tag, {"href": url, "style": f"color: {_link_color(url)};"}]
    if mark_type == "comment":
        return [tag, {"data-mark": "comment",
                      "data-comment-id": attrs.get("id")}]
    return [tag]


def mark_flags_arrays() -> Tuple[Tuple[bool, ...], Tuple[bool, ...]]:
    """(inclusive flags, allow_multiple flags), indexed by ``MARK_INDEX``.

    Returned as plain tuples so callers can embed them as traced constants.
    """
    inclusive = tuple(MARK_SPEC[m].inclusive for m in ALL_MARKS)
    multiple = tuple(MARK_SPEC[m].allow_multiple for m in ALL_MARKS)
    return inclusive, multiple
