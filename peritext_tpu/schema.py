"""Declarative mark schema.

Mirrors the semantics the reference derives from its ProseMirror ``markSpec``
(reference ``src/schema.ts:45-96``): per-mark-type behavior flags that the CRDT
core consults.  The CRDT reads only:

* ``inclusive`` — whether the *end* of a span grows to absorb characters
  inserted at its right boundary (``src/micromerge.ts:651``).  Span starts never
  grow (``:650``).
* ``allow_multiple`` — whether concurrent marks of this type form a set
  (comments) or resolve last-writer-wins (strong/em/link)
  (``src/micromerge.ts:403-405``).

For the device path each mark type is interned to a stable small integer and
the flags become traced-constant arrays (:func:`mark_flags_arrays`), so the
schema compiles into the kernel rather than being branched on at runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple


@dataclass(frozen=True)
class MarkSchema:
    """Behavior of one mark type."""

    #: Does the span end grow to include text inserted at its right edge?
    inclusive: bool
    #: Multiple concurrent values coexist (set semantics) vs last-writer-wins.
    allow_multiple: bool
    #: Names of data attributes carried by the mark ("url", "id", ...).
    attr_keys: Tuple[str, ...] = field(default=())


#: The default schema, matching the reference's four mark types.
MARK_SPEC: Dict[str, MarkSchema] = {
    "strong": MarkSchema(inclusive=True, allow_multiple=False),
    "em": MarkSchema(inclusive=True, allow_multiple=False),
    "comment": MarkSchema(inclusive=False, allow_multiple=True, attr_keys=("id",)),
    "link": MarkSchema(inclusive=False, allow_multiple=False, attr_keys=("url",)),
}

#: Stable ordering for device-side integer encoding of mark types.
ALL_MARKS: Tuple[str, ...] = ("strong", "em", "comment", "link")

MARK_INDEX: Dict[str, int] = {name: i for i, name in enumerate(ALL_MARKS)}


def is_mark_type(s: str) -> bool:
    return s in MARK_SPEC


def mark_flags_arrays() -> Tuple[Tuple[bool, ...], Tuple[bool, ...]]:
    """(inclusive flags, allow_multiple flags), indexed by ``MARK_INDEX``.

    Returned as plain tuples so callers can embed them as traced constants.
    """
    inclusive = tuple(MARK_SPEC[m].inclusive for m in ALL_MARKS)
    multiple = tuple(MARK_SPEC[m].allow_multiple for m in ALL_MARKS)
    return inclusive, multiple
