"""String interning for the device boundary.

Actor IDs and mark attributes (urls, comment ids) are strings at the API
boundary but int32 on device.  Actor indices must preserve the reference's
*string* ordering (op IDs tie-break on lexicographic actor comparison,
reference src/micromerge.ts:1389-1403), so actor tables are built from the
full sorted actor set of a workload.  Attribute interning needs no ordering,
only per-id identity — except link URLs, whose winner is picked by op ID, not
URL order.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np


def content_hash32(s: str) -> int:
    """FNV-1a 32-bit over UTF-8 bytes — the cross-session identity of an
    interned string.  Interned IDS are session-local (they depend on arrival
    order); digests and other cross-session comparisons gather these content
    hashes through per-session id->hash tables instead, so two sessions that
    interned the same strings in different orders still agree."""
    h = 2166136261
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class Interner:
    """Bidirectional string <-> int32 table; index 0 is reserved for 'none'."""

    def __init__(self, strings: Iterable[str] = ()) -> None:
        self._to_int: Dict[str, int] = {}
        self._to_str: List[Optional[str]] = [None]
        self._hashes: Optional[np.ndarray] = None
        for s in strings:
            self.intern(s)

    def content_hashes(self) -> np.ndarray:
        """uint32 array mapping every interned id to its content hash (id 0,
        the reserved none slot, maps to 0).  Cached; rebuilt only after the
        table has grown."""
        n = len(self._to_str)
        if self._hashes is None or len(self._hashes) != n:
            self._hashes = np.asarray(
                [0 if s is None else content_hash32(s) for s in self._to_str],
                np.uint32,
            )
        return self._hashes

    def intern(self, s: str) -> int:
        idx = self._to_int.get(s)
        if idx is None:
            idx = len(self._to_str)
            self._to_int[s] = idx
            self._to_str.append(s)
        return idx

    def lookup(self, idx: int) -> Optional[str]:
        return self._to_str[idx]

    def get(self, s: str) -> Optional[int]:
        return self._to_int.get(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def __contains__(self, s: str) -> bool:
        return s in self._to_int


class OrderedActorTable(Interner):
    """Actor interner whose int ordering equals string ordering.

    Built from the complete actor set up front (sorted), so
    ``idx(a) < idx(b) iff a < b`` — the property the device's int32
    lexicographic op-ID comparison relies on.  ``intern`` of an unseen actor
    raises: growing the table could violate the order invariant; rebuild with
    the enlarged actor set instead (cheap, host-side).
    """

    def __init__(self, actors: Iterable[str]) -> None:
        super().__init__()
        for actor in sorted(set(actors)):
            Interner.intern(self, actor)

    def intern(self, s: str) -> int:
        idx = self.get(s)
        if idx is None:
            raise KeyError(
                f"Actor {s!r} not in the ordered actor table; rebuild the table "
                "with the full actor set (ordering must match string order)"
            )
        return idx
