"""String interning for the device boundary.

Actor IDs and mark attributes (urls, comment ids) are strings at the API
boundary but int32 on device.  Actor indices must preserve the reference's
*string* ordering (op IDs tie-break on lexicographic actor comparison,
reference src/micromerge.ts:1389-1403), so actor tables are built from the
full sorted actor set of a workload.  Attribute interning needs no ordering,
only per-id identity — except link URLs, whose winner is picked by op ID, not
URL order.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


class Interner:
    """Bidirectional string <-> int32 table; index 0 is reserved for 'none'."""

    def __init__(self, strings: Iterable[str] = ()) -> None:
        self._to_int: Dict[str, int] = {}
        self._to_str: List[Optional[str]] = [None]
        for s in strings:
            self.intern(s)

    def intern(self, s: str) -> int:
        idx = self._to_int.get(s)
        if idx is None:
            idx = len(self._to_str)
            self._to_int[s] = idx
            self._to_str.append(s)
        return idx

    def lookup(self, idx: int) -> Optional[str]:
        return self._to_str[idx]

    def get(self, s: str) -> Optional[int]:
        return self._to_int.get(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def __contains__(self, s: str) -> bool:
        return s in self._to_int


class OrderedActorTable(Interner):
    """Actor interner whose int ordering equals string ordering.

    Built from the complete actor set up front (sorted), so
    ``idx(a) < idx(b) iff a < b`` — the property the device's int32
    lexicographic op-ID comparison relies on.  ``intern`` of an unseen actor
    raises: growing the table could violate the order invariant; rebuild with
    the enlarged actor set instead (cheap, host-side).
    """

    def __init__(self, actors: Iterable[str]) -> None:
        super().__init__()
        for actor in sorted(set(actors)):
            Interner.intern(self, actor)

    def intern(self, s: str) -> int:
        idx = self.get(s)
        if idx is None:
            raise KeyError(
                f"Actor {s!r} not in the ordered actor table; rebuild the table "
                "with the full actor set (ordering must match string order)"
            )
        return idx
