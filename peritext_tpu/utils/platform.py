"""Platform pinning for virtual-mesh (CPU) runs.

The multi-chip paths are exercised on N virtual CPU devices
(``--xla_force_host_platform_device_count``), which requires two things to
happen *before any jax backend initializes*:

* the forced host device count must be in ``XLA_FLAGS`` (XLA parses it once,
  at CPU-client creation), and
* the default platform must be pinned to ``cpu`` at BOTH the env level
  (``JAX_PLATFORMS``) and the config level (``jax.config``) — a TPU plugin
  that pins ``jax_platforms`` at config level would otherwise override the
  env var, and an eager array created on the default backend would try to
  initialize the TPU client (which must never happen on a host whose
  libtpu/driver is broken: the CPU mesh does not need it).

The driver entry point and the scaling scripts share this logic; keep fixes
here so they reach all of them.  Two sites intentionally differ:
``tests/conftest.py`` hand-rolls the env part (it must run before pytest
imports anything else), and the fuzz CLI's ``--mesh`` mode honors an
existing ``JAX_PLATFORMS`` instead of forcing CPU (mesh fuzz may target real
chips).
"""

from __future__ import annotations

import contextlib
import os
import re
from typing import Iterator, List

_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=(\d+)")


def pin_cpu_platform(n_devices: int) -> List["object"]:
    """Persistently pin the process to CPU with >= ``n_devices`` virtual
    devices and return them.

    Mutates ``XLA_FLAGS`` / ``JAX_PLATFORMS`` / ``jax.config`` for the rest
    of the process (use :func:`cpu_platform` for a restoring variant).
    Raises ``RuntimeError`` if the count cannot be satisfied — which happens
    when a caller already initialized a jax backend, because XLA reads the
    forced count exactly once, at CPU-client creation.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = _COUNT_RE.search(flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        # An existing smaller count would otherwise win (the substring is
        # present, but too small) and guarantee failure below.
        os.environ["XLA_FLAGS"] = _COUNT_RE.sub(
            f"--xla_force_host_platform_device_count={n_devices}", flags
        )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # graftlint: boundary(config update after backend init raises version-dependent types; the devices check below decides)
        pass  # backends already initialized; devices check below decides

    devices = jax.devices("cpu")
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} CPU devices, have {len(devices)}; a jax "
            "backend initialized before the forced host device count was "
            "set — call this (or set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n_devices}) before "
            "any jax use"
        )
    return devices


@contextlib.contextmanager
def cpu_platform(n_devices: int) -> Iterator[List["object"]]:
    """Context manager: CPU default platform with >= ``n_devices`` virtual
    devices; eager arrays inside the block land on the first CPU device.

    Restores ``JAX_PLATFORMS`` / ``XLA_FLAGS`` / ``jax.config`` on exit so a
    healthy-TPU caller can keep using its chip after a CPU-mesh dryrun.
    (The CPU backend itself stays alive, so arrays created inside the block
    remain valid after exit.)
    """
    prev_env = os.environ.get("JAX_PLATFORMS")
    prev_flags = os.environ.get("XLA_FLAGS")

    import jax

    prev_cfg = getattr(jax.config, "jax_platforms", None)
    try:
        devices = pin_cpu_platform(n_devices)
        with jax.default_device(devices[0]):
            yield devices
    finally:
        if prev_env is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_env
        if prev_flags is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = prev_flags
        try:
            jax.config.update("jax_platforms", prev_cfg)
        except Exception:  # graftlint: boundary(best-effort restore mirrors pin_cpu_platform's tolerant update)
            pass
