"""The ONE power-of-two width spelling.

Every bucketed device path (padded stream widths, paged page-count groups,
cursor-axis widths, digest row buckets) rounds a dynamic count up to a
power of two so jax's compile cache is hit by a small logarithmic family of
shapes instead of one shape per exact count.  Before this module each site
spelled the same while-loop privately (``store/paged._pow2``,
``parallel/streaming._width_bucket``, ``ops/resolve.cursor_width_bucket``);
graftlint's ``bucket_fns`` config had to track the whole family by name.
Now they all delegate here and differ only in their floor.

The ragged layout (ops/ragged.py, store/ragged.py) deliberately imports
NOTHING from this module: its entire point is that per-doc true counts
reach the device as traced loop bounds under one compiled shape, so any
pow-2 rounding in ragged planning is a bug — enforced by graftlint PTL007.
"""

from __future__ import annotations


def next_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor).

    ``floor`` must itself be a power of two — it seeds the doubling walk,
    so a non-power seed would return non-power widths and silently fork the
    compile-cache bucket family.
    """
    if floor < 1 or (floor & (floor - 1)):
        raise ValueError(f"floor must be a positive power of two, got {floor}")
    w = floor
    while w < n:
        w *= 2
    return w
