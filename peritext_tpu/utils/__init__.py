"""Cross-cutting utilities: interning, tracing, metrics, checkpointing."""

from .interning import Interner, OrderedActorTable
from .shapes import next_pow2

__all__ = ["Interner", "OrderedActorTable", "next_pow2"]
