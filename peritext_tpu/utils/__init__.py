"""Cross-cutting utilities: interning, tracing, metrics, checkpointing."""

from .interning import Interner, OrderedActorTable

__all__ = ["Interner", "OrderedActorTable"]
