"""Scalar document oracle: the full Peritext/Micromerge semantics, in Python.

This is the framework's *specification layer*: a faithful, single-document
implementation of the reference CRDT (reference ``src/micromerge.ts``), used

1. as ground truth for differential testing of the batched TPU kernels, and
2. as the host-side engine for interactive (single-doc, editor-bridge) use,
   where exact incremental ``Patch`` streams are required.

The bulk path (:mod:`peritext_tpu.ops`) re-derives the same final states from
a packed op-table formulation; this class keeps the reference's incremental
materialized-gap representation because patch emission is defined against it.

Design notes / intentional deviations (see also core/spans.py docstring):

* Op IDs are ``(counter, actor)`` tuples; ordering is native tuple order
  (reference compareOpIds, src/micromerge.ts:1389-1403).
* Gap "sets" of mark ops are insertion-ordered dicts keyed by op ID.  The
  reference uses JS ``Set`` with object identity; op IDs are unique, so keying
  by ID is equivalent (and makes the end-anchor self-exclusion at
  src/micromerge.ts:1087-1093 explicit).
* removeMark patches for comments carry ``attrs: {"id"}`` so that patch
  consumers can remove exactly one comment; the reference omits attrs there
  (src/micromerge.ts:962) which makes comment removal unreplayable from
  patches.
* ``makeMap`` emits no patch, matching the reference's acknowledged gap
  (src/micromerge.ts:1167), and ``makeList`` hardcodes path ["text"] (:1165).
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

from ..schema import MARK_SPEC, is_mark_type
from .errors import CausalityError, IndexOutOfBounds, MissingObject, PeritextError
from .opids import HEAD, ROOT, ElemRef, ObjectId, OpId
from .spans import add_characters_to_spans, ops_to_marks
from .types import (
    AFTER,
    BEFORE,
    END_OF_TEXT,
    Boundary,
    Change,
    Clock,
    FormatSpan,
    InputOperation,
    MarkMap,
    Operation,
    Patch,
)

CONTENT_KEY = "text"

#: Gap set: insertion-ordered map from op ID to the mark op (add or remove).
MarkOpSet = Dict[OpId, Operation]


@dataclass
class ListItemMeta:
    """CRDT metadata for one list element (reference ListItemMetadata,
    src/micromerge.ts:341-357)."""

    elem_id: OpId
    value_id: OpId
    deleted: bool = False
    #: Mark ops governing the gap before/after this element; None = inherit
    #: from the closest materialized gap to the left.
    mark_ops_before: Optional[MarkOpSet] = None
    mark_ops_after: Optional[MarkOpSet] = None


@dataclass
class MapMeta:
    """CRDT metadata for a map object: LWW op ids per key + child object ids."""

    ops: Dict[str, OpId] = field(default_factory=dict)
    children: Dict[str, ObjectId] = field(default_factory=dict)


Metadata = Union[List[ListItemMeta], MapMeta]

Cursor = Dict[str, Any]  # {"objectId": ObjectId, "elemId": OpId}


class Doc:
    """A single collaborative document replica (reference class Micromerge)."""

    content_key = CONTENT_KEY

    def __init__(self, actor_id: Optional[str] = None) -> None:
        self.actor_id: str = actor_id if actor_id is not None else uuid.uuid4().hex
        self._seq: int = 0
        self._max_op: int = 0
        self.clock: Clock = {}
        self._objects: Dict[Any, Any] = {ROOT: {}}
        self._metadata: Dict[Any, Metadata] = {ROOT: MapMeta()}

    @classmethod
    def resume(cls, actor_id: str, ordered_changes) -> "Doc":
        """Reconstruct a replica AND resume its actor identity.

        ``apply_change`` alone rebuilds state but leaves the local sequence
        counter at zero (the reference behaves the same: ``this.seq`` only
        advances through ``change()``, src/micromerge.ts:566-577), so a
        replica restored by replay would mint colliding ``(actor, seq=1)``
        changes.  This constructor replays ``ordered_changes`` (already in a
        causally-valid order) and then continues the actor's own numbering —
        the event-sourcing restore path (checkpoint.py).
        """
        doc = cls(actor_id)
        for change in ordered_changes:
            doc.apply_change(change)
        doc._seq = doc.clock.get(actor_id, 0)
        return doc

    # ------------------------------------------------------------------
    # Public read API
    # ------------------------------------------------------------------

    @property
    def root(self) -> Dict[str, Any]:
        return self._objects[ROOT]

    def get_root(self) -> Dict[str, Any]:
        return self._objects[ROOT]

    def get_object_id_for_path(self, path) -> ObjectId:
        object_id: ObjectId = ROOT
        for path_elem in path:
            meta = self._metadata.get(object_id)
            if meta is None:
                raise MissingObject(f"No object at path {path!r}")
            if not isinstance(meta, MapMeta):
                raise PeritextError(f"Object {path_elem} in path {path!r} is a list")
            child = meta.children.get(path_elem)
            if child is None:
                raise MissingObject(f"Child not found: {path_elem} in {object_id!r}")
            object_id = child
        return object_id

    def get_text_with_formatting(self, path) -> List[FormatSpan]:
        """Flatten the document into contiguous spans of identically-formatted
        text (the "batch" read path, reference src/micromerge.ts:796-857)."""
        object_id = self.get_object_id_for_path(path)
        text = self._objects.get(object_id)
        metadata = self._metadata.get(object_id)
        if not isinstance(text, list) or not isinstance(metadata, list):
            raise PeritextError(f"Expected a list at object ID {object_id!r}")

        spans: List[FormatSpan] = []
        characters: List[str] = []
        marks: MarkMap = {}
        visible = 0

        for index, el in enumerate(metadata):
            # Formatting changes in the gap before this character come from the
            # "before" set of this element or the "after" set of the previous
            # one; "before" is later in gap order and takes precedence.
            new_marks: Optional[MarkMap] = None
            if el.mark_ops_before is not None:
                new_marks = ops_to_marks(el.mark_ops_before.values())
            elif index > 0 and metadata[index - 1].mark_ops_after is not None:
                new_marks = ops_to_marks(metadata[index - 1].mark_ops_after.values())

            if new_marks is not None:
                add_characters_to_spans(characters, marks, spans)
                characters = []
                marks = new_marks

            if not el.deleted:
                characters.append(text[visible])
                visible += 1

        add_characters_to_spans(characters, marks, spans)
        return spans

    def get_cursor(self, path, index: int) -> Cursor:
        object_id = self.get_object_id_for_path(path)
        return {
            "objectId": object_id,
            "elemId": self._get_list_element_id(object_id, index),
        }

    def resolve_cursor(self, cursor: Cursor) -> int:
        """Current visible index of a stable cursor; collapses left over
        tombstones (reference src/micromerge.ts:868-870)."""
        _, visible = self._find_list_element(cursor["objectId"], cursor["elemId"])
        return visible

    # ------------------------------------------------------------------
    # Local change generation (reference change(), src/micromerge.ts:566)
    # ------------------------------------------------------------------

    def change(self, ops: List[InputOperation]) -> Tuple[Change, List[Patch]]:
        """Convert index-based input operations into a new transactional
        Change, applying it locally; returns (change, patches).

        Input ops are validated *before* any state (seq/clock/doc) mutates, so
        a bad index or missing mark attrs raises cleanly and leaves the
        replica able to keep syncing.  (The reference advances seq first and
        can poison its replication stream on bad input.)"""
        self._validate_input_ops(ops)
        deps = dict(self.clock)
        self._seq += 1
        self.clock[self.actor_id] = self._seq

        change = Change(
            actor=self.actor_id,
            seq=self._seq,
            deps=deps,
            start_op=self._max_op + 1,
            ops=[],
        )
        patches: List[Patch] = []

        for input_op in ops:
            obj_id = self.get_object_id_for_path(input_op["path"])
            obj = self._objects.get(obj_id)
            if obj is None:
                raise MissingObject(f"Object doesn't exist: {obj_id!r}")

            action = input_op["action"]
            if isinstance(obj, list):
                if action == "insert":
                    self._input_insert(change, obj_id, input_op, patches)
                elif action == "delete":
                    self._input_delete(change, obj_id, input_op, patches)
                elif action in ("addMark", "removeMark"):
                    self._input_mark(change, obj_id, obj, input_op, patches)
                else:
                    raise PeritextError(f"Unsupported list op: {action}")
            else:
                if action in ("makeList", "makeMap", "del"):
                    _, ps = self._make_new_op(
                        change,
                        Operation(action=action, obj=obj_id, opid=(0, ""), key=input_op["key"]),
                    )
                    patches.extend(ps)
                elif action == "set":
                    _, ps = self._make_new_op(
                        change,
                        Operation(
                            action="set",
                            obj=obj_id,
                            opid=(0, ""),
                            key=input_op["key"],
                            value=input_op["value"],
                        ),
                    )
                    patches.extend(ps)
                else:
                    raise PeritextError(f"Not a list: {input_op['path']!r}")

        return change, patches

    def _validate_input_ops(self, ops: List[InputOperation]) -> None:
        """Reject malformed input before mutating anything.  Visible lengths
        evolve predictably across the batch (inserts add, deletes remove,
        marks don't change length), so bounds can be checked with a simple
        simulated length per list object."""
        lengths: Dict[Any, int] = {}
        created: Dict[Tuple[str, ...], str] = {}  # batch-local makeList/makeMap

        def resolve(path) -> Tuple[Any, int]:
            """(resolution key, visible length or -1 for maps), accounting for
            objects created earlier in this same batch."""
            pt = tuple(path)
            if pt in created:
                key = ("virtual", pt)
                if key not in lengths:
                    lengths[key] = 0 if created[pt] == "list" else -1
                return key, lengths[key]
            obj_id = self.get_object_id_for_path(path)
            if obj_id not in lengths:
                obj = self._objects.get(obj_id)
                if obj is None:
                    raise MissingObject(f"Object doesn't exist: {obj_id!r}")
                lengths[obj_id] = len(obj) if isinstance(obj, list) else -1
            return obj_id, lengths[obj_id]

        for input_op in ops:
            action = input_op["action"]
            obj_id, n = resolve(input_op["path"])
            is_list = n >= 0
            if action == "insert":
                if not is_list:
                    raise PeritextError(f"Not a list: {input_op['path']!r}")
                if not 0 <= input_op["index"] <= n:
                    raise IndexOutOfBounds(
                        f"Insert index {input_op['index']} out of bounds for length {n}"
                    )
                lengths[obj_id] = n + len(input_op["values"])
            elif action == "delete":
                if not is_list:
                    raise PeritextError(f"Not a list: {input_op['path']!r}")
                index, count = input_op["index"], input_op["count"]
                if index < 0 or count < 0 or index + count > n:
                    raise IndexOutOfBounds(
                        f"Delete [{index}, {index + count}) out of bounds for length {n}"
                    )
                lengths[obj_id] = n - count
            elif action in ("addMark", "removeMark"):
                if not is_list:
                    raise PeritextError(f"Not a list: {input_op['path']!r}")
                mark_type = input_op.get("markType")
                if mark_type is None or not is_mark_type(mark_type):
                    raise PeritextError(f"Unknown mark type: {mark_type}")
                start, end = input_op["startIndex"], input_op["endIndex"]
                if not (0 <= start < end <= n):
                    raise IndexOutOfBounds(
                        f"Mark range [{start}, {end}) invalid for length {n}"
                    )
                attrs = input_op.get("attrs") or {}
                required = MARK_SPEC[mark_type].attr_keys
                needs_attrs = action == "addMark" or mark_type == "comment"
                if needs_attrs:
                    for key in required:
                        if key not in attrs:
                            raise PeritextError(
                                f"{action} {mark_type} requires attr {key!r}"
                            )
            elif action in ("makeList", "makeMap", "set", "del"):
                if is_list:
                    raise PeritextError(f"Map operation on a list: {action}")
                if "key" not in input_op:
                    raise PeritextError(f"{action} requires a key")
                if action in ("makeList", "makeMap"):
                    child_path = tuple(input_op["path"]) + (input_op["key"],)
                    created[child_path] = "list" if action == "makeList" else "map"
            else:
                raise PeritextError(f"Unknown action: {action}")

    def _input_insert(self, change, obj_id, input_op, patches) -> None:
        index = input_op["index"]
        # Insert after the predecessor; peek past trailing tombstones carrying
        # span-end anchors so non-growing marks ending on a tombstone exclude
        # the new characters (reference :1351-1373).
        elem_ref: ElemRef = (
            HEAD
            if index == 0
            else self._get_list_element_id(obj_id, index - 1, look_after_tombstones=True)
        )
        for value in input_op["values"]:
            opid, ps = self._make_new_op(
                change,
                Operation(
                    action="set",
                    obj=obj_id,
                    opid=(0, ""),
                    elem_id=elem_ref,
                    insert=True,
                    value=value,
                ),
            )
            elem_ref = opid  # chain multi-char inserts
            patches.extend(ps)

    def _input_delete(self, change, obj_id, input_op, patches) -> None:
        # The delete index stays fixed: each iteration deletes the character
        # that slid into position `index` (reference :615-645).
        for _ in range(input_op["count"]):
            elem = self._get_list_element_id(obj_id, input_op["index"])
            _, ps = self._make_new_op(
                change, Operation(action="del", obj=obj_id, opid=(0, ""), elem_id=elem)
            )
            patches.extend(ps)

    def _input_mark(self, change, obj_id, obj, input_op, patches) -> None:
        action = input_op["action"]
        mark_type = input_op["markType"]
        if not is_mark_type(mark_type):
            raise PeritextError(f"Unknown mark type: {mark_type}")
        start_index, end_index = input_op["startIndex"], input_op["endIndex"]

        # Span starts never grow; ends grow iff the mark is "inclusive".
        # Growth is encoded purely in anchor choice (reference :650-682).
        start = Boundary(BEFORE, self._get_list_element_id(obj_id, start_index))
        if MARK_SPEC[mark_type].inclusive:
            if end_index < len(obj):
                end = Boundary(BEFORE, self._get_list_element_id(obj_id, end_index))
            else:
                end = Boundary(END_OF_TEXT)
        else:
            end = Boundary(AFTER, self._get_list_element_id(obj_id, end_index - 1))

        attrs = input_op.get("attrs")
        _, ps = self._make_new_op(
            change,
            Operation(
                action=action,
                obj=obj_id,
                opid=(0, ""),
                start=start,
                end=end,
                mark_type=mark_type,
                attrs=dict(attrs) if attrs is not None else None,
            ),
        )
        patches.extend(ps)

    def _make_new_op(self, change: Change, op: Operation) -> Tuple[OpId, List[Patch]]:
        self._max_op += 1
        op.opid = (self._max_op, self.actor_id)
        patches = self._apply_op(op)
        change.ops.append(op)
        return op.opid, patches

    # ------------------------------------------------------------------
    # Remote change application (reference applyChange, src/micromerge.ts:892)
    # ------------------------------------------------------------------

    def apply_change(self, change: Change) -> List[Patch]:
        last_seq = self.clock.get(change.actor, 0)
        if change.seq != last_seq + 1:
            raise CausalityError(
                f"Expected sequence number {last_seq + 1} from {change.actor}, got {change.seq}"
            )
        for actor, dep in (change.deps or {}).items():
            if self.clock.get(actor, 0) < dep:
                raise CausalityError(f"Missing dependency: change {dep} by actor {actor}")

        patches: List[Patch] = []
        for op in change.ops:
            patches.extend(self._apply_op(op))

        # Record the change as applied only after every op succeeded, so a
        # malformed change is never silently marked as delivered.  (Ops of a
        # well-formed change can't fail once the causality checks pass.)
        self.clock[change.actor] = change.seq
        self._max_op = max(self._max_op, change.start_op + len(change.ops) - 1)
        return patches

    # ------------------------------------------------------------------
    # Op application
    # ------------------------------------------------------------------

    def _apply_op(self, op: Operation) -> List[Patch]:
        metadata = self._metadata.get(op.obj)
        obj = self._objects.get(op.obj)
        if metadata is None or obj is None:
            raise MissingObject(f"Object does not exist: {op.obj!r}")

        if op.action == "makeMap":
            self._objects[op.opid] = {}
            self._metadata[op.opid] = MapMeta()
        elif op.action == "makeList":
            self._objects[op.opid] = []
            self._metadata[op.opid] = []

        if isinstance(metadata, list):
            if op.action == "set":
                if op.elem_id is None:
                    raise PeritextError("Must specify elemId when setting in a list")
                return self._apply_list_insert(op)
            if op.action == "del":
                if op.elem_id is None:
                    raise PeritextError("Must specify elemId when deleting in a list")
                return self._apply_list_delete(op)
            if op.action in ("addMark", "removeMark"):
                return self._apply_mark_op(op, metadata, obj)
            raise PeritextError(f"Unsupported op on list: {op.action}")

        # Map object: last-writer-wins per key by op ID (reference :1151-1175).
        key = op.key
        if op.action in ("addMark", "removeMark"):
            raise PeritextError("Can't add or remove marks on a map")
        if key is None:
            raise PeritextError("Must specify key for map operations")
        key_meta = metadata.ops.get(key)
        if key_meta is None or key_meta < op.opid:
            metadata.ops[key] = op.opid
            if op.action == "del":
                obj.pop(key, None)
            elif op.action == "makeList":
                obj[key] = self._objects[op.opid]
                metadata.children[key] = op.opid
                return [{"action": "makeList", "path": [CONTENT_KEY], "key": key}]
            elif op.action == "makeMap":
                # Matches the reference's acknowledged gap: no patch emitted.
                obj[key] = self._objects[op.opid]
                metadata.children[key] = op.opid
            elif op.action == "set":
                obj[key] = op.value
            else:
                raise PeritextError(f"Unsupported op on map: {op.action}")
        return []

    def _apply_list_insert(self, op: Operation) -> List[Patch]:
        """RGA insert-after-reference (reference applyListInsert, :1187-1245)."""
        meta = self._metadata[op.obj]
        obj = self._objects[op.obj]

        if op.elem_id is HEAD:
            index, visible = -1, 0
        else:
            index, visible = self._find_list_element(op.obj, op.elem_id)
        if index >= 0 and not meta[index].deleted:
            visible += 1
        index += 1

        # Convergence rule: skip right past elements whose elemId is greater
        # than the inserting op's ID, so concurrent inserts at one position
        # land in descending op-ID order on every replica (:1201-1208).
        while index < len(meta) and op.opid < meta[index].elem_id:
            if not meta[index].deleted:
                visible += 1
            index += 1

        meta.insert(index, ListItemMeta(elem_id=op.opid, value_id=op.opid))
        if not isinstance(op.value, str):
            raise PeritextError("Expected a string value inserted into text")
        obj.insert(visible, op.value)

        # New characters inherit the formatting active at their position.
        marks = ops_to_marks(self._closest_mark_ops_left(meta, index, BEFORE).values())
        return [
            {
                "path": [CONTENT_KEY],
                "action": "insert",
                "index": visible,
                "values": [op.value],
                "marks": marks,
            }
        ]

    def _apply_list_delete(self, op: Operation) -> List[Patch]:
        """Tombstone a list element (reference applyListUpdate, :1250-1297)."""
        index, visible = self._find_list_element(op.obj, op.elem_id)
        meta = self._metadata[op.obj][index]
        if not meta.deleted:
            meta.deleted = True
            self._objects[op.obj].pop(visible)
            return [
                {
                    "path": [CONTENT_KEY],
                    "action": "delete",
                    "index": visible,
                    "count": 1,
                }
            ]
        return []

    # -- mark op application (the Peritext span walk, reference :1002-1138) --

    def _apply_mark_op(self, op: Operation, metadata: List[ListItemMeta], obj: list) -> List[Patch]:
        patches: List[Patch] = []

        def emit(partial: Patch, end_index: int) -> None:
            # Suppress zero-width / beyond-visible patches; truncate overlong
            # ones (reference emitPatch, :1006-1022).  Flags are computed
            # before truncation, exactly as the reference does.
            patch = dict(partial)
            patch["endIndex"] = end_index
            not_zero_length = patch["endIndex"] > patch["startIndex"]
            affects_visible = patch["startIndex"] < len(obj)
            if patch["endIndex"] > len(obj):
                patch["endIndex"] = len(obj)
            if not_zero_length and affects_visible:
                patches.append(patch)

        op_intersects_item = False
        visible_index = 0
        partial: Optional[Patch] = None

        for index, el in enumerate(metadata):
            for side, prop in ((BEFORE, "mark_ops_before"), (AFTER, "mark_ops_after")):
                # Patch indices address visible characters: the gap after a
                # visible character maps to the next visible index.
                index_for_patch = (
                    visible_index + 1 if (side == AFTER and not el.deleted) else visible_index
                )
                gap: Optional[MarkOpSet] = getattr(el, prop)

                if op.start.kind == side and op.start.elem == el.elem_id:
                    # Start anchor: seed the gap from the closest set to the
                    # left if it isn't materialized, then add this op.
                    existing = (
                        gap
                        if gap is not None
                        else self._closest_mark_ops_left(metadata, index, side)
                    )
                    new_ops = dict(existing)
                    new_ops[op.opid] = op
                    setattr(el, prop, new_ops)
                    if ops_to_marks(existing.values()) != ops_to_marks(new_ops.values()):
                        partial = self._partial_patch(op, index_for_patch)
                    op_intersects_item = True

                elif op.end.kind == side and op.end.elem == el.elem_id:
                    # End anchor: materialize what's active to the right —
                    # everything inherited from the left minus this op.
                    if gap is None:
                        base = self._closest_mark_ops_left(metadata, index, side)
                        base.pop(op.opid, None)
                        setattr(el, prop, base)
                    if partial is not None:
                        emit(partial, index_for_patch)
                        partial = None
                    return patches

                elif op_intersects_item and gap is not None:
                    # Explicit intermediate gap inside the span: close any open
                    # patch segment at this boundary, add the op, and reopen a
                    # segment if visible formatting changed.
                    if partial is not None:
                        emit(partial, index_for_patch)
                        partial = None
                    new_ops = dict(gap)
                    new_ops[op.opid] = op
                    if ops_to_marks(gap.values()) != ops_to_marks(new_ops.values()):
                        partial = self._partial_patch(op, index_for_patch)
                    setattr(el, prop, new_ops)

            if not el.deleted:
                visible_index += 1

        # Span runs to endOfText (or past all materialized gaps): close at the
        # end of the visible sequence.
        if partial is not None:
            emit(partial, len(obj))
        return patches

    def _partial_patch(self, op: Operation, start_index: int) -> Patch:
        partial: Patch = {
            "action": op.action,
            "markType": op.mark_type,
            "path": [CONTENT_KEY],
            "startIndex": start_index,
        }
        if op.action == "addMark" and op.mark_type in ("link", "comment"):
            partial["attrs"] = dict(op.attrs)
        # Deviation from reference: carry the comment id on removeMark patches
        # so consumers can remove exactly that comment (see module docstring).
        if op.action == "removeMark" and op.mark_type == "comment" and op.attrs:
            partial["attrs"] = dict(op.attrs)
        return partial

    def _closest_mark_ops_left(
        self, metadata: List[ListItemMeta], index: int, side: str
    ) -> MarkOpSet:
        """The nearest materialized gap set at or left of (index, side),
        excluding that position itself; {} if none (reference :916-947).
        Always returns a fresh dict safe to mutate."""
        if side == AFTER and metadata[index].mark_ops_before is not None:
            return dict(metadata[index].mark_ops_before)
        for i in range(index - 1, -1, -1):
            if metadata[i].mark_ops_after is not None:
                return dict(metadata[i].mark_ops_after)
            if metadata[i].mark_ops_before is not None:
                return dict(metadata[i].mark_ops_before)
        return {}

    # ------------------------------------------------------------------
    # Element <-> index resolution
    # ------------------------------------------------------------------

    def _find_list_element(self, object_id: ObjectId, elem_id: ElemRef) -> Tuple[int, int]:
        """(metadata index, count of visible elements before it)."""
        meta = self._metadata.get(object_id)
        if not isinstance(meta, list):
            raise MissingObject(f"List object not found: {object_id!r}")
        visible = 0
        for index, el in enumerate(meta):
            if el.elem_id == elem_id:
                return index, visible
            if not el.deleted:
                visible += 1
        raise IndexOutOfBounds(f"List element not found: {elem_id!r}")

    def _get_list_element_id(
        self, object_id: ObjectId, index: int, look_after_tombstones: bool = False
    ) -> OpId:
        """Element ID of the index-th visible element.  With
        ``look_after_tombstones``, return instead the last trailing tombstone
        that carries a span-end ("after") anchor, so inserts land outside
        non-growing spans that end on a tombstone (reference :1334-1381)."""
        meta = self._metadata.get(object_id)
        if not isinstance(meta, list):
            raise MissingObject(f"List object not found: {object_id!r}")
        visible = -1
        for meta_index, el in enumerate(meta):
            if el.deleted:
                continue
            visible += 1
            if visible == index:
                if look_after_tombstones:
                    chosen = meta_index
                    peek = meta_index + 1
                    latest_after_tombstone: Optional[int] = None
                    while peek < len(meta) and meta[peek].deleted:
                        if meta[peek].mark_ops_after is not None:
                            latest_after_tombstone = peek
                        peek += 1
                    if latest_after_tombstone is not None:
                        chosen = latest_after_tombstone
                    return meta[chosen].elem_id
                return el.elem_id
        raise IndexOutOfBounds(f"List index out of bounds: {index}")

    # ------------------------------------------------------------------
    # Introspection for tests / debugging
    # ------------------------------------------------------------------

    def list_metadata(self, path=("text",)) -> List[ListItemMeta]:
        object_id = self.get_object_id_for_path(path)
        meta = self._metadata[object_id]
        assert isinstance(meta, list)
        return meta


#: Alias matching the reference's class name.
Micromerge = Doc
