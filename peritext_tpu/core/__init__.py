"""Scalar CRDT core: the specification layer of the framework."""

from .doc import Doc, ListItemMeta, MapMeta, Micromerge
from .errors import (
    CapacityExceeded,
    CausalityError,
    IndexOutOfBounds,
    MissingObject,
    PeritextError,
)
from .opids import HEAD, ROOT, OpId, compare_opids, format_opid, parse_opid
from .spans import add_characters_to_spans, ops_to_marks, spans_text
from .types import Boundary, Change, Clock, InputOperation, Operation, Patch, span

__all__ = [
    "Doc",
    "Micromerge",
    "ListItemMeta",
    "MapMeta",
    "Boundary",
    "Change",
    "Clock",
    "InputOperation",
    "Operation",
    "Patch",
    "span",
    "HEAD",
    "ROOT",
    "OpId",
    "compare_opids",
    "format_opid",
    "parse_opid",
    "add_characters_to_spans",
    "ops_to_marks",
    "spans_text",
    "PeritextError",
    "CausalityError",
    "IndexOutOfBounds",
    "MissingObject",
    "CapacityExceeded",
]
