"""Operation identifiers and sentinel objects for the CRDT core.

The reference encodes op IDs as strings ``"counter@actorId"`` and compares them
by counter first, then lexicographically by actor (reference:
``src/micromerge.ts:1389-1403``).  We represent them natively as tuples
``(counter, actor)`` so Python's tuple ordering *is* the CRDT ordering, and only
serialize to the string form at the JSON wire boundary.  On device, actor IDs
are interned to dense int32 indices so an op ID becomes an ``(int32, int32)``
lexicographic pair (see :mod:`peritext_tpu.utils.interning`).
"""

from __future__ import annotations

from typing import Tuple, Union

#: An operation identifier: ``(counter, actor_id)``.  Natural tuple ordering
#: matches the reference's ``compareOpIds``: counter first, then actor string.
OpId = Tuple[int, str]


class _Sentinel:
    """Unique singleton markers (compared by identity, like JS Symbols)."""

    __slots__ = ("_name",)

    def __init__(self, name: str) -> None:
        self._name = name

    def __repr__(self) -> str:
        return self._name

    # Sentinels sometimes end up in sorted containers next to opids; make them
    # hashable but never orderable so misuse fails loudly.
    def __hash__(self) -> int:
        return id(self)


#: The document root object (reference ``src/micromerge.ts:7``).
ROOT = _Sentinel("ROOT")
#: The virtual list head an insert at index 0 references (``:8``).
HEAD = _Sentinel("HEAD")

#: An object ID is the op ID of the op that created the object, or ROOT.
ObjectId = Union[OpId, _Sentinel]
#: A list-element reference: the op ID of the insert that created it, or HEAD.
ElemRef = Union[OpId, _Sentinel]

# Wire encodings used by the reference's JSON (`traces/*.json`): HEAD is a JS
# Symbol, dropped entirely by JSON.stringify, so "missing elemId" means HEAD.
_HEAD_WIRE = "_head"
_ROOT_WIRE = "_root"


def compare_opids(a: OpId, b: OpId) -> int:
    """Three-way compare, semantics of reference ``compareOpIds`` (:1389)."""
    if a == b:
        return 0
    return -1 if a < b else 1


def format_opid(opid: OpId) -> str:
    """``(3, "alice")`` -> ``"3@alice"`` (reference wire format)."""
    return f"{opid[0]}@{opid[1]}"


def parse_opid(s: str) -> OpId:
    """``"3@alice"`` -> ``(3, "alice")``.  Actor may itself contain ``@``."""
    counter, _, actor = s.partition("@")
    return (int(counter), actor)


def format_elem_ref(ref: ElemRef) -> str:
    if ref is HEAD:
        return _HEAD_WIRE
    return format_opid(ref)  # type: ignore[arg-type]


def parse_elem_ref(s: Union[str, None]) -> ElemRef:
    if s is None or s == _HEAD_WIRE:
        return HEAD
    return parse_opid(s)


def format_object_id(obj: ObjectId) -> str:
    if obj is ROOT:
        return _ROOT_WIRE
    return format_opid(obj)  # type: ignore[arg-type]


def parse_object_id(s: Union[str, None]) -> ObjectId:
    if s is None or s == _ROOT_WIRE:
        return ROOT
    return parse_opid(s)
