"""Exception types for the CRDT core."""


class PeritextError(Exception):
    """Base class for framework errors."""


class CausalityError(PeritextError):
    """A change arrived before its causal dependencies were satisfied
    (reference raises RangeError, src/micromerge.ts:894-902).  Delivery layers
    catch this and requeue the change (test/merge.ts:4-23)."""


class IndexOutOfBounds(PeritextError, IndexError):
    """A list index was outside the visible sequence
    (reference RangeError, src/micromerge.ts:1380)."""


class MissingObject(PeritextError):
    """An operation referenced an object that does not exist."""


class CapacityExceeded(PeritextError):
    """A packed device buffer (slots / mark table / op stream) overflowed its
    static capacity; callers should rebucket or fall back to the host path."""


class DecodeError(PeritextError, ValueError):
    """A wire frame failed decode or validation (truncated bytes, bit-flips,
    malformed varints, out-of-range indices, bad checksum).  Subclasses
    ValueError so every pre-existing ``except ValueError`` corrupt-frame
    handler keeps working; fault-domain code catches the typed form to
    quarantine the affected doc instead of failing the whole batch."""


class TransportError(PeritextError, ConnectionError):
    """A multihost transport operation failed after its timeout/retry budget
    (connect refused, peer stalled past the socket deadline, connection torn
    mid-message).  Subclasses ConnectionError so existing handlers keep
    working; carries no protocol state — the store is append-only and
    duplicate-tolerant, so the caller's next anti-entropy round repairs by
    re-shipping whatever the peer is still missing."""


class DeviceRoundError(PeritextError):
    """A guarded device round failed or overran its wall-clock deadline.
    The fault-domain supervisor translates this into a rollback to the last
    good checkpoint plus scalar-fallback replay (degraded but correct)."""
