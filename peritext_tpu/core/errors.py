"""Exception types for the CRDT core."""


class PeritextError(Exception):
    """Base class for framework errors."""


class CausalityError(PeritextError):
    """A change arrived before its causal dependencies were satisfied
    (reference raises RangeError, src/micromerge.ts:894-902).  Delivery layers
    catch this and requeue the change (test/merge.ts:4-23)."""


class IndexOutOfBounds(PeritextError, IndexError):
    """A list index was outside the visible sequence
    (reference RangeError, src/micromerge.ts:1380)."""


class MissingObject(PeritextError):
    """An operation referenced an object that does not exist."""


class CapacityExceeded(PeritextError):
    """A packed device buffer (slots / mark table / op stream) overflowed its
    static capacity; callers should rebucket or fall back to the host path."""
