"""Comment sidebar model (reference ``src/comment.ts`` + the ``comments`` map
the reference's ``RootDoc`` declares next to ``text``, src/bridge.ts:30-33).

The reference defines the type but no demo writes the map — comment *marks*
are the implemented half.  This framework implements both halves: comment
marks live in the CRDT mark engine (schema ``comment``, allow-multiple set
semantics), and this module stores the comment *bodies* in a nested CRDT map
``comments: {id: {id, actor, content}}`` so they replicate with the document
and resolve concurrent edits per-field by LWW, like any map entry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from .doc import Doc
from .types import Change, Patch

COMMENTS_KEY = "comments"


@dataclass(frozen=True)
class Comment:
    """One comment body (reference ``Comment``, src/comment.ts:5-12)."""

    id: str
    actor: str
    content: str


def put_comment(doc: Doc, comment: Comment) -> Tuple[Change, List[Patch]]:
    """Create or overwrite a comment body in the document's comments map.

    Creates the root ``comments`` map on first use; per-field sets mean
    concurrent edits to one comment converge field-wise by op-ID LWW.
    """
    ops = []
    if COMMENTS_KEY not in doc.root:
        ops.append({"path": [], "action": "makeMap", "key": COMMENTS_KEY})
    ops.append({"path": [COMMENTS_KEY], "action": "makeMap", "key": comment.id})
    path = [COMMENTS_KEY, comment.id]
    ops.extend(
        {"path": path, "action": "set", "key": k, "value": v}
        for k, v in (("id", comment.id), ("actor", comment.actor), ("content", comment.content))
    )
    return doc.change(ops)


def remove_comment(doc: Doc, comment_id: str) -> Tuple[Change, List[Patch]]:
    """Delete a comment body (the mark is removed separately via removeMark)."""
    return doc.change([{"path": [COMMENTS_KEY], "action": "del", "key": comment_id}])


def get_comment(doc: Doc, comment_id: str) -> Optional[Comment]:
    entry = doc.root.get(COMMENTS_KEY, {}).get(comment_id)
    if entry is None:
        return None
    return Comment(id=entry.get("id"), actor=entry.get("actor"), content=entry.get("content"))


def list_comments(doc: Doc) -> List[Comment]:
    """All comment bodies, id-sorted (deterministic across replicas)."""
    table = doc.root.get(COMMENTS_KEY, {})
    return [
        Comment(id=e.get("id"), actor=e.get("actor"), content=e.get("content"))
        for _, e in sorted(table.items())
    ]
