"""Core CRDT data types: changes, internal operations, boundaries, patches.

The *public* boundary of the framework is identical in shape to the
reference's (``src/micromerge.ts:191-199`` for input operations, ``:14-19`` for
patches): plain JSON-style dicts.  Input operations look like::

    {"action": "insert", "path": ["text"], "index": 3, "values": ["a", "b"]}
    {"action": "delete", "path": ["text"], "index": 3, "count": 2}
    {"action": "addMark", "path": ["text"], "startIndex": 1, "endIndex": 4,
     "markType": "link", "attrs": {"url": "https://..."}}
    {"action": "removeMark", ...}
    {"action": "makeList", "path": [], "key": "text"}
    {"action": "makeMap" | "set" | "del", ...}

and patches are the same index-based shapes flowing outward (insert patches
additionally carry ``marks``).  Internally, operations are anchored to stable
element IDs rather than indices, which is what makes them commutative.

``Change`` is the replication unit (reference ``src/micromerge.ts:67-78``): a
transactional batch of internal ops with vector-clock deps.  ``to_json`` /
``from_json`` speak the reference's exact wire format so recorded traces in
``/root/reference/traces/*.json`` replay directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .opids import (
    HEAD,
    ElemRef,
    ObjectId,
    OpId,
    format_elem_ref,
    format_object_id,
    format_opid,
    parse_elem_ref,
    parse_object_id,
    parse_opid,
)

#: Vector clock: actor id -> latest sequence number seen from that actor.
Clock = Dict[str, int]

# Boundary kinds (reference ``BoundaryPosition``, src/micromerge.ts:266-270).
BEFORE = "before"
AFTER = "after"
START_OF_TEXT = "startOfText"
END_OF_TEXT = "endOfText"


@dataclass(frozen=True)
class Boundary:
    """A mark anchor: one of the 2n+2 gaps around the character sequence."""

    kind: str  # BEFORE | AFTER | START_OF_TEXT | END_OF_TEXT
    elem: Optional[OpId] = None  # set iff kind is BEFORE/AFTER

    def to_json(self) -> Dict[str, Any]:
        if self.kind in (BEFORE, AFTER):
            return {"type": self.kind, "elemId": format_opid(self.elem)}
        return {"type": self.kind}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Boundary":
        kind = d["type"]
        if kind in (BEFORE, AFTER):
            return Boundary(kind, parse_opid(d["elemId"]))
        return Boundary(kind)


@dataclass
class Operation:
    """An internal, element-anchored operation (reference ``Operation``,
    src/micromerge.ts:309-317).  One dataclass covers all actions; unused
    fields stay None."""

    action: str  # "set" | "del" | "makeList" | "makeMap" | "addMark" | "removeMark"
    obj: ObjectId
    opid: OpId
    # map ops
    key: Optional[str] = None
    # list ops
    elem_id: Optional[ElemRef] = None
    insert: bool = False
    value: Any = None
    # mark ops
    start: Optional[Boundary] = None
    end: Optional[Boundary] = None
    mark_type: Optional[str] = None
    attrs: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "opId": format_opid(self.opid),
            "action": self.action,
            "obj": format_object_id(self.obj),
        }
        if self.key is not None:
            d["key"] = self.key
        if self.action in ("addMark", "removeMark"):
            d["start"] = self.start.to_json()
            d["end"] = self.end.to_json()
            d["markType"] = self.mark_type
            if self.attrs is not None:
                d["attrs"] = dict(self.attrs)
        elif self.insert:
            d["insert"] = True
            d["value"] = self.value
            # HEAD is omitted on the wire (the reference's HEAD is a JS Symbol
            # which JSON.stringify drops).
            if self.elem_id is not HEAD:
                d["elemId"] = format_elem_ref(self.elem_id)
        else:
            if self.elem_id is not None:
                d["elemId"] = format_elem_ref(self.elem_id)
            if self.action == "set":
                d["value"] = self.value
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Operation":
        action = d["action"]
        obj = parse_object_id(d.get("obj"))
        opid = parse_opid(d["opId"])
        if action in ("addMark", "removeMark"):
            return Operation(
                action=action,
                obj=obj,
                opid=opid,
                start=Boundary.from_json(d["start"]),
                end=Boundary.from_json(d["end"]),
                mark_type=d["markType"],
                attrs=dict(d["attrs"]) if "attrs" in d and d["attrs"] is not None else None,
            )
        if action in ("makeList", "makeMap") or ("key" in d and not d.get("insert")):
            # map-shaped op (set/del on a map also lands here via "key")
            op = Operation(action=action, obj=obj, opid=opid, key=d.get("key"))
            if action == "set":
                op.value = d.get("value")
            return op
        # list-shaped set/del
        if d.get("insert"):
            return Operation(
                action="set",
                obj=obj,
                opid=opid,
                elem_id=parse_elem_ref(d.get("elemId")),
                insert=True,
                value=d.get("value"),
            )
        return Operation(
            action=action,
            obj=obj,
            opid=opid,
            elem_id=parse_elem_ref(d.get("elemId")) if "elemId" in d else None,
            value=d.get("value"),
        )


@dataclass
class Change:
    """A transactional batch of ops from one actor (the replication unit)."""

    actor: str
    seq: int
    deps: Clock
    start_op: int
    ops: List[Operation] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "actor": self.actor,
            "seq": self.seq,
            "deps": dict(self.deps),
            "startOp": self.start_op,
            "ops": [op.to_json() for op in self.ops],
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Change":
        return Change(
            actor=d["actor"],
            seq=d["seq"],
            deps=dict(d.get("deps") or {}),
            start_op=d["startOp"],
            ops=[Operation.from_json(op) for op in d["ops"]],
        )


# ---------------------------------------------------------------------------
# Public-boundary shapes (kept as plain dicts; helpers for construction only).
# ---------------------------------------------------------------------------

Path = Tuple[str, ...]
InputOperation = Dict[str, Any]
Patch = Dict[str, Any]
MarkMap = Dict[str, Any]  # cleaned mark map, no op ids
FormatSpan = Dict[str, Any]  # {"text": str, "marks": MarkMap}


def span(text: str, marks: Optional[MarkMap] = None) -> FormatSpan:
    """Convenience constructor for expected-result literals in tests."""
    return {"marks": marks or {}, "text": text}
