"""Mark-set resolution and span flattening.

``ops_to_marks`` is the heart of convergence: it maps a *set* of mark
operations (in any order) to the resulting mark map, resolving conflicts by
op-ID comparison, so all replicas agree regardless of delivery order
(reference ``opsToMarks``, src/micromerge.ts:417-495).

Semantics, per mark type (driven by :mod:`peritext_tpu.schema`):

* ``strong``/``em`` — last-writer-wins boolean by max op ID; the key appears in
  the output only when the winner is an addMark.
* ``link`` — last-writer-wins whole value by max op ID.
* ``comment`` — per-id resolution: a comment id is present iff the max-op-ID
  operation carrying that id is an addMark.  Output is id-sorted.

Documented deviations from the reference (which this framework *fixes*; the
reference's own ``traces/`` record divergence in exactly these corners):

* Reference ``opsToMarks`` resolves comment add/remove in set-iteration order
  (insertion order, i.e. application order), which is replica-dependent; we use
  per-id LWW, which is order-independent (src/micromerge.ts:435-449).
* A "removed" link yields ``{"active": false}`` in the reference's cleaned
  output (src/micromerge.ts:489) while removed strong/em are omitted; we omit
  removed links too, and omit empty comment lists, so "no mark" has a single
  representation.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..schema import MARK_SPEC
from .types import FormatSpan, MarkMap, Operation


def ops_to_marks(ops: Iterable[Operation]) -> MarkMap:
    """Resolve a set of addMark/removeMark ops into a cleaned mark map."""
    # winners for LWW types: mark_type -> op; comments: id -> op
    lww_winner: Dict[str, Operation] = {}
    comment_winner: Dict[str, Operation] = {}

    for op in ops:
        mt = op.mark_type
        if mt is None:
            continue
        if MARK_SPEC[mt].allow_multiple:
            cid = op.attrs["id"]
            prev = comment_winner.get(cid)
            if prev is None or op.opid > prev.opid:
                comment_winner[cid] = op
        else:
            prev = lww_winner.get(mt)
            if prev is None or op.opid > prev.opid:
                lww_winner[mt] = op

    marks: MarkMap = {}
    for mt, op in lww_winner.items():
        if op.action != "addMark":
            continue
        if mt == "link":
            marks["link"] = {"active": True, "url": op.attrs["url"]}
        else:
            marks[mt] = {"active": True}

    active_ids = sorted(cid for cid, op in comment_winner.items() if op.action == "addMark")
    if active_ids:
        marks["comment"] = [{"id": cid} for cid in active_ids]

    return marks


def add_characters_to_spans(
    characters: List[str], marks: MarkMap, spans: List[FormatSpan]
) -> None:
    """Append characters with the given marks, merging into the last span when
    the formatting is identical (reference ``addCharactersToSpans``, :498)."""
    if not characters:
        return
    if spans and spans[-1]["marks"] == marks:
        spans[-1]["text"] += "".join(characters)
    else:
        spans.append({"marks": dict(marks), "text": "".join(characters)})


def spans_text(spans: Iterable[FormatSpan]) -> str:
    """Plain text of a span list."""
    return "".join(s["text"] for s in spans)


def copy_marks(marks: MarkMap) -> MarkMap:
    """One-level-deep copy of a flattened MarkMap (list-valued comment
    entries copied per item; scalar values passed through)."""
    out: MarkMap = {}
    for k, v in marks.items():
        if isinstance(v, list):
            out[k] = [dict(item) for item in v]
        elif isinstance(v, dict):
            out[k] = dict(v)
        else:
            out[k] = v
    return out


def spans_equal(a: List[FormatSpan], b: List[FormatSpan]) -> bool:
    return a == b


def chars_with_marks_to_spans(
    chars: Iterable[str], mark_maps: Iterable[Optional[MarkMap]]
) -> List[FormatSpan]:
    """Flatten parallel (char, marks) streams into merged spans."""
    spans: List[FormatSpan] = []
    for ch, m in zip(chars, mark_maps):
        add_characters_to_spans([ch], m or {}, spans)
    return spans
