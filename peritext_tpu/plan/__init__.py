"""Device-as-OS planner: the schedule is derived, not hand-picked.

Two halves of one idea (ROADMAP "Device-as-OS serving"):

* :mod:`.fusion` — deterministic cross-tenant fusion planning: which
  tenants share a device lane, at which doc-row bases, and which row
  extents a batching window stages (the serve tier's
  ``FusedMuxGroup`` executes these plans; ``plan/fusion.py`` itself is
  merge-scope — no wall clock, the assembled dispatch order must be a
  pure function of the committed window).
* :mod:`.model` + :mod:`.tuner` — the closed loop: a cost model over a
  devprof snapshot (bucket occupancy, XLA cost/memory analyses,
  page-pool fragmentation) proposes a typed :class:`~.tuner.PlanProposal`
  — bucket widths, slot capacity, page size, fused depth, admission
  window — minimizing modeled padded-FLOPs + recompiles under an
  executable-bytes budget.  ``python -m peritext_tpu.obs plan`` is the
  operator surface; the proposal is validated by replaying a bench row
  against the perf ledger, never trusted on model faith alone.
"""

from .fusion import FusionGroup, LanePlan, LaneSlot, TenantSpec
from .model import CostModel, load_devprof
from .tuner import PlanProposal, history_values, propose

__all__ = [
    "CostModel",
    "FusionGroup",
    "LanePlan",
    "LaneSlot",
    "PlanProposal",
    "TenantSpec",
    "history_values",
    "load_devprof",
    "propose",
]
