"""Cost model over a devprof snapshot: what the statics actually cost.

Every shape constant in the serving stack is a static someone once
hand-picked: the round stream widths (``round_*_capacity``), the slot
capacity, the P=64 page size, the fused depth ladder, the admission
window clamps.  PR 5's :mod:`~..obs.devprof` already measures what those
choices cost — per-site XLA cost/memory analyses keyed by shape bucket,
the bucket-occupancy (padding waste) tables, page-pool fragmentation —
so the model here is READ, not guessed: it parses one devprof snapshot
into the observed configuration plus enough per-term structure to score
a candidate configuration's modeled padded-FLOPs, recompile count, and
executable-bytes footprint.  :mod:`.tuner` searches candidates over it;
``python -m peritext_tpu.obs plan`` is the operator surface.

Wall-clock numbers appear only as data READ FROM the snapshot (this is
observability scope); nothing here reads a clock or touches a device.
"""

from __future__ import annotations

import json
import math
import re
from pathlib import Path
from typing import Any, Dict, Optional, Sequence

#: the bucket-occupancy key spelling (obs/devprof.occupancy_key)
OCC_KEY_RE = re.compile(
    r"^D(?P<docs>\d+)\.ki(?P<ki>\d+)\.kd(?P<kd>\d+)"
    r"\.km(?P<km>\d+)\.kp(?P<kp>\d+)$"
)

#: modeled FLOPs charged per padded op slot when the snapshot carries no
#: captured cost analyses (capture_costs off): the model still ranks
#: candidates by padded capacity, just in op units instead of FLOPs
DEFAULT_FLOPS_PER_OP = 1.0

#: per compiled variant: executable-bytes estimate used when the
#: snapshot's memory section can't price one (argument/temp bytes of the
#: biggest captured bucket stand in otherwise)
DEFAULT_EXECUTABLE_BYTES = 1 << 20

#: fraction of device memory the compiled-program cache may claim
DEFAULT_BUDGET_FRACTION = 0.10


def load_devprof(source: Any) -> Dict[str, Any]:
    """A devprof snapshot dict from a path, JSON string, or dict.

    Accepts the raw :meth:`~..obs.devprof.DeviceProfiler.snapshot` body,
    a ``/devprof.json`` scrape, or a ``/health.json``-style wrapper
    carrying a ``devprof`` key (the ``obs`` CLI loaders' discipline)."""
    if isinstance(source, (str, Path)):
        text = Path(source).read_text()
        snap = json.loads(text)
    elif isinstance(source, dict):
        snap = source
    else:
        raise TypeError(f"cannot load devprof from {type(source).__name__}")
    if not isinstance(snap, dict):
        raise ValueError("devprof snapshot must be a JSON object")
    if "sites" not in snap and isinstance(snap.get("devprof"), dict):
        snap = snap["devprof"]
    if "sites" not in snap or "occupancy" not in snap:
        raise ValueError(
            "not a devprof snapshot: missing 'sites'/'occupancy' sections"
        )
    return snap


def _pow2_at_least(n: int, floor: int = 1) -> int:
    cap = max(int(floor), 1)
    while cap < n:
        cap *= 2
    return cap


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Ceil-rank percentile over an ascending list (the history plane's
    convention, restated here so the plan tier stays import-free of obs)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
    return float(sorted_vals[min(idx, len(sorted_vals) - 1)])


class CostModel:
    """Deterministic scoring of serving configurations against one
    devprof snapshot.

    A configuration is the dict the tuner proposes over: ``insert_width``
    / ``delete_width`` / ``mark_width`` / ``map_width`` (the round stream
    widths), ``slot_capacity``, ``page_size``, ``fused_depth``, plus the
    optional ``shards`` (mesh device count on the doc axis).  The
    score is ``modeled padded-FLOPs + RECOMPILE_WEIGHT * recompiles``,
    with :meth:`executable_bytes` as the side constraint the tuner
    enforces.  Same snapshot -> same numbers, always: every term is
    arithmetic over the snapshot's own tables.
    """

    #: one recompile's score weight, in modeled-FLOP units.  An XLA
    #: compile of the staged apply costs seconds of wall — worth more
    #: than any single round's padded compute; calibrated against
    #: DISPATCH_WEIGHT so a deeper fused ladder pays for its extra
    #: variants once the capture shows tens of rounds to amortize over
    RECOMPILE_WEIGHT = 1e7
    #: one device dispatch's score weight (the ~11 ms/dispatch platform
    #: floor the fused pipeline exists to amortize), in modeled-FLOP
    #: units — this is what makes fused depth a real trade instead of
    #: "fewest variants always wins"
    DISPATCH_WEIGHT = 1e6

    def __init__(self, snapshot: Dict[str, Any],
                 occupancy_history: Optional[Sequence[float]] = None) -> None:
        self.snapshot = load_devprof(snapshot)
        #: observed per-window occupancy rows from the history plane's
        #: closed loop (FusedMuxGroup -> TimeSeriesPlane.record_occupancy
        #: -> propose(history=...)); empty means "snapshot point estimate
        #: only" and every term behaves exactly as before
        self.occupancy_history = sorted(
            float(v) for v in (occupancy_history or ())
        )
        occ = self.snapshot.get("occupancy") or {}
        self.rows = []
        for key in sorted(occ):
            m = OCC_KEY_RE.match(key)
            if not m:
                continue
            entry = occ[key]
            self.rows.append({
                "docs": int(m.group("docs")),
                "widths": (int(m.group("ki")), int(m.group("kd")),
                           int(m.group("km")), int(m.group("kp"))),
                "rounds": int(entry.get("rounds", 0)),
                "real_ops": int(entry.get("real_ops", 0)),
                "padded_capacity": int(entry.get("padded_capacity", 0)),
            })
        self.total_real_ops = sum(r["real_ops"] for r in self.rows)
        self.total_padded = sum(r["padded_capacity"] for r in self.rows)
        self.total_rounds = sum(r["rounds"] for r in self.rows)
        self._flops_per_op = self._derive_flops_per_op()

    # -- observed terms ----------------------------------------------------

    def _derive_flops_per_op(self) -> float:
        """Modeled FLOPs per padded op slot, from the captured XLA cost
        analyses when present (total modeled flops across apply-site
        buckets / total padded capacity), else the unit default."""
        flops = 0.0
        for site in sorted(self.snapshot.get("sites") or {}):
            buckets = (self.snapshot["sites"][site] or {}).get("buckets") or {}
            for key in sorted(buckets):
                cost = (buckets[key] or {}).get("cost") or {}
                f = cost.get("flops")
                if isinstance(f, (int, float)) and f > 0:
                    flops += float(f) * int(buckets[key].get("dispatches", 1))
        if flops > 0 and self.total_padded:
            return flops / self.total_padded
        return DEFAULT_FLOPS_PER_OP

    def observed_config(self) -> Dict[str, Any]:
        """The configuration the snapshot was captured UNDER — recovered
        from the snapshot itself (occupancy keys carry the widths; the
        page-pool section carries the page size), so the proposal's
        baseline is what actually ran, not what someone remembers
        configuring."""
        widths = max(
            (r["widths"] for r in self.rows), default=(64, 32, 32, 16),
        )
        # fused depth: the deepest round-chained dispatch the staged
        # sites saw (distinct shapes on the stacked/staged sites form the
        # R-ladder; depth itself isn't in the bucket key, so the ladder
        # size is the observable)
        sites = self.snapshot.get("sites") or {}
        fused_sites = [
            s for s in sites
            if "staged_rounds" in s or "stacked_rounds" in s
        ]
        fused_depth = 8 if fused_sites else 1
        pool = self.snapshot.get("page_pool") or {}
        cfg = {
            "insert_width": widths[0],
            "delete_width": widths[1],
            "mark_width": widths[2],
            "map_width": widths[3],
            "slot_capacity": self._observed_slot_capacity(),
            "page_size": int(pool.get("page_size", 64)),
            "fused_depth": fused_depth,
        }
        return cfg

    def _observed_slot_capacity(self) -> int:
        """Slot capacity from the page-pool section when paged (allocated
        slots per resident doc, pow-2), else a conservative pow-2 over
        the per-doc admitted insert estimate."""
        pool = self.snapshot.get("page_pool") or {}
        docs = int(pool.get("docs_resident", 0))
        if docs and pool.get("allocated_slots"):
            return _pow2_at_least(
                -(-int(pool["allocated_slots"]) // docs), 64,
            )
        per_doc = self._inserts_per_doc()
        return _pow2_at_least(int(per_doc * 2) or 64, 64)

    def _inserts_per_doc(self) -> float:
        """Estimated admitted inserts per doc over the capture: real ops
        attributed to the insert stream by width share, / docs."""
        ops = 0.0
        docs = 0
        for r in self.rows:
            k = sum(r["widths"])
            if k:
                ops += r["real_ops"] * (r["widths"][0] / k)
            docs = max(docs, r["docs"])
        return ops / docs if docs else 0.0

    def utilization(self) -> float:
        """The utilization estimate the width-shrink gate spends headroom
        against.  With occupancy history: the p90 of the observed
        per-window distribution — a width must survive the BUSY tail of
        real windows, not the quiet mean a single snapshot happened to
        catch.  Without history: real ops / padded capacity over the
        capture (the original point estimate)."""
        if self.occupancy_history:
            return _percentile(self.occupancy_history, 0.90)
        if not self.total_padded:
            return 1.0
        return self.total_real_ops / self.total_padded

    def occupancy_distribution(self) -> Dict[str, Any]:
        """The observed occupancy distribution the history-weighted terms
        cite: count, mean, p10/p50/p90, and the sparse-window fraction
        (occupancy < 0.5 — windows that under-amortize the dispatch
        floor)."""
        vals = self.occupancy_history
        if not vals:
            return {"count": 0}
        sparse = sum(1 for v in vals if v < 0.5)
        return {
            "count": len(vals),
            "mean": round(sum(vals) / len(vals), 6),
            "p10": _percentile(vals, 0.10),
            "p50": _percentile(vals, 0.50),
            "p90": _percentile(vals, 0.90),
            "sparse_frac": round(sparse / len(vals), 6),
        }

    def dispatch_weight_factor(self) -> float:
        """History weighting of the dispatch term: sparse windows ship
        the same ~11 ms dispatch floor for less useful work, so the floor
        counts ``1 + sparse_frac`` times when the observed distribution
        says most windows ran thin.  1.0 without history."""
        if not self.occupancy_history:
            return 1.0
        sparse = sum(1 for v in self.occupancy_history if v < 0.5)
        return 1.0 + sparse / len(self.occupancy_history)

    # -- candidate terms ---------------------------------------------------

    def padded_flops(self, config: Dict[str, Any]) -> float:
        """Modeled padded-FLOPs of replaying the capture under
        ``config``: each occupancy row's padded capacity rescaled by the
        candidate/observed total-width ratio (the (D, K) staging planes
        and the apply's per-slot scan both scale linearly in K), priced
        at the captured FLOPs-per-op."""
        k_new = (config["insert_width"] + config["delete_width"]
                 + config["mark_width"] + config["map_width"])
        total = 0.0
        for r in self.rows:
            k_old = sum(r["widths"])
            scale = (k_new / k_old) if k_old else 1.0
            total += r["padded_capacity"] * scale
        # the shard term: a mesh-sharded host splits the doc axis over
        # ``shards`` devices, so per-device padded compute divides while
        # the dispatch/recompile floors (paid once per shard_map program,
        # not per shard) stay whole
        shards = max(1, int(config.get("shards", 1)))
        return total * self._flops_per_op / shards

    def recompiles(self, config: Dict[str, Any]) -> int:
        """Modeled compiled-variant count under ``config``: one apply
        variant per distinct width set (the one-shape serving discipline
        keeps this 1), times the fused-depth ladder (a drain of R rounds
        compiles each depth 1..R it ever commits at — log2 ladder), plus
        the log2 slot-window ladder up to the slot capacity."""
        import math

        depth_ladder = int(math.log2(config["fused_depth"])) + 1
        slot_ladder = max(1, int(math.log2(max(config["slot_capacity"], 2))))
        return depth_ladder + slot_ladder

    def executable_bytes(self, config: Dict[str, Any]) -> int:
        """Modeled compiled-program cache footprint: variants x the
        per-variant executable estimate (peak captured bucket memory
        stands in for executable size when the snapshot has one)."""
        per = DEFAULT_EXECUTABLE_BYTES
        peaks = []
        for site in sorted(self.snapshot.get("sites") or {}):
            buckets = (self.snapshot["sites"][site] or {}).get("buckets") or {}
            for key in sorted(buckets):
                mem = (buckets[key] or {}).get("memory") or {}
                pb = mem.get("peak_bytes")
                if isinstance(pb, (int, float)) and pb > 0:
                    peaks.append(int(pb))
        if peaks:
            per = max(peaks)
        return self.recompiles(config) * per

    def memory_budget(self) -> Optional[int]:
        """The executable-bytes budget: a fraction of the device memory
        the snapshot observed in use at peak (None when the backend
        exposes no memory stats — the tuner then skips the constraint)."""
        mem = self.snapshot.get("memory") or {}
        peak = mem.get("peak_bytes_in_use")
        if isinstance(peak, (int, float)) and peak > 0:
            # peak observed use stands in for device capacity scale: the
            # cache may claim DEFAULT_BUDGET_FRACTION of 10x the peak
            return int(peak * 10 * DEFAULT_BUDGET_FRACTION)
        return None

    def dispatches(self, config: Dict[str, Any]) -> float:
        """Modeled dispatch count of replaying the capture's rounds at
        ``config``'s fused depth (a drain of R pending rounds is one
        staged program)."""
        depth = max(1, int(config["fused_depth"]))
        return -(-self.total_rounds // depth) if self.total_rounds else 0

    def score(self, config: Dict[str, Any]) -> float:
        return (self.padded_flops(config)
                + self.RECOMPILE_WEIGHT * self.recompiles(config)
                + (self.DISPATCH_WEIGHT * self.dispatch_weight_factor()
                   * self.dispatches(config)))
