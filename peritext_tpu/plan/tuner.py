"""The closed-loop half: search candidate configurations over the model.

``propose(snapshot)`` is a PURE function of its inputs: candidates come
from a deterministic pow-2 grid anchored at the observed configuration
(widths may shrink only while the measured utilization keeps a 2x safety
headroom; slot capacity may shrink only to a pow-2 still twice the
observed per-doc insert estimate; page size walks one pow-2 step either
way of the observed; fused depth walks the {1, 2, 4, 8} ladder), every
candidate is scored by :class:`~.model.CostModel` and filtered by the
executable-bytes budget, and ties break on the candidate tuple itself —
same snapshot (and ledger, and history), same :class:`PlanProposal`,
always.  ``history=`` closes the ROADMAP's occupancy feedback loop: pass
the occupancy rows the fused serving tier recorded into the history
plane (a live :class:`~..obs.timeseries.TimeSeriesPlane`, its snapshot
dict, its ``occupancy_rows`` list, or plain floats) and the model's
utilization gate and dispatch term are weighted by the observed
per-window occupancy DISTRIBUTION instead of the devprof point estimate
— ``modeled["history"]["weighted_terms"]`` names exactly which terms
moved, and ``obs plan`` prints them.  The
proposal is ADVICE with a paper trail, not an actuation: the validation
loop (scripts/plan_smoke.py, the CI plan-smoke job) replays a proposal
through a bench row and gates it against the perf ledger before anyone
re-pins a static.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .model import CostModel, load_devprof

#: proposals within this fractional score band of the current config are
#: "your statics are fine" — the CLI exits 0 inside it, 1 beyond it
DEFAULT_TOLERANCE = 0.10

#: shrink a stream width only while candidate capacity keeps this factor
#: over the observed real-op share (a too-tight width demotes docs to the
#: scalar fallback — correctness headroom is not the tuner's to spend)
WIDTH_HEADROOM = 2.0

#: the fused-depth ladder candidates walk (streaming.FUSE_MAX_ROUNDS caps
#: the top rung)
FUSED_DEPTHS = (1, 2, 4, 8)

#: admission-window clamps (serve.mux.BatchWindowTuner floor/ceiling)
WINDOW_FLOOR = 0.002
WINDOW_CEILING = 0.25
WINDOW_MARGIN = 1.0


@dataclass(frozen=True)
class PlanProposal:
    """One typed planner verdict: the proposed statics, the observed
    baseline they would replace, and the modeled terms that justify the
    trade.  ``to_json()`` is the golden-schema surface the CLI prints and
    tests pin."""

    insert_width: int
    delete_width: int
    mark_width: int
    map_width: int
    slot_capacity: int
    page_size: int
    fused_depth: int
    window_seconds: float
    current: Dict[str, Any] = field(default_factory=dict)
    modeled: Dict[str, Any] = field(default_factory=dict)

    def beats_current(self, tolerance: float = DEFAULT_TOLERANCE) -> bool:
        """Whether the proposal's modeled score improves on the current
        configuration's by MORE than the tolerance band — the "your
        statics are stale" signal (CLI exit 1)."""
        cur = self.modeled.get("current_score")
        new = self.modeled.get("proposed_score")
        if not cur or new is None:
            return False
        return (cur - new) / cur > tolerance

    def to_json(self) -> Dict[str, Any]:
        return {
            "proposal": {
                "insert_width": self.insert_width,
                "delete_width": self.delete_width,
                "mark_width": self.mark_width,
                "map_width": self.map_width,
                "slot_capacity": self.slot_capacity,
                "page_size": self.page_size,
                "fused_depth": self.fused_depth,
                "window_seconds": self.window_seconds,
            },
            "current": dict(self.current),
            "modeled": dict(self.modeled),
        }


def _pow2_down(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _width_candidates(model: CostModel, observed: Tuple[int, int, int, int],
                      ) -> List[Tuple[int, int, int, int]]:
    """Uniform pow-2 shrink factors of the observed widths, largest
    shrink first capped where utilization x headroom still fits: the
    serving discipline wants ONE width set (a per-kind mix would mint
    variant products), so candidates scale all four together."""
    util = model.utilization()
    out = [tuple(observed)]
    scale = 2
    while scale <= 8:
        cand = tuple(max(4, w // scale) for w in observed)
        k_old, k_new = sum(observed), sum(cand)
        if k_old and k_new / k_old < min(1.0, util * WIDTH_HEADROOM):
            break
        out.append(cand)
        scale *= 2
    return out


def _window_from_ledger(ledger_records: Optional[Sequence[Dict]]) -> float:
    """The admission window the BatchWindowTuner would pick, replayed
    from the ledger's serve rows: margin x the most recent serve row's
    per-frame seconds estimate, clamped like the tuner clamps.  No serve
    evidence -> the floor (lowest latency is the safe direction)."""
    p99 = None
    for rec in ledger_records or []:
        for row in rec.get("rows", []):
            name = row.get("row") or ""
            if not name.startswith("serve"):
                continue
            value, unit = row.get("value"), row.get("unit")
            if unit in ("docs/s", "ops/s") and isinstance(
                    value, (int, float)) and value > 0:
                p99 = 1.0 / value
    if p99 is None:
        return WINDOW_FLOOR
    return float(min(WINDOW_CEILING, max(WINDOW_FLOOR, WINDOW_MARGIN * p99)))


def history_values(history: Any) -> List[float]:
    """Normalize a ``propose(history=...)`` input to a flat list of
    per-window occupancy values.  Accepts None, a live
    :class:`~..obs.timeseries.TimeSeriesPlane` (or anything with
    ``occupancy_values()``), a plane SNAPSHOT dict (``occupancy_rows``),
    a sequence of row dicts (``occupancy`` key), or plain floats."""
    if history is None:
        return []
    fn = getattr(history, "occupancy_values", None)
    if callable(fn):
        return [float(v) for v in fn()]
    if isinstance(history, dict):
        rows = history.get("occupancy_rows") or ()
        return [float(r["occupancy"]) for r in rows]
    out: List[float] = []
    for item in history:
        if isinstance(item, dict):
            out.append(float(item["occupancy"]))
        else:
            out.append(float(item))
    return out


def propose(
    snapshot: Any,
    ledger_records: Optional[Sequence[Dict]] = None,
    *,
    budget_bytes: Optional[int] = None,
    tolerance: float = DEFAULT_TOLERANCE,
    history: Any = None,
) -> PlanProposal:
    """The planner: one deterministic :class:`PlanProposal` from one
    devprof snapshot (+ optional perf-ledger records for the admission
    window term, + optional occupancy ``history`` for distribution-
    weighted cost terms — see the module doc)."""
    occupancy = history_values(history)
    model = CostModel(load_devprof(snapshot), occupancy_history=occupancy)
    observed = model.observed_config()
    budget = budget_bytes if budget_bytes is not None else model.memory_budget()

    widths_obs = (observed["insert_width"], observed["delete_width"],
                  observed["mark_width"], observed["map_width"])
    width_cands = _width_candidates(model, widths_obs)
    slot_obs = observed["slot_capacity"]
    slot_need = _pow2_down(max(64, int(model._inserts_per_doc() * 2) or 64))
    slot_cands = sorted({slot_obs, max(64, min(slot_obs, slot_need))})
    page_obs = observed["page_size"]
    page_cands = (
        sorted({page_obs // 2, page_obs, page_obs * 2})
        if model.snapshot.get("page_pool") else [page_obs]
    )
    page_cands = [p for p in page_cands if p >= 8]

    best = None
    for widths in sorted(width_cands):
        for slot in slot_cands:
            for page in page_cands:
                for depth in FUSED_DEPTHS:
                    cand = {
                        "insert_width": widths[0],
                        "delete_width": widths[1],
                        "mark_width": widths[2],
                        "map_width": widths[3],
                        "slot_capacity": slot,
                        "page_size": page,
                        "fused_depth": depth,
                    }
                    if budget is not None and (
                            model.executable_bytes(cand) > budget):
                        continue
                    key = (model.score(cand), tuple(sorted(cand.items())))
                    if best is None or key < best[0]:
                        best = (key, cand)
    if best is None:
        # budget excludes everything: the observed config stands
        best = ((model.score(observed), ()), dict(observed))
    cand = best[1]
    window = _window_from_ledger(ledger_records)
    current_score = model.score(observed)
    proposed_score = model.score(cand)
    modeled = {
        "current_score": round(current_score, 2),
        "proposed_score": round(proposed_score, 2),
        "savings_frac": (
            round((current_score - proposed_score) / current_score, 4)
            if current_score else 0.0
        ),
        "padded_flops_current": round(model.padded_flops(observed), 2),
        "padded_flops_proposed": round(model.padded_flops(cand), 2),
        "recompiles_current": model.recompiles(observed),
        "recompiles_proposed": model.recompiles(cand),
        "dispatches_current": model.dispatches(observed),
        "dispatches_proposed": model.dispatches(cand),
        "executable_bytes": model.executable_bytes(cand),
        "budget_bytes": budget,
        "utilization": round(model.utilization(), 4),
        "tolerance": tolerance,
    }
    if occupancy:
        modeled["history"] = {
            "rows": len(occupancy),
            "occupancy": model.occupancy_distribution(),
            "dispatch_weight_factor": round(
                model.dispatch_weight_factor(), 4
            ),
            "weighted_terms": ["dispatch_cost", "utilization"],
        }
    return PlanProposal(
        insert_width=cand["insert_width"],
        delete_width=cand["delete_width"],
        mark_width=cand["mark_width"],
        map_width=cand["map_width"],
        slot_capacity=cand["slot_capacity"],
        page_size=cand["page_size"],
        fused_depth=cand["fused_depth"],
        window_seconds=round(window, 6),
        current=observed,
        modeled=modeled,
    )
