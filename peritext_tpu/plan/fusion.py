"""Cross-tenant fusion planning: tenants -> device lanes -> doc rows.

A serving host today drains each tenant's :class:`~..serve.mux.SessionMux`
session as its OWN staged device program — N tenants pay N dispatch
floors per batching window.  A :class:`FusionGroup` assigns many tenants
to shared ``static_rounds`` device lanes (one
:class:`~..parallel.streaming.StreamingMerge` per storage layout), each
tenant owning a DISJOINT doc-row range, so one window commits one staged
program per touched lane no matter how many tenants rode it.  Documents
are independent CRDTs and rows never alias, so per-tenant byte equality
with the unfused path holds by construction; cross-tenant isolation is a
row-range property, not a runtime check.

This module is MERGE SCOPE (``analysis.engine.LintConfig
.merge_scope_files``) even though it lives outside the merge
directories: the group assembly decides device dispatch order, and a
wall-clock or RNG read here would make the assembled program
replica-local — the exact bug class PTL006 exists for.  All wall-clock
ownership (window opening/closing, drain timing) stays in the serve
tier's ``FusedMuxGroup`` wiring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

#: storage layouts a lane may be built over (mirrors StreamingMerge)
LANE_LAYOUTS = ("padded", "paged", "ragged")


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's lane requirements: a stable name, its doc-slot
    budget, and the storage layout its sessions need."""

    tenant: str
    docs: int
    layout: str = "padded"

    def __post_init__(self):
        if not self.tenant:
            raise ValueError("tenant name must be non-empty")
        if self.docs <= 0:
            raise ValueError(f"tenant {self.tenant!r}: docs must be > 0")
        if self.layout not in LANE_LAYOUTS:
            raise ValueError(
                f"tenant {self.tenant!r}: unknown layout {self.layout!r}"
            )


@dataclass(frozen=True)
class LaneSlot:
    """A tenant's placement inside a lane: ``[doc_base, doc_base+docs)``
    of the lane session's doc axis belongs to exactly this tenant."""

    tenant: str
    lane: int
    layout: str
    doc_base: int
    docs: int


@dataclass(frozen=True)
class LanePlan:
    """One shared device lane: a ``static_rounds`` session of ``docs``
    total rows, tiled by the tenants in ``slots`` (base-ascending)."""

    lane: int
    layout: str
    docs: int
    slots: Tuple[LaneSlot, ...]

    def to_json(self) -> Dict:
        return {
            "lane": self.lane,
            "layout": self.layout,
            "docs": self.docs,
            "tenants": [s.tenant for s in self.slots],
        }


class FusionGroup:
    """Deterministic tenant -> (lane, doc_base) assignment plus the
    per-window doc-row extents the multi-tenant staged dispatch needs.

    Assignment is a pure function of the tenant specs: tenants sort by
    ``(layout, tenant)`` and first-fit pack into lanes of at most
    ``lane_capacity`` doc rows, one lane sequence per layout — two hosts
    given the same specs assemble byte-identical groups.  ``lane_capacity``
    bounds a lane's padded doc axis (its (D, K) staging planes are a real
    per-round host->device cost), not the tenant count.

    ``shard_rows`` aligns placement to a mesh-sharded lane session: a
    tenant's ``[doc_base, doc_base+docs)`` never straddles a multiple of
    ``shard_rows`` mid-shard (bases bump to the next shard boundary when
    the block would spill over), so one tenant's window drain touches
    whole shards or stays inside one — the shard_map fused commit never
    sees a tenant split unevenly across devices.
    """

    def __init__(self, tenants: Sequence[TenantSpec],
                 lane_capacity: int = 4096,
                 shard_rows: Optional[int] = None) -> None:
        if lane_capacity <= 0:
            raise ValueError(f"lane_capacity must be > 0, got {lane_capacity}")
        if shard_rows is not None:
            if shard_rows <= 0:
                raise ValueError(
                    f"shard_rows must be > 0, got {shard_rows}")
            if lane_capacity % shard_rows:
                raise ValueError(
                    f"lane_capacity {lane_capacity} must be a multiple of "
                    f"shard_rows {shard_rows}: a lane is a whole number of "
                    "mesh shards"
                )
        self.shard_rows = int(shard_rows) if shard_rows else None
        names = [t.tenant for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError("duplicate tenant names in fusion group")
        for t in tenants:
            if t.docs > lane_capacity:
                raise ValueError(
                    f"tenant {t.tenant!r} needs {t.docs} docs > "
                    f"lane_capacity {lane_capacity}"
                )
        self.lane_capacity = int(lane_capacity)
        lanes: list = []
        slots: Dict[str, LaneSlot] = {}
        # first-fit in sorted order: stable, and layout-grouped so a mixed
        # window touches one lane per layout present, not an interleaving
        open_lane: Dict[str, list] = {}
        for spec in sorted(tenants, key=lambda t: (t.layout, t.tenant)):
            cur = open_lane.get(spec.layout)
            base = self._aligned_base(cur[1], spec.docs) if cur else 0
            if cur is None or base + spec.docs > lane_capacity:
                cur = open_lane[spec.layout] = [len(lanes), 0, spec.layout, []]
                lanes.append(cur)
                base = 0
            slot = LaneSlot(
                tenant=spec.tenant, lane=cur[0], layout=spec.layout,
                doc_base=base, docs=spec.docs,
            )
            cur[1] = base + spec.docs
            cur[3].append(slot)
            slots[spec.tenant] = slot
        self.lanes: Tuple[LanePlan, ...] = tuple(
            LanePlan(lane=i, layout=layout, docs=docs, slots=tuple(ss))
            for i, docs, layout, ss in lanes
        )
        self.slots: Dict[str, LaneSlot] = slots

    def _aligned_base(self, used: int, docs: int) -> int:
        """The next doc base that keeps ``[base, base+docs)`` off a
        mid-shard boundary: within-shard when the block fits in the
        current shard's remainder, else bumped to the next multiple of
        ``shard_rows`` (multi-shard tenants always start on one)."""
        s = self.shard_rows
        if not s:
            return used
        off = used % s
        if off and off + docs > s:
            return used + (s - off)
        return used

    # -- lookups -----------------------------------------------------------

    def slot_of(self, tenant: str) -> LaneSlot:
        slot = self.slots.get(tenant)
        if slot is None:
            raise KeyError(f"unknown tenant {tenant!r}")
        return slot

    def lane_of(self, tenant: str) -> LanePlan:
        return self.lanes[self.slot_of(tenant).lane]

    # -- per-window assembly ----------------------------------------------

    def window_rows(
        self, lane: int, active: Sequence[str],
    ) -> Optional[Tuple[Tuple[int, ...], int]]:
        """The staged dispatch's doc-row extents for one window: the row
        bases of the ACTIVE tenants on ``lane`` (base-ascending — dispatch
        order is a function of placement, never of arrival) plus the
        uniform per-tenant block size.  Returns None when the active
        tenants' doc budgets differ — the multi-tenant staged form ships
        one ``(T, block_docs, ...)`` tensor set, so a ragged tenant mix
        falls back to full-lane staging (still one program)."""
        plan = self.lanes[lane]
        chosen = sorted(
            (self.slots[t] for t in set(active)),
            key=lambda s: s.doc_base,
        )
        for s in chosen:
            if s.lane != lane:
                raise ValueError(
                    f"tenant {s.tenant!r} is on lane {s.lane}, not {lane}"
                )
        if not chosen:
            return None
        block = chosen[0].docs
        if any(s.docs != block for s in chosen):
            return None
        if len(chosen) == len(plan.slots) and plan.docs == block * len(chosen):
            # every tenant active: full-lane staging is strictly cheaper
            # (no offset plane, shared compile with the stacked form)
            return None
        return tuple(s.doc_base for s in chosen), block

    def window_occupancy(self, lane: int, active: Sequence[str]) -> float:
        """Active doc rows / lane doc rows for one window (the fusion
        analog of the bucket-occupancy tables' padding efficiency)."""
        plan = self.lanes[lane]
        if not plan.docs:
            return 0.0
        live = sum(self.slots[t].docs for t in set(active))
        return live / plan.docs

    def to_json(self) -> Dict:
        return {
            "lanes": [p.to_json() for p in self.lanes],
            "tenants": len(self.slots),
            "lane_capacity": self.lane_capacity,
            "shard_rows": self.shard_rows,
        }
