"""Native host-runtime loader.

Compiles ``src/native.cpp`` into a shared library on first use (g++ is in the
image; there is no pybind11, so the boundary is a plain C ABI bound with
ctypes) and exposes typed wrappers.  The build is cached next to the source
keyed by a source hash; set ``PERITEXT_TPU_NO_NATIVE=1`` to force the pure
Python fallbacks (every native entry point has one — the native layer is an
accelerator, never a requirement).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).parent / "src" / "native.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[Path]:
    source = _SRC.read_bytes()
    tag = hashlib.sha256(source).hexdigest()[:16]
    out = _BUILD_DIR / f"libptnative-{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    # Unique tmp name per process: concurrent first-use builds (pytest
    # workers, shared FS) must not interleave writes before the atomic
    # rename installs the hash-keyed artifact.
    tmp = _BUILD_DIR / f".libptnative-{tag}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        tmp.unlink(missing_ok=True)
    return out


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PERITEXT_TPU_NO_NATIVE") == "1":
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.pt_causal_schedule.restype = ctypes.c_int32
        lib.pt_causal_schedule.argtypes = [
            ctypes.c_int32, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int32, i32p, i32p,
        ]
        lib.pt_varint_encode.restype = ctypes.c_int64
        lib.pt_varint_encode.argtypes = [i32p, ctypes.c_int64, u8p, ctypes.c_int64]
        lib.pt_varint_decode.restype = ctypes.c_int64
        lib.pt_varint_decode.argtypes = [u8p, ctypes.c_int64, i32p, ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def causal_schedule_indices(
    actor: np.ndarray,
    seq: np.ndarray,
    dep_off: np.ndarray,
    dep_actor: np.ndarray,
    dep_seq: np.ndarray,
    n_actors: int,
    base_clock: np.ndarray,
) -> Optional[np.ndarray]:
    """Native schedule; returns ordered change indices or None if no native."""
    lib = load()
    if lib is None:
        return None
    n = int(actor.shape[0])
    out = np.empty(n, np.int32)
    count = lib.pt_causal_schedule(
        n,
        np.ascontiguousarray(actor, np.int32),
        np.ascontiguousarray(seq, np.int32),
        np.ascontiguousarray(dep_off, np.int32),
        np.ascontiguousarray(dep_actor, np.int32),
        np.ascontiguousarray(dep_seq, np.int32),
        int(n_actors),
        np.ascontiguousarray(base_clock, np.int32),
        out,
    )
    return out[:count]


def varint_encode(values: np.ndarray) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.int32)
    cap = int(values.size) * 5 + 16
    out = np.empty(cap, np.uint8)
    written = lib.pt_varint_encode(values, int(values.size), out, cap)
    if written < 0:
        raise ValueError("varint encode overflow")
    return out[:written].tobytes()


def varint_decode(data: bytes, expected: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    out = np.empty(expected, np.int32)
    count = lib.pt_varint_decode(
        np.ascontiguousarray(buf), int(buf.size), out, expected
    )
    if count < 0 or count != expected:
        raise ValueError("malformed varint payload")
    return out
