"""Native host-runtime loader.

Compiles ``src/native.cpp`` into a shared library on first use (g++ is in the
image; there is no pybind11, so the boundary is a plain C ABI bound with
ctypes) and exposes typed wrappers.  The build is cached next to the source
keyed by a source hash; set ``PERITEXT_TPU_NO_NATIVE=1`` to force the pure
Python fallbacks (every native entry point has one — the native layer is an
accelerator, never a requirement).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_SRC = Path(__file__).parent / "src" / "native.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> Optional[Path]:
    source = _SRC.read_bytes()
    tag = hashlib.sha256(source).hexdigest()[:16]
    out = _BUILD_DIR / f"libptnative-{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    # Unique tmp name per process: concurrent first-use builds (pytest
    # workers, shared FS) must not interleave writes before the atomic
    # rename installs the hash-keyed artifact.
    tmp = _BUILD_DIR / f".libptnative-{tag}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        tmp.unlink(missing_ok=True)
    return out


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when unavailable/disabled."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PERITEXT_TPU_NO_NATIVE") == "1":
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.pt_causal_schedule.restype = ctypes.c_int32
        lib.pt_causal_schedule.argtypes = [
            ctypes.c_int32, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int32, i32p, i32p,
        ]
        lib.pt_varint_encode.restype = ctypes.c_int64
        lib.pt_varint_encode.argtypes = [i32p, ctypes.c_int64, u8p, ctypes.c_int64]
        lib.pt_varint_decode.restype = ctypes.c_int64
        lib.pt_varint_decode.argtypes = [u8p, ctypes.c_int64, i32p, ctypes.c_int64]
        lib.pt_parse_changes.restype = ctypes.c_int32
        lib.pt_parse_changes.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int32,  # vals, n_vals, n_changes
            i32p, ctypes.c_int32,  # str2actor, n_strings
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # actor_bits, max_ctr, version
            i32p, i32p,  # ch_actor, ch_seq
            i32p, i32p, i32p, ctypes.c_int64,  # dep_off, dep_actor, dep_seq, dep_cap
            i32p, i32p, ctypes.c_int64,  # ops_off, ops, op_cap
            i32p, i32p, i32p, i32p,  # cnt_ins, cnt_del, cnt_mark, cnt_map
        ]
        lib.pt_schedule_split_batch.restype = ctypes.c_int32
        lib.pt_schedule_split_batch.argtypes = (
            [ctypes.c_int32, ctypes.c_int32]  # n_docs, n_actors
            + [i32p] * 3  # ch_off, doc_row, text_obj
            + [i32p] * 2  # ch_actor, ch_seq
            + [i32p] * 3  # dep_off, dep_actor, dep_seq
            + [i32p] * 2  # ops_off, ops
            + [i32p]  # clock
            + [ctypes.c_int32] * 4  # ki, kd, km, kp
            + [i32p] * 12  # ins x3, del, marks x8
            + [i32p] * 5  # map stream x5
            + [i32p] * 5  # n_ins, n_del, n_mark, n_map, n_admitted
            + [u8p] * 2  # admitted, status
        )
        lib.pt_scalar_apply.restype = ctypes.c_int64
        lib.pt_scalar_apply.argtypes = [
            i32p, ctypes.c_int64,  # ops, n_ops
            i32p, ctypes.c_int64,  # out_text, out_cap
            i64p, i64p,  # out_visible, out_check
        ]
        lib.pt_parse_frames.restype = ctypes.c_int32
        lib.pt_parse_frames.argtypes = [
            u8p, i64p, ctypes.c_int32,  # data, frame_off, n_frames
            u8p, i64p, ctypes.c_int32,  # actor_bytes, actor_off, n_actors
            ctypes.c_int32, ctypes.c_int32,  # actor_bits, max_ctr
            i32p, i32p, i32p,  # f_status, f_ch_off, f_str_off
            i64p, i32p, ctypes.c_int64,  # str_start, str_len, str_cap
            i32p, i32p, ctypes.c_int64,  # ch_actor, ch_seq, ch_cap
            i32p, i32p, i32p, ctypes.c_int64,  # dep_off, dep_actor, dep_seq, dep_cap
            i32p, i32p, ctypes.c_int64,  # ops_off, ops, op_cap
            i32p, i32p, i32p, i32p,  # cnt_ins, cnt_del, cnt_mark, cnt_map
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def causal_schedule_indices(
    actor: np.ndarray,
    seq: np.ndarray,
    dep_off: np.ndarray,
    dep_actor: np.ndarray,
    dep_seq: np.ndarray,
    n_actors: int,
    base_clock: np.ndarray,
) -> Optional[np.ndarray]:
    """Native schedule; returns ordered change indices or None if no native."""
    lib = load()
    if lib is None:
        return None
    n = int(actor.shape[0])
    out = np.empty(n, np.int32)
    count = lib.pt_causal_schedule(
        n,
        np.ascontiguousarray(actor, np.int32),
        np.ascontiguousarray(seq, np.int32),
        np.ascontiguousarray(dep_off, np.int32),
        np.ascontiguousarray(dep_actor, np.int32),
        np.ascontiguousarray(dep_seq, np.int32),
        int(n_actors),
        np.ascontiguousarray(base_clock, np.int32),
        out,
    )
    return out[:count]


def _grow_capacities(call, dep_cap: int, op_cap: int, attempts: int = 12) -> int:
    """Run ``call(dep_cap, op_cap)`` (which allocates its outputs and returns
    the native rc), doubling whichever capacity the parser reports exhausted
    (-2 deps, -3 ops).  Wire-v2 elided headers emit dep entries from ZERO
    payload ints, so output sizes are no longer payload-bounded and a fixed
    cap can legitimately fall short.  Raises on exhaustion — a capacity
    condition, distinct from frame corruption."""
    rc = None
    for _ in range(attempts):
        rc = call(dep_cap, op_cap)
        if rc == -2:
            dep_cap *= 2
        elif rc == -3:
            op_cap *= 2
        else:
            return rc
    raise RuntimeError(
        f"native parse output capacity exhausted after {attempts} growth "
        f"attempts (rc={rc})"
    )


def parse_changes(
    values: np.ndarray,
    n_changes: int,
    str2actor: np.ndarray,
    actor_bits: int,
    max_ctr: int,
    version: int = 1,
):
    """Native frame-payload parse (see pt_parse_changes in native.cpp).

    Returns ``(ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops,
    cnt_ins, cnt_del, cnt_mark, cnt_map)`` with ``ops`` shaped (n_ops, 10),
    or None when the native library is unavailable.  Raises ValueError on a
    malformed payload.
    """
    lib = load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.int32)
    str2actor = np.ascontiguousarray(str2actor, np.int32)
    n = int(n_changes)
    # v2 elided headers can emit dep entries from zero wire ints (see
    # parse_frames): start from an estimate and grow on capacity returns
    dep_cap = int(values.size) // 2 + 1 + 4 * (n + 1)
    op_cap = int(values.size) // 2 + 1
    ch_actor = np.empty(n, np.int32)
    ch_seq = np.empty(n, np.int32)
    dep_off = np.empty(n + 1, np.int32)
    ops_off = np.empty(n + 1, np.int32)
    cnt_ins = np.empty(n, np.int32)
    cnt_del = np.empty(n, np.int32)
    cnt_mark = np.empty(n, np.int32)
    cnt_map = np.empty(n, np.int32)
    out = {}

    def call(dc, oc):
        out["dep_actor"] = np.empty(dc, np.int32)
        out["dep_seq"] = np.empty(dc, np.int32)
        out["ops"] = np.empty((oc, 10), np.int32)
        return lib.pt_parse_changes(
            values, int(values.size), n,
            str2actor, int(str2actor.size),
            int(actor_bits), int(max_ctr), int(version),
            ch_actor, ch_seq,
            dep_off, out["dep_actor"], out["dep_seq"], dc,
            ops_off, out["ops"].reshape(-1), oc,
            cnt_ins, cnt_del, cnt_mark, cnt_map,
        )

    rc = _grow_capacities(call, dep_cap, op_cap)
    dep_actor, dep_seq, ops = out["dep_actor"], out["dep_seq"], out["ops"]
    if rc != 0:
        raise ValueError(f"malformed change frame payload (native rc={rc})")
    n_deps = int(dep_off[n])
    n_ops = int(ops_off[n])
    return (
        ch_actor, ch_seq,
        dep_off, dep_actor[:n_deps].copy(), dep_seq[:n_deps].copy(),
        ops_off, ops[:n_ops].copy(),
        cnt_ins, cnt_del, cnt_mark, cnt_map,
    )


def parse_frames(
    data: np.ndarray,  # concatenated frame bytes, uint8
    frame_off: np.ndarray,  # (F+1,) int64 byte offsets
    header_counts,  # (n_changes_total, n_strings_total, n_ints_total) from headers
    actor_strings,  # declared actor names in interner order (index i -> id i+1)
    actor_bits: int,
    max_ctr: int,
):
    """Bulk whole-frame parse (see pt_parse_frames in native.cpp).

    Returns ``(f_status, f_ch_off, f_str_off, str_start, str_len, ch_actor,
    ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops, cnt_ins, cnt_del,
    cnt_mark, cnt_map)`` with all change/dep/op arrays flattened across
    frames and trimmed to their true lengths, or None when no native
    library.  Corrupt frames are reported per frame via ``f_status`` (1),
    never an exception.
    """
    lib = load()
    if lib is None:
        return None
    n_frames = int(frame_off.shape[0]) - 1
    ch_total, str_total, ints_total = (int(x) for x in header_counts)
    raw = [s.encode("utf-8") for s in actor_strings]
    actor_bytes = np.frombuffer(b"".join(raw) or b"\x00", np.uint8)
    actor_off = np.concatenate(
        [[0], np.cumsum([len(r) for r in raw], dtype=np.int64)]
    ).astype(np.int64)

    # v2 DEPS_SAME / elided-own-dep headers emit dep entries from ZERO wire
    # ints, so dep output is no longer bounded by the payload size — start
    # from a realistic estimate and grow on a capacity return.
    dep_cap = ints_total // 2 + 2 + 4 * (ch_total + 1)
    op_cap = ints_total // 2 + 2
    str_cap = str_total + 1
    f_status = np.empty(n_frames, np.int32)
    f_ch_off = np.empty(n_frames + 1, np.int32)
    f_str_off = np.empty(n_frames + 1, np.int32)
    str_start = np.empty(str_cap, np.int64)
    str_len = np.empty(str_cap, np.int32)
    ch_actor = np.empty(ch_total + 1, np.int32)
    ch_seq = np.empty(ch_total + 1, np.int32)
    dep_off = np.empty(ch_total + 2, np.int32)
    ops_off = np.empty(ch_total + 2, np.int32)
    cnt_ins = np.empty(ch_total + 1, np.int32)
    cnt_del = np.empty(ch_total + 1, np.int32)
    cnt_mark = np.empty(ch_total + 1, np.int32)
    cnt_map = np.empty(ch_total + 1, np.int32)

    out = {}

    def call(dc, oc):
        out["dep_actor"] = np.empty(dc, np.int32)
        out["dep_seq"] = np.empty(dc, np.int32)
        out["ops"] = np.empty((oc, 10), np.int32)
        return lib.pt_parse_frames(
            np.ascontiguousarray(data), np.ascontiguousarray(frame_off, np.int64),
            n_frames,
            np.ascontiguousarray(actor_bytes), actor_off, len(raw),
            int(actor_bits), int(max_ctr),
            f_status, f_ch_off, f_str_off,
            str_start, str_len, str_cap,
            ch_actor, ch_seq, ch_total + 1,
            dep_off, out["dep_actor"], out["dep_seq"], dc,
            ops_off, out["ops"].reshape(-1), oc,
            cnt_ins, cnt_del, cnt_mark, cnt_map,
        )

    rc = _grow_capacities(call, dep_cap, op_cap)
    dep_actor, dep_seq, ops = out["dep_actor"], out["dep_seq"], out["ops"]
    if rc != 0:  # non-capacity rc: sizing bug — surface loudly, don't mis-parse
        raise RuntimeError(f"pt_parse_frames capacity error rc={rc}")
    nc = int(f_ch_off[n_frames])
    ns = int(f_str_off[n_frames])
    n_deps = int(dep_off[nc]) if nc else 0
    n_ops = int(ops_off[nc]) if nc else 0
    return (
        f_status, f_ch_off, f_str_off,
        str_start[:ns], str_len[:ns],
        ch_actor[:nc], ch_seq[:nc],
        dep_off[: nc + 1], dep_actor[:n_deps].copy(), dep_seq[:n_deps].copy(),
        ops_off[: nc + 1], ops[:n_ops].copy(),
        cnt_ins[:nc], cnt_del[:nc], cnt_mark[:nc], cnt_map[:nc],
    )


def schedule_split_batch(
    n_actors: int,
    ch_off: np.ndarray,
    doc_row: np.ndarray,
    text_obj: np.ndarray,
    parsed_cols,  # (ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops)
    clock: np.ndarray,  # (n_docs, n_actors) int32, updated in place
    caps,  # (ki, kd, km, kp)
    ins_arrays,  # (ins_ref, ins_op, ins_char) each (D, ki) int32
    del_array: np.ndarray,  # (D, kd)
    mark_arrays,  # dict of 8 (D, km) arrays in MARK_COLS order
    map_arrays,  # dict of 5 (D, kp) arrays in MAP_STREAM_COLS order
):
    """One-call round scheduling for every frame-mode doc (see
    pt_schedule_split_batch).  Returns ``(total, n_ins, n_del, n_mark,
    n_map, n_admitted, admitted, status)`` or None when no native library."""
    lib = load()
    if lib is None:
        return None
    n_docs = int(ch_off.shape[0]) - 1
    ch_actor, ch_seq, dep_off, dep_actor, dep_seq, ops_off, ops = parsed_cols
    n_changes = int(ch_actor.shape[0])
    n_ins = np.zeros(n_docs, np.int32)
    n_del = np.zeros(n_docs, np.int32)
    n_mark = np.zeros(n_docs, np.int32)
    n_map = np.zeros(n_docs, np.int32)
    n_admitted = np.zeros(n_docs, np.int32)
    admitted = np.zeros(n_changes, np.uint8)
    status = np.zeros(n_docs, np.uint8)
    c = lambda a: np.ascontiguousarray(a, np.int32)  # noqa: E731
    total = lib.pt_schedule_split_batch(
        n_docs, int(n_actors),
        c(ch_off), c(doc_row), c(text_obj),
        c(ch_actor), c(ch_seq),
        c(dep_off), c(dep_actor), c(dep_seq),
        c(ops_off), c(ops).reshape(-1),
        clock,
        int(caps[0]), int(caps[1]), int(caps[2]), int(caps[3]),
        ins_arrays[0], ins_arrays[1], ins_arrays[2],
        del_array,
        mark_arrays["m_action"], mark_arrays["m_type"],
        mark_arrays["m_start_kind"], mark_arrays["m_start_elem"],
        mark_arrays["m_end_kind"], mark_arrays["m_end_elem"],
        mark_arrays["m_op"], mark_arrays["m_attr"],
        map_arrays["p_obj"], map_arrays["p_key"], map_arrays["p_op"],
        map_arrays["p_kind"], map_arrays["p_val"],
        n_ins, n_del, n_mark, n_map, n_admitted,
        admitted, status,
    )
    return total, n_ins, n_del, n_mark, n_map, n_admitted, admitted, status


def scalar_apply(ops: np.ndarray):
    """Single-core scalar baseline apply (see pt_scalar_apply): ops is the
    (N, 10) parsed op matrix in causal application order.  Returns
    ``(applied, visible_codepoints)`` or None when no native library."""
    lib = load()
    if lib is None:
        return None
    ops = np.ascontiguousarray(ops, np.int32)
    cap = int(ops.shape[0]) + 8
    out_text = np.empty(cap, np.int32)
    out_visible = np.zeros(1, np.int64)
    out_check = np.zeros(1, np.int64)
    applied = lib.pt_scalar_apply(
        ops.reshape(-1), int(ops.shape[0]), out_text, cap, out_visible, out_check
    )
    return int(applied), out_text[: int(out_visible[0])].copy()


def varint_encode(values: np.ndarray) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, np.int32)
    cap = int(values.size) * 5 + 16
    out = np.empty(cap, np.uint8)
    written = lib.pt_varint_encode(values, int(values.size), out, cap)
    if written < 0:
        raise ValueError("varint encode overflow")
    return out[:written].tobytes()


def varint_decode(data: bytes, expected: int) -> Optional[np.ndarray]:
    lib = load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    out = np.empty(expected, np.int32)
    count = lib.pt_varint_decode(
        np.ascontiguousarray(buf), int(buf.size), out, expected
    )
    if count < 0 or count != expected:
        raise ValueError("malformed varint payload")
    return out
