// Native host runtime for peritext-tpu.
//
// The TPU owns op application (JAX/XLA kernels); the host owns the
// irregular work around it.  Two of those paths are hot enough at pod scale
// to be native (SURVEY §5.8: host-side causal scheduling runs per document
// per round; the wire codec runs per change batch on every DCN hop):
//
//  1. pt_causal_schedule — deterministic topological schedule of a change
//     set against a vector clock (the C++ twin of
//     peritext_tpu/parallel/causal.py::causal_schedule; the reference's
//     catch-and-requeue loop is test/merge.ts:4-23).
//  2. pt_varint_encode / pt_varint_decode — zigzag-varint packing of int32
//     streams, the payload core of the binary change-frame codec
//     (peritext_tpu/parallel/codec.py).
//
// Plain C ABI throughout: the Python side binds with ctypes (no pybind11 in
// the image), and everything crossing the boundary is int32/uint8 arrays.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {
inline int64_t key_of(int32_t actor, int32_t seq) {
    return (static_cast<int64_t>(actor) << 32) | static_cast<uint32_t>(seq);
}

// ---- wire v2 change/op walk (codec.py is the format's reference) ---------
//
// v2 delta-encodes against frame-scoped context so the hot shapes cost a
// few bytes/op: change headers carry a combo int (actor strid << 4 | flags
// eliding dseq/dstart/deps/nops), dep sets transmit only changed vector
// clock entries, op ids/objects/insert-refs elide behind per-op flags, and
// explicit element counters are deltas against the op's own counter.
// This struct is the decoder's running context (one per frame).
struct WireV2Ctx {
    // change-header state, indexed by frame string id
    std::vector<int32_t> last_seq, prev_end, dep_base;
    std::vector<uint8_t> own_elided, has_dep_set;
    std::vector<std::vector<std::pair<int32_t, int32_t>>> dep_set;  // (strid, seq)
    // duplicate-dep detection scratch (epoch-stamped, O(1) reset per change)
    std::vector<int32_t> dep_seen;
    int32_t dep_epoch = 0;
    // op state
    bool has_prev_op = false;
    int32_t prev_obj = 0;      // packed (-1 ROOT)
    bool prev_obj_bad = false;
    int32_t prev_opid = 0;     // packed
    bool prev_opid_bad = false;
    explicit WireV2Ctx(int32_t n_strings)
        : last_seq(n_strings, 0), prev_end(n_strings, 0), dep_base(n_strings, 0),
          own_elided(n_strings, 0), has_dep_set(n_strings, 0),
          dep_set(n_strings), dep_seen(n_strings, -1) {}
};

// v2 per-op flags (codec.py _F_*)
constexpr int32_t kFOpidSeq = 1, kFObjPrev = 2, kFRefPrev = 4, kFRefHead = 8;
// v2 change-header flags (codec.py _H_*)
constexpr int32_t kHDseqZero = 1, kHDstartZero = 2, kHDepsSame = 4, kHNopsOne = 8;
// internal op-row kind for a native-decoded makeList (codec v2 encodes the
// doc's makeList as map-op kind 5 with flag kFRefHead instead of a JSON
// spillover; the Python ingest layer adopts it exactly like the JSON form)
constexpr int32_t kKindMakeList = 7;

// Output sinks + cursors shared by the two entry points (single-frame
// parse writes from 0; bulk parse appends at its global cursors).
struct WireOut {
    int32_t* ch_actor; int32_t* ch_seq;
    int32_t* dep_off; int32_t* dep_actor; int32_t* dep_seq; int64_t dep_cap;
    int32_t* ops_off; int32_t* ops; int64_t op_cap;
    int32_t* cnt_ins; int32_t* cnt_del; int32_t* cnt_mark; int32_t* cnt_map;
};

// Decode a v2 payload (codec.py encode_frame v2 is the format reference).
// s2a maps frame string ids to declared actor interner ids (>=1) or -1.
// str_base globalizes string ids stored into op rows (0 for single-frame).
// Returns 0 ok, 1 corrupt/malformed, -2 dep capacity, -3 op capacity;
// cursors nc/nd/no advance only as records are written (caller rolls back
// on nonzero).
int32_t walk_v2(const int32_t* vals, int64_t n_vals, int32_t n_changes,
                const int32_t* s2a, int32_t n_strings, int32_t n_declared,
                int32_t actor_bits, int32_t max_ctr, int32_t str_base,
                WireOut& o, int64_t& nc, int64_t& nd, int64_t& no) {
    WireV2Ctx ctx(n_strings);
    const int64_t nd0 = nd;  // bulk parses share nd across frames: budget
                             // must meter THIS frame's emission only
    int64_t p = 0;
    auto take = [&](int64_t k) -> const int32_t* {
        if (p + k > n_vals) return nullptr;
        const int32_t* ptr = vals + p;
        p += k;
        return ptr;
    };
    auto actor_of = [&](int32_t strid) -> int32_t {
        if (strid < 0 || strid >= n_strings) return -2;
        return s2a[strid];
    };
    auto pack = [&](int64_t ctr, int32_t strid, bool* bad) -> int32_t {
        const int32_t a = actor_of(strid);
        if (a == -2) { *bad = true; return 0; }
        if (a < 0 || ctr < 0 || ctr > max_ctr) { *bad = true; return 0; }
        return (static_cast<int32_t>(ctr) << actor_bits) | a;
    };

    for (int32_t c = 0; c < n_changes; ++c) {
        const int32_t* cb = take(1);
        if (!cb) return 1;
        const int32_t strid = *cb >> 4, hflags = *cb & 15;
        if (*cb < 0 || strid >= n_strings) return 1;
        int32_t dseq = 0, dstart = 0;
        if (!(hflags & kHDseqZero)) {
            const int32_t* v = take(1); if (!v) return 1; dseq = *v;
        }
        if (!(hflags & kHDstartZero)) {
            const int32_t* v = take(1); if (!v) return 1; dstart = *v;
        }
        // wire deltas are attacker-controlled: do the reconstruction in
        // int64 and reject anything leaving int32 range as corrupt (signed
        // int32 overflow would be UB, and a wrapped value would propagate
        // downstream instead of flagging the frame)
        const int64_t seq64 =
            static_cast<int64_t>(ctx.last_seq[strid]) + 1 + dseq;
        const int64_t start64 =
            static_cast<int64_t>(ctx.prev_end[strid]) + dstart;
        if (seq64 < 0 || seq64 > INT32_MAX || start64 < 0 ||
            start64 > INT32_MAX) {
            return 1;
        }
        const int32_t seq = static_cast<int32_t>(seq64);
        const int32_t start_op = static_cast<int32_t>(start64);
        const int32_t a = actor_of(strid);
        o.ch_actor[nc] = a;  // may be -1: undeclared actor, caller demotes
        o.ch_seq[nc] = seq;

        int32_t own;
        if (hflags & kHDepsSame) {
            if (!ctx.has_dep_set[strid]) return 1;
            own = ctx.own_elided[strid];
        } else {
            const int32_t* v = take(1);
            if (!v || *v < 0) return 1;
            own = *v & 1;
            const bool delta = (*v >> 1) & 1;
            const int32_t count = *v >> 2;
            // Dep sets referencing far more actors than the session declares
            // leave the fast path by DEMOTION (the object path's Python
            // decoder accepts them — same route as undeclared-actor deps),
            // but their storage is bounded here: without a cap, a small
            // DEPS_SAME-spamming frame forces multi-GB dep output and
            // quadratic re-emission (review finding r3).  Entries beyond the
            // cap are consumed from the stream (alignment) but not stored.
            const int32_t dep_store_cap = n_declared + 64;
            auto& entries = ctx.dep_set[strid];
            if (delta) {
                if (!ctx.has_dep_set[strid]) return 1;
                for (int32_t i = 0; i < count; ++i) {
                    const int32_t* dp = take(2);
                    if (!dp) return 1;
                    const int32_t da = dp[0];
                    if (da < 0 || da >= n_strings) return 1;
                    bool found = false;
                    for (auto& e : entries) {
                        if (e.first == da) {
                            const int64_t ds64 =
                                static_cast<int64_t>(e.second) + dp[1];
                            if (ds64 < 0 || ds64 > INT32_MAX) return 1;
                            e.second = static_cast<int32_t>(ds64);
                            ctx.dep_base[da] = e.second;
                            found = true;
                            break;
                        }
                    }
                    if (!found) return 1;
                }
            } else {
                entries.clear();
                ++ctx.dep_epoch;
                for (int32_t i = 0; i < count; ++i) {
                    const int32_t* dp = take(2);
                    if (!dp) return 1;
                    const int32_t da = dp[0];
                    if (da < 0 || da >= n_strings) return 1;
                    // duplicate dep actors never occur in a legit encoding
                    // (deps are a per-actor map, and codec.py rejects dups
                    // identically): corrupt
                    if (ctx.dep_seen[da] == ctx.dep_epoch) return 1;
                    ctx.dep_seen[da] = ctx.dep_epoch;
                    const int64_t ds64 =
                        static_cast<int64_t>(
                            std::max(ctx.dep_base[da], ctx.last_seq[da])) +
                        dp[1];
                    if (ds64 < 0 || ds64 > INT32_MAX) return 1;
                    if (static_cast<int32_t>(entries.size()) < dep_store_cap) {
                        entries.push_back({da, static_cast<int32_t>(ds64)});
                    } else {
                        // over the storage cap: demote this doc off the
                        // fast path (decode_frame handles the full set)
                        o.ch_actor[nc] = -1;
                    }
                    ctx.dep_base[da] = static_cast<int32_t>(ds64);
                }
            }
            ctx.own_elided[strid] = static_cast<uint8_t>(own);
            ctx.has_dep_set[strid] = 1;
        }
        // Total-emission budget (review finding r3 medium): every change
        // re-emits its stored dep set, so a frame of tiny DEPS_SAME headers
        // otherwise forces ~(n_declared+64) output entries per ~1 payload
        // int, which the host's capacity doubling obligingly allocates.
        // Over-budget changes are DEMOTED (ch_actor = -1), not rejected —
        // huge-actor sessions are valid data and the object path decodes
        // them in shared O(1)-per-change memory.
        const int64_t dep_emit_budget =
            std::min<int64_t>(64 * n_vals + 4096, 16000000);
        const auto& emit_set = ctx.dep_set[strid];
        const int64_t need =
            (own ? 1 : 0) + static_cast<int64_t>(emit_set.size());
        if ((nd - nd0) + need > dep_emit_budget) {
            o.ch_actor[nc] = -1;
        } else {
            if (own) {
                if (a < 0) {
                    o.ch_actor[nc] = -1;  // dep on undeclared (own) actor
                } else {
                    if (nd >= o.dep_cap) return -2;
                    o.dep_actor[nd] = a;
                    o.dep_seq[nd] = seq - 1;
                    ++nd;
                }
            }
            for (const auto& e : emit_set) {
                const int32_t da = actor_of(e.first);
                if (da == -2) return 1;
                if (da < 0) { o.ch_actor[nc] = -1; continue; }
                if (nd >= o.dep_cap) return -2;
                o.dep_actor[nd] = da;
                o.dep_seq[nd] = e.second;
                ++nd;
            }
        }
        o.dep_off[nc + 1] = static_cast<int32_t>(nd);

        int32_t nops = 1;
        if (!(hflags & kHNopsOne)) {
            const int32_t* v = take(1);
            if (!v || *v < 0) return 1;
            nops = *v;
        }
        const int64_t end64 = static_cast<int64_t>(start_op) + nops;
        if (end64 > INT32_MAX) return 1;
        ctx.last_seq[strid] = seq;
        ctx.prev_end[strid] = static_cast<int32_t>(end64);

        int32_t ci = 0, cd = 0, cm = 0, cp = 0;
        for (int32_t k = 0; k < nops; ++k) {
            if (no >= o.op_cap) return -3;
            int32_t* row = o.ops + no * 10;
            for (int i = 0; i < 10; ++i) row[i] = 0;
            const int32_t* fp = take(1);
            if (!fp || *fp < 0) return 1;
            const int32_t kind = *fp & 7, of = *fp >> 3;
            bool bad = (o.ch_actor[nc] < 0);
            if (kind == 4) {  // JSON spillover (no flags, no ctx update)
                if (of) return 1;
                const int32_t* b = take(1);
                if (!b) return 1;
                if (b[0] < 0 || b[0] >= n_strings) return 1;
                row[0] = 3;
                row[3] = str_base + b[0];
            } else {
                if (of >> 4) return 1;
                if ((of & kFRefPrev) && kind != 0) return 1;
                if ((of & kFRefHead) && kind != 0 && kind != 5) return 1;
                if ((of & kFRefPrev) && (of & kFRefHead)) return 1;
                int32_t obj;
                bool obj_bad = false;
                if (of & kFObjPrev) {
                    if (!ctx.has_prev_op) return 1;
                    obj = ctx.prev_obj;
                    obj_bad = ctx.prev_obj_bad;
                } else {
                    const int32_t* b = take(3);
                    if (!b) return 1;
                    obj = (b[0] == 0) ? -1 : pack(b[1], b[2], &obj_bad);
                }
                if (obj_bad) bad = true;
                int64_t op_ctr;
                int32_t op_strid;
                if (of & kFOpidSeq) {
                    op_ctr = static_cast<int64_t>(start_op) + k;
                    op_strid = strid;
                } else {
                    const int32_t* b = take(2);
                    if (!b) return 1;
                    op_ctr = b[0];
                    op_strid = b[1];
                }
                bool opid_bad = false;
                const int32_t opid = pack(op_ctr, op_strid, &opid_bad);
                if (opid_bad) bad = true;
                const int32_t prev_opid = ctx.prev_opid;
                const bool prev_opid_bad = ctx.prev_opid_bad;
                const bool had_prev = ctx.has_prev_op;
                ctx.prev_obj = obj;
                ctx.prev_obj_bad = obj_bad;
                ctx.prev_opid = opid;
                ctx.prev_opid_bad = opid_bad;
                ctx.has_prev_op = true;

                if (kind == 0) {  // insert
                    int32_t ref = 0;
                    if (of & kFRefPrev) {
                        if (!had_prev) return 1;
                        ref = prev_opid;
                        if (prev_opid_bad) bad = true;
                    } else if (!(of & kFRefHead)) {
                        const int32_t* b = take(2);
                        if (!b) return 1;
                        bool rb = false;
                        ref = pack(op_ctr + b[0], b[1], &rb);
                        if (rb) bad = true;
                    }
                    const int32_t* cch = take(1);
                    if (!cch) return 1;
                    const int64_t cp = static_cast<int64_t>(cch[0]) + 110;
                    if (cp < INT32_MIN || cp > INT32_MAX) return 1;
                    row[0] = 0; row[1] = obj; row[2] = opid; row[3] = ref;
                    row[4] = static_cast<int32_t>(cp);  // codec char bias
                    ++ci;
                } else if (kind == 1) {  // delete
                    const int32_t* b = take(2);
                    if (!b) return 1;
                    bool eb = false;
                    row[0] = 1; row[1] = obj; row[2] = opid;
                    row[3] = pack(op_ctr + b[0], b[1], &eb);
                    if (eb) bad = true;
                    ++cd;
                } else if (kind == 2 || kind == 3) {  // marks
                    const int32_t* pk = take(1);
                    if (!pk || pk[0] < 0 || (pk[0] >> 6)) return 1;
                    row[0] = 2; row[1] = obj; row[2] = opid;
                    row[3] = (kind == 2) ? 1 : 2;
                    row[4] = pk[0] & 3;       // mark type
                    row[5] = (pk[0] >> 2) & 3;  // start kind
                    row[7] = (pk[0] >> 4) & 3;  // end kind
                    int64_t base_ctr = op_ctr;
                    if (row[5] <= 1) {
                        const int32_t* b = take(2);
                        if (!b) return 1;
                        bool sb = false;
                        base_ctr += b[0];
                        row[6] = pack(base_ctr, b[1], &sb);
                        if (sb) bad = true;
                    }
                    if (row[7] <= 1) {
                        const int32_t* b = take(2);
                        if (!b) return 1;
                        bool ebb = false;
                        row[8] = pack(base_ctr + b[0], b[1], &ebb);
                        if (ebb) bad = true;
                    }
                    const int32_t* at = take(1);
                    if (!at) return 1;
                    if (at[0] < 0 || at[0] > n_strings) return 1;
                    row[9] = (at[0] == 0) ? 0 : str_base + at[0];
                    ++cm;
                } else if (kind == 5 && (of & kFRefHead)) {  // makeList
                    const int32_t* b = take(1);
                    if (!b) return 1;
                    if (b[0] < 0 || b[0] >= n_strings) return 1;
                    row[0] = kKindMakeList;
                    row[1] = obj; row[2] = opid;
                    row[3] = str_base + b[0];
                    // adopted (and counted) by the Python ingest layer,
                    // exactly like v1's JSON-spillover makeList
                } else if (kind == 5 || kind == 7) {  // makeMap / map del
                    const int32_t* b = take(1);
                    if (!b) return 1;
                    if (b[0] < 0 || b[0] >= n_strings) return 1;
                    row[0] = 6; row[1] = obj; row[2] = opid;
                    row[3] = str_base + b[0];
                    row[4] = (kind == 5) ? 6 : 0;  // VK_OBJ / VK_DELETED
                    row[5] = (kind == 5) ? row[2] : 0;
                    ++cp;
                } else if (kind == 6) {  // map set
                    const int32_t* b = take(3);
                    if (!b) return 1;
                    if (b[0] < 0 || b[0] >= n_strings) return 1;
                    if (b[1] < 1 || b[1] > 5) return 1;
                    if (b[1] == 1 && (b[2] < 0 || b[2] >= n_strings)) return 1;
                    row[0] = 6; row[1] = obj; row[2] = opid;
                    row[3] = str_base + b[0];
                    row[4] = b[1];
                    row[5] = (b[1] == 1) ? str_base + b[2] + 1 : b[2];
                    ++cp;
                } else {
                    return 1;  // unknown op kind
                }
            }
            if (bad) row[0] = 4;
            ++no;
        }
        o.ops_off[nc + 1] = static_cast<int32_t>(no);
        o.cnt_ins[nc] = ci;
        o.cnt_del[nc] = cd;
        o.cnt_mark[nc] = cm;
        o.cnt_map[nc] = cp;
        ++nc;
    }
    if (p != n_vals) return 1;
    return 0;
}
}  // namespace

extern "C" {

// Deterministic causal schedule.
//
//   n         : number of candidate changes
//   actor[i]  : actor index of change i (indices follow actor-string order)
//   seq[i]    : per-actor sequence number (1-based, contiguous per actor)
//   deps for change i live at dep_actor/dep_seq[dep_off[i] .. dep_off[i+1])
//   n_actors  : actor table size
//   base_clock: per-actor applied frontier (length n_actors)
//   out_order : caller-allocated, capacity n; receives scheduled change
//               indices in application order
//
// Returns the number scheduled; the remaining changes are causally stuck
// (their dependencies are not in the set).  Duplicates of one (actor, seq)
// and changes already below the clock are skipped (not scheduled, not stuck):
// mirrored from causal.py so the two implementations are interchangeable.
int32_t pt_causal_schedule(int32_t n, const int32_t* actor, const int32_t* seq,
                           const int32_t* dep_off, const int32_t* dep_actor,
                           const int32_t* dep_seq, int32_t n_actors,
                           const int32_t* base_clock, int32_t* out_order) {
    std::vector<int32_t> clock(base_clock, base_clock + n_actors);
    std::unordered_map<int64_t, int32_t> pending;  // (actor,seq) -> change idx
    pending.reserve(static_cast<size_t>(n) * 2);

    for (int32_t i = 0; i < n; ++i) {
        if (seq[i] <= clock[actor[i]]) continue;           // already applied
        pending.emplace(key_of(actor[i], seq[i]), i);      // first wins (dup skip)
    }

    auto admissible = [&](int32_t i) -> bool {
        if (seq[i] != clock[actor[i]] + 1) return false;
        for (int32_t d = dep_off[i]; d < dep_off[i + 1]; ++d) {
            if (clock[dep_actor[d]] < dep_seq[d]) return false;
        }
        return true;
    };

    // waiters: blocker (actor, seq) -> change indices waiting on it
    std::unordered_map<int64_t, std::vector<int32_t>> waiters;
    waiters.reserve(pending.size());
    for (const auto& [key, i] : pending) {
        if (seq[i] > 1 && clock[actor[i]] < seq[i] - 1) {
            waiters[key_of(actor[i], seq[i] - 1)].push_back(i);
        }
        for (int32_t d = dep_off[i]; d < dep_off[i + 1]; ++d) {
            if (dep_actor[d] != actor[i] && clock[dep_actor[d]] < dep_seq[d]) {
                waiters[key_of(dep_actor[d], dep_seq[d])].push_back(i);
            }
        }
    }

    // min-heap over (actor, seq): smallest ready first == Python determinism
    using HeapKey = std::pair<int64_t, int32_t>;  // (key, change idx)
    std::priority_queue<HeapKey, std::vector<HeapKey>, std::greater<HeapKey>> ready;
    for (const auto& [key, i] : pending) {
        if (admissible(i)) ready.emplace(key, i);
    }

    int32_t count = 0;
    while (!ready.empty()) {
        auto [key, i] = ready.top();
        ready.pop();
        auto it = pending.find(key);
        if (it == pending.end()) continue;  // woken more than once
        pending.erase(it);
        out_order[count++] = i;
        clock[actor[i]] = seq[i];
        auto w = waiters.find(key);
        if (w != waiters.end()) {
            for (int32_t j : w->second) {
                auto pj = pending.find(key_of(actor[j], seq[j]));
                if (pj != pending.end() && admissible(j)) {
                    ready.emplace(key_of(actor[j], seq[j]), j);
                }
            }
            waiters.erase(w);
        }
    }
    return count;
}

// Zigzag-varint encode int32 stream into out (capacity cap bytes).
// Returns bytes written, or -1 if cap is insufficient.
int64_t pt_varint_encode(const int32_t* in, int64_t n, uint8_t* out, int64_t cap) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n; ++i) {
        uint32_t z = (static_cast<uint32_t>(in[i]) << 1) ^
                     static_cast<uint32_t>(in[i] >> 31);
        do {
            if (pos >= cap) return -1;
            uint8_t byte = z & 0x7F;
            z >>= 7;
            out[pos++] = byte | (z ? 0x80 : 0);
        } while (z);
    }
    return pos;
}

// Decode nbytes of zigzag-varint into out (capacity cap ints).
// Returns ints written, or -1 on malformed/overflowing input.
int64_t pt_varint_decode(const uint8_t* in, int64_t nbytes, int32_t* out,
                         int64_t cap) {
    int64_t pos = 0, count = 0;
    while (pos < nbytes) {
        uint32_t z = 0;
        int shift = 0;
        while (true) {
            if (pos >= nbytes || shift > 28) return -1;
            uint8_t byte = in[pos++];
            z |= static_cast<uint32_t>(byte & 0x7F) << shift;
            if (!(byte & 0x80)) break;
            shift += 7;
        }
        if (count >= cap) return -1;
        out[count++] = static_cast<int32_t>((z >> 1) ^ (~(z & 1) + 1));
    }
    return count;
}

// ---------------------------------------------------------------------------
// pt_parse_changes — the frame-native ingest fast path.
//
// Walks a binary change-frame's decoded int payload (the exact layout
// written by peritext_tpu/parallel/codec.py::encode_frame) straight into
// (a) per-change metadata arrays and (b) a uniform 10-column op matrix in
// device-packed identifier form, skipping Python Change objects entirely.
// Everything downstream (causal budget, stream splitting, padding) is then
// vectorizable numpy on these arrays.
//
// Column layout of ops[row*10 + c] (kinds: 0 insert, 1 delete, 2 mark,
// 3 json-spillover, 4 unsupported/undeclared, 6 map-register op):
//   c0 kind
//   c1 obj id, packed (ctr << actor_bits | actor); -1 = ROOT, 0 = n/a
//   c2 op id, packed
//   c3 insert: ref elem packed (0 = HEAD) | delete: target elem packed
//      | mark: action (1 add, 2 remove)   | json: string-table index
//      | map: key string-table index
//   c4 insert: codepoint | mark: mark-type index
//      | map: register value kind (packed.VK_*: 0 del, 1 str, 2 int,
//        3 true, 4 false, 5 null, 6 child map)
//   c5 mark: start boundary kind (0 before, 1 after, 2 startOf, 3 endOf)
//      | map: payload (str: string-table index + 1; int: the value;
//        child map: its own packed op id)
//   c6 mark: start elem packed (0 = none)
//   c7 mark: end boundary kind
//   c8 mark: end elem packed
//   c9 mark: attr string-table index + 1 (0 = none)
//
// str2actor maps frame string-table indices to *declared* actor-table
// indices (-1 = string is not a declared actor): identifier packing must
// use the session's stable actor numbering, not frame-local order.
//
// Returns 0 on success; -1 malformed payload; -2 dep capacity; -3 op
// capacity.  A change whose actor is undeclared gets ch_actor[i] = -1 and
// all its ops marked kind 4 (the caller demotes the doc to the object
// path); an op with an undeclared actor or an over-wide counter is kind 4.
int32_t pt_parse_changes(
    const int32_t* vals, int64_t n_vals, int32_t n_changes,
    const int32_t* str2actor, int32_t n_strings,
    int32_t actor_bits, int32_t max_ctr, int32_t version,
    int32_t* ch_actor, int32_t* ch_seq,
    int32_t* dep_off, int32_t* dep_actor, int32_t* dep_seq, int64_t dep_cap,
    int32_t* ops_off, int32_t* ops, int64_t op_cap,
    int32_t* cnt_ins, int32_t* cnt_del, int32_t* cnt_mark, int32_t* cnt_map) {
    int64_t p = 0;       // cursor into vals
    int64_t nd = 0;      // deps written
    int64_t no = 0;      // op rows written
    dep_off[0] = 0;
    ops_off[0] = 0;
    if (version >= 2) {
        // declared-actor count: distinct positive ids in str2actor
        int32_t n_declared = 0;
        for (int32_t i = 0; i < n_strings; ++i) {
            if (str2actor[i] > 0) ++n_declared;
        }
        WireOut o{ch_actor, ch_seq, dep_off, dep_actor, dep_seq, dep_cap,
                  ops_off, ops, op_cap, cnt_ins, cnt_del, cnt_mark, cnt_map};
        int64_t nc = 0;
        const int32_t rc = walk_v2(vals, n_vals, n_changes, str2actor,
                                   n_strings, n_declared, actor_bits, max_ctr,
                                   0, o, nc, nd, no);
        return (rc == 1) ? -1 : rc;
    }

    auto take = [&](int64_t k) -> const int32_t* {
        if (p + k > n_vals) return nullptr;
        const int32_t* ptr = vals + p;
        p += k;
        return ptr;
    };
    auto actor_of = [&](int32_t strid) -> int32_t {
        if (strid < 0 || strid >= n_strings) return -2;  // malformed
        return str2actor[strid];
    };
    // pack an opid pair; returns 0 with *bad set when unsupported
    auto pack = [&](int32_t ctr, int32_t strid, bool* bad) -> int32_t {
        int32_t a = actor_of(strid);
        if (a == -2) { *bad = true; return 0; }
        if (a < 0 || ctr < 0 || ctr > max_ctr) { *bad = true; return 0; }
        return (ctr << actor_bits) | a;
    };

    for (int32_t c = 0; c < n_changes; ++c) {
        const int32_t* h = take(4);  // actor, seq, start_op, n_deps
        if (!h) return -1;
        int32_t a = actor_of(h[0]);
        if (a == -2) return -1;
        ch_actor[c] = a;  // may be -1: undeclared actor, caller demotes
        ch_seq[c] = h[1];
        int32_t ndeps = h[3];
        if (ndeps < 0) return -1;
        for (int32_t d = 0; d < ndeps; ++d) {
            const int32_t* dp = take(2);
            if (!dp) return -1;
            int32_t da = actor_of(dp[0]);
            if (da == -2) return -1;
            if (da < 0) { ch_actor[c] = -1; continue; }  // dep on undeclared
            if (nd >= dep_cap) return -2;
            dep_actor[nd] = da;
            dep_seq[nd] = dp[1];
            ++nd;
        }
        dep_off[c + 1] = static_cast<int32_t>(nd);

        const int32_t* nop = take(1);
        if (!nop) return -1;
        int32_t nops = nop[0];
        if (nops < 0) return -1;
        int32_t ci = 0, cd = 0, cm = 0, cp = 0;
        for (int32_t k = 0; k < nops; ++k) {
            if (no >= op_cap) return -3;
            int32_t* row = ops + no * 10;
            for (int i = 0; i < 10; ++i) row[i] = 0;
            const int32_t* kindp = take(1);
            if (!kindp) return -1;
            int32_t kind = *kindp;
            bool bad = (ch_actor[c] < 0);
            if (kind == 4) {  // JSON spillover: [strid]
                const int32_t* b = take(1);
                if (!b) return -1;
                if (b[0] < 0 || b[0] >= n_strings) return -1;
                row[0] = 3;
                row[3] = b[0];
            } else if (kind == 0) {  // insert: obj(3) opid(2) ref(3) char
                const int32_t* b = take(9);
                if (!b) return -1;
                row[0] = 0;
                row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                row[2] = pack(b[3], b[4], &bad);
                row[3] = b[5] == 0 ? 0 : pack(b[6], b[7], &bad);
                row[4] = b[8];
                ++ci;
            } else if (kind == 1) {  // delete: obj(3) opid(2) elem(2)
                const int32_t* b = take(7);
                if (!b) return -1;
                row[0] = 1;
                row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                row[2] = pack(b[3], b[4], &bad);
                row[3] = pack(b[5], b[6], &bad);
                ++cd;
            } else if (kind == 2 || kind == 3) {
                // mark: obj(3) opid(2) mtype s(3) e(3) attr
                const int32_t* b = take(13);
                if (!b) return -1;
                if (b[6] < 0 || b[6] > 3 || b[9] < 0 || b[9] > 3) return -1;
                row[0] = 2;
                row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                row[2] = pack(b[3], b[4], &bad);
                row[3] = (kind == 2) ? 1 : 2;  // MA_ADD / MA_REMOVE
                row[4] = b[5];
                row[5] = b[6];
                row[6] = (b[6] <= 1) ? pack(b[7], b[8], &bad) : 0;
                row[7] = b[9];
                row[8] = (b[9] <= 1) ? pack(b[10], b[11], &bad) : 0;
                if (b[12] < 0 || b[12] > n_strings) return -1;
                row[9] = b[12];
                ++cm;
            } else if (kind == 5 || kind == 7) {  // makeMap / map del: obj(3) opid(2) key
                const int32_t* b = take(6);
                if (!b) return -1;
                if (b[5] < 0 || b[5] >= n_strings) return -1;
                row[0] = 6;
                row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                row[2] = pack(b[3], b[4], &bad);
                row[3] = b[5];
                row[4] = (kind == 5) ? 6 : 0;  // VK_OBJ / VK_DELETED
                row[5] = (kind == 5) ? row[2] : 0;
                ++cp;
            } else if (kind == 6) {  // map set: obj(3) opid(2) key vkind payload
                const int32_t* b = take(8);
                if (!b) return -1;
                if (b[5] < 0 || b[5] >= n_strings) return -1;
                if (b[6] < 1 || b[6] > 5) return -1;  // VK_STR..VK_NULL
                if (b[6] == 1 && (b[7] < 0 || b[7] >= n_strings)) return -1;
                row[0] = 6;
                row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                row[2] = pack(b[3], b[4], &bad);
                row[3] = b[5];
                row[4] = b[6];
                row[5] = (b[6] == 1) ? b[7] + 1 : b[7];  // str: strid + 1
                ++cp;
            } else {
                return -1;  // unknown op kind: frame is corrupt
            }
            if (bad) row[0] = 4;
            ++no;
        }
        ops_off[c + 1] = static_cast<int32_t>(no);
        cnt_ins[c] = ci;
        cnt_del[c] = cd;
        cnt_mark[c] = cm;
        cnt_map[c] = cp;
    }
    if (p != n_vals) return -1;  // trailing garbage
    return 0;
}

// ---------------------------------------------------------------------------
// pt_schedule_split_batch — one call schedules and splits EVERY frame-mode
// document's pending parsed changes for a round.
//
// Per doc d: admit the longest causally-valid prefix (vector-clock admission,
// same rules as pt_causal_schedule) whose op usage fits the static round
// widths (ki/kd/km), and scatter its ops into the doc's padded stream rows
// (row-major (D, K) arrays shared with the object path; doc_row[d] selects
// the row).  Clocks advance in place.  This replaces ~30 small numpy calls
// per doc per round with one native call per round (the host-side bottleneck
// at pod scale — SURVEY §5.8 / BASELINE config 5).
//
// Within-round application order may differ from the scalar path's; any
// causally-valid order converges to the same state (the RGA skip rule and
// the order-independent mark table), which the differential tests assert.
//
// admitted[c]: 1 = applied this round, 2 = stale duplicate (consumed),
// 0 = deferred (stuck or over budget).  status[d]: 0 = ok, 1 = demote the
// doc (op on a non-text object, or a change that can never fit the widths).
// Returns total changes admitted.
int32_t pt_schedule_split_batch(
    int32_t n_docs, int32_t n_actors,
    const int32_t* ch_off, const int32_t* doc_row, const int32_t* text_obj,
    const int32_t* ch_actor, const int32_t* ch_seq,
    const int32_t* dep_off, const int32_t* dep_actor, const int32_t* dep_seq,
    const int32_t* ops_off, const int32_t* ops,
    int32_t* clock,  // (n_docs, n_actors) row-major, in/out
    int32_t ki, int32_t kd, int32_t km, int32_t kp,
    int32_t* ins_ref, int32_t* ins_op, int32_t* ins_char,
    int32_t* del_target,
    int32_t* m_action, int32_t* m_type, int32_t* m_sk, int32_t* m_se,
    int32_t* m_ek, int32_t* m_ee, int32_t* m_op, int32_t* m_attr,
    int32_t* p_obj, int32_t* p_key, int32_t* p_op, int32_t* p_kind,
    int32_t* p_val,
    int32_t* n_ins, int32_t* n_del, int32_t* n_mark, int32_t* n_map,
    int32_t* n_admitted,
    uint8_t* admitted, uint8_t* status) {
    int32_t total_admitted = 0;
    std::vector<int32_t> order;
    std::vector<int32_t> clock_save(n_actors);

    for (int32_t d = 0; d < n_docs; ++d) {
        const int32_t lo = ch_off[d], hi = ch_off[d + 1];
        int32_t* dclock = clock + static_cast<int64_t>(d) * n_actors;
        std::memcpy(clock_save.data(), dclock, n_actors * sizeof(int32_t));
        const int32_t row = doc_row[d];
        int32_t* r_ins_ref = ins_ref + static_cast<int64_t>(row) * ki;
        int32_t* r_ins_op = ins_op + static_cast<int64_t>(row) * ki;
        int32_t* r_ins_char = ins_char + static_cast<int64_t>(row) * ki;
        int32_t* r_del = del_target + static_cast<int64_t>(row) * kd;
        int64_t mbase = static_cast<int64_t>(row) * km;
        int64_t pbase = static_cast<int64_t>(row) * kp;

        order.clear();
        for (int32_t c = lo; c < hi; ++c) order.push_back(c);
        std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
            if (ch_actor[a] != ch_actor[b]) return ch_actor[a] < ch_actor[b];
            if (ch_seq[a] != ch_seq[b]) return ch_seq[a] < ch_seq[b];
            return a < b;
        });

        int32_t ci = 0, cd = 0, cm = 0, cp = 0, nch = 0;
        bool demote = false, budget_closed = false, progress = true;
        while (progress && !demote) {
            progress = false;
            for (int32_t c : order) {
                if (admitted[c] || demote) continue;
                const int32_t a = ch_actor[c], s = ch_seq[c];
                if (s <= dclock[a]) { admitted[c] = 2; continue; }  // stale dup
                if (budget_closed || s != dclock[a] + 1) continue;
                bool ok = true;
                for (int32_t dd = dep_off[c]; dd < dep_off[c + 1]; ++dd) {
                    if (dclock[dep_actor[dd]] < dep_seq[dd]) { ok = false; break; }
                }
                if (!ok) continue;
                // count this change's streams
                int32_t wi = 0, wd = 0, wm = 0, wp = 0;
                for (int32_t o = ops_off[c]; o < ops_off[c + 1]; ++o) {
                    const int32_t k = ops[static_cast<int64_t>(o) * 10];
                    if (k == 0) ++wi;
                    else if (k == 1) ++wd;
                    else if (k == 2) ++wm;
                    else if (k == 6) ++wp;
                    else if (k != 5) { demote = true; break; }  // json/bad left over
                }
                if (demote) break;
                if (wi > ki || wd > kd || wm > km || wp > kp) {
                    demote = true; break;  // never fits
                }
                if (ci + wi > ki || cd + wd > kd || cm + wm > km || cp + wp > kp) {
                    budget_closed = true;  // prefix semantics: round is full
                    continue;
                }
                // validate + scatter the ops
                for (int32_t o = ops_off[c]; o < ops_off[c + 1] && !demote; ++o) {
                    const int32_t* r = ops + static_cast<int64_t>(o) * 10;
                    const int32_t k = r[0];
                    if (k == 5) continue;
                    if (k == 6) {
                        // map-register op: container must not be the text
                        // LIST (a malformed peer targeting it would diverge
                        // from the scalar oracle, which raises); other
                        // object-kind validation is the sender encoder's job
                        if (r[1] == text_obj[d] && text_obj[d] != 0) {
                            demote = true; break;
                        }
                        p_obj[pbase + cp] = r[1]; p_key[pbase + cp] = r[3];
                        p_op[pbase + cp] = r[2]; p_kind[pbase + cp] = r[4];
                        p_val[pbase + cp] = r[5];
                        ++cp;
                        continue;
                    }
                    if (r[1] != text_obj[d]) { demote = true; break; }
                    if (k == 0) {
                        r_ins_ref[ci] = r[3]; r_ins_op[ci] = r[2]; r_ins_char[ci] = r[4];
                        ++ci;
                    } else if (k == 1) {
                        r_del[cd] = r[3];
                        ++cd;
                    } else {
                        m_action[mbase + cm] = r[3]; m_type[mbase + cm] = r[4];
                        m_sk[mbase + cm] = r[5]; m_se[mbase + cm] = r[6];
                        m_ek[mbase + cm] = r[7]; m_ee[mbase + cm] = r[8];
                        m_op[mbase + cm] = r[2]; m_attr[mbase + cm] = r[9];
                        ++cm;
                    }
                }
                if (demote) break;
                dclock[a] = s;
                admitted[c] = 1;
                ++nch;
                progress = true;
            }
        }

        if (demote) {
            // discard this doc's round: zero rows, restore clock, flag it
            std::memcpy(dclock, clock_save.data(), n_actors * sizeof(int32_t));
            std::memset(r_ins_ref, 0, ki * sizeof(int32_t));
            std::memset(r_ins_op, 0, ki * sizeof(int32_t));
            std::memset(r_ins_char, 0, ki * sizeof(int32_t));
            std::memset(r_del, 0, kd * sizeof(int32_t));
            for (int32_t* col : {m_action, m_type, m_sk, m_se, m_ek, m_ee, m_op, m_attr})
                std::memset(col + mbase, 0, km * sizeof(int32_t));
            for (int32_t* col : {p_obj, p_key, p_op, p_kind, p_val})
                std::memset(col + pbase, 0, kp * sizeof(int32_t));
            for (int32_t c = lo; c < hi; ++c) admitted[c] = 0;
            n_ins[d] = n_del[d] = n_mark[d] = n_map[d] = n_admitted[d] = 0;
            status[d] = 1;
            continue;
        }
        n_ins[d] = ci; n_del[d] = cd; n_mark[d] = cm; n_map[d] = cp;
        n_admitted[d] = nch;
        status[d] = 0;
        total_admitted += nch;
    }
    return total_admitted;
}

// ---------------------------------------------------------------------------
// pt_parse_frames — bulk whole-frame ingest: N raw wire frames -> flat parsed
// arrays in ONE call.
//
// This is the pod-scale data-loader path (SURVEY §5.8, BASELINE config 5):
// per-frame Python — header/string-table walks, actor lookups, per-frame
// array allocation — dominates streaming ingest once thousands of docs ship
// frames every round, so the whole loop moves here.  The frame layout is
// exactly parallel/codec.py::encode_frame (29-byte header, zigzag-varint
// string lengths + UTF-8 bytes, zigzag-varint int payload); the per-change
// payload walk matches pt_parse_changes above, with string-table and
// dep/op offsets GLOBALIZED across frames (f_str_off / f_ch_off give each
// frame's slice).
//
// Outputs use the same conventions as pt_parse_changes; additionally:
//   f_status[f]  : 0 ok, 1 corrupt (that frame contributes nothing; its
//                  slice in f_ch_off/f_str_off is empty)
//   str_start/str_len : byte spans of every string-table entry, absolute
//                  into `data`, so Python can lazily decode only the strings
//                  it needs (mark attrs, JSON-spillover rows)
//   ops col 3 (json rows) and col 9 (mark attr + 1) hold GLOBAL string ids.
//
// Actor identity: actor_bytes/actor_off list the declared actor table's
// UTF-8 names in interner order (index i -> interner id i+1; id 0 is the
// reserved None slot, matching utils/interning.Interner).
//
// Returns 0 on success, negative on output-capacity overflow (a caller
// sizing bug: capacities derive exactly from the validated frame headers).
int32_t pt_parse_frames(
    const uint8_t* data, const int64_t* frame_off, int32_t n_frames,
    const uint8_t* actor_bytes, const int64_t* actor_off, int32_t n_actors,
    int32_t actor_bits, int32_t max_ctr,
    int32_t* f_status, int32_t* f_ch_off, int32_t* f_str_off,
    int64_t* str_start, int32_t* str_len, int64_t str_cap,
    int32_t* ch_actor, int32_t* ch_seq, int64_t ch_cap,
    int32_t* dep_off, int32_t* dep_actor, int32_t* dep_seq, int64_t dep_cap,
    int32_t* ops_off, int32_t* ops, int64_t op_cap,
    int32_t* cnt_ins, int32_t* cnt_del, int32_t* cnt_mark, int32_t* cnt_map) {
    std::unordered_map<std::string_view, int32_t> amap;
    amap.reserve(static_cast<size_t>(n_actors) * 2);
    for (int32_t i = 0; i < n_actors; ++i) {
        amap.emplace(
            std::string_view(reinterpret_cast<const char*>(actor_bytes) + actor_off[i],
                             static_cast<size_t>(actor_off[i + 1] - actor_off[i])),
            i + 1);
    }

    int64_t nc = 0, nd = 0, no = 0, ns = 0;  // global cursors
    dep_off[0] = 0;
    ops_off[0] = 0;
    f_ch_off[0] = 0;
    f_str_off[0] = 0;
    std::vector<int32_t> vals;  // reused per-frame payload scratch
    std::vector<int32_t> s2a;   // frame string idx -> actor interner id | -1

    for (int32_t f = 0; f < n_frames; ++f) {
        const int64_t lo = frame_off[f], hi = frame_off[f + 1];
        const int64_t save_nc = nc, save_nd = nd, save_no = no, save_ns = ns;
        bool corrupt = false;

        do {
            if (hi - lo < 29 || hi > frame_off[n_frames]) { corrupt = true; break; }
            // header: magic(4) ver(1) n_changes(u32) n_strings(u32)
            //         n_ints(u64) payload_len(u64)  — little-endian packed
            const int32_t version = data[lo + 4];
            if (std::memcmp(data + lo, "PTXF", 4) != 0 ||
                (version != 1 && version != 2)) {
                corrupt = true; break;
            }
            uint32_t h_changes, h_strings;
            uint64_t h_ints, h_payload;
            std::memcpy(&h_changes, data + lo + 5, 4);
            std::memcpy(&h_strings, data + lo + 9, 4);
            std::memcpy(&h_ints, data + lo + 13, 8);
            std::memcpy(&h_payload, data + lo + 21, 8);
            const uint64_t body = static_cast<uint64_t>(hi - lo - 29);
            // min ints/change: 5 for v1 headers, 2 for v2's delta-elided form
            const uint64_t min_change_ints = (version == 1) ? 5 : 2;
            if (h_payload > body || h_ints > h_payload || h_strings > body ||
                static_cast<uint64_t>(h_changes) * min_change_ints > h_ints) {
                corrupt = true; break;
            }
            if (nc + h_changes > ch_cap) return -2;
            if (ns + h_strings > str_cap) return -4;

            // string table: zigzag-varint length + UTF-8 bytes per entry
            int64_t pos = lo + 29;
            s2a.assign(h_strings, -1);
            for (uint32_t s = 0; s < h_strings && !corrupt; ++s) {
                uint32_t z = 0;
                int shift = 0;
                while (true) {
                    if (pos >= hi || shift > 28) { corrupt = true; break; }
                    const uint8_t byte = data[pos++];
                    z |= static_cast<uint32_t>(byte & 0x7F) << shift;
                    if (!(byte & 0x80)) break;
                    shift += 7;
                }
                if (corrupt) break;
                const int32_t length = static_cast<int32_t>((z >> 1) ^ (~(z & 1) + 1));
                if (length < 0 || pos + length > hi) { corrupt = true; break; }
                str_start[ns + s] = pos;
                str_len[ns + s] = length;
                auto it = amap.find(std::string_view(
                    reinterpret_cast<const char*>(data) + pos,
                    static_cast<size_t>(length)));
                s2a[s] = (it == amap.end()) ? -1 : it->second;
                pos += length;
            }
            if (corrupt) break;
            if (pos + static_cast<int64_t>(h_payload) > hi) { corrupt = true; break; }

            // payload: zigzag varints, exactly h_ints of them
            vals.assign(h_ints, 0);
            {
                int64_t p = pos, count = 0;
                const int64_t pend = pos + static_cast<int64_t>(h_payload);
                while (p < pend) {
                    uint32_t z = 0;
                    int shift = 0;
                    while (true) {
                        if (p >= pend || shift > 28) { corrupt = true; break; }
                        const uint8_t byte = data[p++];
                        z |= static_cast<uint32_t>(byte & 0x7F) << shift;
                        if (!(byte & 0x80)) break;
                        shift += 7;
                    }
                    if (corrupt) break;
                    if (count >= static_cast<int64_t>(h_ints)) { corrupt = true; break; }
                    vals[count++] = static_cast<int32_t>((z >> 1) ^ (~(z & 1) + 1));
                }
                if (!corrupt && count != static_cast<int64_t>(h_ints)) corrupt = true;
            }
            if (corrupt) break;

            if (version == 2) {
                WireOut o{ch_actor, ch_seq, dep_off, dep_actor, dep_seq,
                          dep_cap, ops_off, ops, op_cap,
                          cnt_ins, cnt_del, cnt_mark, cnt_map};
                const int32_t rc = walk_v2(
                    vals.data(), static_cast<int64_t>(h_ints),
                    static_cast<int32_t>(h_changes), s2a.data(),
                    static_cast<int32_t>(h_strings), n_actors, actor_bits,
                    max_ctr, static_cast<int32_t>(ns), o, nc, nd, no);
                if (rc == -2) return -2;
                if (rc == -3) return -3;
                if (rc != 0) { corrupt = true; break; }
                ns += h_strings;
                break;  // frame done (the do-while(false) exits)
            }

            // v1 change walk (the pt_parse_changes logic, offsets globalized)
            const int32_t n_strings_f = static_cast<int32_t>(h_strings);
            int64_t p = 0;
            const int64_t n_vals = static_cast<int64_t>(h_ints);
            auto take = [&](int64_t k) -> const int32_t* {
                if (p + k > n_vals) return nullptr;
                const int32_t* ptr = vals.data() + p;
                p += k;
                return ptr;
            };
            auto actor_of = [&](int32_t strid) -> int32_t {
                if (strid < 0 || strid >= n_strings_f) return -2;
                return s2a[strid];
            };
            auto pack = [&](int32_t ctr, int32_t strid, bool* bad) -> int32_t {
                const int32_t a = actor_of(strid);
                if (a == -2) { *bad = true; return 0; }
                if (a < 0 || ctr < 0 || ctr > max_ctr) { *bad = true; return 0; }
                return (ctr << actor_bits) | a;
            };

            for (uint32_t c = 0; c < h_changes && !corrupt; ++c) {
                const int32_t* h = take(4);
                if (!h) { corrupt = true; break; }
                const int32_t a = actor_of(h[0]);
                if (a == -2) { corrupt = true; break; }
                ch_actor[nc] = a;  // may be -1: undeclared, caller demotes
                ch_seq[nc] = h[1];
                const int32_t ndeps = h[3];
                if (ndeps < 0) { corrupt = true; break; }
                for (int32_t d = 0; d < ndeps; ++d) {
                    const int32_t* dp = take(2);
                    if (!dp) { corrupt = true; break; }
                    const int32_t da = actor_of(dp[0]);
                    if (da == -2) { corrupt = true; break; }
                    if (da < 0) { ch_actor[nc] = -1; continue; }
                    if (nd >= dep_cap) return -2;
                    dep_actor[nd] = da;
                    dep_seq[nd] = dp[1];
                    ++nd;
                }
                if (corrupt) break;
                dep_off[nc + 1] = static_cast<int32_t>(nd);

                const int32_t* nop = take(1);
                if (!nop) { corrupt = true; break; }
                const int32_t nops = *nop;
                if (nops < 0) { corrupt = true; break; }
                int32_t ci = 0, cd = 0, cm = 0, cp = 0;
                for (int32_t k = 0; k < nops && !corrupt; ++k) {
                    if (no >= op_cap) return -3;
                    int32_t* row = ops + no * 10;
                    for (int i = 0; i < 10; ++i) row[i] = 0;
                    const int32_t* kindp = take(1);
                    if (!kindp) { corrupt = true; break; }
                    const int32_t kind = *kindp;
                    bool bad = (ch_actor[nc] < 0);
                    if (kind == 4) {  // JSON spillover: [strid] -> global id
                        const int32_t* b = take(1);
                        if (!b) { corrupt = true; break; }
                        if (b[0] < 0 || b[0] >= n_strings_f) { corrupt = true; break; }
                        row[0] = 3;
                        row[3] = static_cast<int32_t>(ns) + b[0];
                    } else if (kind == 0) {  // insert
                        const int32_t* b = take(9);
                        if (!b) { corrupt = true; break; }
                        row[0] = 0;
                        row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                        row[2] = pack(b[3], b[4], &bad);
                        row[3] = b[5] == 0 ? 0 : pack(b[6], b[7], &bad);
                        row[4] = b[8];
                        ++ci;
                    } else if (kind == 1) {  // delete
                        const int32_t* b = take(7);
                        if (!b) { corrupt = true; break; }
                        row[0] = 1;
                        row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                        row[2] = pack(b[3], b[4], &bad);
                        row[3] = pack(b[5], b[6], &bad);
                        ++cd;
                    } else if (kind == 2 || kind == 3) {  // add/remove mark
                        const int32_t* b = take(13);
                        if (!b) { corrupt = true; break; }
                        if (b[6] < 0 || b[6] > 3 || b[9] < 0 || b[9] > 3) {
                            corrupt = true; break;
                        }
                        row[0] = 2;
                        row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                        row[2] = pack(b[3], b[4], &bad);
                        row[3] = (kind == 2) ? 1 : 2;
                        row[4] = b[5];
                        row[5] = b[6];
                        row[6] = (b[6] <= 1) ? pack(b[7], b[8], &bad) : 0;
                        row[7] = b[9];
                        row[8] = (b[9] <= 1) ? pack(b[10], b[11], &bad) : 0;
                        if (b[12] < 0 || b[12] > n_strings_f) { corrupt = true; break; }
                        row[9] = b[12] == 0
                            ? 0
                            : static_cast<int32_t>(ns) + (b[12] - 1) + 1;
                        ++cm;
                    } else if (kind == 5 || kind == 7) {  // makeMap / map del
                        const int32_t* b = take(6);
                        if (!b) { corrupt = true; break; }
                        if (b[5] < 0 || b[5] >= n_strings_f) { corrupt = true; break; }
                        row[0] = 6;
                        row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                        row[2] = pack(b[3], b[4], &bad);
                        row[3] = static_cast<int32_t>(ns) + b[5];
                        row[4] = (kind == 5) ? 6 : 0;  // VK_OBJ / VK_DELETED
                        row[5] = (kind == 5) ? row[2] : 0;
                        ++cp;
                    } else if (kind == 6) {  // map set
                        const int32_t* b = take(8);
                        if (!b) { corrupt = true; break; }
                        if (b[5] < 0 || b[5] >= n_strings_f) { corrupt = true; break; }
                        if (b[6] < 1 || b[6] > 5) { corrupt = true; break; }
                        if (b[6] == 1 && (b[7] < 0 || b[7] >= n_strings_f)) {
                            corrupt = true; break;
                        }
                        row[0] = 6;
                        row[1] = b[0] == 0 ? -1 : pack(b[1], b[2], &bad);
                        row[2] = pack(b[3], b[4], &bad);
                        row[3] = static_cast<int32_t>(ns) + b[5];
                        row[4] = b[6];
                        row[5] = (b[6] == 1)
                            ? static_cast<int32_t>(ns) + b[7] + 1
                            : b[7];
                        ++cp;
                    } else {
                        corrupt = true; break;
                    }
                    if (bad) row[0] = 4;
                    ++no;
                }
                if (corrupt) break;
                ops_off[nc + 1] = static_cast<int32_t>(no);
                cnt_ins[nc] = ci;
                cnt_del[nc] = cd;
                cnt_mark[nc] = cm;
                cnt_map[nc] = cp;
                ++nc;
            }
            if (!corrupt && p != n_vals) corrupt = true;  // trailing garbage
            if (!corrupt) ns += h_strings;
        } while (false);

        if (corrupt) {
            nc = save_nc; nd = save_nd; no = save_no; ns = save_ns;
            f_status[f] = 1;
        } else {
            f_status[f] = 0;
        }
        f_ch_off[f + 1] = static_cast<int32_t>(nc);
        f_str_off[f + 1] = static_cast<int32_t>(ns);
    }
    return 0;
}

// ---------------------------------------------------------------------------
// pt_scalar_apply — the single-core scalar BASELINE the device path is
// measured against (BASELINE config 1).
//
// An honest C++ re-expression of the reference's applyChange hot loop
// (src/micromerge.ts:892-1297) over the parsed op matrix: sequential RGA
// insert with the convergence skip and O(n) reference scans
// (:1187-1245, :1304), tombstone deletes (:1250-1277), mark ops paying the
// reference's per-op anchor walk (the gap walk scans the whole metadata,
// :1002-1138 — modeled here as the two anchor scans), and map-register LWW
// (:1151-1175).  No batching, no vectorization — one op at a time on one
// core, exactly what "single-thread native baseline" means.
//
// ops: (n_ops, 10) rows in causally-applied order (pt_parse_changes layout).
// out_text receives the visible codepoints (capacity out_cap); returns the
// number of ops applied, visible count via *out_visible, and an anchor
// checksum via *out_check (defeats dead-code elimination of the scans).
int64_t pt_scalar_apply(
    const int32_t* ops, int64_t n_ops,
    int32_t* out_text, int64_t out_cap,
    int64_t* out_visible, int64_t* out_check) {
    struct Elem { int32_t id; int32_t ch; bool deleted; };
    std::vector<Elem> elems;
    elems.reserve(4096);
    struct Reg { int32_t obj, key, op, kind, val; };
    std::vector<Reg> regs;
    int64_t applied = 0;
    int64_t check = 0;

    auto find = [&](int32_t id) -> int64_t {
        for (int64_t i = 0; i < static_cast<int64_t>(elems.size()); ++i) {
            if (elems[i].id == id) return i;
        }
        return -1;
    };

    for (int64_t o = 0; o < n_ops; ++o) {
        const int32_t* r = ops + o * 10;
        const int32_t k = r[0];
        if (k == 0) {  // insert after ref (0 = HEAD), RGA skip rule
            int64_t p = -1;
            if (r[3] != 0) {
                p = find(r[3]);
                if (p < 0) continue;  // malformed: skip (oracle would throw)
            }
            int64_t q = p + 1;
            while (q < static_cast<int64_t>(elems.size()) && elems[q].id > r[2]) ++q;
            elems.insert(elems.begin() + q, Elem{r[2], r[4], false});
        } else if (k == 1) {  // delete -> tombstone
            int64_t p = find(r[3]);
            if (p < 0) continue;
            elems[p].deleted = true;
        } else if (k == 2) {  // mark: the reference walks the metadata per op
            if (r[6] != 0) check += find(r[6]);
            if (r[8] != 0) check += find(r[8]);
        } else if (k == 6) {  // map register LWW
            bool found = false;
            for (auto& g : regs) {
                if (g.obj == r[1] && g.key == r[3]) {
                    if (r[2] > g.op) { g.op = r[2]; g.kind = r[4]; g.val = r[5]; }
                    found = true;
                    break;
                }
            }
            if (!found) regs.push_back(Reg{r[1], r[3], r[2], r[4], r[5]});
        } else {
            continue;  // JSON / SKIP rows
        }
        ++applied;
    }

    int64_t vis = 0;
    for (const auto& e : elems) {
        if (!e.deleted && vis < out_cap) out_text[vis++] = e.ch;
    }
    *out_visible = vis;
    *out_check = check;
    return applied;
}

}  // extern "C"
