// Native host runtime for peritext-tpu.
//
// The TPU owns op application (JAX/XLA kernels); the host owns the
// irregular work around it.  Two of those paths are hot enough at pod scale
// to be native (SURVEY §5.8: host-side causal scheduling runs per document
// per round; the wire codec runs per change batch on every DCN hop):
//
//  1. pt_causal_schedule — deterministic topological schedule of a change
//     set against a vector clock (the C++ twin of
//     peritext_tpu/parallel/causal.py::causal_schedule; the reference's
//     catch-and-requeue loop is test/merge.ts:4-23).
//  2. pt_varint_encode / pt_varint_decode — zigzag-varint packing of int32
//     streams, the payload core of the binary change-frame codec
//     (peritext_tpu/parallel/codec.py).
//
// Plain C ABI throughout: the Python side binds with ctypes (no pybind11 in
// the image), and everything crossing the boundary is int32/uint8 arrays.

#include <cstdint>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <vector>

namespace {
inline int64_t key_of(int32_t actor, int32_t seq) {
    return (static_cast<int64_t>(actor) << 32) | static_cast<uint32_t>(seq);
}
}  // namespace

extern "C" {

// Deterministic causal schedule.
//
//   n         : number of candidate changes
//   actor[i]  : actor index of change i (indices follow actor-string order)
//   seq[i]    : per-actor sequence number (1-based, contiguous per actor)
//   deps for change i live at dep_actor/dep_seq[dep_off[i] .. dep_off[i+1])
//   n_actors  : actor table size
//   base_clock: per-actor applied frontier (length n_actors)
//   out_order : caller-allocated, capacity n; receives scheduled change
//               indices in application order
//
// Returns the number scheduled; the remaining changes are causally stuck
// (their dependencies are not in the set).  Duplicates of one (actor, seq)
// and changes already below the clock are skipped (not scheduled, not stuck):
// mirrored from causal.py so the two implementations are interchangeable.
int32_t pt_causal_schedule(int32_t n, const int32_t* actor, const int32_t* seq,
                           const int32_t* dep_off, const int32_t* dep_actor,
                           const int32_t* dep_seq, int32_t n_actors,
                           const int32_t* base_clock, int32_t* out_order) {
    std::vector<int32_t> clock(base_clock, base_clock + n_actors);
    std::unordered_map<int64_t, int32_t> pending;  // (actor,seq) -> change idx
    pending.reserve(static_cast<size_t>(n) * 2);

    for (int32_t i = 0; i < n; ++i) {
        if (seq[i] <= clock[actor[i]]) continue;           // already applied
        pending.emplace(key_of(actor[i], seq[i]), i);      // first wins (dup skip)
    }

    auto admissible = [&](int32_t i) -> bool {
        if (seq[i] != clock[actor[i]] + 1) return false;
        for (int32_t d = dep_off[i]; d < dep_off[i + 1]; ++d) {
            if (clock[dep_actor[d]] < dep_seq[d]) return false;
        }
        return true;
    };

    // waiters: blocker (actor, seq) -> change indices waiting on it
    std::unordered_map<int64_t, std::vector<int32_t>> waiters;
    waiters.reserve(pending.size());
    for (const auto& [key, i] : pending) {
        if (seq[i] > 1 && clock[actor[i]] < seq[i] - 1) {
            waiters[key_of(actor[i], seq[i] - 1)].push_back(i);
        }
        for (int32_t d = dep_off[i]; d < dep_off[i + 1]; ++d) {
            if (dep_actor[d] != actor[i] && clock[dep_actor[d]] < dep_seq[d]) {
                waiters[key_of(dep_actor[d], dep_seq[d])].push_back(i);
            }
        }
    }

    // min-heap over (actor, seq): smallest ready first == Python determinism
    using HeapKey = std::pair<int64_t, int32_t>;  // (key, change idx)
    std::priority_queue<HeapKey, std::vector<HeapKey>, std::greater<HeapKey>> ready;
    for (const auto& [key, i] : pending) {
        if (admissible(i)) ready.emplace(key, i);
    }

    int32_t count = 0;
    while (!ready.empty()) {
        auto [key, i] = ready.top();
        ready.pop();
        auto it = pending.find(key);
        if (it == pending.end()) continue;  // woken more than once
        pending.erase(it);
        out_order[count++] = i;
        clock[actor[i]] = seq[i];
        auto w = waiters.find(key);
        if (w != waiters.end()) {
            for (int32_t j : w->second) {
                auto pj = pending.find(key_of(actor[j], seq[j]));
                if (pj != pending.end() && admissible(j)) {
                    ready.emplace(key_of(actor[j], seq[j]), j);
                }
            }
            waiters.erase(w);
        }
    }
    return count;
}

// Zigzag-varint encode int32 stream into out (capacity cap bytes).
// Returns bytes written, or -1 if cap is insufficient.
int64_t pt_varint_encode(const int32_t* in, int64_t n, uint8_t* out, int64_t cap) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n; ++i) {
        uint32_t z = (static_cast<uint32_t>(in[i]) << 1) ^
                     static_cast<uint32_t>(in[i] >> 31);
        do {
            if (pos >= cap) return -1;
            uint8_t byte = z & 0x7F;
            z >>= 7;
            out[pos++] = byte | (z ? 0x80 : 0);
        } while (z);
    }
    return pos;
}

// Decode nbytes of zigzag-varint into out (capacity cap ints).
// Returns ints written, or -1 on malformed/overflowing input.
int64_t pt_varint_decode(const uint8_t* in, int64_t nbytes, int32_t* out,
                         int64_t cap) {
    int64_t pos = 0, count = 0;
    while (pos < nbytes) {
        uint32_t z = 0;
        int shift = 0;
        while (true) {
            if (pos >= nbytes || shift > 28) return -1;
            uint8_t byte = in[pos++];
            z |= static_cast<uint32_t>(byte & 0x7F) << shift;
            if (!(byte & 0x80)) break;
            shift += 7;
        }
        if (count >= cap) return -1;
        out[count++] = static_cast<int32_t>((z >> 1) ^ (~(z & 1) + 1));
    }
    return count;
}

}  // extern "C"
