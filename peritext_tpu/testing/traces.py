"""Replay of recorded reference traces.

The reference repo ships ``traces/*.json`` — failure records from fuzz runs
(reference test/fuzz.ts:16-20).  Each contains per-actor change ``queues``:
replayable ``Change`` lists in the reference's JSON wire format.  The stored
final texts are divergence *evidence*, NOT ground truth (the reference's own
replicas disagreed), so replay asserts convergence of our implementation
across replicas and delivery orders instead of comparing against stored text.

These replays double as real-workload inputs for the batch/TPU merge path.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from ..core.doc import Doc
from ..core.types import Change
from ..parallel.causal import causal_sort

REFERENCE_TRACES_DIR = "/root/reference/traces"


def load_trace_queues(path: str) -> Dict[str, List[Change]]:
    """Parse a recorded trace's per-actor change queues."""
    with open(path) as f:
        data = json.load(f)
    queues = data["queues"] if "queues" in data else data
    return {
        actor: [Change.from_json(c) for c in changes]
        for actor, changes in queues.items()
    }


def available_traces(directory: str = REFERENCE_TRACES_DIR) -> List[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.endswith(".json")
    )


def replay_queues(queues: Dict[str, List[Change]], actor_id: str = "replayer") -> Doc:
    """Build a fresh replica by applying every queued change in causal order."""
    all_changes = [ch for log in queues.values() for ch in log]
    doc = Doc(actor_id)
    for ch in causal_sort(all_changes):
        doc.apply_change(ch)
    return doc
