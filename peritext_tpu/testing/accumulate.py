"""Naive patch-replay oracle.

Accumulates a stream of incremental ``Patch`` dicts into a per-character model
and re-flattens it to format spans — the reference's "dumb model vs. optimized
implementation" differential-testing pattern (reference
``test/accumulatePatches.ts:8-80``).  Used to assert that the incremental
patch path converges to the same document as the batch read path.

Deviation from the reference (a fix, documented): a removeMark patch for a
comment removes only the comment id carried in ``attrs``, rather than wiping
every comment at the position (the reference's accumulator deletes the whole
markType entry because its removeMark patches carry no attrs, making comment
removal unreplayable; reference test/accumulatePatches.ts:54-58).
"""

from __future__ import annotations

from typing import Any, Dict, List

from ..core.spans import add_characters_to_spans, copy_marks as _copy_marks
from ..core.types import FormatSpan, Patch


def accumulate_patches(patches: List[Patch]) -> List[FormatSpan]:
    # Parallel per-character metadata: {"character": str, "marks": {...}}
    metadata: List[Dict[str, Any]] = []

    for patch in patches:
        if list(patch["path"]) != ["text"]:
            raise ValueError("accumulate_patches only supports the 'text' path")
        action = patch["action"]

        if action == "insert":
            for value_index, character in enumerate(patch["values"]):
                metadata.insert(
                    patch["index"] + value_index,
                    {"character": character, "marks": _copy_marks(patch["marks"])},
                )
        elif action == "delete":
            del metadata[patch["index"] : patch["index"] + patch["count"]]
        elif action == "addMark":
            for index in range(patch["startIndex"], patch["endIndex"]):
                marks = metadata[index]["marks"]
                if patch["markType"] == "comment":
                    comments = marks.get("comment", [])
                    cid = patch["attrs"]["id"]
                    if not any(c["id"] == cid for c in comments):
                        marks["comment"] = sorted(
                            comments + [{"id": cid}], key=lambda c: c["id"]
                        )
                else:
                    marks[patch["markType"]] = {
                        "active": True,
                        **{k: v for k, v in patch.get("attrs", {}).items()},
                    }
        elif action == "removeMark":
            for index in range(patch["startIndex"], patch["endIndex"]):
                marks = metadata[index]["marks"]
                if patch["markType"] == "comment" and "attrs" in patch:
                    cid = patch["attrs"]["id"]
                    comments = [c for c in marks.get("comment", []) if c["id"] != cid]
                    if comments:
                        marks["comment"] = comments
                    else:
                        marks.pop("comment", None)
                else:
                    marks.pop(patch["markType"], None)
        elif action == "makeList":
            pass
        else:
            raise ValueError(f"Unknown patch action: {action}")

    spans: List[FormatSpan] = []
    for meta in metadata:
        add_characters_to_spans([meta["character"]], meta["marks"], spans)
    return spans


