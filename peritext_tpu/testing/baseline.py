"""Scalar-baseline workload preparation (shared by bench.py and tests).

Parses fuzz workloads into causally-ordered op matrices for the C++
single-core baseline (native.pt_scalar_apply) and validates its output
against the Python oracle.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .. import native
from ..api.batch import _oracle_doc
from ..ops.frames import parse_frame
from ..parallel.causal import causal_sort
from ..parallel.codec import encode_frame
from ..utils.interning import Interner, OrderedActorTable


def workload_op_matrices(workloads) -> Tuple[List[np.ndarray], int]:
    """Per-doc (N, 10) parsed op matrices in causal application order, plus
    the total op count across all docs."""
    matrices: List[np.ndarray] = []
    total_ops = 0
    for w in workloads:
        changes = causal_sort([ch for log in w.values() for ch in log])
        actors = OrderedActorTable(
            {ch.actor for ch in changes}
            | {op.opid[1] for ch in changes for op in ch.ops}
        )
        parsed, _ = parse_frame(
            encode_frame(changes), actors, Interner(), 0, Interner()
        )
        matrices.append(parsed.ops)
        total_ops += sum(len(ch.ops) for ch in changes)
    return matrices, total_ops


def check_scalar_apply_matches_oracle(workloads, matrices) -> None:
    """Raise RuntimeError if the native baseline diverges from the oracle's
    visible text on ANY doc (skipped-op masking must never inflate ops/s)."""
    for d, (w, m) in enumerate(zip(workloads, matrices)):
        _, text = native.scalar_apply(m)
        got = "".join(chr(int(c)) for c in text)
        expected = "".join(
            s["text"] for s in _oracle_doc(w).get_text_with_formatting(["text"])
        )
        if got != expected:
            raise RuntimeError(
                f"native scalar baseline diverged from the oracle on doc {d}"
            )
