"""Seed-document generation for tests and workloads
(reference ``test/generateDocs.ts:11-42``)."""

from __future__ import annotations

from typing import List, Tuple

from ..core.doc import Doc
from ..core.types import Change, Patch

DEFAULT_TEXT = "The Peritext editor"


def generate_docs(
    text: str = DEFAULT_TEXT, count: int = 2
) -> Tuple[List[Doc], List[List[Patch]], Change]:
    """Create ``count`` replicas sharing one origin change: doc1 makes the text
    list and inserts ``text``; the rest apply that change."""
    docs = [Doc(f"doc{i + 1}") for i in range(count)]
    patches: List[List[Patch]] = [[] for _ in range(count)]

    initial_change, initial_patches = docs[0].change(
        [
            {"path": [], "action": "makeList", "key": "text"},
            {"path": ["text"], "action": "insert", "index": 0, "values": list(text)},
        ]
    )
    patches[0] = initial_patches
    for i in range(1, count):
        patches[i] = docs[i].apply_change(initial_change)
    return docs, patches, initial_change
