"""Synthetic op-stream generation for benchmarking.

The fuzz harness (testing/fuzz.py) produces semantically-checked workloads but
pays full scalar-oracle cost per generated op — fine for correctness, too slow
to build 10K-doc x 4K-op benchmark batches.  This module emits *valid encoded
split streams directly* (inserts reference existing elements, deletes target
existing elements, mark anchors are real), which is exactly what the device
kernel consumes after host-side causal scheduling; generation is cheap numpy.

Packed op ids are (k+1 << ACTOR_BITS | random actor) for stream position k —
unique per doc, with random actor bits so the RGA convergence skip path gets
exercised.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..ops.encode import MARK_COLS
from ..ops.packed import ACTOR_BITS, BK_AFTER, BK_BEFORE, MA_ADD, MA_REMOVE
from ..schema import MARK_INDEX

SynthStreams = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, Dict[str, np.ndarray], np.ndarray]


def synth_streams(
    num_docs: int,
    inserts_per_doc: int,
    deletes_per_doc: int = 0,
    marks_per_doc: int = 0,
    num_actors: int = 4,
    seed: int = 0,
    ctr_offset: int = 0,
) -> SynthStreams:
    """Split-stream tuple (ins_ref, ins_op, ins_char, del_target, marks,
    mark_count) shaped for ops/kernel.apply_batch.

    ``ctr_offset`` shifts all op-id counters; pass the number of ops already
    applied when synthesizing a follow-up round for carried state, so ids
    stay unique per document (the kernel's invariant).
    """
    rng = np.random.default_rng(seed)
    d, ki, kd, km = num_docs, inserts_per_doc, deletes_per_doc, marks_per_doc

    actors = rng.integers(1, num_actors + 1, size=(d, ki), dtype=np.int32)
    ctrs = np.broadcast_to(
        np.arange(ctr_offset + 1, ctr_offset + ki + 1, dtype=np.int32), (d, ki)
    )
    ins_op = (ctrs << ACTOR_BITS) | actors

    # ref for insert k: HEAD (5%) or a uniformly random earlier insert
    pick = rng.random((d, ki))
    ref_idx = (pick * np.arange(ki)[None, :]).astype(np.int64)  # in [0, k)
    ins_ref = np.where(
        (np.arange(ki)[None, :] == 0) | (pick < 0.05),
        np.int32(0),
        np.take_along_axis(ins_op, ref_idx, axis=1),
    ).astype(np.int32)
    ins_char = rng.integers(ord("a"), ord("z") + 1, size=(d, ki), dtype=np.int32)

    # deletes target random inserted elements
    del_idx = rng.integers(0, ki, size=(d, kd), dtype=np.int64)
    del_target = (
        np.take_along_axis(ins_op, del_idx, axis=1) if kd else np.zeros((d, 0), np.int32)
    ).astype(np.int32)

    marks = {col: np.zeros((d, km), np.int32) for col in MARK_COLS}
    if km:
        a_idx = rng.integers(0, ki, size=(d, km), dtype=np.int64)
        b_idx = rng.integers(0, ki, size=(d, km), dtype=np.int64)
        marks["m_action"][:] = np.where(rng.random((d, km)) < 0.7, MA_ADD, MA_REMOVE)
        marks["m_type"][:] = rng.integers(0, len(MARK_INDEX), size=(d, km))
        marks["m_start_kind"][:] = np.where(rng.random((d, km)) < 0.5, BK_BEFORE, BK_AFTER)
        marks["m_start_elem"][:] = np.take_along_axis(ins_op, a_idx, axis=1)
        marks["m_end_kind"][:] = np.where(rng.random((d, km)) < 0.5, BK_BEFORE, BK_AFTER)
        marks["m_end_elem"][:] = np.take_along_axis(ins_op, b_idx, axis=1)
        # mark op ids continue the counter space above the inserts
        m_ctrs = np.broadcast_to(
            np.arange(ctr_offset + ki + 1, ctr_offset + ki + km + 1, dtype=np.int32),
            (d, km),
        )
        m_actors = rng.integers(1, num_actors + 1, size=(d, km), dtype=np.int32)
        marks["m_op"][:] = (m_ctrs << ACTOR_BITS) | m_actors
        marks["m_attr"][:] = rng.integers(1, 16, size=(d, km))
    mark_count = np.full(d, km, np.int32)

    return ins_ref, ins_op, ins_char, del_target, marks, mark_count


def synth_total_ops(streams: SynthStreams) -> int:
    ins_ref, ins_op, _, del_target, marks, _ = streams
    return int(ins_op.size + del_target.size + marks["m_action"].size)
