"""Synthetic op-stream generation for benchmarking.

The fuzz harness (testing/fuzz.py) produces semantically-checked workloads but
pays full scalar-oracle cost per generated op — fine for correctness, too slow
to build 10K-doc x 4K-op benchmark batches.  This module emits *valid encoded
op streams directly* (inserts reference existing elements, deletes target
existing elements, mark anchors are real), which is exactly what the device
kernel consumes after host-side causal scheduling; generation is cheap numpy.

Opids are (k+1, random actor) for stream position k — unique per doc, with
random actor tie-breaking so the RGA convergence skip path gets exercised.
"""

from __future__ import annotations

import numpy as np

from ..schema import MARK_INDEX
from ..ops.encode import (
    F_ATTR,
    F_CHAR,
    F_END_ACTOR,
    F_END_CTR,
    F_END_KIND,
    F_KIND,
    F_MARK_TYPE,
    F_OP_ACTOR,
    F_OP_CTR,
    F_REF_ACTOR,
    F_REF_CTR,
    F_START_ACTOR,
    F_START_CTR,
    F_START_KIND,
    K_ADD_MARK,
    K_DELETE,
    K_INSERT,
    K_REMOVE_MARK,
    NUM_FIELDS,
)
from ..ops.packed import BK_AFTER, BK_BEFORE


def synth_op_streams(
    num_docs: int,
    ops_per_doc: int,
    num_actors: int = 4,
    insert_frac: float = 0.7,
    delete_frac: float = 0.15,
    seed: int = 0,
) -> np.ndarray:
    """(D, K, NUM_FIELDS) int32 op streams, every doc independent."""
    rng = np.random.default_rng(seed)
    d, k = num_docs, ops_per_doc
    out = np.zeros((d, k, NUM_FIELDS), np.int32)

    u = rng.random((d, k))
    kinds = np.where(
        u < insert_frac,
        K_INSERT,
        np.where(u < insert_frac + delete_frac, K_DELETE, K_ADD_MARK),
    ).astype(np.int32)
    # a slice of the marks are removals
    mark_mask = kinds == K_ADD_MARK
    removes = rng.random((d, k)) < 0.3
    kinds = np.where(mark_mask & removes, K_REMOVE_MARK, kinds)
    # first op of every doc must insert (nothing exists yet)
    kinds[:, 0] = K_INSERT

    actors = rng.integers(1, num_actors + 1, size=(d, k), dtype=np.int32)
    chars = rng.integers(ord("a"), ord("z") + 1, size=(d, k), dtype=np.int32)
    mark_types = rng.integers(0, len(MARK_INDEX), size=(d, k), dtype=np.int32)
    attrs = rng.integers(1, 16, size=(d, k), dtype=np.int32)
    sides = rng.integers(0, 2, size=(d, k, 2), dtype=np.int32)  # BK_BEFORE/AFTER

    # Random reference selection: pick a uniform earlier stream position that
    # was an insert (all inserts create elements with ctr = pos + 1).
    ref_pick = rng.random((d, k))
    anchor_pick = rng.random((d, k, 2))

    for di in range(d):
        insert_ctrs: list = []  # ctrs of elements created so far (this doc)
        for ki in range(k):
            row = out[di, ki]
            kind = kinds[di, ki]
            n_elems = len(insert_ctrs)
            if kind != K_INSERT and n_elems == 0:
                kind = K_INSERT
            row[F_KIND] = kind
            row[F_OP_CTR] = ki + 1
            row[F_OP_ACTOR] = actors[di, ki]
            if kind == K_INSERT:
                # ref: HEAD with small probability, else random existing elem
                if n_elems == 0 or ref_pick[di, ki] < 0.05:
                    pass  # HEAD = (0, 0)
                else:
                    j = int(ref_pick[di, ki] * n_elems) % n_elems
                    row[F_REF_CTR] = insert_ctrs[j]
                    # actor of that elem: reconstruct from stream
                    row[F_REF_ACTOR] = out[di, insert_ctrs[j] - 1, F_OP_ACTOR]
                row[F_CHAR] = chars[di, ki]
                insert_ctrs.append(ki + 1)
            elif kind == K_DELETE:
                j = int(ref_pick[di, ki] * n_elems) % n_elems
                row[F_REF_CTR] = insert_ctrs[j]
                row[F_REF_ACTOR] = out[di, insert_ctrs[j] - 1, F_OP_ACTOR]
            else:  # marks
                j0 = int(anchor_pick[di, ki, 0] * n_elems) % n_elems
                j1 = int(anchor_pick[di, ki, 1] * n_elems) % n_elems
                row[F_START_KIND] = BK_BEFORE if sides[di, ki, 0] == 0 else BK_AFTER
                row[F_START_CTR] = insert_ctrs[j0]
                row[F_START_ACTOR] = out[di, insert_ctrs[j0] - 1, F_OP_ACTOR]
                row[F_END_KIND] = BK_BEFORE if sides[di, ki, 1] == 0 else BK_AFTER
                row[F_END_CTR] = insert_ctrs[j1]
                row[F_END_ACTOR] = out[di, insert_ctrs[j1] - 1, F_OP_ACTOR]
                row[F_MARK_TYPE] = mark_types[di, ki]
                row[F_ATTR] = attrs[di, ki]
    return out
