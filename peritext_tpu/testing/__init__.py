"""Test/fuzz harness utilities."""

from .accumulate import accumulate_patches
from .generate import generate_docs

__all__ = ["accumulate_patches", "generate_docs"]
