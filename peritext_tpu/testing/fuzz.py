"""Seeded generative fuzz harness (reference ``test/fuzz.ts``, fixed).

N replicas make random edits (insert / delete / addMark / removeMark) and
randomly pairwise-sync via vector-clock anti-entropy.  Three convergence
oracles after every sync (reference test/fuzz.ts:207-278):

1. patch path == batch path on each replica (accumulate_patches vs
   get_text_with_formatting),
2. synced replicas have identical spans,
3. synced replicas have identical clocks.

Fixes over the reference fuzzer (documented deviations):

* removeMark actually emits removeMark — the reference's ``removeMarkChange``
  emits addMark by mistake (test/fuzz.ts:80), so mark removal was never
  fuzzed upstream.
* Deterministic seeding (``random.Random(seed)``) for reproducibility.
* delete ranges are generated in-bounds (the reference's generator can
  produce out-of-range deletes, its "delete everything goes wonky" bug zone,
  test/fuzz.ts:127-128).

The generated per-actor change logs are also the workload generator for the
batched TPU merge path.
"""

from __future__ import annotations

import random
import string
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.doc import Doc
from ..core.types import Change, InputOperation, Patch
from ..native import available as native_available
from ..parallel.anti_entropy import ChangeStore, apply_changes
from ..parallel.causal import causal_schedule
from ..parallel.faults import FaultSpec, perturb_delivery
from .accumulate import accumulate_patches
from .generate import generate_docs

MARK_TYPES = ("strong", "em", "link", "comment")
EXAMPLE_URLS = tuple(f"{c}.com" for c in string.ascii_uppercase)


@dataclass
class FuzzState:
    docs: List[Doc]
    store: ChangeStore
    patch_lists: List[List[Patch]]
    rng: random.Random
    comment_history: List[str] = field(default_factory=list)
    ops_generated: int = 0
    syncs: int = 0


def make_fuzz_state(seed: int, num_replicas: int = 3, initial_text: str = "ABCDE") -> FuzzState:
    docs, patch_lists, initial_change = generate_docs(initial_text, num_replicas)
    store = ChangeStore()
    store.append(initial_change)
    return FuzzState(
        docs=docs, store=store, patch_lists=patch_lists, rng=random.Random(seed)
    )


def random_input_op(state: FuzzState, doc: Doc) -> Optional[InputOperation]:
    rng = state.rng
    length = len(doc.root["text"])
    kind = rng.choice(("insert", "remove", "addMark", "removeMark"))

    if kind == "insert" or length == 0:
        index = rng.randint(0, length)
        count = rng.randint(1, 3)
        values = [rng.choice(string.ascii_lowercase + "0123456789") for _ in range(count)]
        return {"path": ["text"], "action": "insert", "index": index, "values": values}

    if kind == "remove":
        index = rng.randrange(length)
        count = rng.randint(1, length - index)
        return {"path": ["text"], "action": "delete", "index": index, "count": count}

    # addMark / removeMark
    start = rng.randrange(length)
    end = rng.randint(start + 1, length)
    mark_type = rng.choice(MARK_TYPES)
    op: InputOperation = {
        "path": ["text"],
        "action": "addMark" if kind == "addMark" else "removeMark",
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "link":
        if kind == "addMark":
            op["attrs"] = {"url": rng.choice(EXAMPLE_URLS)}
    elif mark_type == "comment":
        if kind == "addMark":
            cid = f"comment-{rng.randrange(1 << 16):04x}"
            state.comment_history.append(cid)
            op["attrs"] = {"id": cid}
        else:
            if not state.comment_history:
                return None
            op["attrs"] = {"id": rng.choice(state.comment_history)}
    return op


def markheavy_input_op(state: FuzzState, doc: Doc) -> Optional[InputOperation]:
    """A mark-heavy editorial-pass op (ROADMAP scenario family): mostly
    ``addMark``/``removeMark`` over LONG spans drawn across the whole doc,
    so span overlap explodes — every mark lands on text most other marks
    also cover, which is the worst case for mark resolution (the reference's
    span-splitting pressure) and for the device aux tables.  A thin stream
    of inserts keeps the substrate growing so spans always have room."""
    rng = state.rng
    length = len(doc.root["text"])
    if length < 12 or rng.random() > 0.85:
        index = rng.randint(0, length)
        count = rng.randint(2, 6)
        values = [rng.choice(string.ascii_lowercase) for _ in range(count)]
        return {"path": ["text"], "action": "insert", "index": index,
                "values": values}
    # long overlapping spans: start anywhere, reach up to half the doc
    start = rng.randrange(length)
    end = rng.randint(start + 1, min(length, start + max(2, length // 2)))
    kind = "addMark" if rng.random() < 0.7 else "removeMark"
    mark_type = rng.choice(MARK_TYPES)
    op: InputOperation = {
        "path": ["text"],
        "action": kind,
        "startIndex": start,
        "endIndex": end,
        "markType": mark_type,
    }
    if mark_type == "link":
        if kind == "addMark":
            op["attrs"] = {"url": rng.choice(EXAMPLE_URLS)}
    elif mark_type == "comment":
        if kind == "addMark":
            cid = f"comment-{rng.randrange(1 << 16):04x}"
            state.comment_history.append(cid)
            op["attrs"] = {"id": cid}
        else:
            if not state.comment_history:
                return None
            op["attrs"] = {"id": rng.choice(state.comment_history)}
    return op


def fuzz_step(
    state: FuzzState, check: bool = True, faults: Optional[FaultSpec] = None,
    op_fn=random_input_op,
) -> None:
    """One fuzz iteration: a random edit on a random replica, then a random
    pairwise sync with convergence checks.

    With ``faults``, each delivery hop suffers drop/dup/reorder faults
    (SURVEY §5.3): changes lost in transit stay in the store and are re-shipped
    by a later round's vector-clock diff, so convergence is delayed, never
    lost.  Cross-replica convergence is asserted only for clean (lossless)
    syncs; the per-replica patch/batch oracle must hold regardless.
    """
    rng = state.rng
    target = rng.randrange(len(state.docs))
    doc = state.docs[target]

    input_op = op_fn(state, doc)
    if input_op is not None:
        change, patches = doc.change([input_op])
        state.store.append(change)
        state.patch_lists[target].extend(patches)
        state.ops_generated += len(change.ops)

    left = rng.randrange(len(state.docs))
    right = rng.randrange(len(state.docs))
    if left == right:
        return
    state.syncs += 1

    clean = True
    for src, dst in ((left, right), (right, left)):
        missing = state.store.missing_changes(
            state.docs[src].clock, state.docs[dst].clock
        )
        if faults is not None and faults.any_faults():
            delivered = perturb_delivery(missing, rng, faults)
            ordered, stuck = causal_schedule(delivered, state.docs[dst].clock)
            for ch in ordered:
                state.patch_lists[dst].extend(state.docs[dst].apply_change(ch))
            if len(ordered) < len(missing) or stuck:
                clean = False  # losses repair on a later anti-entropy round
        else:
            rng.shuffle(missing)  # delivery order must not matter
            state.patch_lists[dst].extend(apply_changes(state.docs[dst], missing))

    if check:
        if clean:
            left_spans = state.docs[left].get_text_with_formatting(["text"])
            right_spans = state.docs[right].get_text_with_formatting(["text"])
            assert left_spans == right_spans, (
                f"replica divergence after sync #{state.syncs}:\n{left_spans}\n{right_spans}"
            )
            assert state.docs[left].clock == state.docs[right].clock
        # The incremental-vs-batch oracle holds on every replica even when a
        # faulty sync left the pair divergent.
        for idx in (left, right):
            acc = accumulate_patches(state.patch_lists[idx])
            batch = state.docs[idx].get_text_with_formatting(["text"])
            assert acc == batch, (
                f"patch/batch divergence on replica {idx} after sync #{state.syncs}:"
                f"\npatch: {acc}\nbatch: {batch}"
            )


def full_sync(state: FuzzState) -> None:
    """Bring every replica to the store's global frontier with clean
    (fault-free) delivery — the repair round that ends a faulty session."""
    frontier = state.store.clock()
    for idx, doc in enumerate(state.docs):
        missing = state.store.missing_changes(frontier, doc.clock)
        state.patch_lists[idx].extend(apply_changes(doc, missing))


def run_fuzz(
    seed: int,
    iterations: int,
    num_replicas: int = 3,
    check: bool = True,
    faults: Optional[FaultSpec] = None,
) -> FuzzState:
    state = make_fuzz_state(seed, num_replicas)
    for _ in range(iterations):
        fuzz_step(state, check=check, faults=faults)
    return state


def generate_workload(
    seed: int, num_docs: int, ops_per_doc: int, num_replicas: int = 3
) -> List[Dict[str, List[Change]]]:
    """Generate ``num_docs`` independent fuzz change-log sets (no checking) —
    the batched-merge workload for the TPU path."""
    workloads = []
    for d in range(num_docs):
        state = make_fuzz_state(seed + d, num_replicas)
        while state.ops_generated < ops_per_doc:
            fuzz_step(state, check=False)
        workloads.append(
            {actor: list(state.store.log(actor)) for actor in state.store.actors()}
        )
    return workloads


def generate_markheavy_workload(
    seed: int, num_docs: int, ops_per_doc: int, num_replicas: int = 3
) -> List[Dict[str, List[Change]]]:
    """The mark-heavy editorial-pass workload family
    (:func:`markheavy_input_op`): same change-log shape as
    :func:`generate_workload`, so every consumer — the ``markheavy`` bench
    row, the chaos schedule, the scalar-oracle byte-equality check —
    composes unchanged.  Seeds are offset so a campaign running both
    families on the same seed never correlates their randomness."""
    workloads = []
    for d in range(num_docs):
        # +1 keeps the offset non-degenerate at seed=0 (seed*7919+d alone
        # collapses to generate_workload's own per-doc seeds there)
        state = make_fuzz_state((seed * 7919) + d + 1, num_replicas,
                                initial_text="ABCDEFGHIJ")
        while state.ops_generated < ops_per_doc:
            fuzz_step(state, check=False, op_fn=markheavy_input_op)
        workloads.append(
            {actor: list(state.store.log(actor)) for actor in state.store.actors()}
        )
    return workloads


def run_differential(
    seed: int, num_docs: int, ops_per_doc: int, batch=None, cursors_per_doc: int = 4
) -> int:
    """Device-vs-oracle differential round: generate ``num_docs`` fuzz
    workloads, converge them through the batched device path AND the scalar
    oracle, and assert identical spans plus identical resolved cursors.
    Returns the number of device-resolved docs (0 would mean the batch config
    routed everything to fallback — a test-setup bug, so it raises)."""
    import random

    from ..api.batch import DocBatch, _oracle_doc
    from ..core.comment import Comment, put_comment

    if batch is None:
        batch = DocBatch(slot_capacity=512, mark_capacity=128, comment_capacity=32)
    workloads = generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)

    rng = random.Random(seed ^ 0x5EED)
    # ~1 in 6 docs gets comment-body map ops from a fresh actor
    # (core/comment.py): makeMap/set/del flow through the device map-register
    # path (ops/kernel._apply_map_doc), so these docs must STAY on device and
    # their materialized roots must equal the oracle's
    injected = set()
    for d, w in enumerate(workloads):
        if rng.random() < 1 / 6:
            commenter = Doc("commenter")
            change, _ = put_comment(
                commenter,
                Comment(id=f"cb-{d}", actor="commenter", content="body text"),
            )
            w["commenter"] = [change]
            injected.add(d)
    oracle_docs = [_oracle_doc(w) for w in workloads]
    cursors = []
    for doc in oracle_docs:
        n = sum(len(span["text"]) for span in doc.get_text_with_formatting(["text"]))
        indices = [rng.randrange(n) for _ in range(cursors_per_doc)] if n else []
        cursors.append([doc.get_cursor(["text"], i) for i in indices])

    report = batch.merge(workloads, cursors=cursors)
    for d, doc in enumerate(oracle_docs):
        expected = doc.get_text_with_formatting(["text"])
        assert report.spans[d] == expected, (
            f"seed={seed} doc={d}: device spans diverge from oracle\n"
            f"device: {report.spans[d]}\noracle: {expected}"
        )
        expected_cursors = [doc.resolve_cursor(c) for c in cursors[d]]
        got = report.cursor_positions[d]
        assert got == expected_cursors, (
            f"seed={seed} doc={d}: cursor positions diverge: "
            f"device {got} != oracle {expected_cursors}"
        )
    assert not (injected & set(report.fallback_docs)), (
        f"seed={seed}: comment-body docs {sorted(injected & set(report.fallback_docs))} "
        f"fell back — map ops should apply on device (fallbacks: {report.fallback_docs})"
    )
    for d, doc in enumerate(oracle_docs):
        assert report.roots[d] == doc.root, (
            f"seed={seed} doc={d}: device root map diverges from oracle\n"
            f"device: {report.roots[d]}\noracle: {doc.root}"
        )
    device_docs = num_docs - len(report.fallback_docs)
    uninjected = num_docs - len(injected)
    # every doc (incl. map-op docs) should resolve on device at these
    # capacities; all of them falling back indicates a capacity problem
    if uninjected and device_docs == 0:
        raise RuntimeError(
            f"seed={seed}: every doc fell back to the oracle; raise capacities"
        )
    return device_docs


def _campaign_session(num_docs: int, ops_per_doc: int, mesh=None):
    """The streaming-session configuration shared by every streaming fuzz
    campaign (capacities scale with the workload's op count)."""
    from ..parallel.streaming import StreamingMerge

    return StreamingMerge(
        num_docs=num_docs,
        actors=("doc1", "doc2", "doc3"),
        slot_capacity=max(256, 4 * ops_per_doc),
        mark_capacity=max(64, ops_per_doc),
        tomb_capacity=max(128, ops_per_doc),
        round_insert_capacity=128,
        round_delete_capacity=64,
        round_mark_capacity=64,
        mesh=mesh,
    )


def run_differential_frames(
    seed: int, num_docs: int, ops_per_doc: int, chunk: int = 9, mesh=None
) -> int:
    """Streaming frame-ingest differential: deliver each doc's changes as
    shuffled, chunked, partially duplicated wire frames interleaved with
    device rounds; a patch consumer accumulates each doc's incremental
    ``read_patches`` stream every round.  Final spans AND the accumulated
    patch streams must equal the scalar oracle.  Returns the number of docs
    that stayed on the frame fast path."""
    from ..api.batch import _oracle_doc
    from ..parallel.codec import encode_frame
    from .accumulate import accumulate_patches

    rng = random.Random(seed ^ 0xF7A3E5)
    workloads = generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)
    # ~1 in 6 docs gets comment-body map ops (core/comment.py): these must
    # ride the wire fast path into the device map registers, with the
    # materialized root equal to the oracle's.  The comment is authored by a
    # DECLARED replica (doc3) continuing its own history — streaming frames
    # admit only declared actors with causally-valid sequence numbers.
    from ..core.comment import Comment, put_comment
    from ..parallel.causal import causal_sort

    injected = set()
    for d, w in enumerate(workloads):
        if rng.random() < 1 / 6:
            replica = Doc.resume(
                "doc3", causal_sort([c for log in w.values() for c in log])
            )
            change, _ = put_comment(
                replica, Comment(id=f"cb-{d}", actor="doc3", content="body")
            )
            w.setdefault("doc3", []).append(change)
            injected.add(d)
    sess = _campaign_session(num_docs, ops_per_doc, mesh)
    patch_streams = {d: [] for d in range(num_docs)}
    for d, w in enumerate(workloads):
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        frames = [
            encode_frame(changes[i : i + chunk]) for i in range(0, len(changes), chunk)
        ]
        if frames and rng.random() < 0.5:
            frames.insert(rng.randrange(len(frames) + 1), rng.choice(frames))
        for f in frames:
            sess.ingest_frame(d, f)
            if rng.random() < 0.5:
                sess.step()
                if rng.random() < 0.3:
                    patch_streams[d].extend(sess.read_patches(d))
    sess.drain()
    out = sess.read_all()
    for d, w in enumerate(workloads):
        expected = _oracle_doc(w).get_text_with_formatting(["text"])
        assert out[d] == expected, (
            f"seed={seed} doc={d}: frame-streamed spans diverge from oracle\n"
            f"device: {out[d]}\noracle: {expected}"
        )
        patch_streams[d].extend(sess.read_patches(d))
        replayed = accumulate_patches(patch_streams[d])
        assert replayed == expected, (
            f"seed={seed} doc={d}: accumulated patch stream diverges\n"
            f"patches: {replayed}\noracle: {expected}"
        )
    assert sess.pending_count() == 0, f"seed={seed}: undelivered changes remain"
    for d, w in enumerate(workloads):
        oracle_root = _oracle_doc(w).root
        got = sess.read_root(d)
        assert got == oracle_root, (
            f"seed={seed} doc={d}: streamed root map diverges from oracle\n"
            f"device: {got}\noracle: {oracle_root}"
        )
    on_fast_path = sum(1 for s in sess.docs if s.frame_mode and not s.fallback)
    # Without the native core every frame legitimately routes to the object
    # path (the native layer is an accelerator, never a requirement) — only a
    # genuine all-docs demotion with the core present is a regression.
    if native_available():
        fallen = injected & {
            d for d, s in enumerate(sess.docs) if s.fallback or not s.frame_mode
        }
        assert not fallen, (
            f"seed={seed}: comment-body docs {sorted(fallen)} left the frame "
            "fast path — map ops should ride the device registers"
        )
        if num_docs and on_fast_path == 0:
            raise RuntimeError(f"seed={seed}: every doc left the frame fast path")
    return on_fast_path


def run_crash_restore(
    seed: int, num_docs: int = 8, ops_per_doc: int = 80, mesh=None
) -> int:
    """Crash-consistency campaign: kill a streaming session mid-stream and
    restore it from a CheckpointManager checkpoint (event-sourced frame
    histories, checkpoint.py), then repair via one anti-entropy redelivery.

    Per seed: deliver each doc's changes as shuffled chunked frames with
    device rounds interleaved; checkpoint at a random mid-point; "crash"
    (drop the session object); restore from the LATEST checkpoint — a mesh
    session restores MESHLESS, exercising the digest's mesh invariance —
    then redeliver a random overlapping suffix of every doc's frames
    (duplicate-tolerant anti-entropy).  The restored session must reach the
    clean session's digest, spans and roots, all equal to the oracle.
    Returns the number of frames redelivered after restore."""
    import tempfile

    from ..api.batch import _oracle_doc
    from ..checkpoint import CheckpointManager
    from ..parallel.codec import encode_frame

    rng = random.Random(seed ^ 0xC4A54)
    workloads = generate_workload(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)

    def mk(use_mesh):
        return _campaign_session(num_docs, ops_per_doc, use_mesh)

    # per-doc frame schedule
    plans = []
    for w in workloads:
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        chunk = rng.randrange(5, 12)
        plans.append(
            [encode_frame(changes[i : i + chunk]) for i in range(0, len(changes), chunk)]
        )

    # clean reference session (no crash)
    clean = mk(None)
    for d, frames in enumerate(plans):
        for f in frames:
            clean.ingest_frame(d, f)
    clean.drain()
    clean_digest = clean.digest()

    # crashing session: deliver a prefix, checkpoint, deliver a bit more, die
    sess = mk(mesh)
    cut = [rng.randrange(1, len(frames) + 1) for frames in plans]
    for d, frames in enumerate(plans):
        for f in frames[: cut[d]]:
            sess.ingest_frame(d, f)
            if rng.random() < 0.4:
                sess.step()
    sess.drain()
    with tempfile.TemporaryDirectory() as tmp:
        manager = CheckpointManager(tmp, keep=2)
        manager.save(step=1, session=sess)
        # post-checkpoint deliveries that will be LOST in the crash
        for d, frames in enumerate(plans):
            for f in frames[cut[d] : cut[d] + 1]:
                sess.ingest_frame(d, f)
        sess.step()
        del sess  # crash

        restored = manager.latest().session(mesh=None)  # meshless restore
        assert restored is not None

        # anti-entropy repair: redeliver an overlapping suffix (dup-tolerant)
        redelivered = 0
        for d, frames in enumerate(plans):
            start = max(0, cut[d] - rng.randrange(0, 3))  # overlap into the ckpt
            for f in frames[start:]:
                restored.ingest_frame(d, f)
                redelivered += 1
                if rng.random() < 0.3:
                    restored.step()
        restored.drain()

    assert restored.pending_count() == 0, f"seed={seed}: stuck changes after repair"
    assert restored.digest() == clean_digest, (
        f"seed={seed}: restored digest diverges after crash/repair"
    )
    for d, w in enumerate(workloads):
        oracle = _oracle_doc(w)
        expected = oracle.get_text_with_formatting(["text"])
        got = restored.read(d)
        assert got == expected, (
            f"seed={seed} doc={d}: restored spans diverge from oracle"
        )
        assert restored.read_root(d) == oracle.root, (
            f"seed={seed} doc={d}: restored root diverges from oracle"
        )
    return redelivered


def main(argv: Optional[List[str]] = None) -> None:
    """CLI for ``make fuzz`` (the reference's ``npm run fuzz`` analog,
    test/fuzz.ts:167 — but bounded by default and with real removeMark fuzzing).

    ``--differential`` switches to device-vs-oracle differential fuzzing:
    each round converges a fresh batch of fuzz workloads through the batched
    TPU path and asserts span + cursor equality against the scalar oracle.
    ``--differential-frames`` does the same through StreamingMerge's
    frame-native ingest with shuffled/duplicated wire-frame delivery."""
    import argparse

    parser = argparse.ArgumentParser(description="Peritext convergence fuzzer")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--iterations", type=int, default=2000)
    parser.add_argument("--replicas", type=int, default=3)
    parser.add_argument(
        "--differential", action="store_true",
        help="fuzz the batched device path against the scalar oracle",
    )
    parser.add_argument(
        "--differential-frames", action="store_true",
        help="fuzz the streaming frame-ingest path against the scalar oracle",
    )
    parser.add_argument("--docs", type=int, default=32, help="docs per differential round")
    parser.add_argument(
        "--ops-per-doc", type=int, default=160, help="ops per doc per differential round"
    )
    parser.add_argument(
        "--forever", action="store_true",
        help="loop over fresh seeds until interrupted or a failure is found",
    )
    parser.add_argument(
        "--mesh", type=int, default=0, metavar="N",
        help="shard the doc axis over an N-device jax.sharding.Mesh "
             "(needs XLA_FLAGS=--xla_force_host_platform_device_count=N)",
    )
    parser.add_argument(
        "--faults", action="store_true",
        help="scalar fuzz: inject delivery faults (drop 10%%, dup 10%%, "
             "reorder) on every sync hop; anti-entropy must still converge",
    )
    parser.add_argument(
        "--crash-restore", action="store_true",
        help="streaming crash-consistency: checkpoint mid-stream, kill the "
             "session, restore from CheckpointManager (meshless), redeliver "
             "an overlapping suffix; digest/spans/roots must equal a clean "
             "session and the oracle",
    )
    args = parser.parse_args(argv)
    if args.faults and (args.differential or args.differential_frames
                        or args.crash_restore):
        parser.error("--faults applies to the scalar fuzz only; it would be "
                     "silently ignored with the other campaign flags")
    if args.crash_restore and (args.differential or args.differential_frames):
        parser.error("--crash-restore is its own campaign; combine with "
                     "--mesh/--docs/--ops-per-doc only")

    # Honor JAX_PLATFORMS at config level for EVERY campaign (not just
    # --mesh): a TPU plugin registered at interpreter start pins
    # jax_platforms at config level, overriding the env var — with the
    # tunnel down, the differential campaigns would otherwise die (or hang)
    # initializing a backend the caller explicitly routed away from.
    import os

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    mesh = None
    if args.mesh:
        import jax

        from ..parallel.mesh import make_mesh

        if len(jax.devices()) < args.mesh:
            raise SystemExit(
                f"--mesh {args.mesh} needs {args.mesh} devices but only "
                f"{len(jax.devices())} exist; set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={args.mesh}"
            )
        mesh = make_mesh(args.mesh)  # both engines pad the doc axis themselves

    batch = None
    if args.differential:
        from ..api.batch import DocBatch

        batch = DocBatch(
            slot_capacity=512, mark_capacity=128, comment_capacity=32, mesh=mesh
        )

    seed = args.seed
    while True:
        if args.crash_restore:
            redelivered = run_crash_restore(
                seed, num_docs=args.docs, ops_per_doc=args.ops_per_doc, mesh=mesh
            )
            print(
                f"crash-restore seed={seed}: {args.docs} docs x "
                f"{args.ops_per_doc} ops survived kill+restore+repair "
                f"({redelivered} frames redelivered)", flush=True,
            )
        elif args.differential_frames:
            fast = run_differential_frames(seed, args.docs, args.ops_per_doc, mesh=mesh)
            print(
                f"frames-differential seed={seed}: {args.docs} docs x "
                f"{args.ops_per_doc} ops ({fast} on fast path) match the oracle",
                flush=True,
            )
        elif args.differential:
            device_docs = run_differential(
                seed, args.docs, args.ops_per_doc, batch=batch
            )
            print(
                f"differential seed={seed}: {args.docs} docs x {args.ops_per_doc} ops "
                f"({device_docs} on device) match the oracle", flush=True,
            )
        else:
            faults = FaultSpec(drop_p=0.1, dup_p=0.1, reorder=True) if args.faults else None
            state = run_fuzz(
                seed, args.iterations, num_replicas=args.replicas, faults=faults
            )
            if faults is not None:
                # faulted syncs skip the cross-replica oracle (deliveries are
                # deliberately lossy); the property under test is that one
                # clean anti-entropy round repairs everything
                full_sync(state)
                texts = [d.get_text_with_formatting(["text"]) for d in state.docs]
                assert all(t == texts[0] for t in texts), (
                    f"seed={seed}: replicas diverge after fault repair"
                )
                clocks = [d.clock for d in state.docs]
                assert all(c == clocks[0] for c in clocks), (
                    f"seed={seed}: clocks diverge after fault repair"
                )
            print(
                f"fuzz seed={seed}: {state.ops_generated} ops, "
                f"{state.syncs} syncs"
                f"{' (faulted delivery; repaired + converged)' if faults else ''}, "
                f"all convergence oracles passed", flush=True,
            )
        if not args.forever:
            break
        seed += 1


if __name__ == "__main__":
    main()
