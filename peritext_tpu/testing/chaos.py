"""Chaos harness: every fault class composed against the byte-equality oracle.

One :func:`run_chaos` campaign drives a supervised streaming session
(:class:`~..parallel.supervisor.GuardedSession`) through the full fault
space the fault-domain supervisor exists to absorb, in one seeded run:

* **delivery faults** — per-frame drop / duplicate / reorder
  (:class:`~..parallel.faults.FaultSpec`), repaired by redelivery;
* **payload corruption** — truncated / bit-flipped frames
  (:func:`~..parallel.faults.corrupt_detectably`) against a victim subset of
  docs: the codec must reject them (:class:`DecodeError`), the session must
  quarantine exactly those docs with reason ``decode`` and keep the healthy
  docs converging (per-doc fault isolation, checked mid-run);
* **injected device-round failures** — the supervisor's watchdog/rollback
  path: roll back to the last good checkpoint and replay the journal;
* **scalar degradation** — on some seeds one doc is force-demoted to scalar
  replay mid-run (the ladder's last rung) and must still hash byte-equal;
* **peer stall** — a bound-but-unresponsive TCP peer: the transport's
  socket deadline + bounded retry must surface a ``behind``
  :class:`SyncOutcome`, never a hang, and a real peer must then repair;
* **crash-restore** — the supervised session is dropped mid-run and rebuilt
  from its latest checkpoint, then repaired by overlapping redelivery.

The oracle is BYTE EQUALITY: after a final full anti-entropy repair the
chaos session's convergence digest must equal a fault-free session's digest
bit-for-bit, every doc's spans must equal the scalar oracle's, no doc may
remain decode-quarantined (auto re-admission), and nothing may remain
pending.  Any unhandled exception fails the campaign.
"""

from __future__ import annotations

import json
import random
import socket
import tempfile
import threading
import time
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Dict, List, Tuple

from ..api.batch import _oracle_doc
from ..core.errors import DeviceRoundError
from ..core.types import Change
from ..parallel.codec import encode_frame
from ..parallel.faults import FaultSpec, corrupt_detectably
from ..parallel.streaming import REASON_DECODE, REASON_DEVICE_ROUND
from ..parallel.supervisor import GuardedSession
from .fuzz import _campaign_session, generate_workload

#: the composed fault mix one chaos campaign applies to victim docs
CHAOS_SPEC = FaultSpec(
    drop_p=0.15, dup_p=0.15, reorder=True, truncate_p=0.3, bitflip_p=0.3
)


@dataclass
class ChaosReport:
    """Evidence from one seeded chaos campaign (all oracles already held —
    a violated oracle raises instead of returning)."""

    seed: int
    num_docs: int
    delivered_frames: int = 0
    corrupt_frames: int = 0
    dropped_frames: int = 0
    quarantined_peak: int = 0
    rollbacks: int = 0
    crash_restores: int = 0
    transport_behind: int = 0
    transport_repaired: bool = False
    isolation_checked: bool = False
    scalar_degraded_docs: int = 0
    final_digest: int = 0
    #: flight-recorder JSONL dumps the campaign's faults produced (the
    #: quarantine/rollback auto-dumps plus the campaign-end post-mortem)
    flight_dumps: int = 0

    def to_json(self) -> Dict:
        return asdict(self)


class _StallingPeer:
    """A TCP endpoint that accepts connections into its backlog and never
    speaks: the client's connect and first send succeed, then every recv
    stalls — exactly the peer failure `_recv_exact` used to hang on."""

    def __init__(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.address = self._sock.getsockname()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _chaos_transport_episode(workload, report: ChaosReport) -> None:
    """Peer-stall + repair: a stalled peer must yield a ``behind`` outcome
    within the retry budget (no hang, no exception), and a healthy peer must
    then converge the store."""
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.multihost import ReplicaServer, RetryPolicy, try_sync_with

    full = ChangeStore()
    for log in workload.values():
        for change in log:
            full.append(change)
    local = ChangeStore()
    policy = RetryPolicy(attempts=2, base_delay=0.01, max_delay=0.05,
                         jitter=0.5, timeout=0.3)

    stalled = _StallingPeer()
    try:
        outcome = try_sync_with(local, *stalled.address, retry=policy)
        assert outcome.behind and not outcome.ok, (
            "stalled peer must surface as a behind frontier"
        )
        report.transport_behind += 1
    finally:
        stalled.close()

    server = ReplicaServer(full, timeout=5.0)
    host, port = server.start()
    try:
        outcome = try_sync_with(local, host, port, retry=policy)
        assert outcome.ok and outcome.pulled > 0
    finally:
        server.stop()
    assert local.clock() == full.clock(), "repair round must converge the store"
    report.transport_repaired = True


def run_chaos(
    seed: int,
    num_docs: int = 6,
    ops_per_doc: int = 40,
    deadline: float = 60.0,
    transport: bool = True,
    crash: bool = True,
    checkpoint_every: int = 4,
    workload_gen=generate_workload,
) -> ChaosReport:
    """One seeded chaos campaign (see module docstring).  Raises on any
    oracle violation or unhandled fault; returns the evidence report.
    ``workload_gen`` selects the workload family (same change-log shape;
    e.g. ``generate_markheavy_workload`` for the editorial-pass family —
    see :func:`run_markheavy_chaos`)."""
    rng = random.Random(seed ^ 0xC4A05)
    report = ChaosReport(seed=seed, num_docs=num_docs)

    workloads = workload_gen(seed, num_docs=num_docs, ops_per_doc=ops_per_doc)
    oracle_docs = [_oracle_doc(w) for w in workloads]

    # fault-free reference session: the byte-equality digest anchor
    clean = _campaign_session(num_docs, ops_per_doc)
    plans: List[List[bytes]] = []
    for d, w in enumerate(workloads):
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        chunk = rng.randrange(5, 12)
        frames = [
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ]
        plans.append(frames)
        for f in frames:
            clean.ingest_frame(d, f)
    clean.drain()
    clean_digest = clean.digest()

    # the supervised chaos session
    tmp = tempfile.TemporaryDirectory()
    try:
        from ..obs import FlightRecorder

        factory = lambda: _campaign_session(num_docs, ops_per_doc)  # noqa: E731
        # unthrottled flight recorder: every fault dumps, so the campaign's
        # post-mortem oracle below can demand the quarantine evidence even
        # across the crash-restore (which discards the in-memory ring)
        recorder = lambda: FlightRecorder(  # noqa: E731
            capacity=1024, dump_dir=Path(tmp.name) / "flight",
            min_dump_interval=0.0,
        )
        guarded = GuardedSession(
            factory, tmp.name, deadline=deadline,
            checkpoint_every=checkpoint_every, recorder=recorder(),
        )
        victims = set(rng.sample(range(num_docs),
                                 max(1, num_docs // 3)))

        # -- faulty delivery pass ------------------------------------------
        device_faults = rng.randrange(1, 3)
        for d, frames in enumerate(plans):
            delivery = []
            for f in frames:
                if rng.random() < CHAOS_SPEC.drop_p:
                    report.dropped_frames += 1
                    continue
                delivery.append(f)
                if rng.random() < CHAOS_SPEC.dup_p:
                    delivery.append(f)
            rng.shuffle(delivery)
            for f in delivery:
                if d in victims:
                    # detectable corruption only — the quarantine path's
                    # whole fault domain; see faults.corrupt_detectably for
                    # why undetectable damage models as clean delivery
                    bad = corrupt_detectably(f, rng, CHAOS_SPEC)
                    if bad is not None:
                        f = bad
                        report.corrupt_frames += 1
                guarded.ingest_frame(d, f)
                report.delivered_frames += 1
                if rng.random() < 0.3:
                    if device_faults and rng.random() < 0.15:
                        guarded.inject_failure(
                            DeviceRoundError("chaos: injected round failure")
                            if rng.random() < 0.5
                            else RuntimeError("chaos: injected XLA error")
                        )
                        device_faults -= 1
                    guarded.step()
        guarded.drain()
        report.quarantined_peak = max(
            report.quarantined_peak, len(guarded.quarantined())
        )

        # -- per-doc isolation oracle --------------------------------------
        # while >=1 doc sits in quarantine, every healthy doc that received
        # its full frame plan must already equal the oracle
        if report.quarantined_peak:
            quarantined_now = set(guarded.quarantined())
            for d in range(num_docs):
                if d in victims or d in quarantined_now:
                    continue
                # repair healthy docs' dropped frames first (clean redelivery)
                guarded.ingest_frames([(d, f) for f in plans[d]])
            guarded.drain()
            still_quarantined = set(guarded.quarantined())
            for d in range(num_docs):
                if d in victims or d in still_quarantined:
                    continue
                expected = oracle_docs[d].get_text_with_formatting(["text"])
                got = guarded.read(d)
                assert got == expected, (
                    f"seed={seed} doc={d}: healthy doc diverged while "
                    f"{sorted(still_quarantined)} were quarantined"
                )
            report.isolation_checked = bool(still_quarantined)

        # -- scalar-degradation rung (some seeds) --------------------------
        if rng.random() < 0.5:
            victim = rng.randrange(num_docs)
            guarded.session.force_fallback(
                victim, REASON_DEVICE_ROUND, "chaos: forced scalar replay"
            )
            report.scalar_degraded_docs = 1

        # -- peer stall + transport repair ---------------------------------
        if transport:
            _chaos_transport_episode(workloads[rng.randrange(num_docs)], report)

        # -- crash-restore -------------------------------------------------
        if crash:
            guarded.checkpoint()
            # deliver a bit more that the crash will lose
            for d, frames in enumerate(plans):
                if frames and rng.random() < 0.5:
                    guarded.ingest_frame(d, frames[rng.randrange(len(frames))])
            guarded.step()
            old_rollbacks = guarded.rollbacks
            del guarded  # crash: the process state is gone
            guarded = GuardedSession(
                factory, tmp.name, deadline=deadline,
                checkpoint_every=checkpoint_every, recorder=recorder(),
            )
            restored = guarded.manager.latest()
            assert restored is not None
            guarded.adopt_session(restored.session(drain=True))
            guarded.rollbacks = old_rollbacks
            report.crash_restores += 1

        # -- final anti-entropy repair + byte-equality oracle --------------
        for d, frames in enumerate(plans):
            guarded.ingest_frames([(d, f) for f in frames])
        guarded.drain()
        report.rollbacks = guarded.rollbacks

        assert guarded.session.pending_count() == 0, (
            f"seed={seed}: undelivered changes remain after repair"
        )
        decode_q = {
            d: r for d, r in guarded.quarantined().items()
            if r.reason == REASON_DECODE
        }
        assert not decode_q, (
            f"seed={seed}: docs {sorted(decode_q)} still decode-quarantined "
            "after clean redelivery (auto re-admission failed)"
        )
        final = guarded.digest()
        assert final == clean_digest, (
            f"seed={seed}: chaos digest {final:#010x} != fault-free digest "
            f"{clean_digest:#010x}"
        )
        report.final_digest = final
        for d in range(num_docs):
            expected = oracle_docs[d].get_text_with_formatting(["text"])
            got = guarded.read(d)
            assert got == expected, (
                f"seed={seed} doc={d}: spans diverge from oracle after repair"
            )

        # -- flight-recorder oracle ----------------------------------------
        # a campaign that quarantined anything must have produced at least
        # one automatic JSONL dump whose records parse and include the fault
        flight_dir = Path(tmp.name) / "flight"
        auto_dumps = sorted(flight_dir.glob("*.jsonl"))
        final_dump = guarded.recorder.dump(reason="campaign-end")
        records = []
        for dump in auto_dumps + [final_dump]:
            records.extend(
                json.loads(line)
                for line in dump.read_text().splitlines() if line
            )
        if report.corrupt_frames:
            assert auto_dumps, (
                f"seed={seed}: quarantine produced no flight-recorder dump"
            )
            assert any(
                r.get("kind") == "fault" and r.get("reason") == "quarantine"
                for r in records
            ), f"seed={seed}: flight dumps lack the quarantine fault record"
        # campaign-end post-mortem: the ring's spans must reconstruct the
        # recent rounds' stage timeline (guarded rounds + pipeline stages)
        span_names = {r["name"] for r in records if r.get("kind") == "span"}
        assert any(n.startswith("streaming.") for n in span_names) and (
            "supervisor.round" in span_names
        ), f"seed={seed}: flight dump spans missing the round stage timeline"
        report.flight_dumps = len(auto_dumps) + 1
        guarded.close()
    finally:
        tmp.cleanup()
    return report


# ---------------------------------------------------------------------------
# N-host fleet chaos: per-link fault schedules + lag-ordered healing
# ---------------------------------------------------------------------------


class _LinkGate:
    """A DIRECTED TCP gate for one fleet link i→j: host i dials the gate,
    the gate forwards to host j's real replica socket according to its
    current mode.

    * ``open``    — transparent proxy;
    * ``closed``  — accepts and immediately closes (a hard partition: the
      dialer sees a reset/EOF and fails fast);
    * ``rx_only`` — ASYMMETRIC partition: bytes flow dialer→target but the
      target's replies are blackholed.  The target still hears the dialer's
      frontier (how a host keeps learning its lag while unreachable); the
      dialer times out waiting for the response;
    * ``slow``    — transparent but each chunk is delayed ``delay`` seconds
      in both directions (a congested/slow link: exchanges succeed,
      slowly).

    Mode changes apply to NEW connections (each accept snapshots the mode),
    which is exactly a per-round fault schedule's granularity.
    """

    def __init__(self, target: Tuple[str, int], mode: str = "open",
                 delay: float = 0.02) -> None:
        self.target = target
        self.mode = mode
        self.delay = delay
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.address = self._sock.getsockname()
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def set_mode(self, mode: str) -> None:
        assert mode in ("open", "closed", "rx_only", "slow"), mode
        self.mode = mode

    def close(self) -> None:
        self._stop = True
        # shutdown() wakes a thread blocked in accept() (close() alone does
        # not on Linux) so the proxy thread exits instead of lingering
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            mode = self.mode
            if mode == "closed":
                conn.close()
                continue
            threading.Thread(
                target=self._bridge, args=(conn, mode), daemon=True
            ).start()

    def _bridge(self, conn: socket.socket, mode: str) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=5)
        except OSError:
            conn.close()
            return
        delay = self.delay if mode == "slow" else 0.0

        def pump(src: socket.socket, dst: socket.socket,
                 blackhole: bool) -> None:
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    if delay:
                        time.sleep(delay)
                    if not blackhole:
                        dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.close()
                    except OSError:
                        pass

        up = threading.Thread(
            target=pump, args=(conn, upstream, False), daemon=True
        )
        up.start()
        pump(upstream, conn, mode == "rx_only")
        up.join(timeout=10)


def _fleet_change(actor: str, seq: int) -> "Change":
    """One synthetic map-op change (fast codec path, cheap to mint at fleet
    volumes)."""
    from ..core.opids import ROOT
    from ..core.types import Operation

    return Change(
        actor=actor, seq=seq,
        deps={actor: seq - 1} if seq > 1 else {}, start_op=seq,
        ops=[Operation(action="set", obj=ROOT, opid=(seq, actor),
                       key="n", value=seq)],
    )


def _append_changes(store, actor: str, n: int) -> int:
    start = len(store.log(actor)) + 1
    for seq in range(start, start + n):
        store.append(_fleet_change(actor, seq))
    return n


@dataclass
class FleetReport:
    """Evidence from one N-host fleet partition/heal episode (all oracles
    already held — a violated oracle raises instead of returning)."""

    seed: int
    hosts: int
    partition_rounds: int = 0
    #: host0's per-peer observed lag at heal time (monitor watermarks)
    observed_lag: Dict[str, int] = None
    #: the store-truth lag at the same instant (the acceptance instrument:
    #: monitor numbers must EQUAL these)
    expected_lag: Dict[str, int] = None
    #: host0's first post-heal round order (must follow behind-ness)
    heal_order: List[str] = None
    lag_gauge_seen: bool = False
    heal_rounds: int = 0
    ops_drained: int = 0
    heal_seconds: float = 0.0
    converged: bool = False
    final_digest: int = 0
    divergence_incidents: int = 0

    def to_json(self) -> Dict:
        return asdict(self)


def run_fleet_chaos(
    seed: int,
    hosts: int = 4,
    base_ops: int = 8,
    flap_link: bool = True,
    metrics: bool = True,
) -> FleetReport:
    """One N-host fleet episode: converge a fleet, impose an asymmetric
    partition with per-link fault schedules (host0 can hear inbound
    frontiers but every reply and outbound dial is cut; one healthy link
    flaps; the heal leaves the largest-lag link slow), then heal and assert

    * host0's convergence monitor learned its TRUE per-peer lag (equal to
      the store-derived clock-delta sums) through the partition;
    * ``peritext_convergence_lag_ops`` was visible in host0's ``/metrics``
      during the episode (when ``metrics``);
    * host0's first post-heal gossip round followed behind-ness priority
      (most-behind peer first);
    * the fleet drained to IDENTICAL fleet-wide store digests and clocks.

    Raises on any violation; returns the evidence report."""
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.gossip import GossipScheduler
    from ..parallel.multihost import ReplicaServer, RetryPolicy

    rng = random.Random(seed ^ 0xF1EE7)
    assert hosts >= 3, "a fleet episode needs at least 3 hosts"
    report = FleetReport(seed=seed, hosts=hosts)
    policy = RetryPolicy(attempts=1, timeout=0.5)

    stores = [ChangeStore() for _ in range(hosts)]
    servers = [
        ReplicaServer(stores[i], timeout=2.0,
                      metrics_port=0 if (metrics and i == 0) else None)
        for i in range(hosts)
    ]
    for s in servers:
        s.start()
    names = [f"{s.address[0]}:{s.address[1]}" for s in servers]
    # one directed gate per ordered pair: host i dials gate[(i, j)]
    gates = {
        (i, j): _LinkGate(servers[j].address)
        for i in range(hosts) for j in range(hosts) if i != j
    }
    scheds = [
        GossipScheduler(servers[i], retry=policy)
        for i in range(hosts)
    ]
    for i in range(hosts):
        for j in range(hosts):
            if i != j:
                scheds[i].add_peer(*gates[(i, j)].address, name=names[j])

    try:
        # -- phase A: converge the healthy fleet ---------------------------
        for i in range(hosts):
            _append_changes(stores[i], f"host{i}", base_ops + i)
        for _ in range(2):
            for sched in scheds:
                sched.round()
        assert all(s.clock() == stores[0].clock() for s in stores), (
            "healthy fleet failed to converge"
        )

        # -- phase B: asymmetric partition + per-link schedules ------------
        # host0: outbound dials cut, inbound replies blackholed (it HEARS
        # every peer's frontier, can repair nothing); peers cut from each
        # other except one flapping 1<->2 link
        for (i, j), gate in gates.items():
            if j == 0:
                gate.set_mode("rx_only")
            else:
                gate.set_mode("closed")
        partition_rounds = 3
        for r in range(partition_rounds):
            if flap_link:
                flap = "open" if r % 2 == 0 else "closed"
                gates[(1, 2)].set_mode(flap)
                gates[(2, 1)].set_mode(flap)
            for j in range(1, hosts):
                _append_changes(
                    stores[j], f"host{j}", 3 + 2 * j + rng.randrange(3)
                )
            _append_changes(stores[0], "host0", 2 + rng.randrange(3))
            for sched in scheds[1:]:
                sched.round()
            scheds[0].round()  # every dial fails: backoff exercised
        report.partition_rounds = partition_rounds
        if flap_link:
            gates[(1, 2)].set_mode("closed")
            gates[(2, 1)].set_mode("closed")
        # final appends DOMINATE the flap cross-merge, so per-peer lags are
        # strictly ordered: host j ends (200 * j) ops ahead of anything a
        # flapped link could have equalized
        for j in range(1, hosts):
            _append_changes(stores[j], f"host{j}", 200 * j)
        for sched in scheds[1:]:
            # one more rx_only dial: host0 hears the FINAL frontiers (wake
            # first — the peers' own backoff would otherwise skip the dial)
            sched.wake()
            sched.round()

        # monitor truth oracle: host0's watermarks == store-derived lag
        from ..obs.convergence import clock_delta_ops

        clock0 = stores[0].clock()
        report.expected_lag = {
            names[j]: clock_delta_ops(clock0, stores[j].clock())
            for j in range(1, hosts)
        }
        peers0 = servers[0].monitor.peers()
        report.observed_lag = {
            names[j]: peers0[names[j]].ops_behind for j in range(1, hosts)
        }
        assert report.observed_lag == report.expected_lag, (
            f"seed={seed}: monitor watermarks {report.observed_lag} != "
            f"store truth {report.expected_lag}"
        )
        assert len(set(report.observed_lag.values())) == hosts - 1, (
            "per-peer lags must be distinct for the priority oracle"
        )

        # the lag gauges are LIVE during the episode
        if metrics:
            import urllib.request

            mh, mp = servers[0].metrics_address
            text = urllib.request.urlopen(
                f"http://{mh}:{mp}/metrics", timeout=5
            ).read().decode()
            gauge_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("peritext_convergence_lag_ops{")
            ]
            assert gauge_lines and any(
                float(ln.rsplit(" ", 1)[1]) > 0 for ln in gauge_lines
            ), "lag gauge absent or all-zero during the partition"
            report.lag_gauge_seen = True

        # -- phase C: heal — most-behind-first drain -----------------------
        for gate in gates.values():
            gate.set_mode("open")
        # the largest-lag link stays SLOW: priority still reaches it first
        gates[(0, hosts - 1)].set_mode("slow")
        t0 = time.perf_counter()
        scheds[0].wake()
        results = scheds[0].round()
        report.heal_order = list(scheds[0].last_round_order)
        expected_order = [
            name for name, _ in sorted(
                report.expected_lag.items(), key=lambda kv: (-kv[1], kv[0])
            )
        ]
        assert report.heal_order == expected_order, (
            f"seed={seed}: heal order {report.heal_order} does not follow "
            f"behind-ness priority {expected_order}"
        )
        assert all(out.ok for _, out in results), (
            f"seed={seed}: healed links still failing: {results}"
        )
        report.ops_drained = sum(out.pulled + out.pushed for _, out in results)
        # remaining hosts drain (host0's round already fanned most of it)
        rounds = 1
        for _ in range(8):
            if all(s.clock() == stores[0].clock() for s in stores):
                break
            for sched in scheds[1:]:
                sched.wake()
                for _, out in sched.round():
                    if out.ok:
                        report.ops_drained += out.pulled + out.pushed
            rounds += 1
        report.heal_seconds = time.perf_counter() - t0
        report.heal_rounds = rounds

        # -- fleet-wide convergence oracle ---------------------------------
        clocks = [s.clock() for s in stores]
        digests = [s.digest() for s in stores]
        assert all(c == clocks[0] for c in clocks), (
            f"seed={seed}: clocks diverged after heal"
        )
        assert all(d == digests[0] for d in digests), (
            f"seed={seed}: digests diverged after heal: {digests}"
        )
        report.converged = True
        report.final_digest = digests[0]
        report.divergence_incidents = sum(
            len(s.monitor.divergence_incidents) for s in servers
        )
        assert report.divergence_incidents == 0, (
            "a lag-only episode must never probe divergent"
        )
    finally:
        for gate in gates.values():
            gate.close()
        for s in servers:
            s.stop()
    return report


def run_divergence_injection(seed: int, dump_dir=None) -> Dict:
    """Seeded same-frontier/different-digest injection: two stores hold the
    SAME vector clock but one change's content differs (a corrupt merge —
    the split-brain failure convergence digests exist to catch).  The
    exchange must classify as a DIVERGENCE incident — counter + latched
    peer flag + flight-recorder dump — never as plain lag.  Returns the
    evidence (asserts already held)."""
    from ..obs import ConvergenceMonitor, FlightRecorder, GLOBAL_COUNTERS
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.multihost import ReplicaServer, RetryPolicy

    rng = random.Random(seed ^ 0xD1FF)
    n = 4 + rng.randrange(4)
    victim = 1 + rng.randrange(n)
    a, b = ChangeStore(), ChangeStore()
    for seq in range(1, n + 1):
        ch = _fleet_change("shared", seq)
        a.append(ch)
        if seq == victim:
            # same (actor, seq, deps) — different op content
            from ..core.opids import ROOT
            from ..core.types import Operation

            ch = Change(
                actor=ch.actor, seq=ch.seq, deps=ch.deps,
                start_op=ch.start_op,
                ops=[Operation(action="set", obj=ROOT,
                               opid=(ch.start_op, ch.actor),
                               key="n", value=-ch.seq)],
            )
        b.append(ch)
    assert a.clock() == b.clock() and a.digest() != b.digest()

    recorder = FlightRecorder(
        capacity=64, dump_dir=dump_dir, min_dump_interval=0.0,
    ) if dump_dir is not None else None
    monitor = ConvergenceMonitor(host="injector", recorder=recorder)
    before = GLOBAL_COUNTERS.get("convergence.divergence_incidents")
    server = ReplicaServer(b)
    host, port = server.start()
    try:
        from ..parallel.multihost import try_sync_with

        outcome = try_sync_with(
            a, host, port, retry=RetryPolicy(attempts=1, timeout=2.0),
            monitor=monitor,
        )
    finally:
        server.stop()
    peer = f"{host}:{port}"
    rec = monitor.peer(peer)
    assert rec.divergent, "same-frontier/different-digest must latch divergent"
    assert rec.last_outcome != "lag", "divergence must never classify as lag"
    assert monitor.divergence_incidents, "incident record missing"
    incident = monitor.divergence_incidents[0]
    assert incident.local_digest != incident.peer_digest
    assert GLOBAL_COUNTERS.get("convergence.divergence_incidents") > before
    evidence = {
        "seed": seed,
        "peer": peer,
        "outcome_ok": outcome.ok,
        "local_digest": incident.local_digest,
        "peer_digest": incident.peer_digest,
        "counter_incremented": True,
        "dump": None,
    }
    if recorder is not None:
        assert recorder.last_dump_path is not None, (
            "divergence must auto-dump the flight ring"
        )
        dump = Path(recorder.last_dump_path)
        records = [json.loads(line) for line in
                   dump.read_text().splitlines() if line]
        assert any(
            r.get("kind") == "fault" and r.get("reason") == "divergence"
            for r in records
        ), "flight dump lacks the divergence fault record"
        evidence["dump"] = str(dump)

    # -- incident-plane oracle: EXACTLY a divergence incident ---------------
    # delta-triggered on the convergence monitor's incident count, so the
    # heal (no further divergent probes) is quiet rounds, nothing else
    from ..obs import IncidentMonitor

    imon = IncidentMonitor(host="injector", clear_after=2)
    fault_round = imon.rounds
    imon.observe_convergence(monitor)
    imon.advance_round()
    assert imon.incident_kinds() == ["divergence"], (
        f"seed={seed}: divergence injection opened {imon.incident_kinds()},"
        " expected exactly ['divergence']"
    )
    assert len(imon.open_incidents()) == 1
    ttd = imon.time_to_detection("divergence", fault_round)
    assert ttd == 1, f"seed={seed}: detection took {ttd} monitor rounds"
    for _ in range(imon.clear_after):
        imon.observe_convergence(monitor)
        imon.advance_round()
    assert not imon.open_incidents(), (
        f"seed={seed}: divergence incident never resolved post-heal"
    )
    evidence["incident_kinds"] = imon.incident_kinds()
    evidence["incident_resolved"] = True
    evidence["incident_detection_rounds"] = ttd
    return evidence


# ---------------------------------------------------------------------------
# Serving-tier chaos: overload + asymmetric partition against the typed-shed
# and byte-equality oracles
# ---------------------------------------------------------------------------


def _serve_session(num_docs: int, ops_per_doc: int):
    """The serving-tier session configuration: `_campaign_session`
    capacities with ``static_rounds`` — one padded apply shape, so chaos
    latency evidence measures the tier, not XLA compile variants."""
    from ..parallel.streaming import StreamingMerge

    return StreamingMerge(
        num_docs=num_docs,
        actors=("doc1", "doc2", "doc3"),
        slot_capacity=max(256, 4 * ops_per_doc),
        mark_capacity=max(64, ops_per_doc),
        tomb_capacity=max(128, ops_per_doc),
        round_insert_capacity=128,
        round_delete_capacity=64,
        round_mark_capacity=64,
        static_rounds=True,
    )


@dataclass
class ServeChaosReport:
    """Evidence from one serving-tier overload + partition episode (all
    oracles already held — a violated oracle raises instead of
    returning)."""

    seed: int
    hosts: int
    num_docs: int
    offered: int = 0
    admitted: int = 0
    delayed: int = 0
    shed: int = 0
    shed_reasons: Dict[str, int] = None
    queue_peak: int = 0
    queue_max_depth: int = 0
    partition_lag_ops: int = 0
    heal_rounds: int = 0
    fleet_converged: bool = False
    serve_digest_matches_reference: bool = False
    repaired_digest_matches_clean: bool = False
    final_digest: int = 0
    #: latency-plane evidence: sampled stage records during the episode,
    #: every one sum-consistent (nonnegative stages telescoping to the
    #: commit total) with typed close causes — the plane's oracle rides
    #: the SAME chaos episode the verdict oracles do
    latency_records: int = 0
    latency_sum_consistent: bool = False
    latency_force_close: Dict[str, int] = None
    #: incident-plane oracle: the episode must open EXACTLY these kinds
    incident_kinds: List[str] = None
    incident_resolved: bool = False
    incident_detection_rounds: int = -1
    #: history-plane oracle: the gauge keys the private TimeSeriesPlane
    #: flagged, and how many monitor rounds after the fault it fired
    #: (must be <= incident_detection_rounds)
    anomaly_keys: List[str] = None
    anomaly_detection_rounds: int = -1

    def to_json(self) -> Dict:
        return asdict(self)


def run_serve_chaos(
    seed: int,
    hosts: int = 3,
    num_docs: int = 4,
    ops_per_doc: int = 30,
    max_depth: int = 24,
    overload_factor: float = 2.0,
) -> ServeChaosReport:
    """One serving-tier chaos episode: a SessionMux takes ``overload_factor``
    times more offered frames than its bounded queue holds WHILE the host
    sits behind an asymmetric partition, then everything heals.  Oracles:

    * **typed sheds only** — every submission returns a verdict, the
      accounting identity ``offered == admitted + delayed + shed`` holds,
      sheds actually happened (the overload was real), and every shed
      reason is in the typed vocabulary — zero silent drops;
    * **bounded queue** — the admission queue's peak depth never exceeds
      its configured bound, overload or not;
    * **no wedge** — the mux keeps applying admitted work mid-partition
      (the serving path does not block on the unreachable peers);
    * **byte equality** — after the episode the mux's device state equals
      a fault-free reference fed exactly the admitted frames (sheds shed
      whole frames, never corrupt one), and after redelivering EVERYTHING
      under normal load the state equals the no-fault session byte-for-bit
      (a shed is retryable, not a write loss);
    * **fleet heal** — the peer stores, diverged under the partition,
      drain to identical digests once the gates open.

    Raises on any violation; returns the evidence report."""
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.gossip import GossipScheduler
    from ..parallel.multihost import ReplicaServer, RetryPolicy
    from ..serve import AdmissionController, SHED_REASONS, SessionMux
    from .fuzz import generate_workload

    rng = random.Random(seed ^ 0x5E4E)
    assert hosts >= 2, "a serve episode needs at least one peer"
    report = ServeChaosReport(seed=seed, hosts=hosts, num_docs=num_docs,
                              queue_max_depth=max_depth)
    policy = RetryPolicy(attempts=1, timeout=0.5)

    # -- the replica fleet (host0 is the serving host) ----------------------
    stores = [ChangeStore() for _ in range(hosts)]
    servers = [ReplicaServer(stores[i], timeout=2.0) for i in range(hosts)]
    for s in servers:
        s.start()
    names = [f"{s.address[0]}:{s.address[1]}" for s in servers]
    gates = {
        (i, j): _LinkGate(servers[j].address)
        for i in range(hosts) for j in range(hosts) if i != j
    }
    scheds = [GossipScheduler(servers[i], retry=policy) for i in range(hosts)]
    for i in range(hosts):
        for j in range(hosts):
            if i != j:
                scheds[i].add_peer(*gates[(i, j)].address, name=names[j])

    # -- the serving tier on host0 ------------------------------------------
    workloads = generate_workload(seed, num_docs=num_docs,
                                  ops_per_doc=ops_per_doc)
    plans: List[List[bytes]] = []
    for w in workloads:
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        chunk = rng.randrange(4, 8)
        plans.append([
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ])

    mux = SessionMux(
        _serve_session(num_docs, ops_per_doc),
        admission=AdmissionController(
            max_depth=max_depth, high_watermark=0.75, low_watermark=0.5,
            session_quota=None,
        ),
        host=names[0],
    )
    # arm a PRIVATE latency plane: the chaos episode doubles as the
    # plane's adversarial oracle (every sampled record must stay
    # sum-consistent under overload + partition), without touching the
    # process-global plane other tests may read
    from ..obs.latency import CLOSE_CAUSES, LatencyPlane, check_sum_consistency
    mux.latency_plane = LatencyPlane().enable()
    sids = []
    for d in range(num_docs):
        sid, verdict = mux.open_session(f"client{d}")
        assert verdict.admitted and sid is not None
        sids.append(sid)

    admitted_frames: List[List[bytes]] = [[] for _ in range(num_docs)]
    try:
        # -- phase A: asymmetric partition + overload at once ---------------
        # host0 can hear inbound frontiers but every reply and outbound dial
        # is cut (the fleet-chaos shape); peers keep appending, so lag builds
        for (i, j), gate in gates.items():
            if j == 0:
                gate.set_mode("rx_only")
            else:
                gate.set_mode("closed")
        for j in range(1, hosts):
            _append_changes(stores[j], f"host{j}", 20 * j)
        for sched in scheds[1:]:
            sched.round()  # rx_only: host0 hears the frontiers, repairs nothing
        scheds[0].round()  # every outbound dial fails

        # the overload burst: offer far more than the queue holds, pumping
        # only occasionally (an ingest spike outrunning device rounds).
        # The incident-plane oracle samples the mux at each pump boundary
        # — BEFORE the flush that lets the tier catch up and clear its
        # recent-shed mark — the cadence a real scrape-fed monitor has
        from ..obs import IncidentMonitor

        imon = IncidentMonitor(host=names[0], clear_after=2)
        shed_fault_round = imon.rounds
        # the history-plane oracle rides the SAME monitor cadence: a
        # PRIVATE TimeSeriesPlane warms a flat baseline on the idle mux,
        # then the overload's first sampled spike must score as an
        # anomaly no later than the round the shed-storm incident opens
        from ..obs.timeseries import TimeSeriesPlane

        tsp = TimeSeriesPlane(sample_every=1, min_frames=4).enable()
        for _ in range(tsp.min_frames + 2):
            tsp.sample(serve=mux)
        anomaly_fault_round = tsp.rounds
        anomaly_round = None
        anomaly_findings: List[Dict] = []
        offered_target = int(overload_factor * max_depth) * 2
        offered = 0
        d = 0
        while offered < offered_target:
            doc = d % num_docs
            frames = plans[doc]
            frame = frames[(offered // num_docs) % len(frames)]
            verdict = mux.submit(sids[doc], frame)
            assert verdict.kind in ("admit", "delay", "shed"), verdict
            if verdict.kind == "admit":
                admitted_frames[doc].append(frame)
            elif verdict.kind == "shed":
                assert verdict.reason in SHED_REASONS, (
                    f"untyped shed reason {verdict.reason!r}"
                )
            assert mux.admission.depth <= max_depth, "queue bound violated"
            offered += 1
            d += 1
            if offered % (max_depth * 2) == 0:
                imon.observe_serve(mux)
                imon.advance_round()
                tsp.sample(serve=mux)
                if anomaly_round is None and tsp.active_anomalies():
                    anomaly_round = tsp.rounds
                    anomaly_findings = tsp.active_anomalies()
                # an occasional pump mid-overload: the device keeps
                # retiring rounds while the partition holds
                mux.flush()
        # incident-plane oracle, detection half: the mid-overload samples
        # must have opened EXACTLY a shed-storm incident
        assert imon.incident_kinds() == ["shed-storm"], (
            f"seed={seed}: overload opened {imon.incident_kinds()}, "
            "expected exactly ['shed-storm']"
        )
        # history-plane oracle, detection half: the overload scored as an
        # anomaly (serve.* keys -> the shed-storm kind) no later than the
        # monitor round the incident opened
        assert anomaly_round is not None, (
            f"seed={seed}: overload never scored as a history anomaly"
        )
        report.anomaly_keys = sorted(a["key"] for a in anomaly_findings)
        report.anomaly_detection_rounds = anomaly_round - anomaly_fault_round
        assert any(a["kind"] == "shed-storm" for a in anomaly_findings), (
            f"seed={seed}: anomaly findings missed the shed-storm "
            f"mapping: {anomaly_findings}"
        )
        detect = imon.time_to_detection("shed-storm", shed_fault_round)
        assert detect is not None and (
            report.anomaly_detection_rounds <= detect
        ), (
            f"seed={seed}: anomaly lagged the incident "
            f"({report.anomaly_detection_rounds} > {detect} rounds)"
        )
        mux.flush()
        stats = mux.admission.stats
        report.offered = stats.submitted
        report.admitted = stats.admitted
        report.delayed = stats.delayed
        report.shed = stats.shed
        report.shed_reasons = dict(sorted(stats.shed_reasons.items()))
        report.queue_peak = mux.admission.peak_depth
        assert stats.submitted == stats.admitted + stats.delayed + stats.shed, (
            f"seed={seed}: verdict accounting leak "
            f"({stats.submitted} != {stats.admitted}+{stats.delayed}+{stats.shed})"
        )
        assert stats.shed > 0, (
            f"seed={seed}: {overload_factor}x overload produced no sheds — "
            "the episode exercised nothing"
        )
        assert report.queue_peak <= max_depth, (
            f"seed={seed}: queue peak {report.queue_peak} exceeded bound "
            f"{max_depth}"
        )
        assert mux.applied > 0, (
            f"seed={seed}: the mux applied nothing mid-partition (wedged)"
        )
        # latency-plane oracle: the overload episode must have sampled
        # stage records, the latest one telescoping cleanly, every close
        # cause drawn from the typed vocabulary — and a read marks the
        # pending records visible so time-to-visibility fills too
        mux.patches(sids[0])
        plane = mux.latency_plane
        assert plane.records > 0, (
            f"seed={seed}: armed latency plane sampled no drain batches"
        )
        assert plane.last is not None and check_sum_consistency(plane.last), (
            f"seed={seed}: latency record not sum-consistent under "
            f"overload: {plane.last}"
        )
        assert set(plane.force_close) <= set(CLOSE_CAUSES), (
            f"seed={seed}: untyped close cause {plane.force_close}"
        )
        assert plane.snapshot()["pending_visibility"] == 0, (
            f"seed={seed}: patch read left records pending visibility"
        )
        report.latency_records = plane.records
        report.latency_sum_consistent = True
        report.latency_force_close = {
            c: n for c, n in sorted(plane.force_close.items()) if n
        }
        # partition truth: host0 really was behind its peers
        from ..obs.convergence import clock_delta_ops

        report.partition_lag_ops = sum(
            clock_delta_ops(stores[0].clock(), stores[j].clock())
            for j in range(1, hosts)
        )
        assert report.partition_lag_ops > 0, "partition built no lag"

        # -- phase B: byte-equality vs a reference fed the admitted set -----
        reference = _serve_session(num_docs, ops_per_doc)
        for doc in range(num_docs):
            for frame in admitted_frames[doc]:
                reference.ingest_frame(doc, frame)
        reference.drain()
        assert mux.session.digest() == reference.digest(), (
            f"seed={seed}: admitted-set digest mismatch — a shed corrupted "
            "state instead of rejecting cleanly"
        )
        report.serve_digest_matches_reference = True

        # -- phase C: heal the partition + redeliver under normal load ------
        for gate in gates.values():
            gate.set_mode("open")
        for sched in scheds:
            sched.wake()
        heal_rounds = 0
        for _ in range(8):
            heal_rounds += 1
            for sched in scheds:
                sched.round()
            if all(s.clock() == stores[0].clock() for s in stores):
                break
        clocks = [s.clock() for s in stores]
        digests = [s.digest() for s in stores]
        assert all(c == clocks[0] for c in clocks), (
            f"seed={seed}: fleet clocks diverged after heal"
        )
        assert all(dg == digests[0] for dg in digests), (
            f"seed={seed}: fleet digests diverged after heal"
        )
        report.fleet_converged = True
        report.heal_rounds = heal_rounds

        # redelivery (what a client retry / anti-entropy does for shed
        # frames): every doc gets its FULL plan again, paced under the
        # queue bound; the end state must be byte-identical to no-fault
        clean = _serve_session(num_docs, ops_per_doc)
        for doc, frames in enumerate(plans):
            for frame in frames:
                clean.ingest_frame(doc, frame)
        clean.drain()
        for doc, frames in enumerate(plans):
            for frame in frames:
                while True:
                    verdict = mux.submit(sids[doc], frame)
                    assert mux.admission.depth <= max_depth
                    if verdict.kind == "admit":
                        break
                    mux.flush()  # drain, then the retry must admit
        mux.flush()
        final = mux.session.digest()
        assert final == clean.digest(), (
            f"seed={seed}: post-redelivery digest {final:#010x} != "
            f"fault-free {clean.digest():#010x} — shed frames lost writes"
        )
        report.repaired_digest_matches_clean = True
        report.final_digest = final
        assert mux.session.pending_count() == 0

        # incident-plane oracle, heal half: redelivery committed clean
        # rounds, so recent_sheds cleared — quiet rounds must resolve the
        # shed-storm and nothing else may have opened
        for _ in range(imon.clear_after + 1):
            imon.observe_serve(mux)
            imon.advance_round()
        assert imon.incident_kinds() == ["shed-storm"], (
            f"seed={seed}: heal phase opened {imon.incident_kinds()}"
        )
        assert not imon.open_incidents(), (
            f"seed={seed}: shed-storm incident never resolved post-heal"
        )
        report.incident_kinds = imon.incident_kinds()
        report.incident_resolved = True
        report.incident_detection_rounds = imon.time_to_detection(
            "shed-storm", shed_fault_round
        )
    finally:
        for gate in gates.values():
            gate.close()
        for s in servers:
            s.stop()
    return report


# ---------------------------------------------------------------------------
# Reconnect storm: a peer back from the dead drains a giant backlog through
# gossip while the serving tier stays under load
# ---------------------------------------------------------------------------


@dataclass
class ReconnectStormReport:
    """Evidence from one reconnect-storm episode (all oracles already held
    — a violated oracle raises instead of returning)."""

    seed: int
    backlog_ops: int = 0
    drain_seconds: float = 0.0
    drain_ops_per_sec: float = 0.0
    offered: int = 0
    admitted: int = 0
    shed: int = 0
    delayed: int = 0
    p99_apply_ms: float = 0.0
    served_rounds: int = 0
    queue_peak: int = 0
    converged: bool = False
    serve_digest_ok: bool = False

    def to_json(self) -> Dict:
        return asdict(self)


def run_reconnect_storm(
    seed: int,
    backlog_ops: int = 1500,
    num_docs: int = 4,
    ops_per_doc: int = 30,
    serve_rate_per_s: float = 150.0,
    storm_duration_s: float = 1.5,
) -> ReconnectStormReport:
    """The ROADMAP's first adversarial workload family: a peer returns
    after a long offline window holding a ``backlog_ops``-change backlog
    and drains it through one anti-entropy exchange WHILE the local
    serving tier carries open-loop client traffic.  Oracles:

    * the backlog fully converges (local store clock == peer clock, store
      digests byte-equal);
    * the serving tier stayed live through the storm: typed verdicts only
      (accounting identity), bounded queue, rounds kept committing;
    * the mux's device state still equals a fault-free reference fed the
      same admitted frames (the storm never corrupted the serving path).

    Used as both the ``reconnect_storm`` bench row (rates from the
    report) and a chaos schedule (the assertions).  Returns the evidence
    report."""
    from ..parallel.anti_entropy import ChangeStore
    from ..parallel.gossip import GossipScheduler
    from ..parallel.multihost import ReplicaServer, RetryPolicy
    from ..serve import AdmissionController, SessionMux, build_arrivals, run_open_loop
    from .fuzz import generate_workload

    rng = random.Random(seed ^ 0x570F)
    report = ReconnectStormReport(seed=seed)

    # the returning peer: offline "for weeks", giant append-only backlog
    peer_store = ChangeStore()
    _append_changes(peer_store, "returning-peer", backlog_ops)
    report.backlog_ops = backlog_ops
    peer_server = ReplicaServer(peer_store, timeout=10.0)
    peer_server.start()

    # the serving host: store + gossip + mux under open-loop load
    local_store = ChangeStore()
    _append_changes(local_store, "serving-host", 10)
    local_server = ReplicaServer(local_store, timeout=10.0)
    local_server.start()
    sched = GossipScheduler(
        local_server, retry=RetryPolicy(attempts=1, timeout=10.0),
    )
    sched.add_peer(*peer_server.address)

    workloads = generate_workload(seed, num_docs=num_docs,
                                  ops_per_doc=ops_per_doc)
    mux = SessionMux(
        _serve_session(num_docs, ops_per_doc),
        admission=AdmissionController(max_depth=256, session_quota=None),
        host="serving-host",
    )
    frames_by_session: Dict[int, List[bytes]] = {}
    for d, w in enumerate(workloads):
        sid, verdict = mux.open_session(f"client{d}")
        assert verdict.admitted
        changes = [ch for log in w.values() for ch in log]
        rng.shuffle(changes)
        chunk = rng.randrange(4, 8)
        frames_by_session[sid] = [
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ]

    try:
        # warm the device programs BEFORE the storm so the measured p99 is
        # the serving tier, not XLA compiles: a THROWAWAY mux (same session
        # shapes — the compile cache is process-wide) replays the full
        # frame plans with interleaved flushes, walking the pow-2
        # slot-window ladder the real storm will occupy
        wmux = SessionMux(
            _serve_session(num_docs, ops_per_doc),
            admission=AdmissionController(max_depth=256, session_quota=None),
        )
        wmap = {}
        for d in range(num_docs):
            wsid, _ = wmux.open_session(f"warm{d}")
            wmap[wsid] = d
        plans = {wsid: frames_by_session[sid] for wsid, sid
                 in zip(sorted(wmap), sorted(frames_by_session))}
        depth = max(len(p) for p in plans.values())
        for k in range(depth):
            for wsid, plan in sorted(plans.items()):
                if k < len(plan):
                    wmux.submit(wsid, plan[k])
            wmux.flush()

        # -- the storm: gossip drain + open-loop serving, concurrently -----
        drain_done = threading.Event()
        drain_result: Dict = {}

        def drain_backlog():
            t0 = time.perf_counter()
            results = sched.round()
            drain_result["seconds"] = time.perf_counter() - t0
            drain_result["ok"] = all(out.ok for _, out in results)
            drain_result["pulled"] = sum(
                out.pulled for _, out in results
            )
            drain_done.set()

        arrivals = build_arrivals(
            frames_by_session, serve_rate_per_s, storm_duration_s,
        )
        storm = threading.Thread(target=drain_backlog, daemon=True)
        storm.start()
        res = run_open_loop(mux, arrivals, deadline_s=storm_duration_s * 4)
        assert drain_done.wait(timeout=30.0), "backlog drain wedged"
        storm.join(timeout=10.0)

        # -- serving-tier oracles ------------------------------------------
        assert res.accounted(), "verdict accounting leak during the storm"
        report.offered = res.offered
        report.admitted = res.admitted
        report.shed = res.shed
        report.delayed = res.delayed
        report.p99_apply_ms = round(res.p99_apply_s * 1e3, 3)
        report.served_rounds = res.rounds
        report.queue_peak = res.queue_peak
        assert res.queue_peak <= mux.admission.max_depth
        assert res.applied > 0 and res.rounds > 0, (
            "the serving tier froze during the backlog drain"
        )

        # -- convergence oracles -------------------------------------------
        assert drain_result["ok"], "reconnect exchange failed"
        assert drain_result["pulled"] == backlog_ops, (
            f"drained {drain_result['pulled']} of {backlog_ops} backlog ops"
        )
        assert local_store.clock() == peer_store.clock()
        assert local_store.digest() == peer_store.digest(), (
            "stores diverged after the reconnect drain"
        )
        report.drain_seconds = round(drain_result["seconds"], 4)
        report.drain_ops_per_sec = round(
            backlog_ops / max(drain_result["seconds"], 1e-9), 1
        )
        report.converged = True

        # the serving path stayed byte-correct through the storm: when
        # nothing was shed/delayed the mux ingested exactly the arrival
        # frames, so a reference session fed the same set must match the
        # mux's device state bit-for-bit (the shed-path digest oracle
        # lives in run_serve_chaos)
        if res.shed == 0 and res.delayed == 0:
            reference = _serve_session(num_docs, ops_per_doc)
            sessions = mux.sessions()
            for _, sid, frame in arrivals:
                reference.ingest_frame(sessions[sid].doc_index, frame)
            reference.drain()
            assert mux.session.digest() == reference.digest(), (
                "serving state diverged from the reference during the storm"
            )
            report.serve_digest_ok = True
    finally:
        peer_server.stop()
        local_server.stop()
    return report


def run_fused_drain_kill(seed: int, checkpoint_root=None) -> Dict:
    """Kill a fused multi-round drain BETWEEN its staged batch commits and
    prove recovery is byte-equal: the fused pipeline commits several
    multi-round device programs per drain, so the nastiest failure point is
    mid-fuse — some batches landed, one died, the donated state is
    half-advanced.  The supervisor must treat the whole fused drain as ONE
    atomic unit: rollback restores the last checkpoint and replays the
    journal (event-sourced ingest), so the recovered session re-derives
    device state from the pre-fuse round boundary — it can never resume
    from a half-applied fused batch.

    Episode: ingest half the workload, drain + checkpoint (the pre-fuse
    boundary is real state, not an empty session); ingest the rest; arm a
    one-shot fault that raises inside the SECOND staged-batch dispatch of
    the next fused drain; guarded drain → watchdog containment → rollback
    → journal replay → clean re-drain.  Oracle: digest + spans byte-equal
    to a fault-free twin, zero pending, exactly one rollback, and the kill
    provably fired mid-fuse (≥ 1 batch committed before it)."""
    tmp = None
    if checkpoint_root is None:
        tmp = tempfile.TemporaryDirectory(prefix="pt-fused-chaos-")
        checkpoint_root = tmp.name
    try:
        docs, opd = 4, 96
        workloads = generate_workload(seed=seed, num_docs=docs, ops_per_doc=opd)

        def factory():
            s = _campaign_session(docs, opd)
            # low round caps + a narrow fuse window force the drain into
            # SEVERAL staged batches (the mid-fuse failure point needs a
            # batch boundary to die on)
            s.round_caps = (8, 8, 8, 8)
            s.FUSE_MAX_ROUNDS = 2
            return s

        frames = []
        for d, w in enumerate(workloads):
            ch = [c for log in sorted(w) for c in w[log]]
            half = len(ch) // 2
            frames.append((encode_frame(ch[:half]), encode_frame(ch[half:])))

        clean = factory()
        for d, (a, b) in enumerate(frames):
            clean.ingest_frame(d, a)
            clean.ingest_frame(d, b)
        clean.drain()

        guarded = GuardedSession(
            factory, checkpoint_root, deadline=120.0, checkpoint_every=1000,
        )
        # incident-plane oracle: a private monitor fed guarded.health()
        # sees the rollback delta as EXACTLY a quarantine-storm incident;
        # the clean pre-kill drain is its zero baseline
        from ..obs import IncidentMonitor

        imon = IncidentMonitor(host="fused-chaos", clear_after=2)
        for d, (a, _) in enumerate(frames):
            guarded.ingest_frame(d, a)
        pre_rounds = guarded.drain()
        assert pre_rounds > 0, "first half must commit"
        imon.observe_supervisor(guarded)
        imon.advance_round()
        assert not imon.incident_kinds(), (
            f"seed={seed}: clean drain opened {imon.incident_kinds()}"
        )
        guarded.checkpoint()  # the pre-fuse boundary rollback must land on

        for d, (_, b) in enumerate(frames):
            guarded.ingest_frame(d, b)
        sess = guarded.session
        orig_dispatch = sess._dispatch_fused_batch
        calls = {"n": 0}

        def killer(batch, statics, inputs, **kw):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("chaos: device died mid-fuse")
            return orig_dispatch(batch, statics, inputs, **kw)

        sess._dispatch_fused_batch = killer
        rolled = guarded.drain()
        assert rolled == 0, "a killed fused drain must report a rollback"
        assert guarded.rollbacks == 1, guarded.rollbacks
        assert calls["n"] == 2, (
            f"kill must fire on the second staged batch (mid-fuse), "
            f"saw {calls['n']} dispatches"
        )
        # recovery: rollback's guarded re-drain already converged the
        # journal replay; the oracle is byte equality with the clean twin
        assert guarded.pending_count() == 0
        digest, clean_digest = guarded.digest(), clean.digest()
        assert digest == clean_digest, (
            f"mid-fuse kill recovery diverged: {digest:#x} != {clean_digest:#x}"
        )
        assert guarded.read_all() == clean.read_all()

        # incident-plane oracle: the rollback edge opens EXACTLY a
        # quarantine-storm; recovery already replayed the journal, so
        # quiet observations resolve it
        kill_mon_round = imon.rounds
        imon.observe_supervisor(guarded)
        imon.advance_round()
        assert imon.incident_kinds() == ["quarantine-storm"], (
            f"seed={seed}: mid-fuse kill opened {imon.incident_kinds()}, "
            "expected exactly ['quarantine-storm']"
        )
        ttd = imon.time_to_detection("quarantine-storm", kill_mon_round)
        for _ in range(imon.clear_after):
            imon.observe_supervisor(guarded)
            imon.advance_round()
        assert not imon.open_incidents(), (
            f"seed={seed}: quarantine-storm never resolved post-recovery"
        )
        return {
            "seed": seed,
            "rollbacks": guarded.rollbacks,
            "batches_before_kill": calls["n"] - 1,
            "pre_fuse_rounds": pre_rounds,
            "digest": digest,
            "incident_kinds": imon.incident_kinds(),
            "incident_resolved": True,
            "incident_detection_rounds": ttd,
        }
    finally:
        if tmp is not None:
            tmp.cleanup()


def run_markheavy_chaos(seed: int, num_docs: int = 4,
                        ops_per_doc: int = 36, **kw) -> ChaosReport:
    """The mark-heavy editorial-pass chaos schedule (ROADMAP scenario
    diversity): the full composed-fault campaign of :func:`run_chaos` —
    delivery faults, detectable corruption + quarantine, injected device
    rounds, crash-restore — run over the span-overlap-explosion workload
    family, against the same byte-equality oracle.  The same workload is
    the ``markheavy`` bench row (bench.py --mode markheavy)."""
    from .fuzz import generate_markheavy_workload

    return run_chaos(
        seed, num_docs=num_docs, ops_per_doc=ops_per_doc,
        workload_gen=generate_markheavy_workload, **kw,
    )


# ---------------------------------------------------------------------------
# Live fleet failover: kill a serving host mid-traffic (ISSUE 10 acceptance)
# ---------------------------------------------------------------------------


@dataclass
class HostKillReport:
    """Evidence from one host-kill failover episode (all oracles already
    held — a violated oracle raises instead of returning)."""

    seed: int
    hosts: int
    num_docs: int
    victim: str = ""
    victim_docs: int = 0
    offered: int = 0
    admitted: int = 0
    delayed: int = 0
    shed: int = 0
    shed_reasons: Dict[str, int] = None
    #: frontend rounds between the kill and the lease's dead verdict
    detection_rounds: int = 0
    failovers: int = 0
    failover_docs: int = 0
    #: frames acked (admitted) for victim docs at the instant of the kill
    acked_at_kill: int = 0
    acked_survived: bool = False
    redelivered: bool = False
    converged: bool = False
    final_digest: int = 0
    flight_dumps: int = 0
    traffic_seconds: float = 0.0
    applied_frames: int = 0
    #: incident-plane oracle: the episode must open EXACTLY these kinds
    incident_kinds: List[str] = None
    incident_resolved: bool = False
    #: monitor rounds from the kill to the host-death incident opening
    incident_detection_rounds: int = -1
    #: history-plane oracle: the fleet delay/shed gauge keys the private
    #: TimeSeriesPlane flagged, and how many monitor rounds after the
    #: kill it fired (must be <= incident_detection_rounds)
    anomaly_keys: List[str] = None
    anomaly_detection_rounds: int = -1

    def to_json(self) -> Dict:
        return asdict(self)


def run_host_kill_failover(
    seed: int,
    hosts: int = 3,
    num_docs: int = 6,
    ops_per_doc: int = 24,
    lease_rounds: int = 2,
    transport: bool = True,
    dump_dir=None,
) -> HostKillReport:
    """Kill a serving host mid-traffic and prove the fleet survives it.

    A ≥3-host :class:`~..serve.FleetFrontend` places ``num_docs`` docs via
    the router and carries round-robin client traffic; mid-traffic, one
    host that serves docs is KILLED (mux dead, ship endpoint closed,
    heartbeats stop).  Oracles, per the ISSUE-10 acceptance criteria:

    * **typed verdicts only** — every submission after the kill still gets
      a typed verdict (``delay`` while the lease drains / failover runs,
      ``shed(failover)`` only if re-placement fails), every shed reason is
      in ``SHED_REASONS``, and the fleet-wide accounting identity
      ``submitted == admitted + delayed + shed`` holds — zero silent drops;
    * **every acked op survives** — immediately after failover (before any
      client retry) each victim doc's state on its NEW host byte-equals a
      reference session fed exactly the frames that were ACKED at kill
      time (the checkpoint ∪ journal invariant);
    * **post-heal byte equality** — after client retries redeliver
      everything, every doc's full-state hash equals a fault-free
      reference run's, and the fleet-wide digest (doc-hash sum) equals the
      fault-free session digest bit-for-bit;
    * **failover timeline dumped** — the flight recorder produced
      host-death and failover-complete dumps that parse (when
      ``dump_dir``).

    Raises on any violation; returns the evidence report."""
    from ..obs import FlightRecorder, IncidentMonitor
    from ..serve import (
        AdmissionController, FleetFrontend, SHED_REASONS, SessionMux,
    )
    from .fuzz import generate_workload

    rng = random.Random(seed ^ 0xFA170)
    assert hosts >= 3, "the acceptance episode needs a >=3-host fleet"
    report = HostKillReport(seed=seed, hosts=hosts, num_docs=num_docs)

    recorder = (
        FlightRecorder(capacity=256, dump_dir=Path(dump_dir),
                       min_dump_interval=0.0, host="frontend")
        if dump_dir is not None else None
    )
    # the incident-plane oracle: a PRIVATE monitor fed the fleet snapshot
    # once per frontend round must open EXACTLY a host-death incident and
    # resolve it once failover re-homes every doc — nothing else
    imon = IncidentMonitor(host="frontend", clear_after=2,
                           recorder=recorder)
    kill_mon_round = 0
    # the history-plane oracle rides the monitor cadence: a PRIVATE
    # TimeSeriesPlane warms a flat baseline before traffic (below); the
    # kill's delay/shed counter spike must then score as an anomaly no
    # later than the monitor round the host-death incident opens.  Only
    # the delay/shed keys count — traffic ramps the admit counters, and
    # a ramp is drift, not a fault signature
    from ..obs.timeseries import TimeSeriesPlane

    tsp = TimeSeriesPlane(sample_every=1, min_frames=4).enable()
    kill_tsp_round = 0
    anomaly_state = {"round": None, "keys": []}

    def monitor_round():
        imon.observe_fleet(fe)
        imon.advance_round()
        tsp.sample(fleet=fe)
        if anomaly_state["round"] is None:
            hits = [a for a in tsp.active_anomalies()
                    if a["key"] in ("fleet.verdicts.delayed",
                                    "fleet.verdicts.shed")]
            if hits:
                anomaly_state["round"] = tsp.rounds
                anomaly_state["keys"] = sorted(a["key"] for a in hits)

    def make_mux():
        return SessionMux(
            _serve_session(max(4, num_docs), ops_per_doc),
            admission=AdmissionController(max_depth=128, session_quota=None),
        )

    fe = FleetFrontend(lease_rounds=lease_rounds, checkpoint_every=2,
                       recorder=recorder)
    for i in range(hosts):
        fe.add_host(f"host{i}", make_mux(), transport=transport)

    workloads = generate_workload(seed, num_docs=num_docs,
                                  ops_per_doc=ops_per_doc)
    plans: Dict[str, List[bytes]] = {}
    for d, w in enumerate(workloads):
        changes = [ch for log in sorted(w) for ch in w[log]]
        rng.shuffle(changes)
        chunk = rng.randrange(4, 8)
        plans[f"doc{d}"] = [
            encode_frame(changes[i:i + chunk])
            for i in range(0, len(changes), chunk)
        ]
        verdict = fe.open_doc(f"doc{d}", f"client{d}")
        assert verdict.admitted, verdict

    acked: Dict[str, List[bytes]] = {k: [] for k in plans}
    pending: Dict[str, List[bytes]] = {k: list(v) for k, v in plans.items()}
    keys = sorted(plans)

    # flat-baseline warmup: the anomaly scorer needs min_frames quiet
    # frames before the kill's spike can be judged against them
    for _ in range(tsp.min_frames + 2):
        tsp.sample(fleet=fe)

    try:
        t0 = time.perf_counter()
        # -- phase A: traffic, with the kill landing mid-way ----------------
        total_frames = sum(len(v) for v in plans.values())
        kill_after = max(2, int(0.4 * total_frames))
        submitted = 0
        killed = False
        victim = None
        kill_round = 0
        while any(pending.values()):
            for k in keys:
                if not pending[k]:
                    continue
                verdict = fe.submit(k, pending[k][0])
                submitted += 1
                assert verdict.kind in ("admit", "delay", "shed"), verdict
                if verdict.kind == "admit":
                    acked[k].append(pending[k].pop(0))
                elif verdict.kind == "shed":
                    assert verdict.reason in SHED_REASONS, verdict
                if not killed and submitted >= kill_after:
                    # kill a host that actually serves docs, mid-traffic
                    serving_hosts = sorted(set(fe._serving.values()))
                    victim = serving_hosts[rng.randrange(len(serving_hosts))]
                    victim_docs = [
                        dk for dk, h in sorted(fe._serving.items())
                        if h == victim
                    ]
                    assert victim_docs, "victim must hold docs"
                    report.victim = victim
                    report.victim_docs = len(victim_docs)
                    report.acked_at_kill = sum(
                        len(acked[dk]) for dk in victim_docs
                    )
                    acked_at_kill = {dk: list(acked[dk])
                                     for dk in victim_docs}
                    fe.hosts[victim].kill()
                    kill_round = fe.rounds
                    kill_mon_round = imon.rounds
                    kill_tsp_round = tsp.rounds
                    killed = True
                    # the very next submission to a victim doc must answer
                    # TYPED (delay: the lease has not expired yet)
                    probe = fe.submit(victim_docs[0],
                                      plans[victim_docs[0]][0])
                    assert probe.kind in ("delay", "shed"), probe
            fe.round()
            monitor_round()
            if killed and not any(pending.values()):
                break
            if fe.rounds > 200:
                raise AssertionError("traffic loop wedged")
        # drive the lease to the dead verdict + failover
        while victim not in fe.ledger.dead_hosts():
            fe.round()
            monitor_round()
            assert fe.rounds - kill_round <= 2 * lease_rounds + 2, (
                "lease never expired"
            )
        report.detection_rounds = fe.rounds - kill_round
        assert fe.failovers == 1, fe.failovers
        report.failovers = fe.failovers
        report.failover_docs = fe.failover_docs
        assert fe.failover_docs == report.victim_docs, (
            f"seed={seed}: {report.victim_docs} docs on {victim}, only "
            f"{fe.failover_docs} re-placed"
        )
        for dk in acked_at_kill:
            new_host = fe._serving[dk]
            assert new_host != victim and fe.hosts[new_host].alive, (
                f"doc {dk} not re-placed off the dead host"
            )

        # -- acked-op survival (before any client retry) --------------------
        # every frame EVER acked for a victim doc — the pre-kill set (which
        # only survived via checkpoint + journal redelivery) plus anything
        # admitted on the new host after failover — must be reflected in
        # the re-homed doc's state, byte-for-byte
        for dk in acked_at_kill:
            assert acked[dk][:len(acked_at_kill[dk])] == acked_at_kill[dk]
            ref = _serve_session(1, ops_per_doc)
            for f in acked[dk]:
                ref.ingest_frame(0, f)
            ref.drain()
            got = fe.doc_digest(dk)
            want = ref.doc_digest(0)
            assert got == want, (
                f"seed={seed} doc={dk}: acked ops lost in failover "
                f"({got:#010x} != {want:#010x})"
            )
        report.acked_survived = True

        # -- phase B: client retries redeliver EVERYTHING -------------------
        for attempt in range(80):
            dirty = False
            for k in keys:
                # shed/delayed frames retry; redelivery of acked frames is
                # harmless (duplicate-tolerant), so retry the whole plan
                for f in plans[k]:
                    verdict = fe.submit(k, f)
                    assert verdict.kind in ("admit", "delay", "shed"), verdict
                    if verdict.kind != "admit":
                        dirty = True
            fe.round()
            monitor_round()
            if not dirty:
                break
        else:
            raise AssertionError("redelivery never fully admitted")
        fe.flush()
        report.redelivered = True
        report.traffic_seconds = time.perf_counter() - t0

        # -- fleet-wide byte equality vs the fault-free reference -----------
        clean = _serve_session(num_docs, ops_per_doc)
        for d in range(num_docs):
            for f in plans[f"doc{d}"]:
                clean.ingest_frame(d, f)
        clean.drain()
        total = 0
        for d in range(num_docs):
            got = fe.doc_digest(f"doc{d}")
            want = clean.doc_digest(d)
            assert got == want, (
                f"seed={seed} doc=doc{d}: post-heal digest {got:#010x} != "
                f"fault-free {want:#010x}"
            )
            total = (total + got) & 0xFFFFFFFF
        assert total == clean.digest(), (
            f"seed={seed}: fleet-wide digest {total:#010x} != fault-free "
            f"session digest {clean.digest():#010x}"
        )
        report.converged = True
        report.final_digest = total

        # -- accounting identity + applied tally ----------------------------
        assert fe.stats.accounted(), fe.stats.to_json()
        stats = fe.stats
        report.offered = stats.submitted
        report.admitted = stats.admitted
        report.delayed = stats.delayed
        report.shed = stats.shed
        report.shed_reasons = dict(sorted(stats.shed_reasons.items()))
        assert stats.delayed + stats.shed > 0, (
            "the kill produced no delay/shed evidence — it landed too late"
        )
        report.applied_frames = sum(
            h.mux.applied for h in fe.hosts.values()
        )

        # -- flight-recorder timeline ---------------------------------------
        if recorder is not None:
            dumps = sorted(Path(dump_dir).glob("*.jsonl"))
            assert dumps, "host death produced no flight dump"
            records = []
            for dump in dumps:
                records.extend(
                    json.loads(line)
                    for line in dump.read_text().splitlines() if line
                )
            reasons = {r.get("reason") for r in records
                       if r.get("kind") == "fault"}
            assert {"host-death", "failover-complete"} <= reasons, (
                f"failover timeline incomplete: {sorted(reasons)}"
            )
            report.flight_dumps = len(dumps)

        # -- incident-plane oracle ------------------------------------------
        # the episode opens EXACTLY a host-death incident; post-heal (docs
        # re-homed, redelivery done) quiet rounds must resolve it
        for _ in range(imon.clear_after + 1):
            monitor_round()
        assert imon.incident_kinds() == ["host-death"], (
            f"seed={seed}: host-kill opened {imon.incident_kinds()}, "
            "expected exactly ['host-death']"
        )
        assert not imon.open_incidents(), (
            f"seed={seed}: host-death incident never resolved post-heal: "
            f"{[i.to_json() for i in imon.open_incidents()]}"
        )
        ttd = imon.time_to_detection("host-death", kill_mon_round)
        assert ttd is not None and ttd <= 2 * lease_rounds + 2, (
            f"seed={seed}: host-death detection took {ttd} monitor rounds"
        )
        report.incident_kinds = imon.incident_kinds()
        report.incident_resolved = True
        report.incident_detection_rounds = ttd

        # history-plane oracle: the kill's delay/shed spike scored as an
        # anomaly no later than the host-death incident opened
        assert anomaly_state["round"] is not None, (
            f"seed={seed}: host kill never scored as a history anomaly"
        )
        report.anomaly_keys = anomaly_state["keys"]
        report.anomaly_detection_rounds = (
            anomaly_state["round"] - kill_tsp_round
        )
        assert report.anomaly_detection_rounds <= ttd, (
            f"seed={seed}: anomaly lagged the incident "
            f"({report.anomaly_detection_rounds} > {ttd} rounds)"
        )
    finally:
        fe.stop()
    return report


def run_campaign(
    seeds: range, num_docs: int = 6, ops_per_doc: int = 40,
    verbose: bool = False, **kw,
) -> List[ChaosReport]:
    """Run one chaos campaign per seed; any oracle violation raises with the
    seed in its message.  Returns all evidence reports."""
    reports = []
    for seed in seeds:
        report = run_chaos(seed, num_docs=num_docs, ops_per_doc=ops_per_doc, **kw)
        reports.append(report)
        if verbose:
            print(
                f"seed {seed:4d}: frames={report.delivered_frames} "
                f"corrupt={report.corrupt_frames} "
                f"quarantine_peak={report.quarantined_peak} "
                f"rollbacks={report.rollbacks} "
                f"behind={report.transport_behind} "
                f"digest={report.final_digest:#010x}"
            )
    return reports
